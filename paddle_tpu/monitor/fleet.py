"""Fleet observability plane — the multi-process half of the monitor
story (ISSUE 11).  Monitor v1–v3 gave each *process* metrics, traces, a
flight recorder and a live endpoint; this module federates N such
processes into one view, the instrument panel the multi-replica serving
tier (ROADMAP item 2) dispatches and fails over on:

- :func:`parse_prometheus` — parse OUR OWN ``export_prometheus()``
  exposition back into typed series, so ``StatRegistry.merge_snapshot``
  can rebuild counters/gauges/histograms exactly (counters sum, gauges
  keep per-replica values, histograms merge buckets — replicas run the
  same code and therefore share bucket bounds);
- :func:`register_replica` / :func:`discover` — endpoint discovery
  through the native TCPStore: ``monitor.start_server()`` self-registers
  under ``PTPU_FLEET_STORE=host:port`` (a minimal stdlib wire client —
  this module must stay importable without jax, like the rest of
  monitor), so ``launch``/elastic jobs are auto-discovered;
- :class:`FleetAggregator` — scrapes every replica's ``/metrics`` +
  ``/healthz`` on an interval, re-exports the merged registry with a
  ``replica`` label on one fleet :class:`~.serve.MonitorServer`, rolls
  replica health up to ``/fleet/healthz`` (healthy / stalled / down),
  harvests a replica's newest flight dump (``/flight/latest``) the
  moment it transitions to stalled or down — one directory of
  post-mortems for a multi-process failure — and answers
  :meth:`FleetAggregator.snapshot` with the per-replica structured
  stats (queue depth, running/waiting, decode tokens/s, and the ISSUE-13
  training keys — step_time, goodput_examples_per_s, data_wait_frac,
  straggler_skew — plus state) a load-aware router consumes;
- :class:`StragglerRollup` — cross-rank straggler detection: per-replica
  ``train/step_time`` ratioed against the fleet median, the slowest rank
  flagged only after a consecutive-cycle streak
  (``fleet/straggler_skew``, ``fleet/straggler{replica}``, and the
  ``straggler`` block on ``/fleet/healthz``).

Activation is opt-in end to end: replicas register only when
``PTPU_FLEET_STORE`` is set, aggregation only runs inside an explicitly
constructed FleetAggregator, and cross-process trace propagation rides
the existing ``PTPU_TRACE`` gate — nothing here adds always-on cost.

All elapsed-time math (scrape ages, stall thresholds, rate windows) is
on ``time.monotonic()``; wall-clock appears only in exported harvest
metadata.
"""
from __future__ import annotations

import concurrent.futures
import json
import os
import re
import socket
import struct
import threading
import time
import urllib.request

from .wire import FLEET_HEALTHZ_SCHEMA_VERSION, ROUTER_FEED_KEYS

__all__ = [
    "parse_prometheus", "register_replica", "discover", "FleetAggregator",
    "StragglerRollup", "REPLICA_KEY_PREFIX", "REPLICA_COUNT_KEY",
]

# -- discovery key layout ----------------------------------------------------
# The TCPStore has no key listing, so registration is an append-only slot
# log: ADD on the count key claims slot n, SET publishes the record at
# fleet/replicas/<n>.  Readers ADD(0) the count and GET each slot; a
# re-registered replica (restart) takes a new slot and the newest record
# per name wins.
REPLICA_COUNT_KEY = "fleet/replicas/next"
REPLICA_KEY_PREFIX = "fleet/replicas/"

ENV_STORE = "PTPU_FLEET_STORE"


# ---------------------------------------------------------------------------
# Minimal TCPStore wire client (stdlib-only).
# ---------------------------------------------------------------------------
# distributed/store.py's client would do, but importing it pulls the
# paddle_tpu package (core.native, resilience) — this module, like the
# rest of monitor, must stay importable headlessly.  The wire protocol is
# the store's own (csrc/tcp_store.cc == _PyHandler): cmd byte, <I>-length
# key, op payload.  Only SET/GET/ADD are needed here.
class _StoreClient:
    CMD_SET, CMD_GET, CMD_ADD = 0, 1, 2

    def __init__(self, host: str, port: int, timeout_s: float = 10.0):
        deadline = time.monotonic() + timeout_s
        delay = 0.05
        while True:
            try:
                self._sock = socket.create_connection((host, port),
                                                      timeout=5)
                break
            except OSError:
                if time.monotonic() >= deadline:
                    raise TimeoutError(
                        f"cannot reach fleet store at {host}:{port} "
                        f"within {timeout_s}s")
                time.sleep(delay)
                delay = min(delay * 2, 1.0)
        # ops stay bounded too: a store that ACCEPTS but never answers
        # (SIGSTOPped, black-holed) must not hang registration or the
        # aggregator's poll thread forever — socket.timeout is an
        # OSError, which every caller already contains
        self._io_timeout = max(float(timeout_s), 5.0)
        self._sock.settimeout(self._io_timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()

    def _read(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("fleet store connection closed")
            buf += chunk
        return buf

    def _req(self, cmd: int, key: str, payload: bytes = b""):
        kb = key.encode()
        self._sock.sendall(
            bytes([cmd]) + struct.pack("<I", len(kb)) + kb + payload)

    def set(self, key: str, value: bytes) -> None:
        with self._lock:
            self._req(self.CMD_SET, key,
                      struct.pack("<I", len(value)) + value)
            self._read(4)

    def get(self, key: str, timeout_ms: int = 2000) -> "bytes | None":
        """Value bytes, or None when the key doesn't appear within the
        timeout (the store's WAIT-then-GET semantics)."""
        with self._lock:
            # the server legitimately holds the reply for up to
            # timeout_ms while waiting on the key — the socket bound
            # must sit ABOVE that, not race it (timeout_ms=0 means the
            # server waits forever; keep the io bound as the backstop)
            self._sock.settimeout(
                self._io_timeout + (timeout_ms / 1e3 if timeout_ms
                                    else self._io_timeout))
            try:
                self._req(self.CMD_GET, key,
                          struct.pack("<I", timeout_ms))
                (found,) = struct.unpack("<I", self._read(4))
                if not found:
                    return None
                (n,) = struct.unpack("<I", self._read(4))
                return self._read(n) if n else b""
            finally:
                self._sock.settimeout(self._io_timeout)

    def add(self, key: str, amount: int = 1) -> int:
        with self._lock:
            self._req(self.CMD_ADD, key, struct.pack("<q", amount))
            return struct.unpack("<q", self._read(8))[0]

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def _split_addr(addr: str):
    host, _, port = addr.rpartition(":")
    if not host or not port.isdigit():
        raise ValueError(
            f"{ENV_STORE} must be host:port, got {addr!r}")
    return host, int(port)


def default_replica_name() -> str:
    """PTPU_REPLICA_ID when set (the launch wiring exports it per spawned
    rank), else host:pid — unique enough for one fleet."""
    rid = os.environ.get("PTPU_REPLICA_ID")
    if rid:
        return rid
    return f"{socket.gethostname()}:{os.getpid()}"


def advertised_url(server) -> str:
    """The URL a replica PUBLISHES for scraping.  A wildcard bind
    (0.0.0.0/::) is unroutable as written — advertise the hostname
    instead.  A loopback bind is advertised as-is: it is only reachable
    by a colocated aggregator, which is the truth (the endpoint's
    default 127.0.0.1 bind is a deliberate exposure decision; cross-host
    fleets must start the server with ``host=`` wider — see README)."""
    host = getattr(server, "host", None)
    if host in ("0.0.0.0", "::"):   # a real bind always resolves "" to
        # one of these, so the wildcard set is exactly two names
        return f"http://{socket.gethostname()}:{server.port}"
    return server.url


def registration_record(url: str, name: str = None) -> dict:
    """The JSON document a replica publishes: endpoint + identity.  The
    "ts" field is a wall-clock EXPORT (cross-process registration age is
    advisory only — monotonic clocks don't travel between hosts)."""
    from . import serve

    rec = {"name": name or default_replica_name(), "url": url,
           "pid": os.getpid(), "ts": time.time()}
    rec.update(serve.identity())
    return rec


def register_replica(server, store=None, name: str = None) -> dict:
    """Publish `server`'s endpoint in the fleet store (PTPU_FLEET_STORE,
    or an injected store-like object with .add/.set/.close).  Returns the
    published record.  Called automatically by ``monitor.start_server``
    when the env var is set."""
    own = False
    if store is None:
        host, port = _split_addr(os.environ.get(ENV_STORE, ""))
        store = _StoreClient(host, port)
        own = True
    try:
        rec = registration_record(advertised_url(server), name=name)
        slot = store.add(REPLICA_COUNT_KEY, 1)
        store.set(f"{REPLICA_KEY_PREFIX}{slot}",
                  json.dumps(rec).encode())
    finally:
        if own:
            store.close()
    return rec


def discover(store_addr: str = None, timeout_ms: int = 5000,
             store=None, connect_timeout_s: float = 10.0) -> "list[dict]":
    """All currently registered replica records (newest wins per name)."""
    own = False
    if store is None:
        host, port = _split_addr(store_addr
                                 or os.environ.get(ENV_STORE, ""))
        store = _StoreClient(host, port, timeout_s=connect_timeout_s)
        own = True
    try:
        count = store.add(REPLICA_COUNT_KEY, 0)
        by_name = {}
        for slot in range(1, count + 1):
            raw = store.get(f"{REPLICA_KEY_PREFIX}{slot}",
                            timeout_ms=timeout_ms)
            if raw is None:   # claimed slot whose SET hasn't landed yet
                continue
            try:
                rec = json.loads(raw.decode())
            except (ValueError, UnicodeDecodeError):
                continue   # foreign/corrupt record: skip, don't poison
            if isinstance(rec, dict) and rec.get("name") and \
                    rec.get("url"):
                by_name[rec["name"]] = rec   # later slot wins (restart)
    finally:
        if own:
            store.close()
    return [by_name[k] for k in sorted(by_name)]


# ---------------------------------------------------------------------------
# Prometheus exposition parser (for OUR exporter's output)
# ---------------------------------------------------------------------------
_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})?\s+(\S+)\s*$')
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
# OpenMetrics exemplar suffix on a bucket line (ISSUE 16):
# `` # {trace_id="..."} <value> <ts>``.  Stripped BEFORE _SAMPLE_RE runs
# — its greedy label group would otherwise swallow the exemplar braces
# and silently drop every exemplar-carrying bucket sample.
_EXEMPLAR_RE = re.compile(r"\s#\s+\{(.*?)\}\s+(\S+)(?:\s+(\S+))?\s*$")


_ESC_RE = re.compile(r"\\(.)")
_ESC_MAP = {"n": "\n", '"': '"', "\\": "\\"}


def _unescape(v: str) -> str:
    # ONE left-to-right pass: ordered str.replace would decode the 'n'
    # of an escaped backslash ('C:\new' exports as 'C:\\new') into a
    # newline and split the series key the replica published
    return _ESC_RE.sub(
        lambda m: _ESC_MAP.get(m.group(1), m.group(1)), v)


def _parse_labels(block: "str | None"):
    if not block:
        return {}
    return {k: _unescape(v) for k, v in _LABEL_RE.findall(block)}


def parse_prometheus(text: str) -> "dict[str, dict]":
    """Parse ``StatRegistry.export_prometheus()`` text back into
    ``{name: {"kind", "help", "series": {label_key: value}}}`` — the
    input shape of ``StatRegistry.merge_snapshot``.

    Histogram series come back as ``{"buckets", "counts", "count",
    "sum"}`` with per-bucket (non-cumulative) counts, reconstructed by
    differencing the ``le``-labeled cumulative samples; ``repr``-ed
    bucket bounds round-trip floats exactly, so merged replicas re-bin
    identically.  OpenMetrics exemplar suffixes on bucket lines are
    parsed into an ``"exemplars"`` list (aligned with ``counts``, the
    last slot the +Inf/overflow bucket) so a replica's trace links
    survive fleet federation.  Unknown/foreign lines are skipped, not
    fatal — the fleet must keep scraping a replica that grew a new
    metric kind."""
    kinds, helps = {}, {}
    # histogram assembly: name -> {series_key: {"le": {bound: cum},
    #                                           "sum": x, "count": n}}
    hist_raw: dict = {}
    out: dict = {}

    def ensure(name):
        if name not in out:
            out[name] = {"kind": kinds.get(name, "gauge"),
                         "help": helps.get(name, ""), "series": {}}
        return out[name]

    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] == "TYPE":
                kinds[parts[2]] = parts[3] if len(parts) > 3 else "gauge"
            elif len(parts) >= 3 and parts[1] == "HELP":
                helps[parts[2]] = parts[3] if len(parts) > 3 else ""
            continue
        exemplar = None
        em = _EXEMPLAR_RE.search(line)
        if em is not None:
            tid = dict(_LABEL_RE.findall(em.group(1))).get("trace_id")
            try:
                ev = float(em.group(2))
                ets = float(em.group(3)) if em.group(3) else 0.0
            except ValueError:
                tid = None
            if tid:
                exemplar = (_unescape(tid), ev, ets)
            line = line[:em.start()]
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        name, label_block, value_s = m.groups()
        labels = _parse_labels(label_block)
        # histogram sample names wear _bucket/_sum/_count suffixes
        base = None
        for suffix in ("_bucket", "_sum", "_count"):
            cand = name[:-len(suffix)] if name.endswith(suffix) else None
            if cand and kinds.get(cand) == "histogram":
                base = cand
                break
        if base is not None:
            le = labels.pop("le", None)
            key = tuple(sorted(labels.items()))
            rec = hist_raw.setdefault(base, {}).setdefault(
                key, {"le": {}, "sum": 0.0, "count": 0})
            if name.endswith("_bucket"):
                bound = None
                if le == "+Inf":
                    bound = float("inf")
                elif le is not None:
                    bound = float(le)
                if bound is not None:
                    rec["le"][bound] = int(float(value_s))
                    if exemplar is not None:
                        rec.setdefault("exm", {})[bound] = exemplar
            elif name.endswith("_sum"):
                rec["sum"] = float(value_s)
            else:
                rec["count"] = int(float(value_s))
            continue
        try:
            value = float(value_s)
        except ValueError:
            continue
        pm = ensure(name)
        pm["series"][tuple(sorted(labels.items()))] = value

    for base, by_key in hist_raw.items():
        pm = ensure(base)
        pm["kind"] = "histogram"
        for key, rec in by_key.items():
            bounds = sorted(b for b in rec["le"] if b != float("inf"))
            counts, prev = [], 0
            for b in bounds:
                cum = rec["le"][b]
                counts.append(cum - prev)
                prev = cum
            counts.append(rec["count"] - prev)   # overflow bucket
            series = {
                "buckets": tuple(bounds), "counts": counts,
                "count": rec["count"], "sum": rec["sum"],
            }
            exm_map = rec.get("exm")
            if exm_map:
                exm = [exm_map.get(b) for b in bounds]
                exm.append(exm_map.get(float("inf")))
                series["exemplars"] = exm
            pm["series"][key] = series
    return out


def series_value(parsed: dict, name: str, default=None, **labels):
    """Convenience read of one parsed series (prometheus-style name)."""
    pm = parsed.get(name)
    if pm is None:
        return default
    key = tuple(sorted((k, str(v)) for k, v in labels.items()))
    return pm["series"].get(key, default)


def _series_extreme(parsed: dict, name: str, pick):
    """min/max across EVERY series of one parsed metric (None when the
    replica doesn't export it) — how the feed rolls a replica's worst
    slo/burn_rate{objective,window} into one routing signal."""
    pm = parsed.get(name)
    if not pm:
        return None
    vals = [v for v in pm["series"].values()
            if isinstance(v, (int, float))]
    return pick(vals) if vals else None


def _tenant_rollup(parsed: dict) -> dict:
    """``{tenant: {"tokens", "admitted", "shed"}}`` from the replica's
    ``serving_tenant_*`` labeled counters (ISSUE 19) — the feed's
    per-tenant block.  Empty when no tenant-labeled traffic has hit the
    replica (default-pool requests export no tenant series)."""
    out: dict = {}
    for metric, field in (("serving_tenant_tokens", "tokens"),
                          ("serving_tenant_admitted", "admitted"),
                          ("serving_tenant_shed", "shed")):
        pm = parsed.get(metric)
        if not pm:
            continue
        for key, val in pm["series"].items():
            tenant = dict(key).get("tenant")
            if tenant is None or not isinstance(val, (int, float)):
                continue
            out.setdefault(tenant, {"tokens": 0, "admitted": 0,
                                    "shed": 0})[field] = val
    return out


def _tenant_kv_rollup(parsed: dict) -> dict:
    """``{tenant: kv_blocks_held}`` from the replica's
    ``serving_kv_blocks_held`` labeled gauge (ISSUE 20 memory
    microscope) — who holds the pool right now.  Empty when the replica
    exports no tenant-labeled KV series (PTPU_MEMOBS off, or only
    default-pool traffic)."""
    out: dict = {}
    pm = parsed.get("serving_kv_blocks_held")
    if not pm:
        return out
    for key, val in pm["series"].items():
        tenant = dict(key).get("tenant")
        if tenant is None or not isinstance(val, (int, float)):
            continue
        out[tenant] = val
    return out


# ---------------------------------------------------------------------------
# The aggregator
# ---------------------------------------------------------------------------
STATE_HEALTHY = "healthy"
STATE_STALLED = "stalled"
STATE_DOWN = "down"
STATE_UNKNOWN = "unknown"
_STATES = (STATE_HEALTHY, STATE_STALLED, STATE_DOWN, STATE_UNKNOWN)


class _Replica:
    """Mutable per-replica scrape state (all mutation under the
    aggregator's lock)."""

    __slots__ = ("name", "url", "state", "fail_streak", "scrape_errors",
                 "last_ok_mono", "last_err", "healthz", "parsed",
                 "prev_counters", "rates", "harvested")

    def __init__(self, name: str, url: str):
        self.name = name
        self.url = url
        self.state = STATE_UNKNOWN
        self.fail_streak = 0
        self.scrape_errors = 0
        self.last_ok_mono = None     # monotonic of last good scrape
        self.last_err = None
        self.healthz = {}
        self.parsed = {}
        self.prev_counters = {}      # name -> (monotonic_ts, value)
        self.rates = {}              # name -> per-second rate
        self.harvested = []          # harvest file paths, oldest first


class StragglerRollup:
    """Cross-rank straggler detection off per-replica ``train/step_time``
    gauges (ISSUE 13 wing d) — the signal the multi-replica training
    tier (ROADMAP item 3's DP fleet) needs that per-process metrics
    can't carry: *which rank* is dragging the synchronous step.

    Per :meth:`update` of ``{replica: step_seconds}``:

    - ``skews`` — every replica's step time over the fleet MEDIAN (the
      robust baseline: one straggler can't drag the denominator the way
      a mean or min-max would);
    - ``slowest`` / ``skew`` — the worst replica and its ratio;
    - ``flagged`` — set only after the SAME replica has been slowest
      with skew above ``threshold`` for ``streak`` consecutive updates
      (one GC pause or scrape-phase artifact must not nominate a
      straggler); recovery (skew back under threshold, or a different
      replica slowest) re-arms the streak.

    Pure host math, mutated only under the owning aggregator's lock;
    also usable standalone on any ``{rank: seconds}`` dict (tests drive
    it directly)."""

    __slots__ = ("threshold", "streak_needed", "skews", "slowest", "skew",
                 "streak", "flagged")

    def __init__(self, threshold: float = 1.5, streak: int = 3):
        self.threshold = float(threshold)
        self.streak_needed = max(1, int(streak))
        self.skews: dict = {}
        self.slowest = None
        self.skew = None
        self.streak = 0
        self.flagged = None

    def update(self, step_times: "dict[str, float]") -> dict:
        valid = {k: float(v) for k, v in step_times.items()
                 if v is not None and v > 0}
        if len(valid) < 2:   # skew is meaningless without a peer
            self.skews = {}
            self.slowest, self.skew, self.streak, self.flagged = (
                None, None, 0, None)
            return self.as_dict()
        vals = sorted(valid.values())
        mid = len(vals) // 2
        med = vals[mid] if len(vals) % 2 else \
            (vals[mid - 1] + vals[mid]) / 2.0
        self.skews = {k: valid[k] / med for k in sorted(valid)}
        slowest = max(sorted(valid), key=lambda k: valid[k])
        skew = self.skews[slowest]
        if skew > self.threshold:
            self.streak = self.streak + 1 if slowest == self.slowest \
                else 1
            self.flagged = slowest if self.streak >= self.streak_needed \
                else None
        else:
            self.streak = 0
            self.flagged = None
        self.slowest, self.skew = slowest, skew
        return self.as_dict()

    def as_dict(self) -> dict:
        return {"slowest": self.slowest, "skew": self.skew,
                "streak": self.streak, "flagged": self.flagged,
                "skews": dict(self.skews)}


class FleetAggregator:
    """Scrape N replica endpoints, federate their metrics, roll health
    up, and harvest post-mortems.

    Replicas come from an explicit ``endpoints`` list (urls or
    registration records) and/or from ``store`` (a ``host:port`` TCPStore
    address — default ``PTPU_FLEET_STORE`` — that ``start_server``-ed
    replicas registered into; re-polled every cycle so late joiners
    appear).  ``fetch`` is injectable for tests (url -> body text).

    States: *healthy* (scrape ok, recent activity), *stalled* (scrape ok
    but ``last_activity_age_s`` > ``stall_after_s`` — the process is up,
    its work loop is not), *down* (``down_after`` consecutive scrape
    failures), *unknown* (not successfully scraped yet, failure streak
    still below the down threshold).  On the transition INTO stalled or
    down the replica's ``/flight/latest`` is pulled and saved
    replica-tagged into ``harvest_dir`` — a stalled replica still serves
    it from the endpoint's daemon thread even while its main thread
    hangs."""

    RATE_COUNTERS = ("serving_decode_tokens", "serving_prefill_tokens")
    # the snapshot() per-replica key set, for router introspection —
    # declared in monitor/wire.py, checked by ptpu-check wire-compat
    FEED_KEYS = ROUTER_FEED_KEYS

    def __init__(self, endpoints=None, store: str = None,
                 interval: float = 2.0, stall_after_s: float = 10.0,
                 down_after: int = 3, harvest_dir: str = None,
                 scrape_timeout: float = 5.0, fetch=None,
                 straggler_threshold: float = 1.5,
                 straggler_streak: int = 3):
        self._lock = threading.Lock()
        self._replicas: "dict[str, _Replica]" = {}
        self._straggler = StragglerRollup(threshold=straggler_threshold,
                                          streak=straggler_streak)
        self.interval = float(interval)
        self.stall_after_s = float(stall_after_s)
        self.down_after = int(down_after)
        self.scrape_timeout = float(scrape_timeout)
        self.harvest_dir = harvest_dir
        self._store_addr = store if store is not None \
            else (os.environ.get(ENV_STORE) or None)
        self._fetch = fetch or self._http_fetch
        self._registry = None
        self._server = None
        self._thread = None
        self._stop_evt = threading.Event()
        self._cycles = 0
        self._harvest_seq = 0
        self._loop_errors = 0
        self._last_loop_err = None
        self._slot_cache = {}   # slot -> record dict | miss count
        #                         (poll-thread-private, no lock needed)
        self._pool = None       # lazy shared scrape executor
        self._inflight = {}     # name -> future still RUNNING after its
        #                         cycle budget expired (poll-thread-
        #                         private): a wedged scrape must not get
        #                         a second worker stacked on it
        self._store_cli = None  # persistent discovery connection
        for ep in endpoints or ():
            if isinstance(ep, str):
                name = ep.split("//", 1)[-1]
                self._replicas[name] = _Replica(name, ep)
            else:
                self._replicas[ep["name"]] = _Replica(ep["name"],
                                                      ep["url"])

    # -- scraping ----------------------------------------------------------
    def _http_fetch(self, url: str) -> str:
        return urllib.request.urlopen(
            url, timeout=self.scrape_timeout).read().decode()

    _SLOT_GIVE_UP = 3   # misses before a slot is treated as a permanent
    #                     hole (a registrant that died between ADD and SET)

    def _refresh_endpoints(self):
        """Incremental discovery with bounded blocking: a dead store
        costs one SHORT connect attempt per cycle (never the
        registration path's patient 10 s retry), resolved slots are
        cached so only new registrations hit the store, and a hole slot
        stops being polled after _SLOT_GIVE_UP misses."""
        if not self._store_addr:
            return
        with self._lock:
            cli = self._store_cli
        if cli is None:
            try:
                host, port = _split_addr(self._store_addr)
                cli = _StoreClient(host, port,
                                   timeout_s=min(2.0,
                                                 self.scrape_timeout))
            except (OSError, ValueError):
                return   # store unreachable: keep scraping what we know
            with self._lock:
                self._store_cli = cli   # ONE persistent connection, not
                # a connect/teardown per cycle against the rendezvous
                # store every rank depends on
        recs = []
        try:
            count = cli.add(REPLICA_COUNT_KEY, 0)
            for slot in range(1, count + 1):
                cached = self._slot_cache.get(slot)
                if isinstance(cached, dict):
                    recs.append(cached)
                    continue
                if cached is not None and cached >= self._SLOT_GIVE_UP:
                    continue
                raw = cli.get(f"{REPLICA_KEY_PREFIX}{slot}",
                              timeout_ms=300)
                if raw is None:
                    self._slot_cache[slot] = (cached or 0) + 1
                    continue
                try:
                    rec = json.loads(raw.decode())
                except (ValueError, UnicodeDecodeError):
                    rec = None
                if isinstance(rec, dict) and rec.get("name") and \
                        rec.get("url"):
                    self._slot_cache[slot] = rec
                    recs.append(rec)
                else:   # foreign/corrupt record: never poll it again
                    self._slot_cache[slot] = self._SLOT_GIVE_UP
        except OSError:
            # store died (or an op timed out, desyncing the framing):
            # drop the connection, next cycle redials from scratch
            cli.close()
            with self._lock:
                if self._store_cli is cli:
                    self._store_cli = None
            return
        by_name = {}
        for rec in recs:   # slot order: the newest record per name wins
            by_name[rec["name"]] = rec
        with self._lock:
            for rec in by_name.values():
                r = self._replicas.get(rec["name"])
                if r is None:
                    self._replicas[rec["name"]] = _Replica(rec["name"],
                                                           rec["url"])
                elif r.url != rec["url"]:
                    r.url = rec["url"]   # restarted on a new port

    def poll_once(self) -> dict:
        """One full scrape cycle (also the unit-test entry point):
        refresh discovery, scrape every replica, update the rollup,
        rebuild + swap the fleet registry.  Returns {name: state}."""
        self._refresh_endpoints()
        with self._lock:
            targets = [(r.name, r.url) for r in
                       self._replicas.values()]
        targets.sort()   # deterministic merge order (float sums)

        def scrape(url):
            try:
                mtext = self._fetch(url + "/metrics")
                hz = json.loads(self._fetch(url + "/healthz"))
                return (parse_prometheus(mtext), hz, None)
            except Exception as e:   # any scrape failure counts toward
                # the down streak — the cause rides last_err
                return (None, None, e)

        # concurrent, outside the lock: a serial walk would let ONE
        # black-holed endpoint delay every other replica's scrape by
        # scrape_timeout — slowest exactly during the multi-replica
        # failures the rollup exists to catch.  One long-lived pool
        # (workers spawn lazily), not a fresh executor per cycle.  The
        # single-replica case rides the pool too: an inline scrape
        # would be unbounded against a wedged resolver.
        results = {}
        if targets:
            with self._lock:
                pool = self._pool
                if pool is None:
                    pool = self._pool = \
                        concurrent.futures.ThreadPoolExecutor(
                            max_workers=16,
                            thread_name_prefix="ptpu-fleet-scrape")
            # bounded wait (ISSUE 14 blocking-in-handler): scrape()
            # itself is fetch-timeout-bounded, but an injected fetch or
            # a wedged RESOLVER isn't (urllib's timeout does not bound
            # DNS) — an unbounded result() here would hang the
            # aggregator's daemon loop forever.  An expiry counts
            # toward the replica's down streak like any other scrape
            # failure.  A future still RUNNING past its budget keeps
            # its worker (threads can't be killed) but is remembered in
            # _inflight so the NEXT cycle does not stack a second
            # worker on the same black hole — one permanently wedged
            # endpoint costs one pool worker total, not one per cycle.
            futs = {}
            for name, url in targets:
                prev = self._inflight.get(name)
                if prev is not None and not prev.done():
                    results[name] = (None, None, TimeoutError(
                        "scrape still wedged from a previous cycle"))
                    continue
                self._inflight.pop(name, None)
                futs[name] = pool.submit(scrape, url)
            deadline = time.monotonic() + 2.0 * self.scrape_timeout + 1.0
            for name, fut in futs.items():
                try:
                    results[name] = fut.result(
                        timeout=max(deadline - time.monotonic(), 0.01))
                except concurrent.futures.TimeoutError:
                    results[name] = (None, None, TimeoutError(
                        "scrape exceeded the cycle budget"))
                    # cancel() drops it if still queued; a running one
                    # is remembered instead of duplicated next cycle
                    if not fut.cancel():
                        self._inflight[name] = fut

        harvests = []
        now = time.monotonic()
        with self._lock:
            for name, (parsed, hz, err) in results.items():
                r = self._replicas.get(name)
                if r is None:   # removed between scrape and update
                    continue
                prev_state = r.state
                if err is not None:
                    r.fail_streak += 1
                    r.scrape_errors += 1
                    r.last_err = repr(err)
                    if r.fail_streak >= self.down_after:
                        r.state = STATE_DOWN
                else:
                    r.fail_streak = 0
                    r.last_ok_mono = now
                    r.healthz = hz
                    r.parsed = parsed
                    self._update_rates(r, now)
                    age = hz.get("last_activity_age_s")
                    r.state = STATE_STALLED if (
                        age is not None and age > self.stall_after_s
                    ) else STATE_HEALTHY
                if r.state != prev_state and r.state in (STATE_STALLED,
                                                         STATE_DOWN):
                    self._harvest_seq += 1
                    harvests.append((r.name, r.url, r.state,
                                     self._harvest_seq))
            # cross-rank straggler rollup (ISSUE 13 wing d): ratio every
            # replica's train/step_time against the fleet median — only
            # replicas scraped OK THIS cycle contribute (a dead rank's
            # stale last reading must not keep it flagged forever)
            self._straggler.update({
                name: series_value(parsed, "train_step_time")
                for name, (parsed, _hz, err) in results.items()
                if err is None})
            self._cycles += 1
            states = {r.name: r.state for r in self._replicas.values()}

        for name, url, state, seq in harvests:   # I/O outside the lock
            self._harvest(name, url, state, seq)

        reg = self._build_registry()
        with self._lock:
            self._registry = reg
            if self._server is not None:
                self._server.registry = reg
        return states

    def _update_rates(self, r: _Replica, now: float):
        for cname in self.RATE_COUNTERS:
            v = series_value(r.parsed, cname)
            if v is None:
                continue
            prev = r.prev_counters.get(cname)
            if prev is not None:
                t0, v0 = prev
                dt = now - t0
                if dt > 0 and v >= v0:
                    r.rates[cname] = (v - v0) / dt
            r.prev_counters[cname] = (now, v)

    # -- harvesting --------------------------------------------------------
    def _harvest(self, name: str, url: str, state: str, seq: int):
        """Pull the replica's newest flight dump and save a
        replica-tagged copy.  A down replica's endpoint is usually gone —
        the attempt is still made (the http thread can outlive a wedged
        main thread) and a failure is recorded, not raised."""
        dir = self.harvest_dir or os.environ.get(
            "PTPU_FLEET_HARVEST_DIR") or os.environ.get("PTPU_FLIGHT_DIR")
        if not dir:
            return
        safe = re.sub(r"[^A-Za-z0-9_.-]", "_", name)
        path = os.path.join(dir, f"harvest_{safe}_{state}_{seq:03d}.json")
        try:
            body = self._fetch(url + "/flight/latest")
            os.makedirs(dir, exist_ok=True)
            tmp = f"{path}.tmp"
            with open(tmp, "w") as f:
                f.write(body)
            os.replace(tmp, path)   # readers never see a partial harvest
        except Exception as e:
            with self._lock:
                r = self._replicas.get(name)
                if r is not None:
                    r.last_err = f"harvest: {e!r}"
            return
        with self._lock:
            r = self._replicas.get(name)
            if r is not None:
                r.harvested.append(path)

    # -- the merged registry ----------------------------------------------
    def _build_registry(self):
        from . import StatRegistry

        reg = StatRegistry()
        with self._lock:
            snap = [(r.name, r.state, r.scrape_errors, r.last_ok_mono,
                     r.parsed) for r in sorted(self._replicas.values(),
                                               key=lambda x: x.name)]
        now = time.monotonic()
        counts = dict.fromkeys(_STATES, 0)
        merge_errors = {}
        for name, state, errors, last_ok, parsed in snap:
            counts[state] += 1
            if parsed:
                try:
                    reg.merge_snapshot(parsed, labels={"replica": name})
                except Exception as e:
                    # one replica's unmergeable exposition (bucket-bound
                    # or kind mismatch — a version-skewed fleet) must not
                    # keep the WHOLE fleet view stale: the others still
                    # merge, and the failure is exported + recorded
                    merge_errors[name] = repr(e)
            g = reg.gauge("fleet/scrape_errors",
                          "scrape failures per replica (cumulative)")
            self._force_set(g.labels(replica=name), errors)
            g = reg.gauge("fleet/scrape_age_s",
                          "seconds since the last successful scrape")
            self._force_set(
                g.labels(replica=name),
                -1.0 if last_ok is None else round(now - last_ok, 3))
        g = reg.gauge("fleet/replicas",
                      "replica count by rollup state")
        for state in _STATES:
            self._force_set(g.labels(state=state), counts[state])
        g = reg.gauge("fleet/merge_errors",
                      "replicas whose exposition failed to merge this "
                      "cycle")
        for name, err in merge_errors.items():
            self._force_set(g.labels(replica=name), 1)
        with self._lock:
            strag = self._straggler.as_dict()
        if strag["skew"] is not None:
            self._force_set(
                reg.gauge("fleet/straggler_skew",
                          "slowest replica's step time over the fleet "
                          "median"), strag["skew"])
        if strag["flagged"] is not None:
            self._force_set(
                reg.gauge("fleet/straggler",
                          "1 = replica flagged as the fleet straggler")
                .labels(replica=strag["flagged"]), 1)
        if merge_errors:
            with self._lock:
                for name, err in merge_errors.items():
                    r = self._replicas.get(name)
                    if r is not None:
                        r.last_err = f"merge: {err}"
        return reg

    @staticmethod
    def _force_set(gauge, v):
        # bypass the PTPU_MONITOR gate: the fleet registry is
        # reconstruction of scraped data, not hot-path instrumentation
        with gauge._lock:
            gauge._value = float(v)
            gauge._touched = True

    @property
    def registry(self):
        """The most recently merged fleet StatRegistry (None before the
        first cycle)."""
        with self._lock:
            return self._registry

    # -- rollup / router API ----------------------------------------------
    def states(self) -> "dict[str, str]":
        with self._lock:
            return {r.name: r.state for r in self._replicas.values()}

    def snapshot(self) -> "dict[str, dict]":
        """Per-replica structured stats — the load-aware-routing feed
        (ROADMAP item 2): queue depth, running/waiting, decode tokens/s,
        last activity, rollup state."""
        now = time.monotonic()
        out = {}
        with self._lock:
            for r in sorted(self._replicas.values(),
                            key=lambda x: x.name):
                # ptpu-wire: router-feed
                out[r.name] = {
                    "url": r.url,
                    "state": r.state,
                    "host": r.healthz.get("host"),
                    "pid": r.healthz.get("pid"),
                    "queue_depth": series_value(
                        r.parsed, "serving_queue_depth"),
                    "running": series_value(r.parsed, "serving_running"),
                    "waiting": series_value(r.parsed, "serving_waiting"),
                    "decode_tokens_per_s": r.rates.get(
                        "serving_decode_tokens"),
                    # ISSUE 12 goodput/padding + process identity: the
                    # load-aware-dispatch signals (None when the replica
                    # predates them — schema keys only ever accrete)
                    "goodput_tokens_per_s": series_value(
                        r.parsed, "serving_goodput_tokens_per_s"),
                    "padding_waste_rows": series_value(
                        r.parsed, "serving_padding_waste", kind="rows"),
                    "kernels_per_step": series_value(
                        r.parsed, "serving_kernels_per_step"),
                    # ISSUE 13 training keys (same accrete-only contract:
                    # a replica predating them reads None, never KeyError)
                    "step_time": series_value(
                        r.parsed, "train_step_time"),
                    "goodput_examples_per_s": series_value(
                        r.parsed, "train_goodput_examples_per_s"),
                    "data_wait_frac": series_value(
                        r.parsed, "train_data_wait_frac"),
                    "straggler_skew": self._straggler.skews.get(r.name),
                    "rss_bytes": r.healthz.get("rss_bytes"),
                    "open_fds": r.healthz.get("open_fds"),
                    "uptime_s": r.healthz.get("uptime_s"),
                    "last_activity_age_s": r.healthz.get(
                        "last_activity_age_s"),
                    "scrape_age_s": None if r.last_ok_mono is None
                    else round(now - r.last_ok_mono, 3),
                    "scrape_errors": r.scrape_errors,
                    "fail_streak": r.fail_streak,
                    "last_err": r.last_err,
                    "harvested": list(r.harvested),
                    # ISSUE 15: speculative-decode acceptance + prefix-
                    # cache heat (accrete-only; None for older replicas)
                    "spec_accept_rate": series_value(
                        r.parsed, "serving_spec_accept_rate"),
                    "prefix_hit_tokens": series_value(
                        r.parsed, "serving_prefix_hit_tokens"),
                    # ISSUE 16: worst SLO burn across every (objective,
                    # window) series + smallest remaining budget — the
                    # admission-shedding inputs (accrete-only; None with
                    # PTPU_SLO unset or for replicas predating them)
                    "slo_max_burn_rate": _series_extreme(
                        r.parsed, "slo_burn_rate", max),
                    "slo_min_budget_remaining": _series_extreme(
                        r.parsed, "slo_budget_remaining", min),
                    # ISSUE 18: circuit-breaker state is router-local —
                    # Router.fleet_view() overlays the live values; the
                    # aggregator can only declare the (accreted) keys
                    "breaker_state": None,
                    "breaker_trips": None,
                    # ISSUE 19: per-tenant served/admitted/shed rollup
                    # for weighted-fair-share dashboards and tenant-
                    # aware dispatch (accrete-only, like every key)
                    "tenants": _tenant_rollup(r.parsed),
                    # ISSUE 20 memory microscope: KV-pool pressure for
                    # capacity-aware routing (accrete-only; None for
                    # replicas predating them or with PTPU_MEMOBS off)
                    "kv_blocks_in_use": series_value(
                        r.parsed, "serving_blocks_in_use"),
                    "kv_block_utilization": series_value(
                        r.parsed, "serving_block_utilization"),
                    "kv_pressure_dumps": series_value(
                        r.parsed, "memory_pressure_dumps"),
                    "tenant_kv_blocks": _tenant_kv_rollup(r.parsed),
                }
        return out

    def healthz(self) -> dict:
        """The /fleet/healthz document."""
        snap = self.snapshot()
        counts = dict.fromkeys(_STATES, 0)
        for rec in snap.values():
            counts[rec["state"]] += 1
        if not snap:
            status = "empty"
        elif counts[STATE_HEALTHY] == len(snap):
            status = "ok"
        else:
            status = "degraded"
        with self._lock:
            loop_errors, last_loop_err = (self._loop_errors,
                                          self._last_loop_err)
            strag = self._straggler.as_dict()
        strag.pop("skews", None)   # per-replica skew rides each
        #                            replica's snapshot entry
        # schema v2 adds the "straggler" rollup (keys only ever accrete;
        # v1 consumers ignore it); declared in monitor/wire.py so drift
        # is a lint failure (ISSUE 14)
        return {"status": status,
                "schema_version": FLEET_HEALTHZ_SCHEMA_VERSION,
                "stall_after_s": self.stall_after_s,
                "down_after": self.down_after,
                "loop_errors": loop_errors,
                "last_loop_err": last_loop_err,
                "straggler": strag,
                "counts": counts, "replicas": snap}

    # -- lifecycle ---------------------------------------------------------
    def serve(self, port: int = 0, host: str = "127.0.0.1"):
        """Expose the merged view on a fleet MonitorServer: /metrics
        serves the federated registry, /fleet/healthz the rollup."""
        from .serve import MonitorServer

        def route():
            return 200, json.dumps(self.healthz()), "application/json"

        with self._lock:
            if self._server is None:
                # before the first cycle the merged view is truthfully
                # EMPTY — never the aggregator process's own metrics
                # masquerading as fleet totals (registry=None would fall
                # back to the module-global exporter)
                reg = self._registry
                if reg is None:
                    from . import StatRegistry

                    reg = StatRegistry()
                self._server = MonitorServer(
                    port, host, registry=reg,
                    routes={"/fleet/healthz": route})
            srv = self._server
        return srv

    def start(self):
        """Run poll_once() every `interval` seconds on a daemon thread."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop_evt.clear()
        self._thread = threading.Thread(
            target=self._loop, name="ptpu-fleet-aggregator", daemon=True)
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop_evt.is_set():
            try:
                self.poll_once()
            except Exception as e:
                # one bad cycle (store hiccup, endpoint mid-restart) must
                # not kill the scrape loop; per-replica scrape/merge
                # failures are already contained + counted, so anything
                # landing here is unexpected — record it where
                # /fleet/healthz surfaces it
                with self._lock:
                    self._loop_errors += 1
                    self._last_loop_err = repr(e)
            self._stop_evt.wait(self.interval)

    def stop(self, timeout: float = 5.0):
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout)
        with self._lock:
            srv, self._server = self._server, None
            pool, self._pool = self._pool, None
            cli, self._store_cli = self._store_cli, None
        if pool is not None:
            pool.shutdown(wait=False)
        if cli is not None:
            cli.close()
        if srv is not None:
            srv.stop()
