"""Wide-event request log — the *what happened to THIS request* layer
(ISSUE 16).

Metrics aggregate away identity and traces cost a span per operation;
the request log sits between them: ONE structured event per finished
serving request, wide enough to answer routing/debugging questions
without a join — arrival, queue wait, TTFT, TPOT stats, prefill chunks,
prefix-cache hits, speculative accept counts, preemptions, peak KV
blocks and the finish reason, keyed by rid/trace_id/replica_id so a
fleet view can stitch one request's journey across the metric, trace
and log planes.

Events land in a bounded in-process ring (served at ``GET
/requests/recent`` on the MonitorServer) and, when ``PTPU_REQLOG``
names a file path, in a size-rotated JSONL sink.  The event schema is
declared accrete-only in :mod:`monitor.wire`
(``REQLOG_EVENT_KEYS`` / ``REQLOG_SCHEMA_VERSION``) and the builder
below carries the ``ptpu-wire: reqlog-event`` anchor, so drifting the
event without registering it is a ``wire-compat`` lint failure.

Design constraints (shared with the rest of the monitor stack):

- **default off, near-zero when disabled**: gate ``PTPU_REQLOG``
  (``1``/``on`` = ring only; a path = ring + JSONL).  The engine's
  per-request emit site checks :func:`enabled` first — one
  module-global read; the per-step cost is nothing (emission happens at
  release time, not per token).
- **stdlib-only, no jax**: importable headlessly like every sibling.
- **bounded**: the ring holds ``PTPU_REQLOG_RING`` events (default
  256); the JSONL sink rotates at ``PTPU_REQLOG_ROTATE_MB`` (default
  16) MiB, keeping one ``.1`` predecessor — a long-lived replica can
  never fill the disk with request logs.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

from .wire import REQLOG_EVENT_KEYS, REQLOG_SCHEMA_VERSION

__all__ = [
    "enabled", "enable", "refresh", "event", "emit", "recent", "reset",
    "sink_path", "REQLOG_EVENT_KEYS", "REQLOG_SCHEMA_VERSION",
]

_DEFAULT_RING = 256
_DEFAULT_ROTATE_MB = 16.0


def _env_value() -> str:
    return os.environ.get("PTPU_REQLOG", "").strip()


def _env_enabled() -> bool:
    return _env_value().lower() not in ("", "0", "false", "off")


def _env_sink() -> "str | None":
    v = _env_value()
    if not _env_enabled():
        return None
    # "1"/"on"/"true" = ring only; anything else is a sink path
    return None if v.lower() in ("1", "true", "on") else v


_enabled = _env_enabled()
_sink_path = _env_sink()


def enabled() -> bool:
    return _enabled


def enable(on: bool = True, sink: "str | None" = None):
    """Flip event collection on/off at runtime (overrides PTPU_REQLOG).
    ``sink`` sets/clears the JSONL path when given (None keeps it)."""
    global _enabled, _sink_path
    _enabled = bool(on)
    if sink is not None:
        _set_sink(sink or None)


def refresh():
    """Re-read PTPU_REQLOG (+ ring/rotation knobs) from the environment."""
    global _enabled
    _enabled = _env_enabled()
    _set_sink(_env_sink())
    _ring_ref[0] = deque(_ring_ref[0], maxlen=_ring_len())


def sink_path() -> "str | None":
    """The active JSONL sink path (None = ring only)."""
    return _sink_path


def _ring_len() -> int:
    try:
        return max(1, int(os.environ.get("PTPU_REQLOG_RING",
                                         str(_DEFAULT_RING))))
    except ValueError:
        return _DEFAULT_RING


def _rotate_bytes() -> int:
    try:
        mb = float(os.environ.get("PTPU_REQLOG_ROTATE_MB",
                                  str(_DEFAULT_ROTATE_MB)))
    except ValueError:
        mb = _DEFAULT_ROTATE_MB
    return max(4096, int(mb * (1 << 20)))


# ring in a one-slot list so refresh() can resize without tearing
# concurrent readers (deque reads/swaps are atomic under the GIL)
_ring_ref = [deque(maxlen=_ring_len())]
_lock = threading.Lock()
_sink_file = None          # lazily-opened file object for _sink_path


def _set_sink(path: "str | None") -> None:
    global _sink_path, _sink_file
    with _lock:
        if path != _sink_path and _sink_file is not None:
            try:
                _sink_file.close()
            except OSError:
                pass
            _sink_file = None
        _sink_path = path


def _replica_id() -> "str | None":
    return os.environ.get("PTPU_REPLICA_ID") or None


def event(rid, trace_id=None, arrival_ts=None, prompt_tokens=0,
          generated_tokens=0, queue_wait_s=None, ttft_s=None,
          tpot_avg_s=None, tpot_max_s=None, prefill_chunks=0,
          prefix_hit_tokens=0, spec_proposed=0, spec_accepted=0,
          preemptions=0, peak_kv_blocks=0, finish_reason="stop",
          tenant=None, priority=None) -> dict:
    """Build one wide event.  THE canonical builder: its keys are pinned
    to ``wire.REQLOG_EVENT_KEYS`` by the wire-compat rule (and by
    tests/test_reqlog.py), so the schema cannot drift silently.
    Unmeasured latencies stay ``None`` (a request aborted before its
    first token has no TTFT), never 0 — consumers must not average
    phantom zeros."""
    # ptpu-wire: reqlog-event
    return {
        "schema_version": REQLOG_SCHEMA_VERSION,
        "rid": rid,
        "trace_id": trace_id,
        "replica_id": _replica_id(),
        "ts": time.time(),
        "arrival_ts": arrival_ts,
        "prompt_tokens": int(prompt_tokens),
        "generated_tokens": int(generated_tokens),
        "queue_wait_s": queue_wait_s,
        "ttft_s": ttft_s,
        "tpot_avg_s": tpot_avg_s,
        "tpot_max_s": tpot_max_s,
        "prefill_chunks": int(prefill_chunks),
        "prefix_hit_tokens": int(prefix_hit_tokens),
        "spec_proposed": int(spec_proposed),
        "spec_accepted": int(spec_accepted),
        "preemptions": int(preemptions),
        "peak_kv_blocks": int(peak_kv_blocks),
        "finish_reason": finish_reason,
        "tenant": tenant,
        "priority": priority,
    }


def emit(ev: dict) -> dict:
    """Append one event to the ring (+ the JSONL sink when configured).
    No-op passthrough when disabled, so callers can emit
    unconditionally; the engine still guards with :func:`enabled` to
    skip even the event build."""
    if not _enabled:
        return ev
    _ring_ref[0].append(ev)
    if _sink_path is not None:
        _write_sink(ev)
    return ev


def _write_sink(ev: dict) -> None:
    """One JSON line, size-rotated.  Sink failures are counted, never
    raised — losing a log line must not abort the request being
    released."""
    global _sink_file
    line = json.dumps(ev, default=str) + "\n"
    with _lock:
        try:
            if _sink_file is None:
                d = os.path.dirname(_sink_path)
                if d:
                    os.makedirs(d, exist_ok=True)
                _sink_file = open(_sink_path, "a")
            _sink_file.write(line)
            _sink_file.flush()
            if _sink_file.tell() >= _rotate_bytes():
                _sink_file.close()
                _sink_file = None
                # one predecessor kept: bounded disk, yesterday's tail
                # still greppable
                os.replace(_sink_path, _sink_path + ".1")
        except OSError as e:
            _sink_file = None
            from . import counter

            counter("reqlog/sink_errors",
                    "reqlog JSONL writes that failed").inc()
            del e


def recent(n: "int | None" = None) -> list:
    """The newest `n` events (default: the whole ring), newest first —
    the ``/requests/recent`` payload."""
    out = list(_ring_ref[0])
    out.reverse()
    return out if n is None else out[:max(0, int(n))]


def reset() -> None:
    """Drop every buffered event and close the sink (tests)."""
    global _sink_file
    with _lock:
        _ring_ref[0].clear()
        if _sink_file is not None:
            try:
                _sink_file.close()
            except OSError:
                pass
            _sink_file = None
