"""Memory microscope — *who holds the memory* (ISSUE 20, monitor v8).

The monitor stack can see programs (perf), requests (reqlog/trace), and
the fleet (fleet), but an admission failure or preemption storm leaves
no forensic record of which requests, tenants, or parked prefix blocks
were squatting on the KV pool, and ``perf/hbm_headroom`` is a
per-program point reading with no history.  This module is the
memory-side instrument plane the ZeRO-sharding and KV-tiering arcs
(ROADMAP items 3/4) will be gated and debugged with.  Four wings:

- **KV block-lifecycle accounting** (:class:`KVAccounting`, owned by
  ``serving.kv_cache.BlockKVCache``): one counter family
  ``serving/kv_blocks{event}`` over every pool transition —
  alloc / free / fork / cow / park / adopt / evict / swap_out /
  swap_in, per block — plus a ``serving/kv_parked_residency_age``
  histogram of how long a parked prefix block stayed adoptable before
  reclaim (the live twin of ``serving/prefix_evictions``: item 4's
  "hot system prompt should survive pressure" invariant needs age
  data, not just an eviction count).  :func:`fragmentation` analyses
  the free list's contiguity (runs vs. contiguous capacity).
- **HBM/host timeline**: a bounded ring of sampled
  ``(monotonic-ts, hbm_peak, hbm_in_use, host_rss)`` readings
  (:func:`sample`) fed from the existing perf capture and the
  ``/healthz`` rss path, mirrored into ``memory/...`` gauges and
  served at ``GET /memory/timeline`` — headroom regressions become a
  trendline instead of a point reading.
- **Pressure forensics**: :class:`StormDetector` (EWMA mean/variance
  over per-step eviction+swap events, the ``LossSpikeDetector`` shape)
  and :class:`PressureReporter`, which writes a replica-tagged
  ``kv_pressure`` flight dump naming ranked holders
  (:func:`rank_holders`: requests by blocks held x age, parked prefix
  chains by residency, tenants by share) — rate-limited so a storm
  produces ONE dump, not thousands.
- **Pool-map publication**: the engine's step loop builds a
  :func:`build_kv_snapshot` document and publishes it here
  (:func:`maybe_publish_kv`, interval-limited); ``GET /kv`` serves the
  published slot so the http handler thread NEVER touches engine
  state or its lock.

Gating: everything is default-off behind ``PTPU_MEMOBS`` (enable at
runtime via :func:`enable`); the per-step hooks live inside the
standing trace_overhead budget (<1% disabled / <5% enabled —
``bench.py --config trace_overhead`` charges the sequence).  Knobs:
``PTPU_MEMOBS_RING`` (timeline ring length, default 512) and
``PTPU_MEMOBS_COOLDOWN_S`` (seconds between kv_pressure dumps,
default 30).  stdlib-only, no jax, like every monitor sibling.
"""
from __future__ import annotations

import math
import os
import threading
import time
from collections import deque

from . import counter as _counter
from . import gauge as _gauge
from . import histogram as _histogram

__all__ = [
    "enabled", "enable", "refresh", "reset",
    "EVENTS", "KVAccounting", "fragmentation", "refcount_histogram",
    "sample", "host_rss_bytes", "timeline_snapshot", "timeline_report",
    "StormDetector", "PressureReporter", "reporter", "rank_holders",
    "build_kv_snapshot", "publish_kv", "maybe_publish_kv", "latest_kv",
    "kv_report",
]

_DEFAULT_RING = 512
_DEFAULT_COOLDOWN_S = 30.0
# /kv pool-map rebuild cadence: the per-step publish check is one
# monotonic read; the O(num_blocks) snapshot build runs at most this
# often (the first call publishes immediately)
KV_PUBLISH_INTERVAL_S = 0.5


def _env_enabled() -> bool:
    return os.environ.get("PTPU_MEMOBS", "").strip().lower() in (
        "1", "true", "on")


_enabled = _env_enabled()


def enabled() -> bool:
    return _enabled


def enable(on: bool = True):
    """Flip the memory microscope on/off at runtime (overrides
    PTPU_MEMOBS)."""
    global _enabled
    _enabled = bool(on)


def refresh():
    """Re-read PTPU_MEMOBS (+ ring knob) from the environment."""
    global _enabled
    _enabled = _env_enabled()
    with _tl_lock:
        _timeline_ref[0] = deque(_timeline_ref[0], maxlen=_ring_len())


# -- (a) KV block-lifecycle accounting ---------------------------------------

# every pool transition, per block.  Events overlap by design — a CoW
# counts one "cow" AND the "alloc" of its fresh block; a swap_in counts
# its blocks under "swap_in" AND "alloc" — each stream answers its own
# question (how much CoW traffic? how fast does the pool cycle?).
EVENTS = ("alloc", "free", "fork", "cow", "park", "adopt", "evict",
          "swap_out", "swap_in")


class KVAccounting:
    """Per-pool lifecycle ledger: plain-int event counts (exact, for
    tests and dumps) twinned with ``serving/kv_blocks{event}`` monitor
    counters.  Every hook checks the module gate first — one global
    read when PTPU_MEMOBS is off."""

    __slots__ = ("events", "_m", "_resid")

    def __init__(self):
        self.events = dict.fromkeys(EVENTS, 0)
        fam = _counter("serving/kv_blocks",
                       "KV pool block transitions, by lifecycle event")
        self._m = {e: fam.labels(event=e) for e in EVENTS}
        self._resid = _histogram(
            "serving/kv_parked_residency_age",
            "seconds a parked prefix block stayed adoptable before "
            "being reclaimed (observed at eviction)")

    def on(self, event: str, n: int = 1) -> None:
        if not _enabled or n <= 0:
            return
        self.events[event] += n
        self._m[event].inc(n)

    def observe_residency(self, age_s: float) -> None:
        if not _enabled:
            return
        self._resid.observe(age_s)


def fragmentation(free_ids, num_blocks: int) -> dict:
    """Free-list contiguity: how many maximal runs of consecutive
    physical ids the free list fragments into, the largest run, and
    ``frag = 1 - largest_run / free`` (0.0 = empty or one contiguous
    extent; toward 1.0 = capacity shredded into single blocks — a
    future contiguous-allocation tier would find no extent even with
    plenty of free blocks)."""
    free = len(free_ids)
    if free == 0:
        return {"free": 0, "total": int(num_blocks), "runs": 0,
                "largest_run": 0, "frag": 0.0}
    ids = sorted(int(i) for i in free_ids)
    runs, largest, run = 1, 1, 1
    for a, b in zip(ids, ids[1:]):
        if b == a + 1:
            run += 1
        else:
            runs += 1
            if run > largest:
                largest = run
            run = 1
    if run > largest:
        largest = run
    return {"free": free, "total": int(num_blocks), "runs": runs,
            "largest_run": largest,
            "frag": round(1.0 - largest / free, 6)}


def refcount_histogram(blocks) -> dict:
    """``{refcount: block count}`` over the pool — how widely shared
    the shared blocks actually are (fork fan-out / prefix adoption)."""
    out: dict = {}
    for blk in blocks:
        r = int(blk.ref)
        out[r] = out.get(r, 0) + 1
    return out


# -- (b) HBM/host timeline ---------------------------------------------------

def _ring_len() -> int:
    try:
        return max(8, int(os.environ.get("PTPU_MEMOBS_RING",
                                         str(_DEFAULT_RING))))
    except ValueError:
        return _DEFAULT_RING


_tl_lock = threading.Lock()
# one-slot list so refresh() can resize without tearing readers (deque
# reads/swaps are atomic under the GIL — the reqlog ring pattern)
_timeline_ref = [deque(maxlen=_ring_len())]

_g_hbm_peak = _gauge("memory/hbm_peak_bytes",
                     "latest sampled peak HBM bytes across compiled "
                     "programs (perf capture)")
_g_hbm_in_use = _gauge("memory/hbm_in_use_bytes",
                       "latest sampled live KV-pool bytes "
                       "(blocks_in_use x bytes_per_block)")
_g_host_rss = _gauge("memory/host_rss_bytes",
                     "latest sampled host resident set size")

# rss reads open /proc per call; a short TTL keeps the per-step sample
# at one monotonic read on the fast path
_RSS_TTL_S = 0.2
_rss_cache = [0.0, None]          # [expires_mono, value]


def host_rss_bytes(ttl_s: float = _RSS_TTL_S):
    """Host RSS via the /healthz path (serve._rss_bytes), cached for
    `ttl_s` so per-step timeline sampling does not open /proc every
    step."""
    now = time.monotonic()
    if now < _rss_cache[0]:
        return _rss_cache[1]
    from .serve import _rss_bytes

    val = _rss_bytes()
    _rss_cache[0] = now + max(0.0, float(ttl_s))
    _rss_cache[1] = val
    return val


def sample(hbm_peak=None, hbm_in_use=None, host_rss=None, ts=None):
    """Append one timeline reading (None fields are recorded as null —
    e.g. hbm_peak with the perf capture off) and mirror the latest
    values into the ``memory/...`` gauges."""
    if not _enabled:
        return
    rec = {"ts": round(time.monotonic() if ts is None else ts, 6),
           "hbm_peak": hbm_peak, "hbm_in_use": hbm_in_use,
           "host_rss": host_rss}
    with _tl_lock:
        _timeline_ref[0].append(rec)
    if hbm_peak is not None:
        _g_hbm_peak.set(hbm_peak)
    if hbm_in_use is not None:
        _g_hbm_in_use.set(hbm_in_use)
    if host_rss is not None:
        _g_host_rss.set(host_rss)


def timeline_snapshot() -> list:
    with _tl_lock:
        return list(_timeline_ref[0])


def timeline_report() -> dict:
    """The ``GET /memory/timeline`` document (ring-only read — safe
    from the http handler thread)."""
    readings = timeline_snapshot()
    return {"enabled": _enabled, "maxlen": _ring_len(),
            "n": len(readings), "readings": readings}


# -- (c) pressure forensics --------------------------------------------------

class StormDetector:
    """EWMA mean/variance detector over per-step pool-pressure events
    (evictions + preemption swaps) — the ``LossSpikeDetector`` shape
    re-aimed at eviction storms and swap thrash.

    A healthy pool evicts occasionally; a storm is a step whose event
    count sits ``sigma`` standard deviations above the EWMA baseline
    (and above ``floor`` — absolute noise guard: the very first
    eviction after a quiet warmup is not a storm).  A flagged step is
    NOT folded into the baseline, and ``cooldown`` observations must
    pass between fires so a sustained storm produces a few markers, not
    one per step."""

    __slots__ = ("alpha", "sigma", "warmup", "cooldown", "floor",
                 "_mean", "_var", "_n", "_step", "_last_fire",
                 "_m_events", "_m_storms")

    def __init__(self, alpha: float = 0.2, sigma: float = 4.0,
                 warmup: int = 8, cooldown: int = 16, floor: float = 2.0):
        self.alpha = float(alpha)
        self.sigma = float(sigma)
        self.warmup = int(warmup)
        self.cooldown = int(cooldown)
        self.floor = float(floor)
        self._mean = 0.0
        self._var = 0.0
        self._n = 0
        self._step = 0
        self._last_fire = None
        self._m_events = _counter(
            "memory/pressure_events",
            "per-step pool-pressure events fed to the storm detector")
        self._m_storms = _counter(
            "memory/eviction_storms",
            "eviction/swap storms flagged by the EWMA detector")

    def observe(self, events: float, step: int = None) -> "dict | None":
        """Feed one step's pressure-event count; returns a storm-info
        dict when the step fires (and drops a flight breadcrumb), else
        None."""
        try:
            events = float(events)
        except (TypeError, ValueError):
            return None
        if step is None:
            step = self._step
        self._step = step + 1
        if events:
            self._m_events.inc(events)
        storm = None
        if self._n >= self.warmup and events >= self.floor:
            sd = math.sqrt(self._var) if self._var > 0 else 0.0
            if events > self._mean + self.sigma * sd:
                storm = {"kind": "eviction_storm", "events": events,
                         "step": step, "ewma": round(self._mean, 4)}
        if storm is not None:
            if self._last_fire is not None and self.cooldown > 0 and \
                    (step - self._last_fire) < self.cooldown:
                return None   # still inside the cooldown window
            self._last_fire = step
            self._m_storms.inc()
            from . import flight

            flight.note("memory/eviction_storm", **storm)
            return storm
        # only a NON-storm step feeds the baseline (a sustained storm
        # must not drag its own baseline up until it disappears)
        self._n += 1
        a = self.alpha if self._n > 1 else 1.0
        delta = events - self._mean
        self._mean += a * delta
        self._var = (1.0 - a) * (self._var + a * delta * delta)
        return None


def _cooldown_s() -> float:
    try:
        return max(0.0, float(os.environ.get(
            "PTPU_MEMOBS_COOLDOWN_S", str(_DEFAULT_COOLDOWN_S))))
    except ValueError:
        return _DEFAULT_COOLDOWN_S


class PressureReporter:
    """Rate-limited ``kv_pressure`` flight dumps.  An admission-failure
    loop or an eviction storm triggers per step; the reporter lets ONE
    dump through per ``cooldown_s`` window (suppressions are counted,
    and consume nothing).  Dumps ride :func:`flight.maybe_dump` — no
    PTPU_FLIGHT_DIR, no file — and are replica-tagged with the
    process's fleet identity."""

    __slots__ = ("cooldown_s", "triggers", "_last_fire", "_m_dumps",
                 "_m_supp")

    def __init__(self, cooldown_s: float = None):
        self.cooldown_s = (_cooldown_s() if cooldown_s is None
                           else float(cooldown_s))
        self.triggers = 0
        self._last_fire = None
        self._m_dumps = _counter(
            "memory/pressure_dumps",
            "kv_pressure flight dumps written (rate-limited)")
        self._m_supp = _counter(
            "memory/pressure_suppressed",
            "kv_pressure triggers suppressed by the dump rate limit")

    def maybe_dump(self, trigger: str, extra: dict = None,
                   now: float = None) -> "str | None":
        """One rate-limited dump attempt; returns the dump path, or
        None (rate-limited, or PTPU_FLIGHT_DIR unset)."""
        from . import flight
        from .serve import identity

        now = time.monotonic() if now is None else now
        self.triggers += 1
        if self._last_fire is not None and \
                now - self._last_fire < self.cooldown_s:
            self._m_supp.inc()
            return None
        self._last_fire = now
        doc = {"trigger": trigger, "replica": identity()}
        if extra:
            doc.update(extra)
        path = flight.maybe_dump("kv_pressure", extra=doc)
        if path:
            self._m_dumps.inc()
        return path


_reporter_ref = [None]


def reporter() -> PressureReporter:
    """The process-wide rate limiter (one cooldown window per process —
    a storm must produce one dump no matter how many triggers see it)."""
    if _reporter_ref[0] is None:
        _reporter_ref[0] = PressureReporter()
    return _reporter_ref[0]


def rank_holders(cache, requests, now: float = None, top: int = 8) -> dict:
    """Ranked memory holders for a ``kv_pressure`` dump / the ``/kv``
    pool map:

    - ``requests``: by ``blocks held x (1 + age_s)`` — the long-held
      large holding outranks both the fresh large and the old small;
    - ``parked_chains``: parked prefix chains (grouped by the chain id
      ``register_prefix`` stamps) by oldest residency;
    - ``tenants``: blocks held per tenant with pool share.

    Reads only host-side dicts (no device sync); call from the engine
    thread."""
    now_pc = time.perf_counter() if now is None else now
    mono = time.monotonic()
    reqs = []
    tenants: dict = {}
    for r in requests:
        table = cache._tables.get(r.req_id)
        if not table:
            continue
        blocks = len(table)
        arr = getattr(r, "arrival_t", None)
        age = max(0.0, now_pc - arr) if arr is not None else 0.0
        tenant = getattr(getattr(r, "params", None), "tenant", None)
        reqs.append({
            "rid": r.req_id,
            "blocks": blocks,
            "age_s": round(age, 3),
            "score": round(blocks * (1.0 + age), 3),
            "tenant": tenant,
            "priority": getattr(getattr(r, "params", None), "priority",
                                None),
        })
        key = tenant or "default"
        tenants[key] = tenants.get(key, 0) + blocks
    reqs.sort(key=lambda d: (-d["score"], -d["blocks"], d["rid"]))
    chains: dict = {}
    for idx, parked_ts in getattr(cache, "_lru", {}).items():
        chain = getattr(cache, "_chain_of", {}).get(idx, "?")
        age = max(0.0, mono - parked_ts) if parked_ts else 0.0
        rec = chains.setdefault(chain, {"chain": chain, "blocks": 0,
                                        "oldest_age_s": 0.0})
        rec["blocks"] += 1
        if age > rec["oldest_age_s"]:
            rec["oldest_age_s"] = round(age, 3)
    parked = sorted(chains.values(),
                    key=lambda d: (-d["oldest_age_s"], -d["blocks"]))
    total = max(getattr(cache, "num_blocks", 0), 1)
    tenant_rows = sorted(
        ({"tenant": t, "blocks": n, "share": round(n / total, 4)}
         for t, n in tenants.items()),
        key=lambda d: (-d["blocks"], d["tenant"]))
    return {"requests": reqs[:top], "parked_chains": parked[:top],
            "tenants": tenant_rows}


# -- (d)/(a) pool-map publication (GET /kv) ----------------------------------

_kv_ref = [None]
_kv_pub_t = [None]


def build_kv_snapshot(cache, requests, now: float = None) -> dict:
    """The structured ``/kv`` pool map: counts, fragmentation, ranked
    holders, parked chains by age, and the refcount histogram.  Built
    on the ENGINE thread and published via :func:`publish_kv` — the
    http handler only ever reads the published document."""
    c = cache.counts()
    doc = {
        "ts": round(time.monotonic(), 6),
        "num_blocks": c["total"],
        "block_size": cache.block_size,
        "bytes_per_block": cache.bytes_per_block,
        "free": c["free"],
        "parked": c["parked"],
        "in_use": c["in_use"],
        "referenced": c["referenced"],
        "allocatable": c["allocatable"],
        "peak_in_use": c["peak_in_use"],
        "utilization": round(c["in_use"] / max(c["total"], 1), 6),
        "fragmentation": fragmentation(cache._free, c["total"]),
        "refcounts": {str(k): v for k, v in sorted(
            refcount_histogram(cache._blocks).items())},
        "events": dict(cache.acct.events),
    }
    doc.update(rank_holders(cache, requests, now=now))
    return doc


def publish_kv(snap: dict) -> None:
    _kv_ref[0] = snap
    _kv_pub_t[0] = time.monotonic()


def maybe_publish_kv(build, now: float = None) -> bool:
    """Interval-limited publication: calls ``build()`` (and publishes
    the result) at most every ``KV_PUBLISH_INTERVAL_S``; the fast path
    is one monotonic read.  First call publishes immediately."""
    if not _enabled:
        return False
    now = time.monotonic() if now is None else now
    t = _kv_pub_t[0]
    if t is not None and now - t < KV_PUBLISH_INTERVAL_S:
        return False
    _kv_ref[0] = build()
    _kv_pub_t[0] = now
    return True


def latest_kv() -> "dict | None":
    return _kv_ref[0]


def kv_report() -> dict:
    """The ``GET /kv`` document (published-slot read only — safe from
    the http handler thread; never touches engine state)."""
    return {"enabled": _enabled, "snapshot": _kv_ref[0]}


def reset() -> None:
    """Test hook: clear the timeline ring, published pool map, and the
    process-wide pressure reporter (counters live in the monitor
    registry and reset with it)."""
    with _tl_lock:
        _timeline_ref[0] = deque(maxlen=_ring_len())
    _kv_ref[0] = None
    _kv_pub_t[0] = None
    _reporter_ref[0] = None
    _rss_cache[0] = 0.0
    _rss_cache[1] = None
