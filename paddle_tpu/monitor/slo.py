"""SLO burn-rate engine — *are we burning our latency budget right now*
(ISSUE 16, the signal ROADMAP item 5's admission shedding reads).

Objectives are declared in ``PTPU_SLO`` as a ``;``-separated list:

    PTPU_SLO="ttft_p95<0.5;tpot_p99<0.05;error_rate<0.01"

Two objective forms:

- ``<hist>_p<q><threshold>`` — a latency objective over an existing
  serving histogram (``ttft``/``tpot``/``queue_wait`` →
  ``serving/<hist>``): at most ``100-q`` percent of requests may exceed
  ``threshold`` seconds.  Evaluated from the histogram's cumulative
  bucket counts (observations in the bucket containing the threshold
  count as good — the conservative read, no samples stored);
- ``error_rate<frac`` — at most ``frac`` of finished requests may end
  abnormally.  Numerator/denominator come from the
  ``serving/finish_reason{reason}`` counters; every reason other than
  ``"stop"`` or ``"migrated"`` (abort/deadline/released) counts as an
  error — a request handed to another replica (drain requeue, failover
  resubmission, prefill→decode disaggregation; ISSUE 17) finishes
  elsewhere, and counting the successful migration as a failure would
  page on every scale-down.

Evaluation is SRE-style multi-window multi-burn-rate: each objective's
*bad fraction* over a fast and a slow trailing window
(``PTPU_SLO_WINDOWS``, default ``60,600`` seconds) is divided by its
error budget — burn rate 1.0 means burning exactly at budget, 14.4 is
the classic page-now threshold.  Cumulative metric state is sampled
into a bounded ring on each tick, so windowed deltas need no
per-request bookkeeping.  Exported as ``slo/burn_rate{objective,
window}`` and ``slo/budget_remaining{objective}`` gauges (scraped and
fleet-merged like every other metric; ``FleetAggregator.snapshot()``
additionally rolls the worst burn into the router feed), and served
structured at ``GET /slo``.

Default off; the per-step cost with ``PTPU_SLO`` unset is the one
module-global read in :func:`maybe_tick` (gated by bench.py --config
trace_overhead).  Enabled, a tick is rate-limited to once per
``min_interval`` (1 s) — a bisect over bucket bounds per objective,
off the request hot path.  stdlib-only, no jax, like every sibling.
"""
from __future__ import annotations

import bisect
import os
import re
import threading
import time
from collections import deque

__all__ = [
    "Objective", "SloEngine", "parse_spec", "enabled", "enable",
    "refresh", "get_engine", "install", "maybe_tick", "report",
    "violates",
]

_LAT_RE = re.compile(r"^([a-z_]+)_p(\d{1,2}(?:\.\d+)?)$")

# the serving histograms a latency objective may target (the metric
# name is assembled from this table only, keeping metric-hygiene's
# literal-name rule meaningful)
_HIST_NAMES = {
    "ttft": "serving/ttft",
    "tpot": "serving/tpot",
    "queue_wait": "serving/queue_wait",
}
_FINISH_NAME = "serving/finish_reason"
# reasons that are NOT errors: a natural finish; a request migrated to
# another replica (it finishes — and is judged — over there); a
# best-effort request deliberately shed by SLO-aware admission control
# (ISSUE 19 — shedding is the SLO engine working, counting it as an
# error would double-charge the budget that triggered it); and an
# HTTP-level client rejection (auth/parse 4xx that never reached the
# scheduler — the client's fault, not the server's)
_GOOD_REASONS = ("stop", "migrated", "shed", "rejected")


def _env_spec() -> str:
    return os.environ.get("PTPU_SLO", "").strip()


def _env_windows() -> "tuple[float, float]":
    raw = os.environ.get("PTPU_SLO_WINDOWS", "60,600")
    try:
        parts = [float(p) for p in raw.split(",")]
        fast, slow = parts[0], parts[1]
        if fast <= 0 or slow <= fast:
            raise ValueError(raw)
        return fast, slow
    except (ValueError, IndexError):
        return 60.0, 600.0


_enabled = bool(_env_spec())


def enabled() -> bool:
    return _enabled


def enable(on: bool = True):
    """Flip evaluation on/off at runtime (overrides PTPU_SLO; turning
    on without a spec ever parsed leaves ticks as no-ops)."""
    global _enabled
    with _engine_lock:
        _enabled = bool(on)


class Objective:
    """One parsed objective: what fraction of requests may be bad, and
    how to count bad/total from cumulative metric state."""

    __slots__ = ("spec", "kind", "hist_name", "quantile", "threshold",
                 "budget")

    def __init__(self, spec: str):
        spec = spec.strip()
        if "<" not in spec:
            raise ValueError(
                f"SLO objective {spec!r}: expected '<metric><target'")
        lhs, _, rhs = spec.partition("<")
        lhs = lhs.strip()
        try:
            target = float(rhs)
        except ValueError:
            raise ValueError(
                f"SLO objective {spec!r}: target {rhs!r} is not a number")
        self.spec = f"{lhs}<{rhs.strip()}"
        if lhs == "error_rate":
            if not 0.0 < target < 1.0:
                raise ValueError(
                    f"SLO objective {spec!r}: error-rate budget must be "
                    "in (0, 1)")
            self.kind = "error_rate"
            self.hist_name = None
            self.quantile = None
            self.threshold = None
            self.budget = target
            return
        m = _LAT_RE.match(lhs)
        if not m or m.group(1) not in _HIST_NAMES:
            raise ValueError(
                f"SLO objective {spec!r}: unknown metric {lhs!r} "
                f"(know {sorted(_HIST_NAMES)} percentiles and "
                "error_rate)")
        q = float(m.group(2))
        if not 0.0 < q < 100.0:
            raise ValueError(
                f"SLO objective {spec!r}: quantile must be in (0, 100)")
        if target <= 0:
            raise ValueError(
                f"SLO objective {spec!r}: latency threshold must be > 0")
        self.kind = "latency"
        self.hist_name = _HIST_NAMES[m.group(1)]
        self.quantile = q
        self.threshold = target
        self.budget = 1.0 - q / 100.0

    def totals(self, registry) -> "tuple[float, float]":
        """Cumulative (bad, total) request counts from the registry —
        monotonic, so windowed deltas are safe."""
        if self.kind == "error_rate":
            c = registry.get(_FINISH_NAME)
            if c is None:
                return 0.0, 0.0
            bad = total = 0.0
            for key, series in c._series():
                v = series._snapshot_value()
                total += v
                if dict(key).get("reason") not in _GOOD_REASONS:
                    bad += v
            return bad, total
        h = registry.get(self.hist_name)
        if h is None or h.kind != "histogram":
            return 0.0, 0.0
        buckets, counts, count, _ = h._bucket_rows()[:4]
        j = bisect.bisect_left(buckets, self.threshold)
        good = sum(counts[:j + 1]) if j < len(buckets) else count
        return float(count - good), float(count)

    def __repr__(self):
        return f"Objective({self.spec})"


def parse_spec(spec: str) -> "list[Objective]":
    """Parse a ``;``-separated PTPU_SLO string (empty parts skipped)."""
    return [Objective(part) for part in spec.split(";") if part.strip()]


class SloEngine:
    """Window accounting + gauge export for a set of objectives.

    ``registry`` defaults to the process StatRegistry; tests hand in a
    synthetic one.  Time is injectable everywhere (``now=``, monotonic
    seconds) so window math is deterministic under test."""

    def __init__(self, objectives, registry=None,
                 windows: "tuple[float, float]" = None,
                 min_interval: float = 1.0):
        if isinstance(objectives, str):
            objectives = parse_spec(objectives)
        self.objectives = list(objectives)
        if registry is None:
            from . import get_registry

            registry = get_registry()
        self.registry = registry
        self.windows = tuple(windows or _env_windows())
        self.min_interval = float(min_interval)
        self._lock = threading.Lock()
        # ring of (t, ((bad, total) per objective)); pruned past the
        # slow window so memory stays bounded at slow/min_interval
        self._samples: deque = deque()
        self._last_tick = None
        self._last_report = None
        # cached gauge handles, one per (objective, window) series
        g_burn = registry.gauge(
            "slo/burn_rate",
            "windowed bad-fraction / error-budget per objective "
            "(1.0 = burning exactly at budget)")
        g_rem = registry.gauge(
            "slo/budget_remaining",
            "fraction of the lifetime error budget left per objective")
        self._g_burn = {
            (o.spec, w): g_burn.labels(objective=o.spec, window=w)
            for o in self.objectives for w in ("fast", "slow")}
        self._g_rem = {o.spec: g_rem.labels(objective=o.spec)
                       for o in self.objectives}

    # -- evaluation ---------------------------------------------------------

    def tick(self, now: "float | None" = None) -> "dict | None":
        """Rate-limited evaluate: cheap enough to call every engine
        step.  Returns the report when it ran, None when skipped."""
        if now is None:
            now = time.monotonic()
        with self._lock:
            if self._last_tick is not None \
                    and now - self._last_tick < self.min_interval:
                return None
        return self.evaluate(now)

    def evaluate(self, now: "float | None" = None) -> dict:
        """Sample cumulative state, compute per-window burn rates,
        write the gauges, and return the /slo report document."""
        if now is None:
            now = time.monotonic()
        totals = tuple(o.totals(self.registry) for o in self.objectives)
        fast_w, slow_w = self.windows
        with self._lock:
            self._last_tick = now
            self._samples.append((now, totals))
            while self._samples and self._samples[0][0] < now - slow_w:
                # keep ONE sample at/past the slow horizon so the slow
                # window always has a full-width baseline
                if len(self._samples) > 1 \
                        and self._samples[1][0] <= now - slow_w:
                    self._samples.popleft()
                else:
                    break
            samples = list(self._samples)
        objs = []
        for i, o in enumerate(self.objectives):
            bad_now, total_now = totals[i]
            burns = {}
            for wname, wsecs in (("fast", fast_w), ("slow", slow_w)):
                base = samples[0]
                for s in samples:
                    if s[0] <= now - wsecs:
                        base = s
                    else:
                        break
                d_bad = bad_now - base[1][i][0]
                d_total = total_now - base[1][i][1]
                frac = (d_bad / d_total) if d_total > 0 else 0.0
                burns[wname] = frac / o.budget
                self._g_burn[(o.spec, wname)].set(burns[wname])
            life_frac = (bad_now / total_now) if total_now > 0 else 0.0
            remaining = min(1.0, max(0.0, 1.0 - life_frac / o.budget))
            self._g_rem[o.spec].set(remaining)
            objs.append({
                "objective": o.spec,
                "kind": o.kind,
                "threshold": o.threshold,
                "budget": o.budget,
                "burn_rate": burns,
                "budget_remaining": remaining,
                "bad": bad_now,
                "total": total_now,
            })
        rep = {
            "enabled": True,
            "windows": {"fast": fast_w, "slow": slow_w},
            "objectives": objs,
        }
        with self._lock:
            self._last_report = rep
        return rep

    def report(self) -> dict:
        """The newest evaluation (evaluating now if none yet) — the
        ``/slo`` endpoint body."""
        with self._lock:
            rep = self._last_report
        return rep if rep is not None else self.evaluate()

    def violates(self, ttft_s=None, tpot_avg_s=None,
                 queue_wait_s=None) -> bool:
        """Does a single request's latency profile exceed any latency
        objective's threshold?  The per-request hook tail sampling and
        the engine's trace keep-marking use — static thresholds only,
        no window math."""
        probe = {"serving/ttft": ttft_s, "serving/tpot": tpot_avg_s,
                 "serving/queue_wait": queue_wait_s}
        for o in self.objectives:
            if o.kind != "latency":
                continue
            v = probe.get(o.hist_name)
            if v is not None and v > o.threshold:
                return True
        return False


# -- process-wide singleton --------------------------------------------------
_engine = None
_engine_lock = threading.Lock()


def get_engine() -> "SloEngine | None":
    """The PTPU_SLO-configured engine (built lazily; None when the spec
    is unset/empty/unparseable — a bad spec warns once rather than
    killing the serving process that merely wanted SLOs)."""
    global _engine, _enabled
    if _engine is not None:
        return _engine
    spec = _env_spec()
    if not spec:
        return None
    with _engine_lock:
        if _engine is None:
            try:
                objectives = parse_spec(spec)
            except ValueError as e:
                import warnings

                warnings.warn(f"PTPU_SLO ignored: {e}")
                _enabled = False
                return None
            if not objectives:
                _enabled = False
                return None
            _engine = SloEngine(objectives)
    return _engine


def install(engine: "SloEngine | None") -> None:
    """Pin the process engine explicitly (tests; None uninstalls)."""
    global _engine, _enabled
    with _engine_lock:
        _engine = engine
        _enabled = engine is not None


def refresh() -> None:
    """Re-read PTPU_SLO/PTPU_SLO_WINDOWS (drops the built engine)."""
    global _engine, _enabled
    with _engine_lock:
        _engine = None
        _enabled = bool(_env_spec())


def maybe_tick(now: "float | None" = None) -> None:
    """The engine-step hook: one module-global read when disabled."""
    if not _enabled:
        return
    eng = get_engine()
    if eng is not None:
        eng.tick(now)


def report() -> dict:
    """The ``/slo`` document (``{"enabled": False}`` when off)."""
    if not _enabled:
        return {"enabled": False, "objectives": []}
    eng = get_engine()
    if eng is None:
        return {"enabled": False, "objectives": []}
    return eng.report()


def violates(ttft_s=None, tpot_avg_s=None, queue_wait_s=None) -> bool:
    """Module-level :meth:`SloEngine.violates` against the configured
    engine (False when disabled)."""
    if not _enabled:
        return False
    eng = get_engine()
    return False if eng is None else eng.violates(
        ttft_s=ttft_s, tpot_avg_s=tpot_avg_s, queue_wait_s=queue_wait_s)
