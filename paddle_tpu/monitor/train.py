"""Training microscope — the training-side twin of the serving
observability stack (ISSUE 13).  Monitor v2–v5 made *serving* richly
observable; training ran on v1-level instruments (one global grad-norm
gauge, byte-only collective counters, a StepGuard that detects a NaN
step without naming where it came from).  This module is the stdlib
half of the v6 training wings:

- **loss-spike forensics** (:class:`LossSpikeDetector`) — an EWMA
  mean/variance detector over the per-step loss that drops a
  pre-divergence warning into the flight ring *before* the NaN lands
  (``train/loss_spikes``, ``flight.note("train/loss_spike")``); the
  device-side half (the per-layer non-finite scan a bad step triggers)
  lives in ``resilience.forensics`` — jax stays out of this module;
- **per-layer training telemetry** (:func:`observe_layer_stats` /
  :func:`report`) — the gauge store + ranked table behind the
  optimizer's sampled fused per-layer grad/param/update reduction
  (``PTPU_TRAIN_STATS=1``, every ``PTPU_TRAIN_STATS_EVERY`` steps);
- **input-pipeline goodput** (:class:`GoodputMeter`) — the training
  twin of ``serving/goodput_tokens_per_s``: examples/s against the
  TOTAL loop wall and the fraction of it spent blocked on the reader,
  wired into the hapi fit loop;
- the per-rank ``train/step_time`` gauge the fleet straggler rollup
  (``monitor.fleet.StragglerRollup``) reads off ``/metrics``.

Gate: ``PTPU_TRAIN_STATS=1`` (default OFF) turns on the *sampling*
diagnostic — the per-layer fused reduction, one extra device sync per
sampled step.  The always-cheap paths (loss-spike EWMA, goodput
accounting, and the ``collective/time`` walls at the already-blocking
barrier/wait boundaries) ride the ordinary ``PTPU_MONITOR`` gate like
the rest of the hot-path metrics and stay inside the trace_overhead
bench budget (<1% disabled / <5% enabled of a train step).

Import constraints (shared with trace/flight/serve/perf/fleet/hlo):
pure stdlib — device reductions happen at the call sites (optimizer,
StepGuard), which already hold jax; this module only stores/ranks.

Exported metrics (all literal, metric-hygiene-clean):
``train/loss`` (gauge, last healthy loss), ``train/loss_ewma``
(gauge), ``train/loss_spikes`` (counter), ``train/grad_norm{layer}`` /
``train/param_norm{layer}`` / ``train/update_ratio{layer}`` (sampled
gauges), ``train/stats_step`` (gauge), ``train/step_time`` (gauge,
seconds), ``train/goodput_examples_per_s`` (gauge),
``train/data_wait_frac`` (gauge), ``train/examples`` (counter).
Companion series recorded at their own sites: ``reader/wait_time``
(io.DataLoader), ``collective/time{kind}`` (barrier/wait),
``resilience/nonfinite{layer,which}`` (StepGuard),
``fleet/straggler_skew`` / ``fleet/straggler{replica}`` (aggregator).
"""
from __future__ import annotations

import math
import os
import threading
from collections import deque

__all__ = [
    "enabled", "enable", "refresh", "sample_every", "LossSpikeDetector",
    "GoodputMeter", "observe_layer_stats", "layer_stats", "report",
    "reset",
]


def _env_enabled() -> bool:
    return os.environ.get("PTPU_TRAIN_STATS", "0").strip().lower() not in (
        "0", "false", "off", "")


# Module-level flag like monitor/trace/perf: the disabled fast path in
# the optimizer's update loop is one global read + branch.
_enabled = _env_enabled()


def enabled() -> bool:
    return _enabled


def enable(on: bool = True):
    """Flip the sampled training diagnostics on/off at runtime
    (overrides PTPU_TRAIN_STATS)."""
    global _enabled
    _enabled = bool(on)


def refresh():
    """Re-read PTPU_TRAIN_STATS from the environment."""
    global _enabled
    _enabled = _env_enabled()


def sample_every() -> int:
    """Stride of the per-layer sampled reduction (PTPU_TRAIN_STATS_EVERY,
    default 10; 1 = every step)."""
    try:
        return max(1, int(os.environ.get("PTPU_TRAIN_STATS_EVERY", "10")))
    except ValueError:
        return 10


def _registry():
    from . import get_registry

    return get_registry()


# ---------------------------------------------------------------------------
# Loss-spike detector (the pre-divergence warning)
# ---------------------------------------------------------------------------

class LossSpikeDetector:
    """EWMA mean/variance spike detector over the per-step loss.

    Divergence almost never starts at the NaN: the loss climbs for a
    handful of steps first.  This detector keeps an exponentially
    weighted mean and variance of the loss and, once warmed up, flags a
    step whose loss sits more than ``sigma`` standard deviations above
    the mean — dropping a ``train/loss_spike`` breadcrumb into the
    flight ring so the post-mortem a later NaN triggers already carries
    the pre-divergence trajectory.

    Robustness choices: a flagged loss is NOT folded into the EWMA (a
    diverging run must not drag its own baseline up until the spike
    disappears), a non-finite loss fires immediately regardless of
    warmup, and ``cooldown`` steps must pass between breadcrumbs so a
    sustained climb writes a few markers, not one per step.

    Host cost per observe: a handful of float ops + two gauge writes —
    callers gate on ``monitor.enabled()`` (one global read when off).
    """

    __slots__ = ("alpha", "sigma", "warmup", "cooldown", "_mean", "_var",
                 "_n", "_last_fire", "_m_loss", "_m_ewma", "_m_spikes")

    def __init__(self, alpha: float = 0.05, sigma: float = 6.0,
                 warmup: int = 20, cooldown: int = 10):
        self.alpha = float(alpha)
        self.sigma = float(sigma)
        self.warmup = int(warmup)
        self.cooldown = int(cooldown)
        self._mean = 0.0
        self._var = 0.0
        self._n = 0
        self._last_fire = None
        reg = _registry()
        self._m_loss = reg.gauge("train/loss",
                                 "last observed (healthy) step loss")
        self._m_ewma = reg.gauge("train/loss_ewma",
                                 "EWMA of the step loss (spike baseline)")
        self._m_spikes = reg.counter(
            "train/loss_spikes",
            "pre-divergence loss-spike warnings (EWMA detector)")

    def observe(self, loss: float, step: int = None) -> "dict | None":
        """Feed one step's loss; returns a spike-info dict when the step
        fires (and drops the flight-ring breadcrumb), else None."""
        try:
            loss = float(loss)
        except (TypeError, ValueError):
            return None
        spike = None
        if not math.isfinite(loss):
            spike = {"kind": "nonfinite", "loss": loss, "step": step,
                     "ewma": self._mean}
        elif self._n >= self.warmup:
            sd = math.sqrt(self._var) if self._var > 0 else 0.0
            if sd > 0 and loss > self._mean + self.sigma * sd:
                spike = {"kind": "spike", "loss": loss, "step": step,
                         "ewma": self._mean, "sigma": (loss - self._mean)
                         / sd}
        if spike is not None:
            if self._last_fire is not None and step is not None and \
                    self.cooldown > 0 and \
                    (step - self._last_fire) < self.cooldown:
                return None   # still inside the cooldown window
            self._last_fire = step
            self._m_spikes.inc()
            from . import flight

            flight.note("train/loss_spike", **{k: v for k, v in
                                               spike.items()
                                               if v is not None})
            return spike
        # only a NON-spike loss feeds the baseline (see class docstring)
        self._n += 1
        a = self.alpha if self._n > 1 else 1.0
        delta = loss - self._mean
        self._mean += a * delta
        self._var = (1.0 - a) * (self._var + a * delta * delta)
        self._m_loss.set(loss)
        self._m_ewma.set(self._mean)
        return None


# ---------------------------------------------------------------------------
# Per-layer telemetry store (the optimizer's sampled reduction lands here)
# ---------------------------------------------------------------------------

# latest sampled table: [(layer, grad_norm, param_norm, update_ratio)]
_layer_rows: list = []
_layer_step = None
_layer_lock = threading.Lock()


def observe_layer_stats(rows, step=None):
    """Record one sampled per-layer stats table.

    ``rows``: iterable of ``(layer, grad_norm, param_norm,
    update_norm)`` floats (the optimizer computes all three in one
    fused device reduction and transfers ONCE).  The update *ratio* —
    ||delta|| / ||param||, the "is the step size sane per layer" number
    — is derived here; gauges are exported per layer and the table is
    kept for :func:`report` / ``Profiler.summary()``."""
    reg = _registry()
    g_g = reg.gauge("train/grad_norm",
                    "per-layer gradient L2 norm (sampled)")
    g_p = reg.gauge("train/param_norm",
                    "per-layer parameter L2 norm (sampled)")
    g_u = reg.gauge("train/update_ratio",
                    "per-layer ||update|| / ||param|| (sampled)")
    table = []
    for layer, gn, pn, un in rows:
        gn, pn, un = float(gn), float(pn), float(un)
        ratio = un / pn if pn > 0 else 0.0
        table.append((str(layer), gn, pn, ratio))
        g_g.labels(layer=layer).set(gn)
        g_p.labels(layer=layer).set(pn)
        g_u.labels(layer=layer).set(ratio)
    global _layer_rows, _layer_step
    with _layer_lock:
        _layer_rows = table
        _layer_step = step
    if step is not None:
        reg.gauge("train/stats_step",
                  "step of the last sampled per-layer table").set(step)


def layer_stats() -> "tuple[list, int | None]":
    """(rows, step) of the latest sampled per-layer table; rows are
    ``(layer, grad_norm, param_norm, update_ratio)``."""
    with _layer_lock:
        return list(_layer_rows), _layer_step


def report(top: int = 30) -> str:
    """Ranked per-layer training table (merged into
    ``Profiler.summary()`` next to the PR-6 perf attribution): layers
    by gradient norm, each with param norm and update ratio — the rows
    that answer "which layer is about to diverge" and "which layer's
    update is out of scale"."""
    rows, step = layer_stats()
    if not rows:
        return ""
    rows = sorted(rows, key=lambda r: -r[1])
    head = "train layer stats" + (f" @ step {step}" if step is not None
                                  else "")
    lines = [head,
             f"  {'layer':36s} {'grad_norm':>12s} {'param_norm':>12s} "
             f"{'upd_ratio':>10s}"]
    for layer, gn, pn, ratio in rows[:top]:
        lines.append(f"  {layer[:36]:36s} {gn:12.4g} {pn:12.4g} "
                     f"{ratio:10.3g}")
    if len(rows) > top:
        lines.append(f"  ... {len(rows) - top} more layers")
    return "\n".join(lines)


def reset():
    """Drop the sampled table (tests)."""
    global _layer_rows, _layer_step
    with _layer_lock:
        _layer_rows = []
        _layer_step = None


# ---------------------------------------------------------------------------
# Input-pipeline goodput (the hapi fit loop's reader boundary)
# ---------------------------------------------------------------------------

class GoodputMeter:
    """Examples/s against the TOTAL training loop wall, and the fraction
    of it spent blocked on the reader — the training twin of
    ``serving/goodput_tokens_per_s``.

    The fit loop calls :meth:`wait` with the seconds it blocked in
    ``next(loader)`` and :meth:`step` with the step's wall + example
    count; both keep O(1) running sums over a sliding ``window`` of
    steps, so per-step cost is a deque append + four gauge writes
    (cached handles — no registry lookups in the loop).

    ``train/step_time`` is set to the window-mean step seconds: the
    per-rank signal ``fleet.StragglerRollup`` ratios across replicas
    (a mean over the window, not the last step, so one GC pause doesn't
    nominate a straggler)."""

    __slots__ = ("window", "_ring", "_wait_s", "_step_s", "_examples",
                 "_pending_wait", "_m_good", "_m_frac", "_m_step",
                 "_m_examples")

    def __init__(self, window: int = 50):
        self.window = max(1, int(window))
        self._ring = deque()
        self._wait_s = 0.0
        self._step_s = 0.0
        self._examples = 0.0
        self._pending_wait = 0.0
        reg = _registry()
        self._m_good = reg.gauge(
            "train/goodput_examples_per_s",
            "examples/s over the total loop wall (incl. reader waits)")
        self._m_frac = reg.gauge(
            "train/data_wait_frac",
            "fraction of loop wall spent blocked on the reader")
        self._m_step = reg.gauge(
            "train/step_time",
            "train step seconds (window mean) — the straggler signal")
        self._m_examples = reg.counter(
            "train/examples", "training examples consumed")

    def wait(self, dt: float):
        """Seconds the loop just spent blocked on the reader (may be
        called more than once per step; accumulates)."""
        self._pending_wait += float(dt)

    def step(self, dt: float, examples: int = 0):
        """One completed train step of `dt` seconds over `examples`."""
        dt = float(dt)
        w = self._pending_wait
        self._pending_wait = 0.0
        self._ring.append((w, dt, float(examples)))
        self._wait_s += w
        self._step_s += dt
        self._examples += examples
        if len(self._ring) > self.window:
            ow, od, oe = self._ring.popleft()
            self._wait_s -= ow
            self._step_s -= od
            self._examples -= oe
        total = self._wait_s + self._step_s
        if total > 0:
            self._m_good.set(self._examples / total)
            self._m_frac.set(self._wait_s / total)
        self._m_step.set(self._step_s / len(self._ring))
        if examples:
            self._m_examples.inc(examples)

    @property
    def data_wait_frac(self) -> float:
        total = self._wait_s + self._step_s
        return self._wait_s / total if total > 0 else 0.0

    @property
    def goodput(self) -> float:
        total = self._wait_s + self._step_s
        return self._examples / total if total > 0 else 0.0
