"""Automatic mixed precision (reference: python/paddle/amp/ —
auto_cast O1 white/black lists, GradScaler dynamic loss scaling).

TPU-native stance: bf16 is the blessed dtype — wide exponent means GradScaler
is a no-op by default (`enable=False` semantics preserved for fp16 parity);
auto_cast('bfloat16') casts op inputs at the dispatch layer via a thread-local
autocast state consulted by nn.functional's heavy ops.
"""
from __future__ import annotations

import threading
from contextlib import contextmanager

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dtype import convert_dtype
from ..autograd import tape

__all__ = ["auto_cast", "amp_guard", "GradScaler", "decorate", "is_auto_cast_enabled", "get_amp_dtype"]

# O1 lists mirrored from the reference (python/paddle/amp/auto_cast.py:28-92)
WHITE_LIST = {"matmul", "linear", "conv2d", "conv1d", "conv3d", "einsum", "flash_attention", "mm", "bmm"}
BLACK_LIST = {
    "exp", "square", "log", "mean", "sum", "cos_sim", "softmax",
    "softmax_with_cross_entropy", "sigmoid_cross_entropy_with_logits",
    "cross_entropy", "layer_norm", "batch_norm", "group_norm",
}


class _AmpState(threading.local):
    def __init__(self):
        self.enabled = False
        self.dtype = jnp.bfloat16
        self.level = "O1"


_state = _AmpState()


def is_auto_cast_enabled():
    return _state.enabled


def get_amp_dtype():
    return _state.dtype


def cast_plan(name, arrays):
    """Resolve the autocast decision for one op NOW: per-input target dtype
    (or None). The dispatch layer bakes this frozen plan into the op
    closure — the tape's lazy vjp re-runs forwards at backward time, when
    the auto_cast context may have exited, so reading thread-local state
    from inside the op function would silently change the op's dtypes
    between record and replay (observed: fp32 re-trace of a bf16-recorded
    matmul → cotangent dtype mismatch)."""
    if not _state.enabled:
        return None
    # black list wins over O2: the reference's pure-fp16/bf16 mode still
    # keeps numerically-sensitive ops (softmax, norms, cross entropy) in
    # fp32 — checking O2 first would make the black list unreachable
    if name in BLACK_LIST:
        plan = tuple(
            jnp.float32
            if hasattr(a, "dtype") and a.dtype in (jnp.bfloat16, jnp.float16)
            else None
            for a in arrays)
    elif _state.level == "O2" or name in WHITE_LIST:
        plan = tuple(
            _state.dtype if hasattr(a, "dtype") and a.dtype == jnp.float32
            else None
            for a in arrays)
    else:
        return None
    return plan if any(p is not None for p in plan) else None


@contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16", use_promote=True):
    prev = (_state.enabled, _state.dtype, _state.level)
    added_w = set(custom_white_list or [])
    added_b = set(custom_black_list or [])
    WHITE_LIST.update(added_w)
    BLACK_LIST.update(added_b)
    _state.enabled = enable
    _state.dtype = jnp.bfloat16 if convert_dtype(dtype) == convert_dtype("bfloat16") else jnp.float16
    _state.level = level
    try:
        yield
    finally:
        _state.enabled, _state.dtype, _state.level = prev
        WHITE_LIST.difference_update(added_w)
        BLACK_LIST.difference_update(added_b)


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """O2: cast model params to bf16/fp16; optimizers keep fp32 master weights
    (multi_precision is on by default in paddle_tpu.optimizer)."""
    dt = convert_dtype(dtype)
    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    if level == "O2":
        for m in model_list:
            m.to(dtype=dt)
    if optimizers is None:
        return models if single else model_list
    return (models if single else model_list), optimizers


class GradScaler:
    """Dynamic loss scaling (reference: python/paddle/amp/grad_scaler.py:38 —
    check_finite_and_unscale + update_loss_scaling ops fused here into the
    unscale step)."""

    def __init__(self, enable=True, init_loss_scaling=2.0**15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._warned_traced = False

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        import jax

        inv = 1.0 / self._scale
        found = False
        for p in optimizer._parameter_list:
            if p.grad is None:
                continue
            g = p.grad._data.astype(jnp.float32) * inv
            if isinstance(g, jax.core.Tracer):
                # under a jit trace the finite check is a traced bool —
                # branching on it would need lax.cond over the whole
                # optimizer update. TPU stance: bf16 training (the blessed
                # dtype) never overflows the exponent, so compiled steps
                # unscale mathematically and skip the inf-skip behavior;
                # eager fp16 keeps the full dynamic-scaling protocol.
                if self._dynamic and not self._warned_traced:
                    import warnings

                    warnings.warn(
                        "GradScaler inside a jit-compiled step: the "
                        "inf/NaN skip of dynamic loss scaling is NOT "
                        "applied under trace (an overflowed fp16 step "
                        "would update with non-finite grads). bf16 "
                        "training does not need loss scaling; for fp16, "
                        "keep the scaler step eager.", stacklevel=3)
                    self._warned_traced = True
                finite = True
            else:
                finite = bool(jnp.all(jnp.isfinite(g)))
            if not finite:
                found = True
            p.grad._data = g
        self._found_inf = found

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        if not self._found_inf:
            self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self.update()

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self.update()

    def update(self):
        if not (self._enable and self._dynamic):
            self._found_inf = False
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_loss_scaling(self):
        from ..ops.creation import full

        return full([1], self._scale)

    def state_dict(self):
        return {
            "scale": self._scale,
            "incr_ratio": self._incr_ratio,
            "decr_ratio": self._decr_ratio,
            "incr_count": self._good_steps,
            "decr_count": self._bad_steps,
        }

    def load_state_dict(self, state_dict):
        self._scale = state_dict.get("scale", self._scale)
        self._good_steps = state_dict.get("incr_count", 0)
        self._bad_steps = state_dict.get("decr_count", 0)
