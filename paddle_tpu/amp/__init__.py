"""Automatic mixed precision (reference: python/paddle/amp/ —
auto_cast O1 white/black lists, GradScaler dynamic loss scaling).

TPU-native stance: bf16 is the blessed dtype — wide exponent means GradScaler
is a no-op by default (`enable=False` semantics preserved for fp16 parity);
auto_cast('bfloat16') casts op inputs at the dispatch layer via a thread-local
autocast state consulted by nn.functional's heavy ops.
"""
from __future__ import annotations

import threading
from contextlib import contextmanager

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dtype import convert_dtype
from ..autograd import tape

__all__ = ["auto_cast", "amp_guard", "GradScaler", "decorate", "is_auto_cast_enabled", "get_amp_dtype"]

# O1 lists mirrored from the reference (python/paddle/amp/auto_cast.py:28-92)
WHITE_LIST = {"matmul", "linear", "conv2d", "conv1d", "conv3d", "einsum", "flash_attention", "mm", "bmm"}
BLACK_LIST = {
    "exp", "square", "log", "mean", "sum", "cos_sim", "softmax",
    "softmax_with_cross_entropy", "sigmoid_cross_entropy_with_logits",
    "cross_entropy", "layer_norm", "batch_norm", "group_norm",
}


class _AmpState(threading.local):
    def __init__(self):
        self.enabled = False
        self.dtype = jnp.bfloat16
        self.level = "O1"


_state = _AmpState()


def is_auto_cast_enabled():
    return _state.enabled


def get_amp_dtype():
    return _state.dtype


def cast_plan(name, arrays):
    """Resolve the autocast decision for one op NOW: per-input target dtype
    (or None). The dispatch layer bakes this frozen plan into the op
    closure — the tape's lazy vjp re-runs forwards at backward time, when
    the auto_cast context may have exited, so reading thread-local state
    from inside the op function would silently change the op's dtypes
    between record and replay (observed: fp32 re-trace of a bf16-recorded
    matmul → cotangent dtype mismatch)."""
    if not _state.enabled:
        return None
    # black list wins over O2: the reference's pure-fp16/bf16 mode still
    # keeps numerically-sensitive ops (softmax, norms, cross entropy) in
    # fp32 — checking O2 first would make the black list unreachable
    if name in BLACK_LIST:
        plan = tuple(
            jnp.float32
            if hasattr(a, "dtype") and a.dtype in (jnp.bfloat16, jnp.float16)
            else None
            for a in arrays)
    elif _state.level == "O2" or name in WHITE_LIST:
        plan = tuple(
            _state.dtype if hasattr(a, "dtype") and a.dtype == jnp.float32
            else None
            for a in arrays)
    else:
        return None
    return plan if any(p is not None for p in plan) else None


@contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16", use_promote=True):
    prev = (_state.enabled, _state.dtype, _state.level)
    added_w = set(custom_white_list or [])
    added_b = set(custom_black_list or [])
    WHITE_LIST.update(added_w)
    BLACK_LIST.update(added_b)
    _state.enabled = enable
    _state.dtype = jnp.bfloat16 if convert_dtype(dtype) == convert_dtype("bfloat16") else jnp.float16
    _state.level = level
    try:
        yield
    finally:
        _state.enabled, _state.dtype, _state.level = prev
        WHITE_LIST.difference_update(added_w)
        BLACK_LIST.difference_update(added_b)


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """O2: cast model params to bf16/fp16; optimizers keep fp32 master weights
    (multi_precision is on by default in paddle_tpu.optimizer)."""
    dt = convert_dtype(dtype)
    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    if level == "O2":
        for m in model_list:
            m.to(dtype=dt)
    if optimizers is None:
        return models if single else model_list
    return (models if single else model_list), optimizers


def _is_tracer(x):
    import jax

    return isinstance(x, jax.core.Tracer)


class GradScaler:
    """Dynamic loss scaling (reference: python/paddle/amp/grad_scaler.py:38 —
    check_finite_and_unscale + update_loss_scaling ops fused here into the
    unscale step).

    Works BOTH eagerly and inside a jit-compiled step. Under trace the
    full reference semantics run in-graph (matching the static AMP path's
    check_finite_and_unscale + update_loss_scaling ops): found_inf is a
    traced all-isfinite reduction, the optimizer update is masked with
    jnp.where so an overflowed fp16 step leaves params/slots untouched,
    and the scale/counters update through the traced flag. Dynamic
    scaling's state (scale, good/bad step counters) must then be threaded
    through the compiled program — register the scaler:

        step = jit.compile(train_step, models=[m], optimizers=[o],
                           scalers=[scaler])

    An unregistered dynamic scaler inside a trace raises (the state
    update would silently vanish when the trace ends); a static-scale
    scaler (use_dynamic_loss_scaling=False) needs no registration — its
    inf-skip masking is stateless per step.
    """

    def __init__(self, enable=True, init_loss_scaling=2.0**15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._unscaled = False
        # set by jit.CompiledFunction while tracing a registered scaler
        self._in_compiled_step = False

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        grads = [p.grad for p in optimizer._parameter_list
                 if p.grad is not None]
        # registration check BEFORE any mutation: raising after writing
        # tracers into p.grad (or setting _unscaled) would leave the
        # scaler/grads poisoned for a caller that catches and retries
        # eagerly
        if (self._dynamic and not self._in_compiled_step
                and any(_is_tracer(g._data) for g in grads)):
            raise RuntimeError(
                "GradScaler with dynamic loss scaling inside a "
                "jit-compiled step: the scale/counter updates are "
                "traced state and must be threaded through the "
                "program — pass the scaler to the compile call: "
                "jit.compile(step, models=..., optimizers=..., "
                "scalers=[scaler]). (bf16 training does not need "
                "loss scaling at all; or set "
                "use_dynamic_loss_scaling=False for a fixed scale, "
                "which needs no registration.)")
        inv = 1.0 / self._scale
        found = None
        traced = False
        for g_t in grads:
            g = g_t._data.astype(jnp.float32) * inv
            bad = ~jnp.all(jnp.isfinite(g))
            traced = traced or _is_tracer(bad)
            found = bad if found is None else jnp.logical_or(found, bad)
            g_t._data = g
        self._unscaled = True
        if found is None:
            self._found_inf = False
        elif traced:
            self._found_inf = found
        else:
            self._found_inf = bool(found)

    def _masked_step(self, optimizer, found):
        """Run optimizer.step() then select the pre-step value for every
        param/slot/master when found_inf — the in-graph analog of the
        reference's per-op skip in check_finite_and_unscale."""
        params = optimizer._parameter_list
        # materialize lazily-created slots/master weights BEFORE the
        # snapshot: otherwise a first-step overflow creates them from
        # inf-scaled grads inside step() and the masking below skips
        # them (inf moments poison every later step)
        for p in params:
            optimizer._ensure_state(p)
        snap_p = [p._data for p in params]
        snap_states = {k: dict(v) for k, v in optimizer._states.items()}
        snap_mw = dict(optimizer._master_weights)
        optimizer.step()
        for p, old in zip(params, snap_p):
            p._data = jnp.where(found, old, p._data)
        for key, slot_dict in optimizer._states.items():
            old_slots = snap_states.get(key, {})
            for sname, new in slot_dict.items():
                if sname in old_slots:
                    slot_dict[sname] = jnp.where(found, old_slots[sname], new)
        for key, new in optimizer._master_weights.items():
            if key in snap_mw:
                optimizer._master_weights[key] = jnp.where(
                    found, snap_mw[key], new)

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        if not self._unscaled:
            self.unscale_(optimizer)
        found = self._found_inf
        if _is_tracer(found):
            self._masked_step(optimizer, found)
        elif not found:
            optimizer.step()
        self.update()

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        if not self._unscaled:
            self.unscale_(optimizer)
        found = self._found_inf
        if _is_tracer(found):
            self._masked_step(optimizer, found)
        elif not found:
            optimizer.step()
        self.update()

    def update(self):
        self._unscaled = False
        if not (self._enable and self._dynamic):
            self._found_inf = False
            return
        found = self._found_inf
        if _is_tracer(found) or _is_tracer(self._scale):
            # traced update_loss_scaling: same recurrence as the eager
            # branch below, expressed with jnp.where over threaded state
            scale = jnp.asarray(self._scale, jnp.float32)
            good = jnp.asarray(self._good_steps, jnp.int32)
            bad = jnp.asarray(self._bad_steps, jnp.int32)
            found = jnp.asarray(found, bool)
            bad = jnp.where(found, bad + 1, 0)
            good = jnp.where(found, 0, good + 1)
            decr = found & (bad >= self._decr_every)
            scale = jnp.where(
                decr, jnp.maximum(scale * self._decr_ratio, 1.0), scale)
            bad = jnp.where(decr, 0, bad)
            incr = (~found) & (good >= self._incr_every)
            scale = jnp.where(incr, scale * self._incr_ratio, scale)
            good = jnp.where(incr, 0, good)
            self._scale, self._good_steps, self._bad_steps = scale, good, bad
            self._found_inf = False
            return
        if found:
            self._apply_backoff()
        else:
            self._good_steps = int(self._good_steps) + 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale = float(self._scale) * self._incr_ratio
                self._good_steps = 0
        self._found_inf = False

    def _apply_backoff(self):
        """The host-side found-inf decrement recurrence (shared by
        update()'s eager branch and the external backoff() hook)."""
        self._bad_steps = int(self._bad_steps) + 1
        self._good_steps = 0
        if self._bad_steps >= self._decr_every:
            self._scale = max(float(self._scale) * self._decr_ratio, 1.0)
            self._bad_steps = 0

    def backoff(self):
        """Apply the found-inf decrement recurrence once from OUTSIDE the
        scaler's own unscale path — the hook `resilience.StepGuard` calls
        when ITS health check (post-update param isfinite) catches a
        non-finite step the scaler never saw.  Host-side only: the guard
        runs between steps, never under trace (a traced scale would mean
        the scaler is registered and doing its own in-graph skip)."""
        if not (self._enable and self._dynamic) or _is_tracer(self._scale):
            return
        self._apply_backoff()

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_loss_scaling(self):
        if isinstance(self._scale, (int, float)):
            from ..ops.creation import full

            return full([1], self._scale)
        return Tensor(jnp.asarray(self._scale, jnp.float32).reshape(1))

    def state_dict(self):
        return {
            "scale": float(self._scale),
            "incr_ratio": self._incr_ratio,
            "decr_ratio": self._decr_ratio,
            "incr_count": int(self._good_steps),
            "decr_count": int(self._bad_steps),
        }

    def load_state_dict(self, state_dict):
        self._scale = state_dict.get("scale", self._scale)
        self._good_steps = state_dict.get("incr_count", 0)
        self._bad_steps = state_dict.get("decr_count", 0)
