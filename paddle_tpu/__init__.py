"""paddle_tpu — a TPU-native deep-learning framework.

A ground-up re-design of the reference framework's capabilities
(KevinKDA-Resources/Paddle, surveyed in SURVEY.md) for TPU hardware:

- eager Tensors ride jax.Array / XLA's async runtime (no hand-written
  allocator/stream stack — that is the hardware-native runtime here),
- autograd records jax.vjp pullbacks (no per-op gradient kernel zoo),
- the blessed performance path is whole-graph compilation (`paddle_tpu.jit`),
- distributed training is SPMD over a `jax.sharding.Mesh` with XLA
  collectives on ICI/DCN (no NCCL, no comm-id bootstrap),
- hot kernels (attention, fused FFN) are Pallas.

The public API mirrors the reference's `paddle.*` surface so users can
switch with minimal churn.
"""
from __future__ import annotations

__version__ = "0.1.0"

import os as _os

if _os.environ.get("PTPU_FORCE_PLATFORM"):
    # launcher/spawn children must pin the backend BEFORE first jax use;
    # a bare JAX_PLATFORMS env var is overridden by site customizations
    # on tunneled-TPU hosts, so the launcher sets this and we apply it.
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["PTPU_FORCE_PLATFORM"])

from .core.tensor import Tensor, TracedValueError, to_tensor
from .core.containers import SelectedRows, StringTensor
from .core.dtype import (
    bool_,
    uint8,
    int8,
    int16,
    int32,
    int64,
    float16,
    bfloat16,
    float32,
    float64,
    complex64,
    complex128,
)
from .core.random import seed
from .core import random as _rng

from .ops import *  # noqa: F401,F403
from .ops import __all__ as _ops_all

from .autograd import no_grad, enable_grad, grad, set_grad_enabled, is_grad_enabled
from . import autograd
from . import ops

__all__ = ["Tensor", "TracedValueError", "to_tensor", "seed", "no_grad",
           "grad"] + list(_ops_all)

# Subsystems (populated progressively; import order matters — nn/optimizer
# build on ops; monitor first — it is stdlib-only and the others report
# telemetry through it).
from . import monitor  # noqa: E402
from . import framework  # noqa: E402
from . import device  # noqa: E402
from . import nn  # noqa: E402
from . import optimizer  # noqa: E402
from . import io  # noqa: E402
from . import amp  # noqa: E402
from . import jit  # noqa: E402
from . import static  # noqa: E402
from . import metric  # noqa: E402
from . import vision  # noqa: E402
from . import distributed  # noqa: E402
from . import resilience  # noqa: E402
from . import incubate  # noqa: E402
from . import utils  # noqa: E402
from . import profiler  # noqa: E402
from . import linalg  # noqa: E402
from . import hapi  # noqa: E402
from .hapi import Model, summary  # noqa: E402
from . import distribution  # noqa: E402
from . import fft  # noqa: E402
from . import signal  # noqa: E402
from . import sparse  # noqa: E402
from . import quantization  # noqa: E402
from . import lowbit  # noqa: E402
from . import geometric  # noqa: E402
from . import text  # noqa: E402
from . import audio  # noqa: E402
from . import inference  # noqa: E402
from . import hub  # noqa: E402
from . import reader  # noqa: E402
from . import dataset  # noqa: E402
from .reader import batch  # noqa: E402
from . import sysconfig  # noqa: E402
from . import onnx  # noqa: E402
from .cost_model import CostModel  # noqa: E402

from .framework.io_ import save, load  # noqa: E402
from .framework.core_ import (  # noqa: E402
    set_default_dtype,
    get_default_dtype,
    set_flags,
    get_flags,
    get_rng_state,
    set_rng_state,
)
from .framework.compat import (  # noqa: E402
    CPUPlace, CUDAPlace, CUDAPinnedPlace, NPUPlace, XPUPlace, CustomPlace,
    iinfo, finfo, set_printoptions, disable_signal_handler, LazyGuard, flops,
)
from .device import set_device, get_device  # noqa: E402
from .nn.layer import ParamAttr  # noqa: E402
from .distributed import DataParallel  # noqa: E402
from .core.dtype import bool_ as bool  # noqa: E402,A001  (reference exports `paddle.bool`)

import numpy as _np  # noqa: E402
dtype = _np.dtype  # paddle.dtype: the dtype class (np.dtype on XLA)
# rng-state aliases: one counter-based PRNG serves every backend (the
# reference separates host and CUDA generator stacks; XLA has one)
get_cuda_rng_state = get_rng_state
set_cuda_rng_state = set_rng_state


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    """Free-function parameter creation (reference
    python/paddle/tensor/creation.py:create_parameter)."""
    from .nn.layer import Layer, ParamAttr

    if name is not None:
        attr = ParamAttr._to_attr(attr)
        if attr is not False and attr.name is None:
            attr.name = name
    holder = Layer()
    return holder.create_parameter(shape, attr=attr, dtype=dtype,
                                   is_bias=is_bias,
                                   default_initializer=default_initializer)

disable_static = static.disable_static
enable_static = static.enable_static
in_dynamic_mode = static.in_dynamic_mode

__all__ += ["save", "load", "set_default_dtype", "get_default_dtype", "set_device", "get_device", "Model", "summary"]
