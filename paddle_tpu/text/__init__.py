"""Text utilities (reference: python/paddle/text/ — datasets; viterbi_decode
op at paddle/phi/kernels/cpu/viterbi_decode_kernel.cc, python surface
paddle.text.viterbi_decode + ViterbiDecoder).

TPU-native: the Viterbi forward pass is a lax.scan over time — one compiled
program, no per-step host loop."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import apply, unwrap
from ..nn.layer import Layer

from .tokenizer import (
    BasicTokenizer, FasterTokenizer, WordpieceTokenizer, load_vocab)

__all__ = ["viterbi_decode", "ViterbiDecoder", "datasets",
           "FasterTokenizer", "BasicTokenizer", "WordpieceTokenizer",
           "load_vocab"]


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """Batched Viterbi decoding (reference: paddle.text.viterbi_decode).

    potentials: [B, T, N] emission scores; transition_params: [N, N]
    (transition_params[i, j] = score of i -> j); lengths: [B] valid steps.
    With include_bos_eos_tag=True the last two tags are BOS (start) and
    EOS (stop), matching the reference contract.
    Returns (scores [B], paths [B, T_max] int64-ish) with positions beyond
    each length zero-padded.
    """

    def fn(pot, trans, lens):
        B, T, N = pot.shape
        if include_bos_eos_tag:
            bos, eos = N - 2, N - 1
            start = pot[:, 0] + trans[bos][None, :]
        else:
            start = pot[:, 0]

        def step(carry, t):
            alpha = carry  # [B, N]
            # score of arriving at j at time t from best i
            cand = alpha[:, :, None] + trans[None, :, :]  # [B, i, j]
            best = jnp.max(cand, axis=1) + pot[:, t]
            back = jnp.argmax(cand, axis=1)  # [B, N]
            # freeze alpha past each sequence's end
            active = (t < lens)[:, None]
            return jnp.where(active, best, alpha), jnp.where(active, back, 0)

        alpha, backs = jax.lax.scan(step, start, jnp.arange(1, T))
        # backs: [T-1, B, N]
        if include_bos_eos_tag:
            alpha = alpha + trans[:, eos][None, :]
        scores = jnp.max(alpha, axis=-1)
        last_tag = jnp.argmax(alpha, axis=-1)  # [B]

        def backtrack(carry, bt):
            tag, t = carry  # [B], scalar step index (reversed)
            prev = jnp.take_along_axis(bt, tag[:, None], axis=1)[:, 0]
            # only step back while t < len-1 (inside the valid window)
            use = (t <= lens - 2)
            tag_new = jnp.where(use, prev, tag)
            return (tag_new, t - 1), tag_new

        (_, _), rev_tags = jax.lax.scan(
            backtrack, (last_tag, jnp.asarray(T - 2)), backs[::-1])
        # rev_tags: [T-1, B] tags for positions T-2..0
        path = jnp.concatenate([rev_tags[::-1], last_tag[None, :]], axis=0).T
        # zero out positions beyond each length, and move each sequence's
        # final tag to position len-1 (shorter sequences end earlier)
        pos = jnp.arange(T)[None, :]
        valid = pos < lens[:, None]
        # for sequences shorter than T the backtrack above kept the tag
        # frozen through the padded tail, so path[:, :len] is the answer
        path = jnp.where(valid, path, 0)
        return scores, path.astype(jnp.int32)

    pot_t = potentials if isinstance(potentials, Tensor) else Tensor(jnp.asarray(potentials))
    lens_arr = unwrap(lengths).astype(jnp.int32)
    return apply(lambda p, tr: fn(p, tr, lens_arr), pot_t, transition_params,
                 n_outs=2, name="viterbi_decode")


class ViterbiDecoder(Layer):
    """Layer wrapper holding the transition matrix (reference:
    paddle.text.ViterbiDecoder)."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions if isinstance(transitions, Tensor) else Tensor(jnp.asarray(transitions))
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


from . import datasets  # noqa: E402

from .datasets import (  # noqa: E402
    Conll05st, Imdb, Imikolov, Movielens, UCIHousing, WMT14, WMT16,
)
