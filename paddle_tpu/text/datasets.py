"""Text datasets (reference: python/paddle/text/datasets/ — Imdb, Imikolov,
Movielens, UCIHousing, Conll05st, WMT14/16).

Zero-egress environment: local files when present under
~/.cache/paddle_tpu/, otherwise deterministic synthetic corpora with the
right schema (`.synthetic` flags it) so examples and tests run anywhere."""
from __future__ import annotations

import os

import numpy as np

from ..io import Dataset

__all__ = ["Imdb", "Imikolov", "UCIHousing", "Movielens"]

_CACHE = os.path.expanduser(os.environ.get("PTPU_DATA_HOME", "~/.cache/paddle_tpu"))


def _synthetic_text(n, vocab_size, max_len, seed, classes=2):
    rng = np.random.RandomState(seed)
    lengths = rng.randint(5, max_len, n)
    labels = rng.randint(0, classes, n).astype(np.int64)
    docs = []
    for i in range(n):
        # class-dependent token distribution so models can actually learn
        base = rng.randint(1, vocab_size // 2, lengths[i])
        if labels[i] == 1:
            base = np.minimum(base + vocab_size // 2, vocab_size - 1)
        docs.append(base.astype(np.int64))
    return docs, labels


class Imdb(Dataset):
    """Sentiment classification: (token_ids, label) (reference:
    text/datasets/imdb.py)."""

    VOCAB_SIZE = 5147

    def __init__(self, data_file=None, mode="train", cutoff=150, download=True):
        self.mode = mode
        self.synthetic = True
        n = 512 if mode == "train" else 128
        self.docs, self.labels = _synthetic_text(
            n, self.VOCAB_SIZE, 200, seed=0 if mode == "train" else 1)
        self.word_idx = {f"w{i}": i for i in range(self.VOCAB_SIZE)}

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """N-gram language-model dataset: tuples of n token ids (reference:
    text/datasets/imikolov.py)."""

    VOCAB_SIZE = 2000

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, download=True):
        self.synthetic = True
        self.window_size = window_size
        rng = np.random.RandomState(2 if mode == "train" else 3)
        n = 2048 if mode == "train" else 256
        seq = rng.randint(1, self.VOCAB_SIZE, n + window_size)
        self.grams = np.stack([seq[i:i + window_size]
                               for i in range(n)]).astype(np.int64)

    def __getitem__(self, idx):
        return tuple(self.grams[idx])

    def __len__(self):
        return len(self.grams)


class UCIHousing(Dataset):
    """Regression: (13 features, price) (reference:
    text/datasets/uci_housing.py)."""

    def __init__(self, data_file=None, mode="train", download=True):
        path = data_file or os.path.join(_CACHE, "uci_housing", "housing.data")
        self.synthetic = not os.path.exists(path)
        if not self.synthetic:
            raw = np.loadtxt(path).astype(np.float32)
        else:
            rng = np.random.RandomState(4)
            feats = rng.randn(506, 13).astype(np.float32)
            w = rng.randn(13).astype(np.float32)
            price = feats @ w + 0.1 * rng.randn(506).astype(np.float32)
            raw = np.concatenate([feats, price[:, None]], 1)
        # standard 80/20 split, feature normalization like the reference
        x, y = raw[:, :-1], raw[:, -1:]
        x = (x - x.mean(0)) / (x.std(0) + 1e-8)
        split = int(0.8 * len(x))
        if mode == "train":
            self.x, self.y = x[:split], y[:split]
        else:
            self.x, self.y = x[split:], y[split:]

    def __getitem__(self, idx):
        return self.x[idx], self.y[idx]

    def __len__(self):
        return len(self.x)


class Movielens(Dataset):
    """Rating prediction: (user_id, gender, age, job, movie_id, title_ids,
    categories, rating) — schema of text/datasets/movielens.py."""

    NUM_USERS = 1000
    NUM_MOVIES = 800

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=True):
        self.synthetic = True
        rng = np.random.RandomState(rand_seed + (0 if mode == "train" else 1))
        n = 4096 if mode == "train" else 512
        self.rows = []
        for _ in range(n):
            user = rng.randint(1, self.NUM_USERS)
            movie = rng.randint(1, self.NUM_MOVIES)
            rating = float(rng.randint(1, 6))
            self.rows.append((
                np.int64(user), np.int64(rng.randint(0, 2)),
                np.int64(rng.randint(1, 7)), np.int64(rng.randint(0, 21)),
                np.int64(movie),
                rng.randint(1, 5000, 4).astype(np.int64),
                rng.randint(0, 18, 3).astype(np.int64),
                np.float32(rating),
            ))

    def __getitem__(self, idx):
        return self.rows[idx]

    def __len__(self):
        return len(self.rows)
