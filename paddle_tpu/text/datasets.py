"""Text datasets (reference: python/paddle/text/datasets/ — Imdb, Imikolov,
Movielens, UCIHousing, Conll05st, WMT14/16).

Zero-egress environment: local files when present under
~/.cache/paddle_tpu/, otherwise deterministic synthetic corpora with the
right schema (`.synthetic` flags it) so examples and tests run anywhere."""
from __future__ import annotations

import os

import numpy as np

from ..io import Dataset

__all__ = ["Imdb", "Imikolov", "UCIHousing", "Movielens"]

_CACHE = os.path.expanduser(os.environ.get("PTPU_DATA_HOME", "~/.cache/paddle_tpu"))


def _synthetic_text(n, vocab_size, max_len, seed, classes=2):
    rng = np.random.RandomState(seed)
    lengths = rng.randint(5, max_len, n)
    labels = rng.randint(0, classes, n).astype(np.int64)
    docs = []
    for i in range(n):
        # class-dependent token distribution so models can actually learn
        base = rng.randint(1, vocab_size // 2, lengths[i])
        if labels[i] == 1:
            base = np.minimum(base + vocab_size // 2, vocab_size - 1)
        docs.append(base.astype(np.int64))
    return docs, labels


class Imdb(Dataset):
    """Sentiment classification: (token_ids, label) (reference:
    text/datasets/imdb.py)."""

    VOCAB_SIZE = 5147

    def __init__(self, data_file=None, mode="train", cutoff=150, download=True):
        self.mode = mode
        self.synthetic = True
        n = 512 if mode == "train" else 128
        self.docs, self.labels = _synthetic_text(
            n, self.VOCAB_SIZE, 200, seed=0 if mode == "train" else 1)
        self.word_idx = {f"w{i}": i for i in range(self.VOCAB_SIZE)}

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """N-gram language-model dataset: tuples of n token ids (reference:
    text/datasets/imikolov.py)."""

    VOCAB_SIZE = 2000

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, download=True):
        self.synthetic = True
        self.window_size = window_size
        rng = np.random.RandomState(2 if mode == "train" else 3)
        n = 2048 if mode == "train" else 256
        seq = rng.randint(1, self.VOCAB_SIZE, n + window_size)
        self.grams = np.stack([seq[i:i + window_size]
                               for i in range(n)]).astype(np.int64)

    def __getitem__(self, idx):
        return tuple(self.grams[idx])

    def __len__(self):
        return len(self.grams)


class UCIHousing(Dataset):
    """Regression: (13 features, price) (reference:
    text/datasets/uci_housing.py)."""

    def __init__(self, data_file=None, mode="train", download=True):
        path = data_file or os.path.join(_CACHE, "uci_housing", "housing.data")
        self.synthetic = not os.path.exists(path)
        if not self.synthetic:
            raw = np.loadtxt(path).astype(np.float32)
        else:
            rng = np.random.RandomState(4)
            feats = rng.randn(506, 13).astype(np.float32)
            w = rng.randn(13).astype(np.float32)
            price = feats @ w + 0.1 * rng.randn(506).astype(np.float32)
            raw = np.concatenate([feats, price[:, None]], 1)
        # standard 80/20 split, feature normalization like the reference
        x, y = raw[:, :-1], raw[:, -1:]
        x = (x - x.mean(0)) / (x.std(0) + 1e-8)
        split = int(0.8 * len(x))
        if mode == "train":
            self.x, self.y = x[:split], y[:split]
        else:
            self.x, self.y = x[split:], y[split:]

    def __getitem__(self, idx):
        return self.x[idx], self.y[idx]

    def __len__(self):
        return len(self.x)


class Movielens(Dataset):
    """Rating prediction: (user_id, gender, age, job, movie_id, title_ids,
    categories, rating) — schema of text/datasets/movielens.py."""

    NUM_USERS = 1000
    NUM_MOVIES = 800

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=True):
        self.synthetic = True
        rng = np.random.RandomState(rand_seed + (0 if mode == "train" else 1))
        n = 4096 if mode == "train" else 512
        self.rows = []
        for _ in range(n):
            user = rng.randint(1, self.NUM_USERS)
            movie = rng.randint(1, self.NUM_MOVIES)
            rating = float(rng.randint(1, 6))
            self.rows.append((
                np.int64(user), np.int64(rng.randint(0, 2)),
                np.int64(rng.randint(1, 7)), np.int64(rng.randint(0, 21)),
                np.int64(movie),
                rng.randint(1, 5000, 4).astype(np.int64),
                rng.randint(0, 18, 3).astype(np.int64),
                np.float32(rating),
            ))

    def __getitem__(self, idx):
        return self.rows[idx]

    def __len__(self):
        return len(self.rows)


class Conll05st(Dataset):
    """CoNLL-2005 SRL (reference text/datasets/conll05.py): tuples of
    (word_ids, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, pred_id, mark, label)
    — synthetic fallback with consistent vocab sizes."""

    WORD_DICT_LEN = 4000
    LABEL_DICT_LEN = 59
    PRED_DICT_LEN = 300

    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None, mode="train",
                 download=True):
        self.synthetic = True
        n = 128 if mode == "train" else 32
        rng = np.random.RandomState(23 if mode == "train" else 29)
        self._rows = []
        for i in range(n):
            L = rng.randint(5, 30)
            words = rng.randint(0, self.WORD_DICT_LEN, L).astype(np.int64)
            ctx = [np.roll(words, k) for k in (2, 1, 0, -1, -2)]
            pred = np.full(L, rng.randint(0, self.PRED_DICT_LEN), np.int64)
            mark = (rng.rand(L) > 0.8).astype(np.int64)
            label = rng.randint(0, self.LABEL_DICT_LEN, L).astype(np.int64)
            self._rows.append((words, *ctx, pred, mark, label))

    def get_dict(self):
        wd = {f"w{i}": i for i in range(self.WORD_DICT_LEN)}
        vd = {f"v{i}": i for i in range(self.PRED_DICT_LEN)}
        ld = {f"l{i}": i for i in range(self.LABEL_DICT_LEN)}
        return wd, vd, ld

    def __getitem__(self, idx):
        return self._rows[idx]

    def __len__(self):
        return len(self._rows)


class _WMT(Dataset):
    """Shared WMT en-de style pair dataset (reference text/datasets/
    wmt14.py, wmt16.py): (src_ids, trg_ids, trg_ids_next) tuples."""

    def __init__(self, mode="train", src_dict_size=3000, trg_dict_size=3000,
                 lang="en", data_file=None, download=True, seed=31):
        self.synthetic = True
        self.src_dict_size = src_dict_size
        self.trg_dict_size = trg_dict_size
        n = 128 if mode == "train" else 32
        rng = np.random.RandomState(seed if mode == "train" else seed + 1)
        self._rows = []
        for _ in range(n):
            ls = rng.randint(4, 20)
            lt = rng.randint(4, 20)
            src = rng.randint(3, src_dict_size, ls).astype(np.int64)
            trg = rng.randint(3, trg_dict_size, lt).astype(np.int64)
            trg_in = np.concatenate([[1], trg])          # <s> prefix
            trg_next = np.concatenate([trg, [2]])        # </s> suffix
            self._rows.append((src, trg_in, trg_next))

    def get_dict(self, lang="en", reverse=False):
        size = self.src_dict_size if lang == "en" else self.trg_dict_size
        d = {f"{lang}{i}": i for i in range(size)}
        return {v: k for k, v in d.items()} if reverse else d

    def __getitem__(self, idx):
        return self._rows[idx]

    def __len__(self):
        return len(self._rows)


class WMT14(_WMT):
    def __init__(self, data_file=None, mode="train", dict_size=3000,
                 download=True):
        super().__init__(mode=mode, src_dict_size=dict_size,
                         trg_dict_size=dict_size, seed=31)


class WMT16(_WMT):
    def __init__(self, data_file=None, mode="train", src_dict_size=3000,
                 trg_dict_size=3000, lang="en", download=True):
        super().__init__(mode=mode, src_dict_size=src_dict_size,
                         trg_dict_size=trg_dict_size, lang=lang, seed=37)


__all__ += ["Conll05st", "WMT14", "WMT16"]
