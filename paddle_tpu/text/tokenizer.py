"""Tokenization (reference: paddle/fluid/operators/string/
faster_tokenizer_op.cc — the in-graph BERT wordpiece tokenizer producing
input_ids / token_type_ids).

TPU-native position: tokenization is host-side string work; XLA consumes
the resulting int arrays. So the op is a host "kernel" on the Layer
surface (matching the reference's CPU-only op that feeds device tensors):
FasterTokenizer(vocab)(text, text_pair) -> (input_ids, token_type_ids)
as int64 device Tensors, with the reference op's padding / truncation /
special-token semantics.
"""
from __future__ import annotations

import unicodedata
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn import Layer

__all__ = ["BasicTokenizer", "WordpieceTokenizer", "FasterTokenizer",
           "load_vocab"]


def load_vocab(path: str) -> Dict[str, int]:
    """One token per line (BERT vocab.txt layout)."""
    vocab = {}
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f):
            tok = line.rstrip("\n")
            if tok:
                vocab[tok] = i
    return vocab


def _is_whitespace(ch):
    return ch in " \t\n\r" or unicodedata.category(ch) == "Zs"


def _is_control(ch):
    if ch in ("\t", "\n", "\r"):
        return False
    return unicodedata.category(ch).startswith("C")


def _is_punctuation(ch):
    cp = ord(ch)
    if (33 <= cp <= 47) or (58 <= cp <= 64) or (91 <= cp <= 96) or (123 <= cp <= 126):
        return True
    return unicodedata.category(ch).startswith("P")


def _is_cjk(cp):
    return (0x4E00 <= cp <= 0x9FFF or 0x3400 <= cp <= 0x4DBF
            or 0x20000 <= cp <= 0x2A6DF or 0xF900 <= cp <= 0xFAFF)


class BasicTokenizer:
    """Whitespace / punctuation / CJK splitting with optional lowercasing
    (faster_tokenizer_op.cc BasicTokenizer)."""

    def __init__(self, do_lower_case: bool = True):
        self.do_lower_case = do_lower_case

    def tokenize(self, text: str) -> List[str]:
        out = []
        for ch in text:
            cp = ord(ch)
            if cp == 0 or cp == 0xFFFD or _is_control(ch):
                continue
            if _is_cjk(cp):
                out.append(" ")
                out.append(ch)
                out.append(" ")
            else:
                out.append(ch)
        text = "".join(out)

        tokens = []
        for tok in text.split():
            if self.do_lower_case:
                tok = tok.lower()
                tok = "".join(c for c in unicodedata.normalize("NFD", tok)
                              if unicodedata.category(c) != "Mn")
            cur = []
            for ch in tok:
                if _is_punctuation(ch):
                    if cur:
                        tokens.append("".join(cur))
                        cur = []
                    tokens.append(ch)
                else:
                    cur.append(ch)
            if cur:
                tokens.append("".join(cur))
        return tokens


class WordpieceTokenizer:
    """Greedy longest-match-first subword split (##-continuations)."""

    def __init__(self, vocab: Dict[str, int], unk_token: str = "[UNK]",
                 max_input_chars_per_word: int = 100):
        self.vocab = vocab
        self.unk_token = unk_token
        self.max_input_chars_per_word = max_input_chars_per_word

    def tokenize(self, token: str) -> List[str]:
        if len(token) > self.max_input_chars_per_word:
            return [self.unk_token]
        out, start = [], 0
        while start < len(token):
            end = len(token)
            cur = None
            while start < end:
                sub = token[start:end]
                if start > 0:
                    sub = "##" + sub
                if sub in self.vocab:
                    cur = sub
                    break
                end -= 1
            if cur is None:
                return [self.unk_token]
            out.append(cur)
            start = end
        return out


class _NativeWordpiece:
    """ctypes front for csrc/wordpiece.cc (the faster_tokenizer_op.cc
    analog's native core). Exact-parity gating: the C++ encoder
    implements the ASCII BasicTokenizer rules, so the Layer dispatches
    here only for `text.isascii()` inputs (full-unicode lowercase/NFD
    stays in Python — the reference leans on utf8proc for that)."""

    def __init__(self, vocab: Dict[str, int], unk_id: int):
        from ..core import native as _native

        self._lib = _native.load()
        self._handle = None
        if self._lib is None:
            return
        h = self._lib.wp_vocab_new(unk_id, 100)
        for tok, i in vocab.items():
            self._lib.wp_vocab_add(h, tok.encode("utf-8"), int(i))
        self._handle = h

    @property
    def ok(self):
        return self._handle is not None

    def encode(self, text: str, do_lower: bool) -> List[int]:
        import ctypes

        cap = max(64, 2 * len(text) + 8)
        while True:
            buf = (ctypes.c_int32 * cap)()
            n = self._lib.wp_encode(self._handle, text.encode("utf-8"),
                                    1 if do_lower else 0, buf, cap)
            if n >= 0:
                return list(buf[:n])
            if n == -(2 ** 31):
                raise RuntimeError("native wordpiece: bad vocab handle")
            cap = -n  # buffer was too small: retry with the exact size

    def __del__(self):
        try:
            if self._handle is not None and self._lib is not None:
                self._lib.wp_vocab_free(self._handle)
        except Exception:  # ptpu-check[silent-except]: interpreter teardown — the native lib
            # may be unloaded before this __del__ runs
            pass


class FasterTokenizer(Layer):
    """BERT-style tokenizer layer (reference faster_tokenizer_op.cc): text
    (and optional text_pair) -> (input_ids, token_type_ids) int64 Tensors.
    ASCII inputs encode through the native C++ core (csrc/wordpiece.cc);
    anything needing unicode lowercase/NFD takes the Python path."""

    def __init__(self, vocab: Union[Dict[str, int], str],
                 do_lower_case: bool = True, is_split_into_words: bool = False):
        super().__init__()
        if isinstance(vocab, str):
            vocab = load_vocab(vocab)
        self.vocab = dict(vocab)
        self.do_lower_case = do_lower_case
        self.is_split_into_words = is_split_into_words
        self.basic = BasicTokenizer(do_lower_case)
        self.wordpiece = WordpieceTokenizer(self.vocab)
        self.cls_id = self.vocab.get("[CLS]", 0)
        self.sep_id = self.vocab.get("[SEP]", 0)
        self.pad_id = self.vocab.get("[PAD]", 0)
        self._native_obj = None    # built lazily: construction may run
                                   # the C++ build and ~|vocab| FFI adds

    @property
    def _native(self):
        if self._native_obj is None:
            unk_id = self.vocab.get(self.wordpiece.unk_token, 0)
            self._native_obj = _NativeWordpiece(self.vocab, unk_id)
        return self._native_obj

    # -- string -> subword ids ----------------------------------------------
    def _encode_one(self, text: str) -> List[int]:
        if self.is_split_into_words:
            words = list(text) if isinstance(text, str) else list(text)
        elif (self._native.ok and isinstance(text, str)
                and text.isascii() and "\x00" not in text):
            # NUL would pass isascii() but truncate the C string; the
            # Python path skips NULs and keeps encoding
            return self._native.encode(text, self.do_lower_case)
        else:
            words = self.basic.tokenize(text)
        ids = []
        for w in words:
            for sub in self.wordpiece.tokenize(w):
                ids.append(self.vocab.get(sub, self.wordpiece.vocab.get(
                    self.wordpiece.unk_token, 0)))
        return ids

    def forward(self, text, text_pair=None, max_seq_len: int = 0,
                pad_to_max_seq_len: bool = False):
        if isinstance(text, str):
            text = [text]
        if isinstance(text_pair, str):
            text_pair = [text_pair]
        if text_pair is not None and len(text_pair) != len(text):
            raise ValueError("text and text_pair batch sizes differ")

        rows, types = [], []
        for i, t in enumerate(text):
            a = self._encode_one(t)
            b = self._encode_one(text_pair[i]) if text_pair is not None else []
            if max_seq_len > 0:
                # longest-first truncation over the pair (reference
                # RunSegmentMean... truncation strategy)
                budget = max_seq_len - 2 - (1 if b else 0)
                while len(a) + len(b) > max(budget, 0):
                    if len(a) >= len(b) and a:
                        a.pop()
                    elif b:
                        b.pop()
                    else:
                        break
            ids = [self.cls_id] + a + [self.sep_id]
            tt = [0] * len(ids)
            if b:
                ids += b + [self.sep_id]
                tt += [1] * (len(b) + 1)
            rows.append(ids)
            types.append(tt)

        width = max(len(r) for r in rows) if rows else 0
        if max_seq_len > 0 and (pad_to_max_seq_len or width > max_seq_len):
            width = max_seq_len
        out_ids = [r[:width] + [self.pad_id] * (width - len(r)) for r in rows]
        out_tt = [t[:width] + [0] * (width - len(t)) for t in types]
        # int32 explicitly: vocab ids fit comfortably, and requesting int64
        # under the x64-disabled default emits a truncation warning per call
        return (Tensor(jnp.asarray(out_ids, jnp.int32)),
                Tensor(jnp.asarray(out_tt, jnp.int32)))
