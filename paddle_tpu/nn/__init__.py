"""paddle_tpu.nn — layer library (reference: python/paddle/nn/)."""
from .layer import Layer, Parameter, ParamAttr
from .common import *  # noqa: F401,F403
from .conv import *  # noqa: F401,F403
from .norm import *  # noqa: F401,F403
from .activation import *  # noqa: F401,F403
from .pooling import *  # noqa: F401,F403
from .container import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .transformer import *  # noqa: F401,F403
from .rnn import *  # noqa: F401,F403
from . import functional
from . import initializer
from .utils_ import clip_grad_norm_, clip_grad_value_, parameters_to_vector, vector_to_parameters
from . import utils

from . import common, conv, norm, activation, pooling, container, loss, transformer, rnn

from .extras import *  # noqa: F401,F403
from . import extras as _extras
from .rnn import RNNCellBase  # noqa: F401
from ..optimizer.clip import (  # noqa: F401  (reference exports these in nn)
    ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue,
)

__all__ = (
    ["Layer", "Parameter", "ParamAttr", "functional", "initializer",
     "RNNCellBase", "ClipGradByGlobalNorm", "ClipGradByNorm",
     "ClipGradByValue"]
    + list(_extras.__all__)
    + list(common.__all__)
    + list(conv.__all__)
    + list(norm.__all__)
    + list(activation.__all__)
    + list(pooling.__all__)
    + list(container.__all__)
    + list(loss.__all__)
    + list(transformer.__all__)
    + list(rnn.__all__)
)
