"""Layer base class (reference: paddle.nn.Layer,
python/paddle/fluid/dygraph/layers.py — hooks, state_dict, sublayers, to()).

Design note: parameters are eager Tensors (jax.Array-backed). The whole
layer tree is also viewable as a pytree of arrays (`state_arrays`), which is
what the jit/distributed paths capture for whole-graph compilation — the
eager object tree and the functional pytree are two views of one state.
"""
from __future__ import annotations

import collections
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dtype import convert_dtype, is_floating_point
from ..core import random as _rng
from ..framework.core_ import get_default_dtype
from .initializer import XavierNormal, Constant, Initializer

__all__ = ["Layer", "Parameter", "ParamAttr"]


class Parameter(Tensor):
    __slots__ = ("trainable", "optimize_attr", "regularizer", "is_distributed", "_sharding_axes", "_lazy_init")

    def __init__(self, data, trainable=True, name=None):
        super().__init__(data, stop_gradient=not trainable, name=name)
        self.trainable = trainable
        self.persistable = True
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.is_distributed = False
        # Per-axis logical mesh axes for SPMD placement (parallel/ fills this).
        self._sharding_axes = None
        # deferred initializer recorded under LazyGuard (framework/compat.py)
        self._lazy_init = None

    def __repr__(self):
        return (
            f"Parameter(name={self.name}, shape={list(self.shape)}, "
            f"dtype={self.dtype}, trainable={self.trainable})\n"
            f"       {np.asarray(self._data)!r}"
        )


class ParamAttr:
    """Mirror of paddle.ParamAttr (subset: name / initializer / lr / trainable)."""

    def __init__(
        self,
        name=None,
        initializer=None,
        learning_rate=1.0,
        regularizer=None,
        trainable=True,
        need_clip=True,
    ):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None:
            return ParamAttr()
        if isinstance(attr, ParamAttr):
            return attr
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        if isinstance(attr, Initializer):
            return ParamAttr(initializer=attr)
        if attr is False:
            return False
        raise TypeError(f"invalid ParamAttr: {attr!r}")


class HookRemoveHelper:
    def __init__(self, hooks, hook_id):
        self._hooks = hooks
        self._hook_id = hook_id

    def remove(self):
        self._hooks.pop(self._hook_id, None)


_name_counter = collections.defaultdict(int)


def _unique_name(prefix):
    n = _name_counter[prefix]
    _name_counter[prefix] += 1
    return f"{prefix}_{n}"


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self._parameters: Dict[str, Parameter] = collections.OrderedDict()
        self._sub_layers: Dict[str, "Layer"] = collections.OrderedDict()
        self._buffers: Dict[str, Tensor] = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self.training = True
        self._dtype = convert_dtype(dtype) or get_default_dtype()
        self._full_name = _unique_name(
            name_scope or self.__class__.__name__.lower()
        )
        self._forward_pre_hooks: Dict[int, Callable] = collections.OrderedDict()
        self._forward_post_hooks: Dict[int, Callable] = collections.OrderedDict()
        self._casted_dtype = None

    # -- construction ------------------------------------------------------
    def create_parameter(
        self,
        shape,
        attr=None,
        dtype=None,
        is_bias=False,
        default_initializer=None,
    ):
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = convert_dtype(dtype) or self._dtype
        from .initializer import _global_init_for

        # priority (reference layer_helper_base.py:374-384): an explicit
        # ParamAttr initializer wins; otherwise a set GLOBAL initializer
        # REPLACES the layer-supplied default (yes, including norm scales
        # — the reference behaves the same; its docs warn about it)
        init = (attr.initializer or _global_init_for(is_bias)
                or default_initializer
                or (Constant(0.0) if is_bias else XavierNormal()))
        from ..framework.compat import LazyGuard

        shape_t = tuple(int(s) for s in shape)
        if LazyGuard._active:
            # deferred init (reference lazy_init.py): cheap zeros now, the
            # real initializer recorded for LazyGuard.materialize
            data = jnp.zeros(shape_t, dtype)
        else:
            data = init(shape_t, dtype)
        p = Parameter(data, trainable=attr.trainable, name=attr.name or _unique_name(self._full_name + ".w"))
        if LazyGuard._active:
            p._lazy_init = lambda param, _i=init, _s=shape_t, _d=dtype: (
                param._set_data(_i(_s, _d)))
        p.optimize_attr["learning_rate"] = attr.learning_rate
        p.regularizer = attr.regularizer
        return p

    def add_parameter(self, name, parameter):
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError("add_parameter expects a Parameter")
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # -- attribute magic ---------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ first")
            params[name] = value
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            object.__setattr__(self, name, value)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ first")
            layers[name] = value
            object.__setattr__(self, name, value)
        else:
            if params is not None and name in params and value is None:
                params[name] = None
            if buffers is not None and name in buffers and isinstance(value, Tensor):
                buffers[name] = value
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        # only called when normal lookup fails
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{self.__class__.__name__}' object has no attribute '{name}'"
        )

    # -- traversal ---------------------------------------------------------
    def named_sublayers(self, prefix="", include_self=False, layers_set=None):
        if layers_set is None:
            layers_set = set()
        if include_self and id(self) not in layers_set:
            layers_set.add(id(self))
            yield prefix, self
        for name, layer in self._sub_layers.items():
            if layer is None:
                continue
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield from layer.named_sublayers(
                prefix=sub_prefix, include_self=True, layers_set=layers_set
            )

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self):
        return iter(l for l in self._sub_layers.values() if l is not None)

    def named_children(self):
        return iter(
            (n, l) for n, l in self._sub_layers.items() if l is not None
        )

    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        stack = [(prefix, self)]
        visited = set()

        def walk(pfx, layer):
            if id(layer) in visited:
                return
            visited.add(id(layer))
            for name, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (f"{pfx}.{name}" if pfx else name), p
            if include_sublayers:
                for name, sub in layer._sub_layers.items():
                    if sub is None:
                        continue
                    sub_pfx = f"{pfx}.{name}" if pfx else name
                    yield from walk(sub_pfx, sub)

        yield from walk(prefix, self)

    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def _named_buffers_with_owner(self, prefix="", include_sublayers=True):
        visited = set()

        def walk(pfx, layer):
            if id(layer) in visited:
                return
            visited.add(id(layer))
            for name, b in layer._buffers.items():
                if b is None:
                    continue
                yield (f"{pfx}.{name}" if pfx else name), b, layer, name
            if include_sublayers:
                for name, sub in layer._sub_layers.items():
                    if sub is None:
                        continue
                    yield from walk(f"{pfx}.{name}" if pfx else name, sub)

        yield from walk(prefix, self)

    def named_buffers(self, prefix="", include_sublayers=True):
        for full, b, _, _ in self._named_buffers_with_owner(prefix, include_sublayers):
            yield full, b

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    # -- modes -------------------------------------------------------------
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    def apply(self, fn):
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    # -- state dict --------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True, use_hook=True):
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(include_sublayers=include_sublayers):
            dest[name] = p
        for name, b, owner, leaf in self._named_buffers_with_owner(
            include_sublayers=include_sublayers
        ):
            # persistability is per owning layer (a sublayer's transient
            # buffer must not leak into checkpoints)
            if leaf in owner._non_persistable_buffer_names:
                continue
            dest[name] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for name, t in own.items():
            if name in state_dict:
                v = state_dict[name]
                arr = v._data if isinstance(v, Tensor) else jnp.asarray(np.asarray(v))
                if tuple(arr.shape) != t.shape:
                    raise ValueError(
                        f"shape mismatch for {name}: ckpt {tuple(arr.shape)} vs model {t.shape}"
                    )
                # copy — never alias the source's buffer (a compiled step may
                # donate this model's state arrays; aliasing would invalidate
                # the checkpoint donor's tensors)
                t._data = jnp.array(arr, dtype=t.dtype, copy=True)
                # loaded values supersede any LazyGuard-deferred initializer
                # (materialize() after load must NOT re-randomize weights)
                if getattr(t, "_lazy_init", None) is not None:
                    t._lazy_init = None
            else:
                missing.append(name)
        for k in state_dict:
            if k not in own:
                unexpected.append(k)
        return missing, unexpected

    load_dict = set_state_dict

    # -- dtype / device ----------------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            dt = convert_dtype(dtype)
            for t in list(self.parameters()) + list(self.buffers()):
                if is_floating_point(t.dtype):
                    t._data = t._data.astype(dt)
            for l in self.sublayers(include_self=True):
                l._dtype = dt
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    def half(self):
        return self.to(dtype="float16")

    # -- hooks -------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        hid = len(self._forward_pre_hooks)
        self._forward_pre_hooks[hid] = hook
        return HookRemoveHelper(self._forward_pre_hooks, hid)

    def register_forward_post_hook(self, hook):
        hid = len(self._forward_post_hooks)
        self._forward_post_hooks[hid] = hook
        return HookRemoveHelper(self._forward_post_hooks, hid)

    # -- call --------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            res = hook(self, inputs, outputs)
            if res is not None:
                outputs = res
        return outputs

    # -- misc --------------------------------------------------------------
    def full_name(self):
        return self._full_name

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = [self.__class__.__name__ + "(" + extra]
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).split("\n")
            lines.append(f"  ({name}): " + sub_repr[0])
            lines.extend("  " + l for l in sub_repr[1:])
        lines.append(")")
        return "\n".join(lines) if len(lines) > 2 else lines[0] + ")"

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    # -- pytree view for jit / SPMD ---------------------------------------
    def state_arrays(self) -> Tuple[Dict[str, "jnp.ndarray"], Dict[str, "jnp.ndarray"]]:
        """(params, buffers) as flat name→array dicts — the functional view
        captured by paddle_tpu.jit and the parallel engine."""
        params = {n: p._data for n, p in self.named_parameters()}
        bufs = {n: b._data for n, b in self.named_buffers()}
        return params, bufs

    def load_state_arrays(self, params=None, buffers=None):
        if params:
            lookup = dict(self.named_parameters())
            for n, a in params.items():
                lookup[n]._data = a
        if buffers:
            lookup = dict(self.named_buffers())
            for n, a in buffers.items():
                lookup[n]._data = a
