"""nn.utils (reference: python/paddle/nn/utils/)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..autograd import tape

__all__ = ["clip_grad_norm_", "clip_grad_value_", "parameters_to_vector", "vector_to_parameters"]


def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p.grad._data for p in parameters if p.grad is not None]
    if not grads:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g)) for g in grads]))
    else:
        total = jnp.sum(jnp.stack([jnp.sum(jnp.abs(g) ** norm_type) for g in grads])) ** (1.0 / norm_type)
    clip_coef = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    for p in parameters:
        if p.grad is not None:
            p.grad._data = p.grad._data * clip_coef
    return Tensor(total)


def clip_grad_value_(parameters, clip_value):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    for p in parameters:
        if p.grad is not None:
            p.grad._data = jnp.clip(p.grad._data, -clip_value, clip_value)


def parameters_to_vector(parameters, name=None):
    from ..ops.manipulation import concat, reshape

    return concat([reshape(p, [-1]) for p in parameters], axis=0)


def vector_to_parameters(vec, parameters, name=None):
    offset = 0
    for p in parameters:
        n = p.size
        p._data = vec._data[offset : offset + n].reshape(p.shape).astype(p.dtype)
        offset += n
