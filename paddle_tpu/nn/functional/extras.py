"""nn.functional long tail (reference: python/paddle/nn/functional/ —
pooling.py adaptive/unpool variants, loss.py margin losses + rnnt,
common.py unfold/bilinear/class_center_sample, input.py,
extension ops gather_tree / sparse_attention / diag_embed).

TPU-native formulations throughout: unpool is a flat scatter, unfold is
XLA's conv_general_dilated_patches, RNN-T loss is an anti-diagonal-free
two-scan DP in log space, sparse_attention gathers the CSR column set per
query row (O(S*nnz), MXU-batched)."""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...core.dispatch import apply
from ...core import random as _rng

__all__ = [
    "adaptive_max_pool1d", "adaptive_max_pool3d", "bilinear",
    "class_center_sample", "diag_embed", "dice_loss", "edit_distance",
    "elu_", "gather_tree",
    "hsigmoid_loss", "margin_cross_entropy", "max_unpool1d", "max_unpool2d",
    "max_unpool3d", "multi_label_soft_margin_loss", "multi_margin_loss",
    "pairwise_distance", "relu_", "rnnt_loss", "soft_margin_loss",
    "softmax_", "sparse_attention", "tanh_",
    "triplet_margin_with_distance_loss", "unfold", "zeropad2d",
]


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def _arr(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def _reduce(val, reduction):
    if reduction == "mean":
        return val.mean()
    if reduction == "sum":
        return val.sum()
    return val


# -- pooling ----------------------------------------------------------------

def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    from . import _adaptive_pool

    return _adaptive_pool(x, output_size, 1, "max", "NCL")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    from . import _adaptive_pool

    return _adaptive_pool(x, output_size, 3, "max", "NCDHW")


def _max_unpool(x, indices, nd, kernel_size, stride, padding, output_size,
                data_format):
    """Shared unpool core: indices are flat positions into the pooled
    input's spatial volume (the return_mask convention of max_poolNd)."""
    stride = stride or kernel_size

    def _tup(v):
        return (v,) * nd if isinstance(v, int) else tuple(v)

    ks, st, pd = _tup(kernel_size), _tup(stride), _tup(padding)

    def fn(a, idx):
        lead = a.shape[:2]
        in_sp = a.shape[2:]
        if output_size is not None:
            out_sp = tuple(output_size[-nd:])
        else:
            out_sp = tuple((in_sp[i] - 1) * st[i] - 2 * pd[i] + ks[i]
                           for i in range(nd))
        vol = int(np.prod(out_sp))
        flat = jnp.zeros(lead + (vol,), a.dtype)
        a_flat = a.reshape(lead + (-1,))
        i_flat = idx.reshape(lead + (-1,)).astype(jnp.int32)
        b = jnp.arange(lead[0])[:, None, None]
        c = jnp.arange(lead[1])[None, :, None]
        flat = flat.at[b, c, i_flat].set(a_flat)
        return flat.reshape(lead + out_sp)

    return apply(fn, _t(x), _t(indices), name=f"max_unpool{nd}d")


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    return _max_unpool(x, indices, 1, kernel_size, stride, padding,
                       output_size, data_format)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    return _max_unpool(x, indices, 2, kernel_size, stride, padding,
                       output_size, data_format)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    return _max_unpool(x, indices, 3, kernel_size, stride, padding,
                       output_size, data_format)


# -- shape / common ---------------------------------------------------------

def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (reference unfold / im2col op): [N, C, H, W] ->
    [N, C*kh*kw, L]. One XLA patch-extraction op — the contraction partner
    rides the MXU."""

    def _tup(v):
        return (v, v) if isinstance(v, int) else tuple(v)

    kh, kw = _tup(kernel_sizes)
    sh, sw = _tup(strides)
    dh, dw = _tup(dilations)
    p = paddings
    if isinstance(p, int):
        pads = ((p, p), (p, p))
    elif len(p) == 2:
        pads = ((p[0], p[0]), (p[1], p[1]))
    else:
        pads = ((p[0], p[2]), (p[1], p[3]))

    def fn(a):
        patches = jax.lax.conv_general_dilated_patches(
            a, (kh, kw), (sh, sw), pads, rhs_dilation=(dh, dw),
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        n, ckk, oh, ow = patches.shape
        return patches.reshape(n, ckk, oh * ow)

    return apply(fn, _t(x), name="unfold")


def zeropad2d(x, padding, data_format="NCHW", name=None):
    p = (padding,) * 4 if isinstance(padding, int) else tuple(padding)

    def fn(a):
        # padding order (reference): [left, right, top, bottom]
        if data_format == "NCHW":
            cfg = ((0, 0), (0, 0), (p[2], p[3]), (p[0], p[1]))
        else:
            cfg = ((0, 0), (p[2], p[3]), (p[0], p[1]), (0, 0))
        return jnp.pad(a, cfg)

    return apply(fn, _t(x), name="zeropad2d")


def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None):
    def fn(a):
        n = a.shape[-1]
        size = n + abs(offset)
        out = jnp.zeros(a.shape[:-1] + (size, size), a.dtype)
        r = jnp.arange(n) + max(-offset, 0)
        c = jnp.arange(n) + max(offset, 0)
        out = out.at[..., r, c].set(a)
        nd = out.ndim
        d1, d2 = dim1 % nd, dim2 % nd
        # the ROW axis of the embedded matrix goes to dim1, the COLUMN axis
        # to dim2 — so swapped dims transpose the result
        order = [i for i in range(nd) if i not in (nd - 2, nd - 1)]
        first, second = ((d1, nd - 2), (d2, nd - 1)) if d1 < d2 else \
            ((d2, nd - 1), (d1, nd - 2))
        order.insert(first[0], first[1])
        order.insert(second[0], second[1])
        return jnp.transpose(out, order)

    return apply(fn, _t(input), name="diag_embed")


def bilinear(x1, x2, weight, bias=None, name=None):
    """out[b, o] = x1[b, :] @ W[o] @ x2[b, :] + bias (reference
    bilinear_tensor_product op) — one einsum on the MXU."""
    args = [_t(x1), _t(x2), _t(weight)]
    if bias is not None:
        args.append(_t(bias))

    def fn(a, b, w, *rest):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if rest:
            out = out + rest[0]
        return out

    return apply(fn, *args, name="bilinear")


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    def fn(a, b):
        d = a - b + epsilon
        return jnp.linalg.norm(d, ord=p, axis=-1, keepdims=keepdim)

    return apply(fn, _t(x), _t(y), name="pairwise_distance")


# -- inplace activations ----------------------------------------------------

def _inplace_act(fn_name):
    from ...ops._inplace import make_inplace

    def call(snap, *a, **k):
        import paddle_tpu.nn.functional as _F

        return getattr(_F, fn_name)(snap, *a, **k)

    return make_inplace(call, name=fn_name + "_")


relu_ = _inplace_act("relu")
elu_ = _inplace_act("elu")
tanh_ = _inplace_act("tanh")
softmax_ = _inplace_act("softmax")


# -- losses -----------------------------------------------------------------

def soft_margin_loss(input, label, reduction="mean", name=None):
    out = apply(lambda x, y: jnp.log1p(jnp.exp(-y * x)), _t(input), _t(label),
                name="soft_margin_loss")
    return _reduce(out, reduction)


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean",
                                 name=None):
    args = [_t(input), _t(label)]
    if weight is not None:
        args.append(_t(weight))

    def fn(x, y, *w):
        per = -(y * jax.nn.log_sigmoid(x) + (1 - y) * jax.nn.log_sigmoid(-x))
        if w:
            per = per * w[0]
        return per.mean(-1)

    return _reduce(apply(fn, *args, name="multi_label_soft_margin_loss"),
                   reduction)


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    args = [_t(input), _t(label)]
    if weight is not None:
        args.append(_t(weight))

    def fn(x, y, *w):
        n, c = x.shape
        correct = jnp.take_along_axis(x, y[:, None].astype(jnp.int32), 1)
        m = jnp.maximum(0.0, margin - correct + x) ** p
        if w:
            m = m * w[0][y.astype(jnp.int32)][:, None]
        mask = jax.nn.one_hot(y.astype(jnp.int32), c, dtype=x.dtype)
        return ((1 - mask) * m).sum(-1) / c

    return _reduce(apply(fn, *args, name="multi_margin_loss"), reduction)


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean", name=None):
    dfn = distance_function or (lambda a, b: pairwise_distance(a, b))
    dp = dfn(_t(input), _t(positive))
    dn = dfn(_t(input), _t(negative))
    if swap:
        dpn = dfn(_t(positive), _t(negative))
        dn = apply(lambda a, b: jnp.minimum(a, b), dn, dpn, name="min_swap")
    out = apply(lambda a, b: jnp.maximum(a - b + margin, 0.0), dp, dn,
                name="triplet_margin_with_distance_loss")
    return _reduce(out, reduction)


def dice_loss(input, label, epsilon=1e-5, name=None):
    """1 - 2|X∩Y| / (|X|+|Y|) over the prob of the labeled class
    (reference nn/functional/loss.py dice_loss)."""

    def fn(x, y):
        yi = y.astype(jnp.int32)
        if yi.ndim == x.ndim:
            yi = yi[..., 0]
        onehot = jax.nn.one_hot(yi, x.shape[-1], dtype=x.dtype)
        reduce_dims = tuple(range(1, x.ndim))
        inter = jnp.sum(x * onehot, axis=reduce_dims)
        union = jnp.sum(x, axis=reduce_dims) + jnp.sum(onehot, axis=reduce_dims)
        return jnp.mean(1 - (2 * inter + epsilon) / (union + epsilon))

    return apply(fn, _t(input), _t(label), name="dice_loss")


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False, name=None):
    """Hierarchical sigmoid loss (reference hsigmoid_loss /
    hierarchical_sigmoid op). Default complete-binary-tree coding; custom
    trees via path_table/path_code (padded with -1)."""
    if path_table is None:
        # complete binary tree over num_classes leaves: internal nodes
        # 0..num_classes-2; leaf c maps to tree node c + num_classes - 1
        depth = max(1, int(math.ceil(math.log2(max(2, num_classes)))))
        tables, codes = [], []
        for c in range(num_classes):
            node = c + num_classes - 1
            tab, code = [], []
            while node > 0:
                parent = (node - 1) // 2
                tab.append(parent)
                code.append(node == 2 * parent + 2)  # right child -> 1
                node = parent
            tab = tab[::-1][:depth] + [-1] * (depth - len(tab))
            code = code[::-1][:depth] + [False] * (depth - len(code))
            tables.append(tab)
            codes.append([int(v) for v in code])
        path_table = jnp.asarray(tables, jnp.int32)
        path_code = jnp.asarray(codes, jnp.int32)
    else:
        path_table = jnp.asarray(
            path_table._data if isinstance(path_table, Tensor) else path_table,
            jnp.int32)
        path_code = jnp.asarray(
            path_code._data if isinstance(path_code, Tensor) else path_code,
            jnp.int32)

    args = [_t(input), _t(label), _t(weight)]
    if bias is not None:
        args.append(_t(bias))

    def fn(x, y, w, *b):
        yi = y.reshape(-1).astype(jnp.int32)
        tab = path_table[yi]                     # [B, D]
        code = path_code[yi].astype(x.dtype)     # [B, D]
        valid = (tab >= 0).astype(x.dtype)
        tab = jnp.maximum(tab, 0)
        wv = w[tab]                              # [B, D, F]
        logits = jnp.einsum("bdf,bf->bd", wv, x)
        if b:
            logits = logits + b[0].reshape(-1)[tab]
        # BCE with code as target, only over valid path entries
        per = -(code * jax.nn.log_sigmoid(logits)
                + (1 - code) * jax.nn.log_sigmoid(-logits))
        return (per * valid).sum(-1, keepdims=True)

    return apply(fn, *args, name="hsigmoid_loss")


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean", name=None):
    """ArcFace/CosFace-style margin softmax CE (reference
    margin_cross_entropy op): cos(m1*θ + m2) - m3 on the target logit,
    then scaled softmax CE. logits must be cosine similarities."""

    def fn(x, y):
        yi = y.reshape(-1).astype(jnp.int32)
        x32 = x.astype(jnp.float32)
        target = jnp.take_along_axis(x32, yi[:, None], 1)[:, 0]
        theta = jnp.arccos(jnp.clip(target, -1.0, 1.0))
        m_target = jnp.cos(margin1 * theta + margin2) - margin3
        onehot = jax.nn.one_hot(yi, x.shape[-1], dtype=x32.dtype)
        adj = x32 * (1 - onehot) + m_target[:, None] * onehot
        adj = adj * scale
        lse = jax.nn.logsumexp(adj, axis=-1)
        loss = lse - jnp.take_along_axis(adj, yi[:, None], 1)[:, 0]
        sm = jax.nn.softmax(adj, axis=-1)
        return loss[:, None], sm

    loss, sm = apply(fn, _t(logits), _t(label), name="margin_cross_entropy")
    loss = _reduce(loss, reduction)
    return (loss, sm) if return_softmax else loss


def class_center_sample(label, num_classes, num_samples, group=None):
    """Sample negative class centers (PartialFC; reference
    class_center_sample op): keep all positive classes plus uniform
    negatives up to num_samples; remap labels into the sampled index
    space. Host-side (dynamic unique set), like the reference CPU path."""
    lab = np.asarray(label._data if isinstance(label, Tensor) else label
                     ).reshape(-1).astype(np.int64)
    pos = np.unique(lab)
    n_extra = max(0, num_samples - len(pos))
    rest = np.setdiff1d(np.arange(num_classes, dtype=np.int64), pos,
                        assume_unique=True)
    seed = int(np.asarray(_rng.next_key())[-1]) % (2 ** 31)
    rng = np.random.RandomState(seed)
    extra = rng.choice(rest, size=min(n_extra, len(rest)), replace=False) \
        if n_extra and len(rest) else np.zeros((0,), np.int64)
    sampled = np.concatenate([pos, np.sort(extra)])
    remap = -np.ones(num_classes, np.int64)
    remap[sampled] = np.arange(len(sampled))
    return (Tensor(jnp.asarray(remap[lab])),
            Tensor(jnp.asarray(sampled)))


# -- beam search / sequence -------------------------------------------------

def gather_tree(ids, parents):
    """Reconstruct full beam paths from per-step parent pointers
    (reference gather_tree op): walk ancestry backward with one lax.scan.
    ids/parents: [T, B, W] -> [T, B, W]."""

    def fn(idv, par):
        T = idv.shape[0]

        def step(beam, t):
            # beam: [B, W] current beam slot per output position
            tok = jnp.take_along_axis(idv[t], beam, axis=-1)
            nxt = jnp.take_along_axis(par[t], beam, axis=-1)
            return nxt.astype(beam.dtype), tok

        w = idv.shape[-1]
        init = jnp.broadcast_to(jnp.arange(w, dtype=idv.dtype),
                                idv.shape[1:])
        _, toks = jax.lax.scan(step, init, jnp.arange(T - 1, -1, -1))
        return toks[::-1]

    return apply(fn, _t(ids), _t(parents), name="gather_tree")


# -- RNN-T loss -------------------------------------------------------------

def rnnt_loss(logits, labels, logit_lengths, label_lengths, blank=0,
              fastemit_lambda=0.0, reduction="mean", name=None):
    """RNN-Transducer loss (reference warprnnt / rnnt_loss op). Log-space
    forward DP over the (T, U) lattice: alpha computed by a lax.scan over
    T with a nested associative scan-free row update over U — static
    shapes, masked for per-sample lengths.

    logits: [B, T, U+1, V]; labels: [B, U] int32.
    """

    def fn(lg, lab, t_len, u_len):
        b, T, U1, V = lg.shape
        U = U1 - 1
        logp = jax.nn.log_softmax(lg.astype(jnp.float32), axis=-1)
        blank_lp = logp[..., blank]                       # [B, T, U+1]
        lab_i = lab.astype(jnp.int32)
        # emit log-prob at (t, u): P(label_u | t, u), u in [0, U)
        emit_lp = jnp.take_along_axis(
            logp[:, :, :U, :], lab_i[:, None, :, None], axis=-1)[..., 0]
        if fastemit_lambda > 0.0:
            # FastEmit (arXiv:2010.11148): scale the EMISSION-arc gradient
            # by (1 + lambda) — identity forward, so the reported loss is
            # the plain RNN-T nll, but training pushes emissions earlier.
            @jax.custom_vjp
            def _scale_grad(v):
                return v

            _scale_grad.defvjp(lambda v: (v, None),
                               lambda _, g: ((1.0 + fastemit_lambda) * g,))
            emit_lp = _scale_grad(emit_lp)
        neg = jnp.float32(-1e30)

        def time_step(alpha_prev, t):
            # alpha_prev: [B, U+1] at time t-1 (or init); returns alpha at t
            from_left = alpha_prev + blank_lp[:, t - 1, :]

            def u_step(carry, u):
                # carry: alpha[t, u-1]; emit from (t, u-1) -> (t, u)
                val = jnp.where(
                    u == 0, from_left[:, 0],
                    jnp.logaddexp(
                        from_left[jnp.arange(b), jnp.minimum(u, U1 - 1)],
                        carry + jnp.where(
                            u > 0,
                            emit_lp[jnp.arange(b), t,
                                    jnp.maximum(u - 1, 0)], neg)))
                return val, val

            _, cols = jax.lax.scan(u_step, jnp.full((b,), neg),
                                   jnp.arange(U1))
            return cols.T, None                            # [B, U+1]

        # t = 0 row: only emissions along u
        def u0_step(carry, u):
            val = jnp.where(u == 0, 0.0,
                            carry + emit_lp[jnp.arange(b), 0,
                                            jnp.maximum(u - 1, 0)])
            return val, val

        _, row0 = jax.lax.scan(u0_step, jnp.zeros((b,)), jnp.arange(U1))
        alpha0 = row0.T

        def scan_t(alpha, t):
            nxt, _ = time_step(alpha, t)
            return nxt, nxt

        alpha_T, rows = jax.lax.scan(scan_t, alpha0, jnp.arange(1, T))
        all_rows = jnp.concatenate([alpha0[None], rows], 0)  # [T, B, U+1]
        t_idx = (t_len - 1).astype(jnp.int32)
        u_idx = u_len.astype(jnp.int32)
        final = all_rows[t_idx, jnp.arange(b), u_idx]
        final_blank = blank_lp[jnp.arange(b), t_idx, u_idx]
        nll = -(final + final_blank)
        return nll

    out = apply(fn, _t(logits), _t(labels), _t(logit_lengths),
                _t(label_lengths), name="rnnt_loss")
    return _reduce(out, reduction)


# -- sparse attention -------------------------------------------------------

def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns,
                     key_padding_mask=None, attn_mask=None, name=None):
    """Block-sparse attention with a CSR pattern (reference
    sparse_attention op, CUDA-only there). TPU-native: pad each query
    row's column set to the max row degree and GATHER the K/V rows —
    O(S * max_nnz) compute/memory, batched on the MXU.

    query/key/value: [B, H, S, D]; offset: [B, H, S+1]; columns:
    [B, H, nnz] (both int32).
    """
    off = np.asarray(sparse_csr_offset._data if isinstance(
        sparse_csr_offset, Tensor) else sparse_csr_offset)
    col = np.asarray(sparse_csr_columns._data if isinstance(
        sparse_csr_columns, Tensor) else sparse_csr_columns)
    b, h, s1 = off.shape
    s = s1 - 1
    deg = off[..., 1:] - off[..., :-1]                 # [B, H, S]
    max_deg = int(deg.max()) if deg.size else 1
    # padded per-row column index + validity mask (host-side: the CSR
    # pattern is static metadata, same stance as the reference op's host
    # descriptor)
    cols_pad = np.zeros((b, h, s, max_deg), np.int32)
    mask_pad = np.zeros((b, h, s, max_deg), bool)
    for bi in range(b):
        for hi in range(h):
            for si in range(s):
                lo, hi_ = off[bi, hi, si], off[bi, hi, si + 1]
                n = hi_ - lo
                cols_pad[bi, hi, si, :n] = col[bi, hi, lo:hi_]
                mask_pad[bi, hi, si, :n] = True
    cols_j = jnp.asarray(cols_pad)
    mask_j = jnp.asarray(mask_pad)

    def fn(q, k, v):
        d = q.shape[-1]
        kg = jnp.take_along_axis(k[:, :, None], cols_j[..., None], axis=3)
        vg = jnp.take_along_axis(v[:, :, None], cols_j[..., None], axis=3)
        logits = jnp.einsum("bhsd,bhsnd->bhsn", q, kg,
                            preferred_element_type=jnp.float32)
        logits = logits / math.sqrt(d)
        logits = jnp.where(mask_j, logits, -1e30)
        p = jax.nn.softmax(logits, axis=-1)
        return jnp.einsum("bhsn,bhsnd->bhsd", p.astype(v.dtype), vg)

    return apply(fn, _t(query), _t(key), _t(value), name="sparse_attention")


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None, name=None):
    """Batch Levenshtein distance (reference nn/functional/loss.py:451 —
    phi edit_distance kernel over LoD or padded int sequences).

    input [B, T1] / label [B, T2] int token ids, optional per-row lengths
    [B]. TPU-native DP: one lax.scan over hypothesis positions whose body
    scans the reference row with a carried left-cell — static [B, T2+1]
    state, variable lengths handled by capturing the row the moment
    i == input_length (per batch row), never by dynamic shapes.

    Returns (distance [B, 1] float32, sequence_num [1] int64-like).
    Non-differentiable (integer op), matching the reference.
    """
    hyp = _arr(input).astype(jnp.int32)
    ref = _arr(label).astype(jnp.int32)
    if hyp.ndim == 1:
        hyp = hyp[None]
    if ref.ndim == 1:
        ref = ref[None]
    b, t1 = hyp.shape
    t2 = ref.shape[1]
    len1 = (jnp.full((b,), t1, jnp.int32) if input_length is None
            else _arr(input_length).astype(jnp.int32).reshape(b))
    len2 = (jnp.full((b,), t2, jnp.int32) if label_length is None
            else _arr(label_length).astype(jnp.int32).reshape(b))

    if ignored_tokens:
        ign = jnp.asarray(list(ignored_tokens), jnp.int32)

        def compact(seq, length):
            pos = jnp.arange(seq.shape[1], dtype=jnp.int32)[None, :]
            valid = (pos < length[:, None]) & ~jnp.isin(seq, ign)
            order = jnp.argsort(~valid, axis=1, stable=True)
            return (jnp.take_along_axis(seq, order, axis=1),
                    valid.sum(axis=1).astype(jnp.int32))

        hyp, len1 = compact(hyp, len1)
        ref, len2 = compact(ref, len2)

    def fn(hyp, ref, len1, len2):
        row0 = jnp.broadcast_to(jnp.arange(t2 + 1, dtype=jnp.float32),
                                (b, t2 + 1))

        def outer(carry, i):
            prev, result = carry  # prev: [B, T2+1] row i-1; result: [B]
            hc = jnp.take_along_axis(hyp, (i - 1)[None, None].repeat(b, 0),
                                     axis=1)[:, 0]          # hyp[:, i-1]

            def inner(left, js):
                up, diag, rc = js                            # [B] each
                cost = (hc != rc).astype(jnp.float32)
                val = jnp.minimum(jnp.minimum(up + 1.0, left + 1.0),
                                  diag + cost)
                return val, val

            left0 = i.astype(jnp.float32) * jnp.ones((b,), jnp.float32)
            _, cols = jax.lax.scan(
                inner, left0,
                (prev[:, 1:].T, prev[:, :-1].T, ref.T))
            row = jnp.concatenate([left0[:, None], cols.T], axis=1)
            # capture D[len1, len2] the iteration the row index hits len1
            at_end = jnp.take_along_axis(row, len2[:, None], axis=1)[:, 0]
            result = jnp.where(len1 == i, at_end, result)
            return (row, result), None

        # len1 == 0 rows: distance is len2
        result0 = len2.astype(jnp.float32)
        (_, result), _ = jax.lax.scan(
            outer, (row0, result0), jnp.arange(1, t1 + 1, dtype=jnp.int32))
        if normalized:
            result = result / jnp.maximum(len2.astype(jnp.float32), 1.0)
        return result[:, None]

    dist = Tensor(fn(hyp, ref, len1, len2))
    dist.stop_gradient = True
    return dist, Tensor(jnp.asarray([b], jnp.int32))
