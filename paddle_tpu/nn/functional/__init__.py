"""nn.functional (reference: python/paddle/nn/functional/*).

Every function is a thin eager op over a pure jax forward; XLA fuses the
elementwise chains into the surrounding matmuls/convs (the role the
reference's hand-fused CUDA ops in operators/fused/ play is taken by the
compiler + the Pallas kernels in paddle_tpu/ops/pallas_ops.py).
"""
from __future__ import annotations

import math
import os
from functools import partial

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...core.dispatch import apply
from ...core.dtype import convert_dtype
from ...core import random as _rng
from ...autograd import tape

__all__ = [
    # activations
    "relu", "relu6", "gelu", "sigmoid", "tanh", "softmax", "log_softmax",
    "leaky_relu", "elu", "selu", "celu", "silu", "swish", "mish",
    "hardswish", "hardsigmoid", "hardtanh", "hardshrink", "softshrink",
    "tanhshrink", "softplus", "softsign", "prelu", "rrelu", "glu",
    "gumbel_softmax", "maxout", "thresholded_relu", "log_sigmoid",
    # linear / conv / pool
    "linear", "conv1d", "conv2d", "conv3d", "conv1d_transpose",
    "conv2d_transpose", "conv3d_transpose",
    "max_pool1d", "max_pool2d", "max_pool3d", "avg_pool1d", "avg_pool2d",
    "avg_pool3d", "adaptive_avg_pool1d", "adaptive_avg_pool2d",
    "adaptive_avg_pool3d", "adaptive_max_pool2d",
    # norm
    "batch_norm", "layer_norm", "instance_norm", "group_norm", "normalize",
    "local_response_norm", "rms_norm",
    # dropout & co
    "dropout", "dropout2d", "dropout3d", "alpha_dropout",
    # embedding / sparse
    "embedding", "one_hot",
    # losses
    "cross_entropy", "softmax_with_cross_entropy", "binary_cross_entropy",
    "binary_cross_entropy_with_logits", "mse_loss", "l1_loss", "nll_loss",
    "smooth_l1_loss", "kl_div", "margin_ranking_loss", "hinge_embedding_loss",
    "cosine_embedding_loss", "ctc_loss", "log_loss", "square_error_cost",
    "sigmoid_focal_loss", "triplet_margin_loss", "poisson_nll_loss",
    # attention / transformer
    "scaled_dot_product_attention", "pad", "interpolate", "upsample",
    "pixel_shuffle", "pixel_unshuffle", "grid_sample", "affine_grid",
    "cosine_similarity", "label_smooth", "sequence_mask", "temporal_shift",
    "npair_loss", "fold", "channel_shuffle",
]


# ---------------------------------------------------------------------------
# activations
# ---------------------------------------------------------------------------

def _unary(fn, name):
    def op(x, name_=None):
        return apply(fn, x, name=name)

    op.__name__ = name
    return op


relu = _unary(lambda a: jnp.maximum(a, 0), "relu")
relu6 = _unary(lambda a: jnp.clip(a, 0, 6), "relu6")
sigmoid = _unary(jax.nn.sigmoid, "sigmoid")
tanh = _unary(jnp.tanh, "tanh")
silu = _unary(jax.nn.silu, "silu")
softsign = _unary(jax.nn.soft_sign, "softsign")
log_sigmoid = _unary(jax.nn.log_sigmoid, "log_sigmoid")
tanhshrink = _unary(lambda a: a - jnp.tanh(a), "tanhshrink")
mish = _unary(lambda a: a * jnp.tanh(jax.nn.softplus(a)), "mish")
hardswish = _unary(lambda a: a * jnp.clip(a + 3, 0, 6) / 6, "hardswish")


def gelu(x, approximate=False, name=None):
    return apply(
        lambda a: jax.nn.gelu(a, approximate=approximate), x, name="gelu"
    )


def softmax(x, axis=-1, dtype=None, name=None):
    def fn(a):
        if dtype is not None:
            a = a.astype(convert_dtype(dtype))
        return jax.nn.softmax(a, axis=axis)

    return apply(fn, x, name="softmax")


def log_softmax(x, axis=-1, dtype=None, name=None):
    def fn(a):
        if dtype is not None:
            a = a.astype(convert_dtype(dtype))
        return jax.nn.log_softmax(a, axis=axis)

    return apply(fn, x, name="log_softmax")


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply(
        lambda a: jnp.where(a >= 0, a, negative_slope * a), x, name="leaky_relu"
    )


def elu(x, alpha=1.0, name=None):
    return apply(lambda a: jax.nn.elu(a, alpha), x, name="elu")


def selu(
    x,
    scale=1.0507009873554804934193349852946,
    alpha=1.6732632423543772848170429916717,
    name=None,
):
    return apply(
        lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)), x, name="selu"
    )


def celu(x, alpha=1.0, name=None):
    return apply(lambda a: jax.nn.celu(a, alpha), x, name="celu")


def swish(x, name=None):
    return silu(x)


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return apply(
        lambda a: jnp.clip(slope * a + offset, 0, 1), x, name="hardsigmoid"
    )


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply(lambda a: jnp.clip(a, min, max), x, name="hardtanh")


def hardshrink(x, threshold=0.5, name=None):
    return apply(
        lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0), x, name="hardshrink"
    )


def softshrink(x, threshold=0.5, name=None):
    return apply(
        lambda a: jnp.where(
            a > threshold, a - threshold, jnp.where(a < -threshold, a + threshold, 0.0)
        ),
        x,
        name="softshrink",
    )


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return apply(
        lambda a: jnp.where(
            a * beta > threshold, a, (1.0 / beta) * jax.nn.softplus(beta * a)
        ),
        x,
        name="softplus",
    )


def prelu(x, weight, data_format="NCHW", name=None):
    def fn(a, w):
        if w.size == 1:
            wb = w.reshape(())
        else:
            shape = [1] * a.ndim
            ch_axis = 1 if data_format.startswith("NC") else a.ndim - 1
            shape[ch_axis] = w.size
            wb = w.reshape(shape)
        return jnp.where(a >= 0, a, wb * a)

    return apply(fn, x, weight, name="prelu")


def rrelu(x, lower=1.0 / 8, upper=1.0 / 3, training=True, name=None):
    if training:
        key = _rng.next_key()

        def fn(a):
            slope = jax.random.uniform(key, a.shape, jnp.float32, lower, upper).astype(a.dtype)
            return jnp.where(a >= 0, a, slope * a)

        return apply(fn, x, name="rrelu")
    mid = (lower + upper) / 2
    return leaky_relu(x, mid)


def thresholded_relu(x, threshold=1.0, name=None):
    return apply(
        lambda a: jnp.where(a > threshold, a, 0.0), x, name="thresholded_relu"
    )


def glu(x, axis=-1, name=None):
    def fn(a):
        a1, a2 = jnp.split(a, 2, axis=axis)
        return a1 * jax.nn.sigmoid(a2)

    return apply(fn, x, name="glu")


def maxout(x, groups, axis=1, name=None):
    def fn(a):
        ax = axis % a.ndim
        c = a.shape[ax]
        new_shape = a.shape[:ax] + (c // groups, groups) + a.shape[ax + 1:]
        return jnp.max(a.reshape(new_shape), axis=ax + 1)

    return apply(fn, x, name="maxout")


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    key = _rng.next_key()

    def fn(a):
        g = jax.random.gumbel(key, a.shape, a.dtype)
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            # tie-safe straight-through one-hot of the argmax
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y)
            y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis, inplace=False) \
                if hasattr(jnp, "put_along_axis") else \
                (jnp.arange(a.shape[axis]).reshape([-1 if i == (axis % a.ndim) else 1 for i in range(a.ndim)]) == idx).astype(a.dtype)
            y = y_hard + y - jax.lax.stop_gradient(y)
        return y

    return apply(fn, x, name="gumbel_softmax")


# ---------------------------------------------------------------------------
# linear / conv / pool
# ---------------------------------------------------------------------------

def linear(x, weight, bias=None, name=None):
    """y = x @ W + b, W shaped [in, out] (reference convention,
    python/paddle/nn/functional/common.py:1783)."""
    if bias is None:
        return apply(lambda a, w: a @ w, x, weight, name="linear")
    return apply(lambda a, w, b: a @ w + b, x, weight, bias, name="linear")


def _tuplize(v, n):
    if isinstance(v, (list, tuple)):
        if len(v) == n:
            return tuple(int(i) for i in v)
        if len(v) == 1:
            return tuple(int(v[0]) for _ in range(n))
        return tuple(int(i) for i in v)
    return tuple(int(v) for _ in range(n))


def _conv_padding(padding, nd, strides=None):
    """Normalize paddle padding spec → lax padding list [(lo,hi)]*nd or str."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * nd
    padding = list(padding)
    if len(padding) == nd and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * nd:
        # [before0, after0, before1, after1...] paddle flat form
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(nd)]
    if all(isinstance(p, (list, tuple)) for p in padding):
        return [tuple(p) for p in padding]
    raise ValueError(f"bad padding {padding}")


def _conv_nd(x, weight, bias, stride, padding, dilation, groups, nd, data_format, transpose=False, output_padding=0):
    strides = _tuplize(stride, nd)
    dils = _tuplize(dilation, nd)
    pad = _conv_padding(padding, nd)
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    spatial = "DHW"[3 - nd:] if nd <= 3 else None
    if nd == 1:
        spec_in = "NCH" if not channel_last else "NHC"
        spec_k = "OIH"
        spec_out = spec_in
    elif nd == 2:
        spec_in = "NCHW" if not channel_last else "NHWC"
        spec_k = "OIHW"
        spec_out = spec_in
    else:
        spec_in = "NCDHW" if not channel_last else "NDHWC"
        spec_k = "OIDHW"
        spec_out = spec_in
    dn = jax.lax.conv_dimension_numbers((1,) * (nd + 2), (1,) * (nd + 2), (spec_in, spec_k, spec_out))

    def fn(a, w, *maybe_b):
        # AMP convention: the weight dtype defines compute precision, so a
        # fp32 input meeting bf16 params (model.bfloat16()) rides the MXU in
        # bf16 instead of erroring in lax.conv_general_dilated.
        if a.dtype != w.dtype and jnp.issubdtype(w.dtype, jnp.floating):
            a = a.astype(w.dtype)
        if transpose:
            opad = _tuplize(output_padding, nd)
            if isinstance(pad, str):
                pads = pad
            else:
                # conv_transpose pad semantics: effective output crop
                k_eff = [dils[i] * (w.shape[2 + i] - 1) + 1 for i in range(nd)]
                pads = [
                    (k_eff[i] - 1 - pad[i][0], k_eff[i] - 1 - pad[i][1] + opad[i])
                    for i in range(nd)
                ]
            if groups > 1:
                # w is [cin, cout/g, k...]; the equivalent forward conv
                # needs [cout, cin/g, k...] with the swap done PER GROUP
                # (a plain swapaxes mixes channels across groups and
                # trips conv_general_dilated's feature-count check)
                ci, cog = w.shape[0], w.shape[1]
                wt = w.reshape((groups, ci // groups, cog) + w.shape[2:])
                wt = jnp.swapaxes(wt, 1, 2).reshape(
                    (groups * cog, ci // groups) + w.shape[2:])
            else:
                wt = jnp.swapaxes(w, 0, 1)  # I O ... for transpose
            wt = jnp.flip(wt, axis=tuple(range(2, 2 + nd)))
            out = jax.lax.conv_general_dilated(
                a,
                wt,
                window_strides=(1,) * nd,
                padding=pads if not isinstance(pads, str) else pads,
                lhs_dilation=strides,
                rhs_dilation=dils,
                dimension_numbers=dn,
                feature_group_count=groups,
            )
        else:
            out = jax.lax.conv_general_dilated(
                a,
                w,
                window_strides=strides,
                padding=pad,
                rhs_dilation=dils,
                dimension_numbers=dn,
                feature_group_count=groups,
            )
        if maybe_b:
            b = maybe_b[0]
            shape = [1] * out.ndim
            ch_axis = 1 if not channel_last else out.ndim - 1
            shape[ch_axis] = b.size
            out = out + b.reshape(shape)
        return out

    args = (x, weight) if bias is None else (x, weight, bias)
    return apply(fn, *args, name=f"conv{nd}d{'_transpose' if transpose else ''}")


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCL", name=None):
    fmt = "NLC" if data_format == "NLC" else "NCH"
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 1, fmt)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCHW", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 2, data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCDHW", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 3, data_format)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, dilation=1, groups=1, data_format="NCL", name=None):
    fmt = "NLC" if data_format == "NLC" else "NCH"
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 1, fmt, transpose=True, output_padding=output_padding)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, dilation=1, groups=1, data_format="NCHW", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 2, data_format, transpose=True, output_padding=output_padding)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, dilation=1, groups=1, data_format="NCDHW", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 3, data_format, transpose=True, output_padding=output_padding)


def _pool_nd(x, kernel, stride, padding, nd, op, data_format, ceil_mode=False, exclusive=True):
    """exclusive=True (paddle default): padded zeros are NOT counted in avg
    denominators; ceil_mode pads the high side so partial windows are kept."""
    ks = _tuplize(kernel, nd)
    st = _tuplize(stride if stride is not None else kernel, nd)
    pad = _conv_padding(padding, nd)
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    if isinstance(pad, str):
        if pad == "VALID":
            pad = [(0, 0)] * nd
        else:  # "SAME": resolve numerically so every downstream branch
               # (ceil extras, inclusive divisors) sees explicit pairs
            spatial_d = x.shape[1:-1] if channel_last else x.shape[2:]
            pad = []
            for i in range(nd):
                total = max((-(-spatial_d[i] // st[i]) - 1) * st[i]
                            + ks[i] - spatial_d[i], 0)
                pad.append((total // 2, total - total // 2))
    pad_base = list(pad)  # pre-ceil pads
    if ceil_mode:
        spatial = x.shape[1:-1] if channel_last else x.shape[2:]
        pad = [
            (lo, hi + _ceil_extra(spatial[i], ks[i], st[i], lo, hi))
            for i, (lo, hi) in enumerate(pad)
        ]
    if channel_last:
        window = (1,) + ks + (1,)
        strides = (1,) + st + (1,)
        pads = [(0, 0)] + list(pad) + [(0, 0)]
    else:
        window = (1, 1) + ks
        strides = (1, 1) + st
        pads = [(0, 0), (0, 0)] + list(pad)

    def fn(a):
        if op == "max":
            init = -jnp.inf if jnp.issubdtype(a.dtype, jnp.floating) else jnp.iinfo(a.dtype).min
            return jax.lax.reduce_window(a, init, jax.lax.max, window, strides, pads)
        # avg / sum
        s = jax.lax.reduce_window(a, 0.0, jax.lax.add, window, strides, pads)
        if op == "sum":
            return s   # divisor_override applies its own divisor
        if not exclusive and not ceil_mode:
            # every window's padded extent is exactly k (PoolOutputSize
            # guarantees hstart+k <= H+pad for floor-mode windows)
            return s / float(np.prod(ks))
        if exclusive:
            ones = jnp.ones_like(a)
            cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window,
                                        strides, pads)
            # a ceil window fully inside padding has zero valid elements;
            # the reference divides 0 by a non-positive extent yielding
            # +-0 — clamp to keep the same finite value without the NaN
            return s / jnp.maximum(cnt, 1.0)
        # inclusive + ceil: reference pooling.cc:84 — the divisor is the
        # window clipped to input + ORIGINAL pad on the high side (left
        # pad rows count, the ceil extra does not). Static per-axis
        # extents broadcast-multiplied.
        spatial = a.shape[1:-1] if channel_last else a.shape[2:]
        div = None
        for i in range(nd):
            lo, hi = pad[i]
            hi0 = pad_base[i][1]              # pre-ceil high pad
            out_i = (spatial[i] + lo + hi - ks[i]) // st[i] + 1
            starts = np.arange(out_i) * st[i] - lo
            ends = np.minimum(starts + ks[i], spatial[i] + hi0)
            ext = np.maximum((ends - starts).astype(np.float32), 1.0)
            shape = [1] * a.ndim
            shape[(1 if channel_last else 2) + i] = out_i
            e = jnp.asarray(ext).reshape(shape)
            div = e if div is None else div * e
        return s / div

    return apply(fn, x, name=f"{op}_pool{nd}d")


def _ceil_extra(size, k, s, lo, hi):
    """Extra high-side padding so the output size matches ceil division.

    PADDLE semantics (the parity contract): plain ceil division —
    reference PoolOutputSize (phi/kernels/funcs/pooling.h:368) KEEPS a
    window that starts inside the right padding. torch drops it; the
    torch-differential tests restrict ceil comparisons to shapes where
    the two agree."""
    floor_out = (size + lo + hi - k) // s + 1
    ceil_out = -((size + lo + hi - k) // -s) + 1
    return (ceil_out - floor_out) * s


def _max_pool_mask(x, ks, st, pads_2d):
    """Window-argmax indices (global H*W flat index, paddle return_mask
    semantics) via conv_general_dilated_patches."""

    def fn(a):
        n, c, h, w = a.shape
        patches = jax.lax.conv_general_dilated_patches(
            a, ks, st, pads_2d, dimension_numbers=("NCHW", "OIHW", "NCHW")
        )  # [N, C*kh*kw, OH, OW]
        oh, ow = patches.shape[2], patches.shape[3]
        patches = patches.reshape(n, c, ks[0] * ks[1], oh, ow)
        # padded cells (patches zero-fills them) must not win the argmax
        starts_i = jnp.arange(oh) * st[0] - pads_2d[0][0]
        starts_j = jnp.arange(ow) * st[1] - pads_2d[1][0]
        ri = starts_i[:, None] + jnp.arange(ks[0])[None, :]      # [oh, kh]
        rj = starts_j[:, None] + jnp.arange(ks[1])[None, :]      # [ow, kw]
        vi = (ri >= 0) & (ri < h)
        vj = (rj >= 0) & (rj < w)
        valid = vi[:, None, :, None] & vj[None, :, None, :]      # [oh,ow,kh,kw]
        valid = valid.transpose(2, 3, 0, 1).reshape(
            1, 1, ks[0] * ks[1], oh, ow)
        patches = jnp.where(valid, patches, -jnp.inf)
        arg = jnp.argmax(patches, axis=2)  # in-window flat idx
        # convert to global flat H*W index
        base_i = starts_i[None, None, :, None]
        base_j = starts_j[None, None, None, :]
        di = arg // ks[1]
        dj = arg % ks[1]
        gi = jnp.clip(base_i + di, 0, h - 1)
        gj = jnp.clip(base_j + dj, 0, w - 1)
        return (gi * w + gj).astype(jnp.int32)

    return Tensor(fn(x._data))


def max_pool1d(x, kernel_size, stride=None, padding=0, ceil_mode=False, return_mask=False, data_format="NCL", name=None):
    return _pool_nd(x, kernel_size, stride, padding, 1, "max",
                    "NLC" if data_format == "NLC" else "NCH",
                    ceil_mode=ceil_mode)


def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False, return_mask=False, data_format="NCHW", name=None):
    out = _pool_nd(x, kernel_size, stride, padding, 2, "max", data_format, ceil_mode=ceil_mode)
    if return_mask:
        ks = _tuplize(kernel_size, 2)
        st = _tuplize(stride if stride is not None else kernel_size, 2)
        pad = _conv_padding(padding, 2)
        if isinstance(pad, str):
            pad = [(0, 0), (0, 0)]
        channel_last = data_format == "NHWC"
        xm = x.transpose([0, 3, 1, 2]) if channel_last else x
        if ceil_mode:
            # the mask must cover the same (possibly ceil-extended)
            # window grid as the pooled output
            spatial = xm.shape[2:]
            pad = [(lo, hi + _ceil_extra(spatial[i], ks[i], st[i], lo, hi))
                   for i, (lo, hi) in enumerate(pad)]
        mask = _max_pool_mask(xm, ks, st, pad)
        if channel_last:
            mask = mask.transpose([0, 2, 3, 1])
        return out, mask
    return out


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False, return_mask=False, data_format="NCDHW", name=None):
    return _pool_nd(x, kernel_size, stride, padding, 3, "max", data_format, ceil_mode=ceil_mode)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False, data_format="NCL", name=None):
    return _pool_nd(x, kernel_size, stride, padding, 1, "avg",
                    "NLC" if data_format == "NLC" else "NCH",
                    ceil_mode=ceil_mode, exclusive=exclusive)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    if divisor_override:
        # window SUM / divisor: rescaling an inclusive average is wrong
        # whenever ceil_mode clips a window (its inclusive divisor is the
        # clipped extent, not k^2)
        s = _pool_nd(x, kernel_size, stride, padding, 2, "sum", data_format,
                     ceil_mode=ceil_mode)
        return s * (1.0 / float(divisor_override))
    return _pool_nd(x, kernel_size, stride, padding, 2, "avg", data_format, ceil_mode=ceil_mode, exclusive=exclusive)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    return _pool_nd(x, kernel_size, stride, padding, 3, "avg", data_format, ceil_mode=ceil_mode, exclusive=exclusive)


def _adaptive_pool_core(a, out_sizes, op, spatial_start=2):
    """Pure-array adaptive pooling (shared by the adaptive_*_pool ops and
    interpolate's 'area' mode): per-axis reshape-reduce when divisible,
    else explicit [floor(j*n/os), ceil((j+1)*n/os)) window gather."""
    out = a
    for i, os in enumerate(out_sizes):
        ax = spatial_start + i
        n = out.shape[ax]
        if os is None:
            continue
        if n % os == 0:
            k = n // os
            new_shape = out.shape[:ax] + (os, k) + out.shape[ax + 1:]
            r = out.reshape(new_shape)
            out = jnp.max(r, axis=ax + 1) if op == "max" else jnp.mean(r, axis=ax + 1)
        else:
            idx = [
                (int(math.floor(j * n / os)), int(math.ceil((j + 1) * n / os)))
                for j in range(os)
            ]
            slices = []
            for lo, hi in idx:
                sl = jax.lax.slice_in_dim(out, lo, hi, axis=ax)
                red = jnp.max(sl, axis=ax, keepdims=True) if op == "max" else jnp.mean(sl, axis=ax, keepdims=True)
                slices.append(red)
            out = jnp.concatenate(slices, axis=ax)
    return out


def _adaptive_pool(x, output_size, nd, op, data_format):
    out_sizes = _tuplize(output_size, nd)
    # channel-last: spatial axes start right after batch
    start = 1 if data_format in ("NHWC", "NLC", "NDHWC") else 2

    def fn(a):
        return _adaptive_pool_core(a, out_sizes, op, spatial_start=start)

    return apply(fn, x, name=f"adaptive_{op}_pool{nd}d")


def adaptive_avg_pool1d(x, output_size, data_format="NCL", name=None):
    return _adaptive_pool(x, output_size, 1, "avg",
                          "NLC" if data_format == "NLC" else "NCH")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, 2, "avg", data_format)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(x, output_size, 3, "avg", data_format)


def adaptive_max_pool2d(x, output_size, return_mask=False,
                        data_format="NCHW", name=None):
    if not return_mask:
        return _adaptive_pool(x, output_size, 2, "max", data_format)
    # mask = flat H*W index of each window's argmax (reference
    # max_pool_with_index semantics)
    out_sizes = _tuplize(output_size, 2)
    channel_last = data_format == "NHWC"

    def fn(a):
        if channel_last:
            a = jnp.moveaxis(a, -1, 1)   # NHWC -> NCHW internally
        N, C, H, W = a.shape
        oh = out_sizes[0] if out_sizes[0] is not None else H
        ow = out_sizes[1] if out_sizes[1] is not None else W
        out_rows, idx_rows = [], []
        for i in range(oh):
            h0, h1 = (i * H) // oh, -((-(i + 1) * H) // oh)
            out_cols, idx_cols = [], []
            for j in range(ow):
                w0, w1 = (j * W) // ow, -((-(j + 1) * W) // ow)
                win = a[:, :, h0:h1, w0:w1]
                kh, kw = h1 - h0, w1 - w0
                flat = win.reshape(N, C, kh * kw)
                out_cols.append(jnp.max(flat, axis=-1))
                am = jnp.argmax(flat, axis=-1)
                gidx = (h0 + am // kw) * W + (w0 + am % kw)
                idx_cols.append(gidx)
            out_rows.append(jnp.stack(out_cols, axis=-1))
            idx_rows.append(jnp.stack(idx_cols, axis=-1))
        out = jnp.stack(out_rows, axis=-2)               # [N, C, oh, ow]
        idx = jnp.stack(idx_rows, axis=-2).astype(jnp.int32)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
            idx = jnp.moveaxis(idx, 1, -1)
        return out, idx

    return apply(fn, x, name="adaptive_max_pool2d")


# ---------------------------------------------------------------------------
# normalization
# ---------------------------------------------------------------------------

def layer_norm_arrays(a, w, b, naxes=(-1,), epsilon=1e-5):
    """Array-level LayerNorm body — THE normalization arithmetic of
    F.layer_norm (fp32 stats via jnp.mean/jnp.var).  Exposed so compiled
    paths that must match Layer-based models bitwise (the serving
    engine's final LN vs `GPTModel.ln_f`) share this exact op sequence
    instead of hand-copying it."""
    mu = jnp.mean(a.astype(jnp.float32), axis=naxes, keepdims=True)
    var = jnp.var(a.astype(jnp.float32), axis=naxes, keepdims=True)
    out = (a.astype(jnp.float32) - mu) * jax.lax.rsqrt(var + epsilon)
    out = out.astype(a.dtype)
    if w is not None:
        out = out * w
    if b is not None:
        out = out + b
    return out


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5, name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    naxes = tuple(range(-len(normalized_shape), 0))

    if (len(normalized_shape) == 1 and weight is not None
            and bias is not None
            and os.environ.get("PTPU_PALLAS_LN") == "1"):
        # opt-in fused Pallas path (single-pass row stats; SURVEY §7
        # phase 7). Flag-gated until the on-chip A/B lands — the XLA
        # fusion below is already good on this op.
        from ...ops.pallas_ops import fused_layernorm_arrays, ln_geometry_ok

        n_rows = int(math.prod(x.shape[:-1])) if len(x.shape) > 1 else 1
        if ln_geometry_ok(n_rows, int(x.shape[-1])):
            # dispatch under the SAME op name so AMP's black list treats
            # both paths identically (flipping the A/B flag must not
            # change autocast behavior)
            return apply(
                lambda a, w, b: fused_layernorm_arrays(a, w, b, eps=epsilon),
                x, weight, bias, name="layer_norm")

    def fn(a, *wb):
        i = 0
        w = b = None
        if weight is not None:
            w = wb[i]
            i += 1
        if bias is not None:
            b = wb[i]
        return layer_norm_arrays(a, w, b, naxes, epsilon)

    args = [x]
    if weight is not None:
        args.append(weight)
    if bias is not None:
        args.append(bias)
    return apply(fn, *args, name="layer_norm")


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    """RMSNorm — capability-gap fill (absent in reference; table stakes for
    modern LLM families)."""

    def fn(a, *w):
        var = jnp.mean(jnp.square(a.astype(jnp.float32)), axis=-1, keepdims=True)
        out = (a.astype(jnp.float32) * jax.lax.rsqrt(var + epsilon)).astype(a.dtype)
        if w:
            out = out * w[0]
        return out

    args = (x,) if weight is None else (x, weight)
    return apply(fn, *args, name="rms_norm")


def batch_norm(
    x,
    running_mean,
    running_var,
    weight=None,
    bias=None,
    training=False,
    momentum=0.9,
    epsilon=1e-5,
    data_format="NCHW",
    use_global_stats=None,
    name=None,
):
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    use_batch_stats = training and not use_global_stats

    def reduce_axes(a):
        ch_axis = a.ndim - 1 if channel_last else 1
        return tuple(i for i in range(a.ndim) if i != ch_axis), ch_axis

    if use_batch_stats:
        def fn(a, *wb):
            axes, ch = reduce_axes(a)
            af = a.astype(jnp.float32)
            mu = jnp.mean(af, axis=axes)
            var = jnp.var(af, axis=axes)
            shape = [1] * a.ndim
            shape[ch] = a.shape[ch]
            out = (af - mu.reshape(shape)) * jax.lax.rsqrt(var.reshape(shape) + epsilon)
            out = out.astype(a.dtype)
            i = 0
            if weight is not None:
                out = out * wb[i].reshape(shape)
                i += 1
            if bias is not None:
                out = out + wb[i].reshape(shape)
            return out, mu, var

        args = [x]
        if weight is not None:
            args.append(weight)
        if bias is not None:
            args.append(bias)
        out, mu, var = apply(fn, *args, name="batch_norm")
        # update running stats in place (eager buffer semantics; under jit
        # tracing the buffer's ._data becomes a tracer captured as an output)
        with tape.no_grad():
            rm = running_mean._data.astype(jnp.float32)
            rv = running_var._data.astype(jnp.float32)
            running_mean._data = (momentum * rm + (1 - momentum) * mu._data).astype(running_mean.dtype)
            running_var._data = (momentum * rv + (1 - momentum) * var._data).astype(running_var.dtype)
        return out

    def fn_eval(a, m, v, *wb):
        ch = a.ndim - 1 if channel_last else 1
        shape = [1] * a.ndim
        shape[ch] = a.shape[ch]
        out = (a.astype(jnp.float32) - m.astype(jnp.float32).reshape(shape)) * jax.lax.rsqrt(
            v.astype(jnp.float32).reshape(shape) + epsilon
        )
        out = out.astype(a.dtype)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        return out

    args = [x, running_mean, running_var]
    if weight is not None:
        args.append(weight)
    if bias is not None:
        args.append(bias)
    return apply(fn_eval, *args, name="batch_norm")


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None, use_input_stats=True, momentum=0.9, eps=1e-5, data_format="NCHW", name=None):
    def fn(a, *wb):
        axes = tuple(range(2, a.ndim))
        af = a.astype(jnp.float32)
        mu = jnp.mean(af, axis=axes, keepdims=True)
        var = jnp.var(af, axis=axes, keepdims=True)
        out = ((af - mu) * jax.lax.rsqrt(var + eps)).astype(a.dtype)
        i = 0
        if weight is not None:
            shape = [1, a.shape[1]] + [1] * (a.ndim - 2)
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            shape = [1, a.shape[1]] + [1] * (a.ndim - 2)
            out = out + wb[i].reshape(shape)
        return out

    args = [x]
    if weight is not None:
        args.append(weight)
    if bias is not None:
        args.append(bias)
    return apply(fn, *args, name="instance_norm")


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None, data_format="NCHW", name=None):
    def fn(a, *wb):
        n, c = a.shape[0], a.shape[1]
        g = num_groups
        rest = a.shape[2:]
        r = a.reshape((n, g, c // g) + rest).astype(jnp.float32)
        axes = tuple(range(2, r.ndim))
        mu = jnp.mean(r, axis=axes, keepdims=True)
        var = jnp.var(r, axis=axes, keepdims=True)
        out = ((r - mu) * jax.lax.rsqrt(var + epsilon)).reshape(a.shape).astype(a.dtype)
        shape = [1, c] + [1] * (a.ndim - 2)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        return out

    args = [x]
    if weight is not None:
        args.append(weight)
    if bias is not None:
        args.append(bias)
    return apply(fn, *args, name="group_norm")


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    return apply(
        lambda a: a / jnp.maximum(
            jnp.linalg.norm(a, ord=p, axis=axis, keepdims=True), epsilon
        ),
        x,
        name="normalize",
    )


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
    def fn(a):
        sq = jnp.square(a)
        half = size // 2
        c = a.shape[1]
        pads = [(0, 0), (half, size - 1 - half)] + [(0, 0)] * (a.ndim - 2)
        sqp = jnp.pad(sq, pads)
        acc = sum(
            jax.lax.slice_in_dim(sqp, i, i + c, axis=1) for i in range(size)
        )
        return a / jnp.power(k + alpha * acc / size, beta)

    return apply(fn, x, name="local_response_norm")


# ---------------------------------------------------------------------------
# dropout
# ---------------------------------------------------------------------------

def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training and p > 0.0:
            return apply(lambda a: a * (1.0 - p), x, name="dropout")
        return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
    key = _rng.next_key()

    def fn(a):
        shape = list(a.shape)
        if axis is not None:
            axes = axis if isinstance(axis, (list, tuple)) else [axis]
            shape = [s if i in axes else 1 for i, s in enumerate(shape)]
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), 0.0).astype(a.dtype)
        return jnp.where(keep, a, 0.0).astype(a.dtype)

    return apply(fn, x, name="dropout")


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    ax = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p=p, axis=ax, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    ax = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p=p, axis=ax, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    key = _rng.next_key()
    alpha = 1.6732632423543772848170429916717
    scale = 1.0507009873554804934193349852946
    alpha_p = -alpha * scale

    def fn(a):
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        q = 1.0 - p
        a_coef = (q + alpha_p**2 * q * p) ** -0.5
        b_coef = -a_coef * alpha_p * p
        return (a_coef * jnp.where(keep, a, alpha_p) + b_coef).astype(a.dtype)

    return apply(fn, x, name="alpha_dropout")


# ---------------------------------------------------------------------------
# embedding
# ---------------------------------------------------------------------------

def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    # indices ride as a real op input (not a closure constant) so graph
    # recordings — static Program replay, onnx export — see the data edge
    def fn(idx, w):
        out = jnp.take(w, idx, axis=0)
        if padding_idx is not None:
            mask = (idx == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out

    return apply(fn, x, weight, name="embedding")


def one_hot(x, num_classes, name=None):
    from ...ops.manipulation import one_hot as _oh

    return _oh(x, num_classes)


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------

def _reduce_loss(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def cross_entropy(
    input,
    label,
    weight=None,
    ignore_index=-100,
    reduction="mean",
    soft_label=False,
    axis=-1,
    use_softmax=True,
    label_smoothing=0.0,
    name=None,
):
    lbl = label._data if isinstance(label, Tensor) else jnp.asarray(label)

    def fn(logits, *w):
        nclass = logits.shape[axis]
        if soft_label:
            if use_softmax:
                logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=axis)
            else:
                logp = jnp.log(jnp.maximum(logits.astype(jnp.float32), 1e-30))
            tgt = lbl.astype(jnp.float32)
            loss = -jnp.sum(tgt * logp, axis=axis)
        else:
            # Hard labels: loss = logsumexp(logits) - logits[label]. The fp32
            # cast feeds straight into reductions/gathers, so XLA never
            # materializes an fp32 [.., V] log-prob or one-hot tensor — on a
            # 50k vocab that is GBs of HBM traffic per step (the bench's
            # single largest non-matmul cost before this formulation).
            li = lbl
            if li.ndim == logits.ndim:
                li = jnp.squeeze(li, axis=axis)
            li_clipped = jnp.clip(li, 0, nclass - 1)
            picked = jnp.squeeze(
                jnp.take_along_axis(
                    logits, jnp.expand_dims(li_clipped, axis), axis=axis),
                axis).astype(jnp.float32)
            if use_softmax:
                lse = jax.scipy.special.logsumexp(
                    logits.astype(jnp.float32), axis=axis)
                nll = lse - picked
                if label_smoothing > 0.0:
                    mean_logit = jnp.mean(
                        logits.astype(jnp.float32), axis=axis)
                    smooth = lse - mean_logit
                    nll = (1.0 - label_smoothing) * nll + label_smoothing * smooth
            else:
                logpicked = jnp.log(jnp.maximum(picked, 1e-30))
                nll = -logpicked
                if label_smoothing > 0.0:
                    logp_all = jnp.log(
                        jnp.maximum(logits.astype(jnp.float32), 1e-30))
                    smooth = -jnp.mean(logp_all, axis=axis)
                    nll = (1.0 - label_smoothing) * nll + label_smoothing * smooth
            valid = li != ignore_index
            loss = jnp.where(valid, nll, 0.0)
            if w:
                wt = jnp.take(w[0], li_clipped)
                loss = loss * wt
            if reduction == "mean":
                if w:
                    denom = jnp.sum(jnp.where(valid, jnp.take(w[0], li_clipped), 0.0))
                else:
                    denom = jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
                return jnp.sum(loss) / denom
        return _reduce_loss(loss, reduction)

    args = (input,) if weight is None else (input, weight)
    return apply(fn, *args, name="cross_entropy")


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100, axis=-1, return_softmax=False):
    loss = cross_entropy(
        logits, label, soft_label=soft_label, ignore_index=ignore_index,
        reduction="none", axis=axis,
    )
    from ...ops.manipulation import unsqueeze

    if not soft_label and loss.ndim < logits.ndim:
        loss = unsqueeze(loss, axis)
    if return_softmax:
        return loss, softmax(logits, axis=axis)
    return loss


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    def fn(p, t, *w):
        pf = jnp.clip(p.astype(jnp.float32), 1e-12, 1 - 1e-7)
        loss = -(t * jnp.log(pf) + (1 - t) * jnp.log(1 - pf))
        if w:
            loss = loss * w[0]
        return _reduce_loss(loss, reduction)

    args = [input, label if isinstance(label, Tensor) else Tensor(jnp.asarray(label))]
    if weight is not None:
        args.append(weight)
    return apply(fn, *args, name="bce")


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean", pos_weight=None, name=None):
    def fn(z, t, *extra):
        zf = z.astype(jnp.float32)
        tf_ = t.astype(jnp.float32)
        if pos_weight is not None:
            pw_arr = extra[-1]
            base = (1 - tf_) * zf + (1 + (pw_arr - 1) * tf_) * (
                jnp.log1p(jnp.exp(-jnp.abs(zf))) + jnp.maximum(-zf, 0)
            )
        else:
            base = jnp.maximum(zf, 0) - zf * tf_ + jnp.log1p(jnp.exp(-jnp.abs(zf)))
        if weight is not None:
            base = base * extra[0]
        return _reduce_loss(base, reduction)

    args = [logit, label if isinstance(label, Tensor) else Tensor(jnp.asarray(label))]
    if weight is not None:
        args.append(weight)
    if pos_weight is not None:
        args.append(pos_weight)
    return apply(fn, *args, name="bce_logits")


def mse_loss(input, label, reduction="mean", name=None):
    return apply(
        lambda a, b: _reduce_loss(jnp.square(a - b), reduction),
        input,
        label if isinstance(label, Tensor) else Tensor(jnp.asarray(label)),
        name="mse_loss",
    )


def l1_loss(input, label, reduction="mean", name=None):
    return apply(
        lambda a, b: _reduce_loss(jnp.abs(a - b), reduction),
        input,
        label if isinstance(label, Tensor) else Tensor(jnp.asarray(label)),
        name="l1_loss",
    )


def square_error_cost(input, label):
    return apply(lambda a, b: jnp.square(a - b), input, label, name="square_error_cost")


def log_loss(input, label, epsilon=1e-4, name=None):
    return apply(
        lambda p, t: -t * jnp.log(p + epsilon) - (1 - t) * jnp.log(1 - p + epsilon),
        input,
        label,
        name="log_loss",
    )


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    lbl = label._data if isinstance(label, Tensor) else jnp.asarray(label)

    def fn(logp, *w):
        nclass = logp.shape[1]
        li = jnp.clip(lbl, 0, nclass - 1)
        oh = jax.nn.one_hot(li, nclass, axis=1, dtype=logp.dtype)
        loss = -jnp.sum(oh * logp, axis=1)
        valid = lbl != ignore_index
        loss = jnp.where(valid, loss, 0.0)
        if w:
            wt = jnp.take(w[0], li)
            loss = loss * wt
            if reduction == "mean":
                return jnp.sum(loss) / jnp.sum(jnp.where(valid, wt, 0.0))
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
        return _reduce_loss(loss, reduction)

    args = (input,) if weight is None else (input, weight)
    return apply(fn, *args, name="nll_loss")


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def fn(a, b):
        d = a - b
        ad = jnp.abs(d)
        loss = jnp.where(ad < delta, 0.5 * d * d / delta, ad - 0.5 * delta)
        return _reduce_loss(loss, reduction)

    return apply(fn, input, label, name="smooth_l1_loss")


def kl_div(input, label, reduction="mean", name=None):
    def fn(logp, t):
        loss = t * (jnp.log(jnp.maximum(t, 1e-30)) - logp)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce_loss(loss, reduction)

    return apply(fn, input, label, name="kl_div")


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    return apply(
        lambda a, b, t: _reduce_loss(jnp.maximum(-t * (a - b) + margin, 0.0), reduction),
        input,
        other,
        label,
        name="margin_ranking_loss",
    )


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    return apply(
        lambda a, t: _reduce_loss(
            jnp.where(t == 1, a, jnp.maximum(0.0, margin - a)), reduction
        ),
        input,
        label,
        name="hinge_embedding_loss",
    )


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean", name=None):
    def fn(a, b, t):
        cos = jnp.sum(a * b, axis=-1) / (
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1) + 1e-12
        )
        loss = jnp.where(t == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce_loss(loss, reduction)

    return apply(fn, input1, input2, label, name="cosine_embedding_loss")


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0, reduction="sum", name=None):
    def fn(z, t, *n):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0) - z * t + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * t + (1 - p) * (1 - t)
        a_t = alpha * t + (1 - alpha) * (1 - t)
        loss = a_t * ((1 - p_t) ** gamma) * ce
        if n:
            loss = loss / n[0]
        return _reduce_loss(loss, reduction)

    args = [logit, label]
    if normalizer is not None:
        args.append(normalizer)
    return apply(fn, *args, name="sigmoid_focal_loss")


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0, epsilon=1e-6, swap=False, reduction="mean", name=None):
    def fn(a, pos, neg):
        dp = jnp.linalg.norm(a - pos + epsilon, ord=p, axis=-1)
        dn = jnp.linalg.norm(a - neg + epsilon, ord=p, axis=-1)
        if swap:
            dn2 = jnp.linalg.norm(pos - neg + epsilon, ord=p, axis=-1)
            dn = jnp.minimum(dn, dn2)
        return _reduce_loss(jnp.maximum(dp - dn + margin, 0.0), reduction)

    return apply(fn, input, positive, negative, name="triplet_margin_loss")


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8, reduction="mean", name=None):
    def fn(a, t):
        if log_input:
            loss = jnp.exp(a) - t * a
        else:
            loss = a - t * jnp.log(a + epsilon)
        if full:
            stirling = t * jnp.log(t + epsilon) - t + 0.5 * jnp.log(2 * jnp.pi * (t + epsilon))
            loss = loss + jnp.where(t > 1, stirling, 0.0)
        return _reduce_loss(loss, reduction)

    return apply(fn, input, label, name="poisson_nll_loss")


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    def fn(a, p, l):
        sim = a @ p.T
        tgt = (l[:, None] == l[None, :]).astype(jnp.float32)
        tgt = tgt / jnp.sum(tgt, axis=1, keepdims=True)
        logp = jax.nn.log_softmax(sim, axis=1)
        ce = -jnp.mean(jnp.sum(tgt * logp, axis=1))
        reg = l2_reg * (jnp.mean(jnp.sum(a * a, axis=1)) + jnp.mean(jnp.sum(p * p, axis=1))) * 0.25
        return ce + reg

    return apply(fn, anchor, positive, labels, name="npair_loss")


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0, reduction="mean", norm_by_times=False):
    """CTC (reference: warpctc op) — dynamic-programming formulation in lax.scan."""
    lp = log_probs._data if isinstance(log_probs, Tensor) else jnp.asarray(log_probs)
    lbl = labels._data if isinstance(labels, Tensor) else jnp.asarray(labels)
    il = input_lengths._data if isinstance(input_lengths, Tensor) else jnp.asarray(input_lengths)
    ll = label_lengths._data if isinstance(label_lengths, Tensor) else jnp.asarray(label_lengths)

    def fn(logits):
        # logits: [T, B, C] (paddle convention max_logit_length first)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        T, B, C = logp.shape
        L = lbl.shape[1]
        S = 2 * L + 1
        # extended labels with blanks
        ext = jnp.full((B, S), blank, dtype=lbl.dtype)
        ext = ext.at[:, 1::2].set(lbl)
        neg_inf = -1e30

        init = jnp.full((B, S), neg_inf)
        init = init.at[:, 0].set(logp[0, :, blank])
        init = init.at[:, 1].set(
            jnp.take_along_axis(logp[0], ext[:, 1:2], axis=1)[:, 0]
        )

        same_as_prev2 = jnp.concatenate(
            [jnp.ones((B, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1
        )

        def step(alpha, logp_t):
            a0 = alpha
            a1 = jnp.concatenate([jnp.full((B, 1), neg_inf), alpha[:, :-1]], axis=1)
            a2 = jnp.concatenate([jnp.full((B, 2), neg_inf), alpha[:, :-2]], axis=1)
            a2 = jnp.where(same_as_prev2, neg_inf, a2)
            m = jnp.maximum(jnp.maximum(a0, a1), a2)
            m_safe = jnp.where(m == neg_inf, 0.0, m)
            merged = m_safe + jnp.log(
                jnp.exp(a0 - m_safe) + jnp.exp(a1 - m_safe) + jnp.exp(a2 - m_safe) + 1e-37
            )
            merged = jnp.where(m == neg_inf, neg_inf, merged)
            emit = jnp.take_along_axis(logp_t, ext, axis=1)
            return merged + emit, merged + emit

        alpha_T, alphas = jax.lax.scan(step, init, logp[1:])
        all_alphas = jnp.concatenate([init[None], alphas], axis=0)  # [T,B,S]
        # gather at t = il-1, s in {2*ll, 2*ll-1}
        t_idx = jnp.clip(il - 1, 0, T - 1)
        per_b = all_alphas[t_idx, jnp.arange(B)]  # [B, S]
        s1 = jnp.clip(2 * ll, 0, S - 1)
        s2 = jnp.clip(2 * ll - 1, 0, S - 1)
        v1 = jnp.take_along_axis(per_b, s1[:, None], axis=1)[:, 0]
        v2 = jnp.take_along_axis(per_b, s2[:, None], axis=1)[:, 0]
        m = jnp.maximum(v1, v2)
        m_safe = jnp.where(m == neg_inf, 0.0, m)
        ll_total = m_safe + jnp.log(jnp.exp(v1 - m_safe) + jnp.exp(v2 - m_safe))
        loss = -ll_total
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(ll.astype(jnp.float32), 1.0))
        return _reduce_loss(loss, reduction)

    return apply(fn, log_probs, name="ctc_loss")


# ---------------------------------------------------------------------------
# attention & misc
# ---------------------------------------------------------------------------

def scaled_dot_product_attention(
    query, key, value, attn_mask=None, dropout_p=0.0, is_causal=False, training=True, name=None
):
    """Fused attention entry point. On TPU this routes to the Pallas flash
    kernel when shapes allow (paddle_tpu/ops/pallas_ops.py); fallback is the
    XLA softmax composition. Layout: [batch, seq, heads, head_dim]
    (reference convention for fused_attention, operators/fused/)."""
    from ...ops import pallas_ops

    return pallas_ops.flash_attention(
        query, key, value, attn_mask=attn_mask, dropout_p=dropout_p,
        is_causal=is_causal, training=training,
    )


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    if isinstance(pad, Tensor):
        pad = [int(v) for v in pad.numpy()]
    pad = [int(p) for p in pad]

    def fn(a):
        nd = a.ndim
        if len(pad) == 2 * nd:
            pairs = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
        else:
            # paddle flat spec: first pair pads the LAST spatial dim
            # ([left, right, top, bottom] for NCHW)
            k = len(pad) // 2
            spatial = [(pad[2 * i], pad[2 * i + 1]) for i in range(k)][::-1]
            if data_format in ("NCHW", "NCL", "NCDHW", "NCH"):
                pairs = [(0, 0), (0, 0)] + spatial
            else:
                pairs = [(0, 0)] + spatial + [(0, 0)]
        jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]
        if jmode == "constant":
            return jnp.pad(a, pairs, mode="constant", constant_values=value)
        return jnp.pad(a, pairs, mode=jmode)

    return apply(fn, x, name="pad")


def _resize_positions(ins, outs, align_corners, align_mode):
    """Source sampling positions per output index (reference
    interp_kernels' coordinate transforms): corner-aligned
    i*(in-1)/(out-1); else align_mode 0 = half-pixel (i+0.5)*scale-0.5,
    align_mode 1 = i*scale."""
    if align_corners:
        if outs == 1:
            return jnp.zeros((1,), jnp.float32)
        return jnp.arange(outs, dtype=jnp.float32) * ((ins - 1) / (outs - 1))
    scale = ins / outs
    if align_mode == 1:
        return jnp.arange(outs, dtype=jnp.float32) * scale
    pos = (jnp.arange(outs, dtype=jnp.float32) + 0.5) * scale - 0.5
    return jnp.maximum(pos, 0.0)


def _resize_axis_linear(a, ax, outs, align_corners, align_mode):
    ins = a.shape[ax]
    pos = _resize_positions(ins, outs, align_corners, align_mode)
    lo = jnp.clip(jnp.floor(pos).astype(jnp.int32), 0, ins - 1)
    hi = jnp.minimum(lo + 1, ins - 1)
    w = (pos - lo.astype(jnp.float32)).astype(a.dtype)
    shape = [1] * a.ndim
    shape[ax] = outs
    wb = w.reshape(shape)
    return (jnp.take(a, lo, axis=ax) * (1 - wb)
            + jnp.take(a, hi, axis=ax) * wb)


def _resize_axis_cubic(a, ax, outs, align_corners):
    """4-tap Keys cubic, A=-0.75 (reference bicubic_interp kernel — the
    same coefficient as the CUDA `cubic_convolution` helpers), taps
    edge-clamped, NO antialiasing on downscale (jax.image.resize's cubic
    antialiases, which the reference op does not)."""
    ins = a.shape[ax]
    pos = _resize_positions(ins, outs, align_corners, 0)
    if not align_corners:
        # cubic keeps the raw half-pixel position (may be < 0 at i=0)
        pos = (jnp.arange(outs, dtype=jnp.float32) + 0.5) * (ins / outs) - 0.5
    i0 = jnp.floor(pos).astype(jnp.int32)
    t = (pos - i0.astype(jnp.float32))
    A = -0.75

    def k1(tt):     # |t| <= 1
        return ((A + 2.0) * tt - (A + 3.0)) * tt * tt + 1.0

    def k2(tt):     # 1 < |t| < 2
        return ((A * tt - 5.0 * A) * tt + 8.0 * A) * tt - 4.0 * A

    weights = [k2(t + 1.0), k1(t), k1(1.0 - t), k2(2.0 - t)]
    shape = [1] * a.ndim
    shape[ax] = outs
    out = None
    for off, w in zip((-1, 0, 1, 2), weights):
        idx = jnp.clip(i0 + off, 0, ins - 1)
        term = jnp.take(a, idx, axis=ax) * w.reshape(shape).astype(a.dtype)
        out = term if out is None else out + term
    return out


def interpolate(x, size=None, scale_factor=None, mode="nearest", align_corners=False, align_mode=0, data_format="NCHW", name=None):
    if mode not in ("nearest", "linear", "bilinear", "trilinear", "bicubic",
                    "area"):
        raise ValueError(f"unsupported interpolate mode {mode!r}")
    channel_last = data_format in ("NHWC", "NWC", "NLC", "NDHWC")
    ax0 = 1 if channel_last else 2           # first spatial axis

    def fn(a):
        in_spatial = (a.shape[1:-1] if channel_last else a.shape[2:])
        if size is not None:
            out_spatial = tuple(int(s) for s in (size if isinstance(size, (list, tuple)) else [size]))
        else:
            sf = scale_factor if isinstance(scale_factor, (list, tuple)) else [scale_factor] * len(in_spatial)
            out_spatial = tuple(int(s * f) for s, f in zip(in_spatial, sf))
        if mode == "area":
            # reference: 'area' is adaptive average pooling — the shared
            # pure core (spatial axes start at 1 for channel-last)
            return _adaptive_pool_core(a, out_spatial, "avg",
                                       spatial_start=ax0)
        out = a
        for i, (ins, outs) in enumerate(zip(in_spatial, out_spatial)):
            ax = ax0 + i
            if mode == "nearest":
                # reference NearestNeighborInterpolate: floor(ratio*i)
                # with ratio in/out, or round(ratio*i) with corner-
                # aligned ratio (in-1)/(out-1) (interpolate_kernel.cc:210)
                if align_corners and outs > 1:
                    r = (ins - 1) / (outs - 1)
                    idx = jnp.floor(jnp.arange(outs) * r + 0.5).astype(jnp.int32)
                else:
                    idx = jnp.floor(jnp.arange(outs) * (ins / outs)).astype(jnp.int32)
                out = jnp.take(out, jnp.clip(idx, 0, ins - 1), axis=ax)
            elif mode == "bicubic":
                out = _resize_axis_cubic(out, ax, outs, align_corners)
            else:  # linear / bilinear / trilinear
                out = _resize_axis_linear(out, ax, outs, align_corners,
                                          align_mode)
        return out

    return apply(fn, x, name="interpolate")


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def fn(a):
        n, c, h, w = a.shape
        oc = c // (r * r)
        out = a.reshape(n, oc, r, r, h, w)
        out = out.transpose(0, 1, 4, 2, 5, 3)
        return out.reshape(n, oc, h * r, w * r)

    return apply(fn, x, name="pixel_shuffle")


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor

    def fn(a):
        n, c, h, w = a.shape
        out = a.reshape(n, c, h // r, r, w // r, r)
        out = out.transpose(0, 1, 3, 5, 2, 4)
        return out.reshape(n, c * r * r, h // r, w // r)

    return apply(fn, x, name="pixel_unshuffle")


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    def fn(a):
        n, c, h, w = a.shape
        out = a.reshape(n, groups, c // groups, h, w)
        out = out.transpose(0, 2, 1, 3, 4)
        return out.reshape(n, c, h, w)

    return apply(fn, x, name="channel_shuffle")


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros", align_corners=True, name=None):
    def fn(a, g):
        n, c, h, w = a.shape
        gx = g[..., 0]
        gy = g[..., 1]
        if align_corners:
            ix = (gx + 1) * (w - 1) / 2
            iy = (gy + 1) * (h - 1) / 2
        else:
            ix = ((gx + 1) * w - 1) / 2
            iy = ((gy + 1) * h - 1) / 2

        def sample(img, yy, xx):
            # img [C,H,W]; yy,xx [Ho,Wo]
            x0 = jnp.floor(xx).astype(jnp.int32)
            y0 = jnp.floor(yy).astype(jnp.int32)
            x1, y1 = x0 + 1, y0 + 1

            def gather(yi, xi):
                valid = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
                yc = jnp.clip(yi, 0, h - 1)
                xc = jnp.clip(xi, 0, w - 1)
                vals = img[:, yc, xc]  # [C,Ho,Wo]
                return jnp.where(valid, vals, 0.0)

            wa = (x1 - xx) * (y1 - yy)
            wb = (xx - x0) * (y1 - yy)
            wc = (x1 - xx) * (yy - y0)
            wd = (xx - x0) * (yy - y0)
            if mode == "nearest":
                return gather(jnp.round(yy).astype(jnp.int32), jnp.round(xx).astype(jnp.int32))
            return (
                gather(y0, x0) * wa + gather(y0, x1) * wb + gather(y1, x0) * wc + gather(y1, x1) * wd
            )

        out = jax.vmap(sample)(a, iy, ix)
        return out.astype(a.dtype)

    return apply(fn, x, grid, name="grid_sample")


def affine_grid(theta, out_shape, align_corners=True, name=None):
    def fn(th):
        n, _, h, w = [int(s) for s in out_shape] if len(out_shape) == 4 else (int(out_shape[0]), 0, int(out_shape[1]), int(out_shape[2]))
        if align_corners:
            ys = jnp.linspace(-1, 1, h)
            xs = jnp.linspace(-1, 1, w)
        else:
            ys = (jnp.arange(h) * 2 + 1) / h - 1
            xs = (jnp.arange(w) * 2 + 1) / w - 1
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1)  # [H,W,3]
        out = jnp.einsum("hwk,nck->nhwc", base, th)
        return out

    return apply(fn, theta, name="affine_grid")


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    def fn(a, b):
        dot = jnp.sum(a * b, axis=axis)
        na = jnp.linalg.norm(a, axis=axis)
        nb = jnp.linalg.norm(b, axis=axis)
        return dot / jnp.maximum(na * nb, eps)

    return apply(fn, x1, x2, name="cosine_similarity")


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def fn(l, *pd):
        k = l.shape[-1]
        if pd:
            return (1 - epsilon) * l + epsilon * pd[0]
        return (1 - epsilon) * l + epsilon / k

    args = (label,) if prior_dist is None else (label, prior_dist)
    return apply(fn, *args, name="label_smooth")


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    lengths = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    ml = int(maxlen) if maxlen is not None else int(jnp.max(lengths))
    out = (jnp.arange(ml)[None, :] < lengths[..., None]).astype(convert_dtype(dtype))
    return Tensor(out)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW", name=None):
    def fn(a):
        nt, c, h, w = a.shape
        n = nt // seg_num
        r = a.reshape(n, seg_num, c, h, w)
        fold = int(c * shift_ratio)
        left = jnp.concatenate([r[:, 1:, :fold], jnp.zeros_like(r[:, :1, :fold])], axis=1)
        right = jnp.concatenate([jnp.zeros_like(r[:, :1, fold:2 * fold]), r[:, :-1, fold:2 * fold]], axis=1)
        rest = r[:, :, 2 * fold:]
        out = jnp.concatenate([left, right, rest], axis=2)
        return out.reshape(nt, c, h, w)

    return apply(fn, x, name="temporal_shift")


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """col2im inverse of unfold."""
    os = _tuplize(output_sizes, 2)
    ks = _tuplize(kernel_sizes, 2)
    st = _tuplize(strides, 2)
    pd = _tuplize(paddings, 2)
    dl = _tuplize(dilations, 2)

    def fn(a):
        n, ckk, l = a.shape
        c = ckk // (ks[0] * ks[1])
        ph, pw = os[0] + 2 * pd[0], os[1] + 2 * pd[1]
        oh = (ph - (dl[0] * (ks[0] - 1) + 1)) // st[0] + 1
        ow = (pw - (dl[1] * (ks[1] - 1) + 1)) // st[1] + 1
        r = a.reshape(n, c, ks[0], ks[1], oh, ow)
        out = jnp.zeros((n, c, ph, pw), a.dtype)
        for i in range(ks[0]):
            for j in range(ks[1]):
                di, dj = i * dl[0], j * dl[1]
                out = out.at[:, :, di : di + oh * st[0] : st[0], dj : dj + ow * st[1] : st[1]].add(r[:, :, i, j])
        return out[:, :, pd[0] : pd[0] + os[0], pd[1] : pd[1] + os[1]]

    return apply(fn, x, name="fold")


from .extras import *  # noqa: E402,F401,F403
from .extras import __all__ as _extras_all  # noqa: E402

__all__ += list(_extras_all)
