"""Recurrent layers (reference: python/paddle/nn/layer/rnn.py).

TPU-native: the time loop is a `jax.lax.scan` inside ONE eager op, so the
whole sequence compiles to a single XLA while-loop (the reference runs a
python loop over cudnn cell kernels; scan is the compiler-friendly form).
Layout: batch-first [B, T, C] by default, matching the reference.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import apply
from .layer import Layer
from .initializer import Uniform

__all__ = ["SimpleRNNCell", "LSTMCell", "GRUCell", "SimpleRNN", "LSTM", "GRU", "RNN", "BiRNN"]


def _std_uniform(hidden):
    k = 1.0 / math.sqrt(hidden)
    return Uniform(-k, k)


class RNNCellBase(Layer):
    def get_initial_states(self, batch, hidden_size):
        from ..ops.creation import zeros

        return zeros([batch, hidden_size])


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        init = _std_uniform(hidden_size)
        self.weight_ih = self.create_parameter([hidden_size, input_size], weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter([hidden_size, hidden_size], weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter([hidden_size], bias_ih_attr, is_bias=True, default_initializer=init)
        self.bias_hh = self.create_parameter([hidden_size], bias_hh_attr, is_bias=True, default_initializer=init)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs.shape[0], self.hidden_size)
        act = jnp.tanh if self.activation == "tanh" else (lambda a: jnp.maximum(a, 0))

        def fn(x, h, wi, wh, bi, bh):
            out = act(x @ wi.T + bi + h @ wh.T + bh)
            return out

        h = apply(fn, inputs, states, self.weight_ih, self.weight_hh,
                  self.bias_ih, self.bias_hh, name="rnn_cell")
        return h, h


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        init = _std_uniform(hidden_size)
        self.weight_ih = self.create_parameter([4 * hidden_size, input_size], weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter([4 * hidden_size, hidden_size], weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter([4 * hidden_size], bias_ih_attr, is_bias=True, default_initializer=init)
        self.bias_hh = self.create_parameter([4 * hidden_size], bias_hh_attr, is_bias=True, default_initializer=init)

    def forward(self, inputs, states=None):
        if states is None:
            h = self.get_initial_states(inputs.shape[0], self.hidden_size)
            c = self.get_initial_states(inputs.shape[0], self.hidden_size)
        else:
            h, c = states

        def fn(x, h0, c0, wi, wh, bi, bh):
            gates = x @ wi.T + bi + h0 @ wh.T + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            c1 = f * c0 + i * g
            h1 = o * jnp.tanh(c1)
            return h1, c1

        h1, c1 = apply(fn, inputs, h, c, self.weight_ih, self.weight_hh,
                       self.bias_ih, self.bias_hh, name="lstm_cell")
        return h1, (h1, c1)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        init = _std_uniform(hidden_size)
        self.weight_ih = self.create_parameter([3 * hidden_size, input_size], weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter([3 * hidden_size, hidden_size], weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter([3 * hidden_size], bias_ih_attr, is_bias=True, default_initializer=init)
        self.bias_hh = self.create_parameter([3 * hidden_size], bias_hh_attr, is_bias=True, default_initializer=init)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs.shape[0], self.hidden_size)

        def fn(x, h0, wi, wh, bi, bh):
            gi = x @ wi.T + bi
            gh = h0 @ wh.T + bh
            ir, iz, ic = jnp.split(gi, 3, axis=-1)
            hr, hz, hc = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            c = jnp.tanh(ic + r * hc)
            return (1 - z) * c + z * h0

        h = apply(fn, inputs, states, self.weight_ih, self.weight_hh,
                  self.bias_ih, self.bias_hh, name="gru_cell")
        return h, h


def _lstm_scan(x, h0, c0, wi, wh, bi, bh, reverse=False):
    # x: [B,T,I] → outputs [B,T,H]
    xs = jnp.swapaxes(x, 0, 1)  # [T,B,I]
    if reverse:
        xs = jnp.flip(xs, 0)

    def step(carry, xt):
        h, c = carry
        gates = xt @ wi.T + bi + h @ wh.T + bh
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c1 = f * c + i * g
        h1 = o * jnp.tanh(c1)
        return (h1, c1), h1

    (hT, cT), ys = jax.lax.scan(step, (h0, c0), xs)
    if reverse:
        ys = jnp.flip(ys, 0)
    return jnp.swapaxes(ys, 0, 1), hT, cT


def _gru_scan(x, h0, wi, wh, bi, bh, reverse=False):
    xs = jnp.swapaxes(x, 0, 1)
    if reverse:
        xs = jnp.flip(xs, 0)

    def step(h, xt):
        gi = xt @ wi.T + bi
        gh = h @ wh.T + bh
        ir, iz, ic = jnp.split(gi, 3, axis=-1)
        hr, hz, hc = jnp.split(gh, 3, axis=-1)
        r = jax.nn.sigmoid(ir + hr)
        z = jax.nn.sigmoid(iz + hz)
        c = jnp.tanh(ic + r * hc)
        h1 = (1 - z) * c + z * h
        return h1, h1

    hT, ys = jax.lax.scan(step, h0, xs)
    if reverse:
        ys = jnp.flip(ys, 0)
    return jnp.swapaxes(ys, 0, 1), hT


def _rnn_scan(x, h0, wi, wh, bi, bh, activation="tanh", reverse=False):
    xs = jnp.swapaxes(x, 0, 1)
    if reverse:
        xs = jnp.flip(xs, 0)
    act = jnp.tanh if activation == "tanh" else (lambda a: jnp.maximum(a, 0))

    def step(h, xt):
        h1 = act(xt @ wi.T + bi + h @ wh.T + bh)
        return h1, h1

    hT, ys = jax.lax.scan(step, h0, xs)
    if reverse:
        ys = jnp.flip(ys, 0)
    return jnp.swapaxes(ys, 0, 1), hT


class _RNNBase(Layer):
    MODE = "lstm"

    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.activation = activation
        self.bidirect = 2 if direction in ("bidirect", "bidirectional") else 1
        gate_mult = {"lstm": 4, "gru": 3, "rnn": 1}[self.MODE]
        init = _std_uniform(hidden_size)
        self._all_weights = []
        for layer in range(num_layers):
            for d in range(self.bidirect):
                in_sz = input_size if layer == 0 else hidden_size * self.bidirect
                suffix = f"_reverse" if d == 1 else ""
                wi = self.create_parameter([gate_mult * hidden_size, in_sz], weight_ih_attr, default_initializer=init)
                wh = self.create_parameter([gate_mult * hidden_size, hidden_size], weight_hh_attr, default_initializer=init)
                bi = self.create_parameter([gate_mult * hidden_size], bias_ih_attr, is_bias=True, default_initializer=init)
                bh = self.create_parameter([gate_mult * hidden_size], bias_hh_attr, is_bias=True, default_initializer=init)
                self.add_parameter(f"weight_ih_l{layer}{suffix}", wi)
                self.add_parameter(f"weight_hh_l{layer}{suffix}", wh)
                self.add_parameter(f"bias_ih_l{layer}{suffix}", bi)
                self.add_parameter(f"bias_hh_l{layer}{suffix}", bh)
                self._all_weights.append((wi, wh, bi, bh))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ..ops.creation import zeros
        from ..ops.manipulation import stack

        x = inputs
        if self.time_major:
            from ..ops.manipulation import transpose

            x = transpose(x, [1, 0, 2])
        b = x.shape[0]
        nstate = self.num_layers * self.bidirect
        if initial_states is None:
            if self.MODE == "lstm":
                h0 = zeros([nstate, b, self.hidden_size])
                c0 = zeros([nstate, b, self.hidden_size])
                initial_states = (h0, c0)
            else:
                initial_states = zeros([nstate, b, self.hidden_size])

        mode = self.MODE
        activation = self.activation

        if mode == "lstm":
            h0_t, c0_t = initial_states
        else:
            h0_t = initial_states
            c0_t = None

        # one eager op for the whole (multi-layer, bidirectional) RNN
        weights_flat = [w for tup in self._all_weights for w in tup]
        num_layers, bidirect, hidden = self.num_layers, self.bidirect, self.hidden_size
        dropout = self.dropout if self.training else 0.0
        drop_keys = None
        if dropout > 0 and num_layers > 1:
            from ..core import random as _rng

            drop_keys = [_rng.next_key() for _ in range(num_layers - 1)]

        def fn(xa, h0a, *rest):
            if mode == "lstm":
                c0a = rest[0]
                ws = rest[1:]
            else:
                c0a = None
                ws = rest
            out = xa
            hTs, cTs = [], []
            for layer in range(num_layers):
                outs_d = []
                for d in range(bidirect):
                    sidx = layer * bidirect + d
                    wi, wh, bi, bh = ws[4 * sidx : 4 * sidx + 4]
                    rev = d == 1
                    if mode == "lstm":
                        y, hT, cT = _lstm_scan(out, h0a[sidx], c0a[sidx], wi, wh, bi, bh, rev)
                        cTs.append(cT)
                    elif mode == "gru":
                        y, hT = _gru_scan(out, h0a[sidx], wi, wh, bi, bh, rev)
                    else:
                        y, hT = _rnn_scan(out, h0a[sidx], wi, wh, bi, bh, activation, rev)
                    outs_d.append(y)
                    hTs.append(hT)
                out = outs_d[0] if bidirect == 1 else jnp.concatenate(outs_d, axis=-1)
                if drop_keys is not None and layer < num_layers - 1:
                    keep = jax.random.bernoulli(drop_keys[layer], 1 - dropout, out.shape)
                    out = jnp.where(keep, out / (1 - dropout), 0.0).astype(out.dtype)
            hN = jnp.stack(hTs, 0)
            if mode == "lstm":
                return out, hN, jnp.stack(cTs, 0)
            return out, hN

        if mode == "lstm":
            out, hN, cN = apply(fn, x, h0_t, c0_t, *weights_flat, name=mode)
            final = (hN, cN)
        else:
            out, hN = apply(fn, x, h0_t, *weights_flat, name=mode)
            final = hN
        if self.time_major:
            from ..ops.manipulation import transpose

            out = transpose(out, [1, 0, 2])
        return out, final


class SimpleRNN(_RNNBase):
    MODE = "rnn"


class LSTM(_RNNBase):
    MODE = "lstm"


class GRU(_RNNBase):
    MODE = "gru"


class RNN(Layer):
    """Wraps a cell into a scan over time (reference: paddle.nn.RNN)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ..ops.manipulation import stack, transpose

        x = inputs
        if self.time_major:
            x = transpose(x, [1, 0, 2])
        T = x.shape[1]
        steps = range(T - 1, -1, -1) if self.is_reverse else range(T)
        states = initial_states
        outs = [None] * T
        for t in steps:
            y, states = self.cell(x[:, t], states)
            outs[t] = y
        out = stack(outs, axis=1)
        if self.time_major:
            out = transpose(out, [1, 0, 2])
        return out, states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ..ops.manipulation import concat

        s_fw, s_bw = (initial_states if initial_states is not None else (None, None))
        out_fw, st_fw = self.rnn_fw(inputs, s_fw)
        out_bw, st_bw = self.rnn_bw(inputs, s_bw)
        return concat([out_fw, out_bw], axis=-1), (st_fw, st_bw)
