"""Normalization layers (reference: python/paddle/nn/layer/norm.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from .layer import Layer
from . import functional as F
from .initializer import Constant

__all__ = [
    "BatchNorm", "BatchNorm1D", "BatchNorm2D", "BatchNorm3D", "SyncBatchNorm",
    "LayerNorm", "GroupNorm", "InstanceNorm1D", "InstanceNorm2D",
    "InstanceNorm3D", "LocalResponseNorm", "SpectralNorm", "RMSNorm",
]


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        if weight_attr is not False:
            self.weight = self.create_parameter(
                shape=[num_features], attr=weight_attr,
                default_initializer=Constant(1.0),
            )
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(shape=[num_features], attr=bias_attr, is_bias=True)
        else:
            self.bias = None
        self.register_buffer("_mean", Tensor(jnp.zeros(num_features, jnp.float32)))
        self.register_buffer("_variance", Tensor(jnp.ones(num_features, jnp.float32)))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum,
            epsilon=self._epsilon, data_format=self._data_format,
            use_global_stats=self._use_global_stats,
        )

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}"


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCL", use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr,
                         "NCHW" if data_format == "NCL" else "NLC", use_global_stats)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCDHW", use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr,
                         "NCHW" if data_format == "NCDHW" else "NDHWC", use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BN. Under SPMD compilation the batch axis is sharded
    over the mesh and XLA's batch-norm reductions become cross-replica
    automatically (psum over 'dp'); eager single-process fallback is plain BN.
    (Reference: sync_batch_norm_op.cu — explicit NCCL allreduce of stats.)"""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            new = SyncBatchNorm(layer._num_features, layer._momentum, layer._epsilon,
                                data_format=layer._data_format)
            new.weight = layer.weight
            new.bias = layer.bias
            new._buffers = layer._buffers
            return new
        for name, sub in list(layer._sub_layers.items()):
            converted = cls.convert_sync_batchnorm(sub)
            if converted is not sub:
                layer._sub_layers[name] = converted
                object.__setattr__(layer, name, converted)
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is not False:
            self.weight = self.create_parameter(
                shape=self._normalized_shape, attr=weight_attr,
                default_initializer=Constant(1.0),
            )
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                shape=self._normalized_shape, attr=bias_attr, is_bias=True
            )
        else:
            self.bias = None

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias, self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}"


class RMSNorm(Layer):
    """Capability-gap fill (no RMSNorm in the reference snapshot; required by
    Llama-class models)."""

    def __init__(self, normalized_shape, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            shape=list(normalized_shape), attr=weight_attr,
            default_initializer=Constant(1.0),
        )

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        if weight_attr is not False:
            self.weight = self.create_parameter(
                shape=[num_channels], attr=weight_attr,
                default_initializer=Constant(1.0),
            )
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(shape=[num_channels], attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight, self.bias)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCL", name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is not False:
            self.weight = self.create_parameter(
                shape=[num_features], attr=weight_attr,
                default_initializer=Constant(1.0),
            )
            self.bias = self.create_parameter(shape=[num_features], attr=bias_attr, is_bias=True)
        else:
            self.weight = None
            self.bias = None

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias, eps=self._epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
        super().__init__()
        self.args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self.args)


class SpectralNorm(Layer):
    """Spectral norm of a weight (power iteration; reference spectral_norm_op)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12, name=None):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._epsilon = epsilon
        import numpy as np

        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        from ..ops.creation import randn

        self.register_buffer("weight_u", randn([h]))
        self.register_buffer("weight_v", randn([w]))

    def forward(self, weight):
        from ..core.dispatch import apply

        dim, iters, eps = self._dim, self._power_iters, self._epsilon
        u0, v0 = self.weight_u._data, self.weight_v._data

        def fn(w):
            wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
            u, v = u0, v0
            for _ in range(iters):
                v = wm.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = wm @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ wm @ v
            return w / sigma

        return apply(fn, weight, name="spectral_norm")
