"""Pooling layers (reference: python/paddle/nn/layer/pooling.py)."""
from __future__ import annotations

from .layer import Layer
from . import functional as F

__all__ = [
    "MaxPool1D", "MaxPool2D", "MaxPool3D", "AvgPool1D", "AvgPool2D",
    "AvgPool3D", "AdaptiveAvgPool1D", "AdaptiveAvgPool2D", "AdaptiveAvgPool3D",
    "AdaptiveMaxPool2D",
]


class _Pool(Layer):
    _fn = None

    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 data_format=None, **kwargs):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.ceil_mode = ceil_mode
        self.data_format = data_format

    def forward(self, x):
        kw = {"ceil_mode": self.ceil_mode}
        if self.data_format is not None:
            kw["data_format"] = self.data_format
        return getattr(F, self._fn)(x, self.kernel_size, self.stride,
                                    self.padding, **kw)


class MaxPool1D(_Pool):
    _fn = "max_pool1d"


class MaxPool2D(_Pool):
    _fn = "max_pool2d"


class MaxPool3D(_Pool):
    _fn = "max_pool3d"


class AvgPool1D(_Pool):
    _fn = "avg_pool1d"


class AvgPool2D(_Pool):
    _fn = "avg_pool2d"


class AvgPool3D(_Pool):
    _fn = "avg_pool3d"


class _AdaptivePool(Layer):
    _fn = None

    def __init__(self, output_size, data_format=None, **kwargs):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format

    def forward(self, x):
        if self.data_format is not None:
            return getattr(F, self._fn)(x, self.output_size,
                                        data_format=self.data_format)
        return getattr(F, self._fn)(x, self.output_size)


class AdaptiveAvgPool1D(_AdaptivePool):
    _fn = "adaptive_avg_pool1d"


class AdaptiveAvgPool2D(_AdaptivePool):
    _fn = "adaptive_avg_pool2d"


class AdaptiveAvgPool3D(_AdaptivePool):
    _fn = "adaptive_avg_pool3d"


class AdaptiveMaxPool2D(_AdaptivePool):
    _fn = "adaptive_max_pool2d"
