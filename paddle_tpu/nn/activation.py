"""Activation layers (reference: python/paddle/nn/layer/activation.py)."""
from __future__ import annotations

from .layer import Layer
from . import functional as F
from .initializer import Constant

__all__ = [
    "ReLU", "ReLU6", "GELU", "Sigmoid", "Tanh", "Softmax", "LogSoftmax",
    "LeakyReLU", "ELU", "SELU", "CELU", "Silu", "Swish", "Mish", "Hardswish",
    "Hardsigmoid", "Hardtanh", "Hardshrink", "Softshrink", "Tanhshrink",
    "Softplus", "Softsign", "PReLU", "RReLU", "GLU", "Maxout",
    "ThresholdedReLU", "LogSigmoid",
]


def _simple(fn_name, **fixed):
    class _Act(Layer):
        def __init__(self, *args, **kwargs):
            super().__init__()
            self._kwargs = {**fixed}
            # positional args map onto the functional's signature in order
            import inspect

            fn = getattr(F, fn_name)
            sig = list(inspect.signature(fn).parameters)[1:]
            for name, val in zip(sig, args):
                self._kwargs[name] = val
            for k, v in kwargs.items():
                if k != "name":
                    self._kwargs[k] = v

        def forward(self, x):
            return getattr(F, fn_name)(x, **self._kwargs)

    _Act.__name__ = fn_name
    return _Act


ReLU = _simple("relu")
ReLU6 = _simple("relu6")
GELU = _simple("gelu")
Sigmoid = _simple("sigmoid")
Tanh = _simple("tanh")
Softmax = _simple("softmax")
LogSoftmax = _simple("log_softmax")
LeakyReLU = _simple("leaky_relu")
ELU = _simple("elu")
SELU = _simple("selu")
CELU = _simple("celu")
Silu = _simple("silu")
Swish = _simple("swish")
Mish = _simple("mish")
Hardswish = _simple("hardswish")
Hardsigmoid = _simple("hardsigmoid")
Hardtanh = _simple("hardtanh")
Hardshrink = _simple("hardshrink")
Softshrink = _simple("softshrink")
Tanhshrink = _simple("tanhshrink")
Softplus = _simple("softplus")
Softsign = _simple("softsign")
RReLU = _simple("rrelu")
GLU = _simple("glu")
Maxout = _simple("maxout")
ThresholdedReLU = _simple("thresholded_relu")
LogSigmoid = _simple("log_sigmoid")


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter(
            shape=[num_parameters], attr=weight_attr,
            default_initializer=Constant(init),
        )

    def forward(self, x):
        return F.prelu(x, self.weight, data_format=self._data_format)
