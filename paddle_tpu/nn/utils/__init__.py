"""nn.utils (reference: python/paddle/nn/utils/ — weight_norm /
remove_weight_norm / spectral_norm hooks + parameter flattening).

weight_norm reparametrizes w = g * v / ||v|| with (g, v) as the trainable
parameters, recomputed in a forward-pre-hook — the dygraph formulation of
the reference's WeightNormParamAttr static rewrite. spectral_norm divides
the weight by its leading singular value via power iteration."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..utils_ import (  # noqa: F401
    clip_grad_norm_, clip_grad_value_, parameters_to_vector,
    vector_to_parameters,
)
from ..layer import Parameter

__all__ = ["weight_norm", "remove_weight_norm", "spectral_norm",
           "parameters_to_vector", "vector_to_parameters",
           "clip_grad_norm_", "clip_grad_value_"]


def _norm_except(v, dim):
    """||v|| reduced over every axis except `dim`; dim=None reduces over
    ALL axes (whole-tensor norm — the reference's -1 sentinel)."""
    if dim is None:
        axes = tuple(range(v.ndim))
    else:
        axes = tuple(i for i in range(v.ndim) if i != dim)
    return (v * v).sum(axis=axes, keepdim=True).sqrt()


def weight_norm(layer, name="weight", dim=0):
    """Apply weight normalization to `layer.name` (reference
    nn/utils/weight_norm_hook.py): replaces the parameter with
    (name_g, name_v); every forward recomputes w = g * v/||v||."""
    w = getattr(layer, name)
    if dim == -1:
        dim = None       # reference norm_except_dim sentinel: whole-tensor
    elif dim is not None and dim < 0:
        dim += w.ndim
    g = Parameter(_norm_except(w, dim)._data)
    v = Parameter(jnp.array(w._data, copy=True))
    del layer._parameters[name]
    layer.add_parameter(name + "_g", g)
    layer.add_parameter(name + "_v", v)

    def _recompute(lyr, inputs):
        gv = getattr(lyr, name + "_g")
        vv = getattr(lyr, name + "_v")
        w_new = vv * (gv / _norm_except(vv, dim))
        object.__setattr__(lyr, name, w_new)
        return inputs

    handle = layer.register_forward_pre_hook(_recompute)
    layer._weight_norm_hook = (handle, name, dim)
    _recompute(layer, ())
    return layer


def remove_weight_norm(layer, name="weight"):
    """Fold (g, v) back into a single parameter and drop the hook."""
    handle, pname, dim = layer._weight_norm_hook
    handle.remove()
    g = getattr(layer, pname + "_g")
    v = getattr(layer, pname + "_v")
    w = v * (g / _norm_except(v, dim))
    del layer._parameters[pname + "_g"]
    del layer._parameters[pname + "_v"]
    restored = Parameter(w._data)
    layer.add_parameter(pname, restored)
    # the hook wrote a plain Tensor into the instance __dict__, which
    # shadows _parameters on attribute lookup — rebind it to the restored
    # Parameter or training silently stops affecting the forward
    object.__setattr__(layer, pname, restored)
    del layer._weight_norm_hook
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=0):
    """Spectral normalization (reference nn/utils/spectral_norm_hook.py):
    w_sn = w / sigma_max(w), sigma estimated by power iteration on the
    [dim, -1] reshaped weight; u persists as a buffer across steps."""
    from ...core.tensor import Tensor

    w = getattr(layer, name)
    mat = np.asarray(w._data)
    if dim != 0:
        order = [dim] + [i for i in range(mat.ndim) if i != dim]
        mat = mat.transpose(order)
    h = mat.shape[0]
    rng = np.random.RandomState(0)
    u0 = rng.randn(h).astype(np.float32)
    layer.register_buffer(name + "_u",
                          Tensor(jnp.asarray(u0 / np.linalg.norm(u0))))

    def _recompute(lyr, inputs):
        wt = getattr(lyr, name + "_orig")
        arr = wt._data
        if dim != 0:
            order = [dim] + [i for i in range(arr.ndim) if i != dim]
            arr2 = jnp.transpose(arr, order)
        else:
            arr2 = arr
        m = arr2.reshape(arr2.shape[0], -1)
        u = getattr(lyr, name + "_u")._data
        for _ in range(n_power_iterations):
            v = m.T @ u
            v = v / jnp.maximum(jnp.linalg.norm(v), eps)
            u = m @ v
            u = u / jnp.maximum(jnp.linalg.norm(u), eps)
        if n_power_iterations <= 0:
            # frozen-u mode (reference n_power_iterations=0): derive v from
            # the persisted u WITHOUT advancing or persisting it
            v = m.T @ u
            v = v / jnp.maximum(jnp.linalg.norm(v), eps)
        else:
            lyr._buffers[name + "_u"]._data = u
        sigma = u @ m @ v
        object.__setattr__(lyr, name, Tensor(arr / sigma,
                                             stop_gradient=wt.stop_gradient))
        return inputs

    orig = Parameter(jnp.array(w._data, copy=True))
    del layer._parameters[name]
    layer.add_parameter(name + "_orig", orig)
    layer.register_forward_pre_hook(_recompute)
    _recompute(layer, ())
    return layer
