"""Weight initializers (reference: python/paddle/nn/initializer/ +
fluid/initializer.py). Each initializer is a callable (shape, dtype) → array
drawing from the global counter-split PRNG."""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ...core import random as _rng
from ...core.dtype import convert_dtype

__all__ = [
    "Initializer",
    "Constant",
    "Normal",
    "TruncatedNormal",
    "Uniform",
    "XavierNormal",
    "XavierUniform",
    "KaimingNormal",
    "KaimingUniform",
    "Assign",
    "Orthogonal",
    "Dirac",
    "calculate_gain",
]


def calculate_gain(nonlinearity, param=None):
    gains = {
        "sigmoid": 1.0,
        "linear": 1.0,
        "conv1d": 1.0,
        "conv2d": 1.0,
        "conv3d": 1.0,
        "tanh": 5.0 / 3,
        "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param or 0.01) ** 2)),
        "selu": 3.0 / 4,
    }
    return gains[nonlinearity]


def _fans(shape):
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        # paddle convention: [in_features, out_features]
        return shape[0], shape[1]
    # conv: [out_c, in_c, *kernel]
    rf = int(np.prod(shape[2:]))
    return shape[1] * rf, shape[0] * rf


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(shape, self.value, convert_dtype(dtype))


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        d = convert_dtype(dtype)
        out = jax.random.normal(_rng.next_key(), shape, jnp.float32)
        return (out * self.std + self.mean).astype(d)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        d = convert_dtype(dtype)
        out = jax.random.truncated_normal(_rng.next_key(), -2.0, 2.0, shape, jnp.float32)
        return (out * self.std + self.mean).astype(d)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        d = convert_dtype(dtype)
        out = jax.random.uniform(
            _rng.next_key(), shape, jnp.float32, self.low, self.high
        )
        return out.astype(d)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return Normal(0.0, std)(shape, dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return Uniform(-limit, limit)(shape, dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        return Normal(0.0, gain / math.sqrt(fi))(shape, dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        return Uniform(-limit, limit)(shape, dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype):
        from ...core.tensor import Tensor

        v = self.value
        if isinstance(v, Tensor):
            arr = v._data
        else:
            arr = jnp.asarray(np.asarray(v))
        if tuple(arr.shape) != tuple(shape):
            arr = arr.reshape(shape)
        return arr.astype(convert_dtype(dtype))


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        flat = (int(np.prod(shape[:-1])), shape[-1])
        a = jax.random.normal(_rng.next_key(), flat, jnp.float32)
        q, r = jnp.linalg.qr(a if flat[0] >= flat[1] else a.T)
        d = jnp.diagonal(r)
        q = q * jnp.sign(d)
        if flat[0] < flat[1]:
            q = q.T
        return (self.gain * q.reshape(shape)).astype(convert_dtype(dtype))


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype):
        out = np.zeros(shape, dtype=np.float32)
        oc, ic = shape[0], shape[1]
        centers = [s // 2 for s in shape[2:]]
        for i in range(min(oc, ic * self.groups)):
            idx = (i, i % ic) + tuple(centers)
            out[idx] = 1.0
        return jnp.asarray(out).astype(convert_dtype(dtype))


class Bilinear(Initializer):
    """Bilinear-upsample kernel init for transposed conv (reference
    nn/initializer/Bilinear): weight[c_out, c_in, kh, kw] filled with the
    separable triangle kernel."""

    def __call__(self, shape, dtype):
        import numpy as np

        if len(shape) != 4:
            raise ValueError("Bilinear initializer expects a 4-D conv weight")
        kh, kw = shape[2], shape[3]

        def tri(k):
            f = (k + 1) // 2
            c = f - 1 if k % 2 == 1 else f - 0.5
            return (1 - abs((np.arange(k) - c) / f))

        kern = np.outer(tri(kh), tri(kw)).astype("float32")
        w = np.zeros(shape, "float32")
        for i in range(shape[0]):
            for j in range(shape[1]):
                w[i, j] = kern
        import jax.numpy as jnp

        return jnp.asarray(w, dtype)


_global_initializer = [None]


def set_global_initializer(weight_init, bias_init=None):
    """Default initializers for subsequently created parameters (reference
    nn/initializer/set_global_initializer). Pass None to reset."""
    _global_initializer[0] = (weight_init, bias_init)


def _global_init_for(is_bias):
    g = _global_initializer[0]
    if g is None:
        return None
    return g[1] if is_bias else g[0]


__all__ += ["Bilinear", "set_global_initializer"]
