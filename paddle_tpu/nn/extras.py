"""nn layer long tail (reference: python/paddle/nn/layer/ — pooling
AdaptiveMaxPool1D/3D + MaxUnPool*, vision PixelShuffle/Unshuffle/
ChannelShuffle, padding ZeroPad2D, distance PairwiseDistance, common
Bilinear, activation Softmax2D, loss {Soft,MultiLabelSoft,Multi}Margin /
TripletMarginWithDistance / HSigmoid / RNNT, and the seq2seq decoding pair
BeamSearchDecoder + dynamic_decode from nn/decode.py)."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor
from . import functional as F
from .layer import Layer
from .initializer import XavierNormal, Constant

__all__ = [
    "AdaptiveMaxPool1D", "AdaptiveMaxPool3D", "MaxUnPool1D", "MaxUnPool2D",
    "MaxUnPool3D", "PixelShuffle", "PixelUnshuffle", "ChannelShuffle",
    "ZeroPad2D", "PairwiseDistance", "Bilinear", "Softmax2D",
    "SoftMarginLoss", "MultiLabelSoftMarginLoss", "MultiMarginLoss",
    "TripletMarginWithDistanceLoss", "HSigmoidLoss", "RNNTLoss",
    "BeamSearchDecoder", "dynamic_decode",
]


class AdaptiveMaxPool1D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._args = (output_size, return_mask)

    def forward(self, x):
        return F.adaptive_max_pool1d(x, *self._args)


class AdaptiveMaxPool3D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._args = (output_size, return_mask)

    def forward(self, x):
        return F.adaptive_max_pool3d(x, *self._args)


class _MaxUnPool(Layer):
    _fn = None

    def __init__(self, kernel_size, stride=None, padding=0, data_format=None,
                 output_size=None, name=None):
        super().__init__()
        self._kw = dict(kernel_size=kernel_size, stride=stride,
                        padding=padding, output_size=output_size)

    def forward(self, x, indices):
        return type(self)._fn(x, indices, **self._kw)


class MaxUnPool1D(_MaxUnPool):
    _fn = staticmethod(F.max_unpool1d)


class MaxUnPool2D(_MaxUnPool):
    _fn = staticmethod(F.max_unpool2d)


class MaxUnPool3D(_MaxUnPool):
    _fn = staticmethod(F.max_unpool3d)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self._f = upscale_factor
        self._df = data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self._f, self._df)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self._f = downscale_factor
        self._df = data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self._f, self._df)


class ChannelShuffle(Layer):
    """Interleave channel groups (ShuffleNet; reference
    nn/layer/vision.py ChannelShuffle)."""

    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self._g = groups
        self._df = data_format

    def forward(self, x):
        from ..core.dispatch import apply

        g = self._g
        ch_axis = 1 if self._df == "NCHW" else -1

        def fn(a):
            shp = list(a.shape)
            c = shp[ch_axis]
            if ch_axis == 1:
                r = a.reshape(shp[0], g, c // g, *shp[2:])
                r = jnp.swapaxes(r, 1, 2)
                return r.reshape(a.shape)
            r = a.reshape(*shp[:-1], g, c // g)
            r = jnp.swapaxes(r, -1, -2)
            return r.reshape(a.shape)

        return apply(fn, x, name="channel_shuffle")


class ZeroPad2D(Layer):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__()
        self._p = padding
        self._df = data_format

    def forward(self, x):
        return F.zeropad2d(x, self._p, self._df)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self._kw = dict(p=p, epsilon=epsilon, keepdim=keepdim)

    def forward(self, x, y):
        return F.pairwise_distance(x, y, **self._kw)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [out_features, in1_features, in2_features], attr=weight_attr,
            default_initializer=XavierNormal())
        self.bias = (None if bias_attr is False else self.create_parameter(
            [out_features], attr=bias_attr, is_bias=True,
            default_initializer=Constant(0.0)))

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class Softmax2D(Layer):
    """Softmax over the channel dim of NCHW (or unbatched CHW) input
    (reference nn/layer/activation.py Softmax2D)."""

    def forward(self, x):
        assert x.ndim in (3, 4), "Softmax2D expects NCHW or CHW input"
        return F.softmax(x, axis=-3)


class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self._r = reduction

    def forward(self, input, label):
        return F.soft_margin_loss(input, label, reduction=self._r)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self._w = weight
        self._r = reduction

    def forward(self, input, label):
        return F.multi_label_soft_margin_loss(input, label, weight=self._w,
                                              reduction=self._r)


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean",
                 name=None):
        super().__init__()
        self._kw = dict(p=p, margin=margin, weight=weight, reduction=reduction)

    def forward(self, input, label):
        return F.multi_margin_loss(input, label, **self._kw)


class TripletMarginWithDistanceLoss(Layer):
    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self._kw = dict(distance_function=distance_function, margin=margin,
                        swap=swap, reduction=reduction)

    def forward(self, input, positive, negative):
        return F.triplet_margin_with_distance_loss(input, positive, negative,
                                                   **self._kw)


class HSigmoidLoss(Layer):
    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False, name=None):
        super().__init__()
        self._num_classes = num_classes
        rows = num_classes if is_custom else num_classes - 1
        self.weight = self.create_parameter(
            [rows, feature_size], attr=weight_attr,
            default_initializer=XavierNormal())
        self.bias = (None if bias_attr is False else self.create_parameter(
            [rows], attr=bias_attr, is_bias=True,
            default_initializer=Constant(0.0)))

    def forward(self, input, label, path_table=None, path_code=None):
        return F.hsigmoid_loss(input, label, self._num_classes, self.weight,
                               self.bias, path_table=path_table,
                               path_code=path_code)


class RNNTLoss(Layer):
    def __init__(self, blank=0, fastemit_lambda=0.0, reduction="mean",
                 name=None):
        super().__init__()
        self._kw = dict(blank=blank, fastemit_lambda=fastemit_lambda,
                        reduction=reduction)

    def forward(self, logits, labels, logit_lengths, label_lengths):
        return F.rnnt_loss(logits, labels, logit_lengths, label_lengths,
                           **self._kw)


class BeamSearchDecoder:
    """Beam-search decoder over an RNN cell (reference nn/decode.py
    BeamSearchDecoder). Host-stepped: each step embeds the previous token,
    advances the cell, and keeps the top-`beam_size` cumulative-log-prob
    continuations; finished beams are held at EOS. Used with
    dynamic_decode (the reference's driver loop)."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    def _logits(self, tok, states):
        emb = self.embedding_fn(tok) if self.embedding_fn is not None else tok
        out, states = self.cell(emb, states)
        if self.output_fn is not None:
            out = self.output_fn(out)
        return out, states


def dynamic_decode(decoder, inits=None, max_step_num=32, **kwargs):
    """Greedy/beam decoding driver (reference nn/decode.py dynamic_decode).
    Returns (ids [B, W, T], scores [B, W]) for a BeamSearchDecoder."""
    import jax

    bsd = decoder
    W = bsd.beam_size
    cell_states = inits

    def _rep(tree, w):
        return jax.tree_util.tree_map(
            lambda a: jnp.repeat(a._data if isinstance(a, Tensor) else a,
                                 w, axis=0), tree)

    # infer batch from the init state
    leaves = jax.tree_util.tree_leaves(cell_states)
    B = int(leaves[0].shape[0]) if leaves else 1
    states = _rep(cell_states, W)                      # [B*W, ...]
    tok = np.full((B, W), bsd.start_token, np.int64)
    scores = np.full((B, W), -1e9, np.float32)
    scores[:, 0] = 0.0                                 # one live beam at t=0
    finished = np.zeros((B, W), bool)
    ids_hist = []

    for _ in range(max_step_num):
        t_in = Tensor(jnp.asarray(tok.reshape(-1)))
        logits, states = bsd._logits(t_in, states)
        lp = jax.nn.log_softmax(
            logits._data if isinstance(logits, Tensor) else logits, -1)
        lp = np.asarray(lp).reshape(B, W, -1)
        V = lp.shape[-1]
        # finished beams only extend with EOS at 0 cost
        lp_fin = np.full((B, W, V), -np.inf, np.float32)
        lp_fin[:, :, bsd.end_token] = 0.0
        lp = np.where(finished[:, :, None], lp_fin, lp)
        total = scores[:, :, None] + lp                # [B, W, V]
        flat = total.reshape(B, -1)
        top = np.argsort(-flat, axis=1)[:, :W]
        scores = np.take_along_axis(flat, top, 1)
        parent = top // V
        tok = (top % V).astype(np.int64)
        finished = np.take_along_axis(finished, parent, 1) | (
            tok == bsd.end_token)
        # reorder states by parent beam
        idx = (np.arange(B)[:, None] * W + parent).reshape(-1)
        states = jax.tree_util.tree_map(
            lambda a: (a._data if isinstance(a, Tensor) else a)[idx], states)
        ids_hist.append((tok.copy(), parent.copy()))
        if finished.all():
            break

    # backtrack through parents
    T = len(ids_hist)
    out = np.zeros((B, W, T), np.int64)
    beam = np.tile(np.arange(W), (B, 1))
    for t in range(T - 1, -1, -1):
        tok_t, par_t = ids_hist[t]
        out[:, :, t] = np.take_along_axis(tok_t, beam, 1)
        beam = np.take_along_axis(par_t, beam, 1)
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(scores))
