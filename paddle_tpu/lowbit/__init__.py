"""paddle_tpu.lowbit — real int8/int4 low-bit runtime (ISSUE 4 tentpole).

Three wings, one storage convention (`ops/lowbit.py`: symmetric abs-max,
``dequant = codes * scale``):

1. **weight-only quantized inference** (`weight_only.py`) —
   `quantize_for_inference(model, weight_dtype="int8"|"int4")` swaps
   `nn.Linear` → `WeightOnlyLinear` (packed codes + per-channel scales,
   dequant-in-kernel matmul with fp32 accumulate); the quantization kit's
   QAT/PTQ `convert(weight_only=...)` targets it with calibrated scales.
2. **quantized KV cache** (`serving.BlockKVCache(kv_quant="int8")`,
   `LLMEngine(EngineConfig(kv_cache_dtype="int8"))`) — int8 block pools
   with per-block-per-head scales, dequantizing gather in
   `ops/paged_attention.py`; ~halved bytes/block ⇒ ~2× blocks per pool.
3. **quantized collectives** (`comm.py`) — EQuARX-style int8 all-reduce /
   all-gather (shared per-chunk scale, int32 reduction, optional error
   feedback), exposed as `distributed.all_reduce(..., compress="int8")`
   and the fleet ``int8_allreduce`` strategy flag.

Monitor series: ``lowbit/bytes_saved{wing}``, ``lowbit/weight_layers``,
``lowbit/kv_blocks{dtype}``, ``lowbit/comm_bytes{kind,mode}``,
``lowbit/comm_compression_ratio{kind}``, ``lowbit/dequant_calls{site}``.
"""
from .weight_only import WeightOnlyLinear, quantize_for_inference
from .comm import (DEFAULT_CHUNK, quantized_all_gather_arrays,
                   quantized_all_reduce_arrays)
from ..ops.lowbit import (dequantize_arrays, pack_int4_arrays,
                          qmax_for_bits, quantize_absmax_arrays,
                          quantized_matmul_arrays, unpack_int4_arrays)

__all__ = [
    "WeightOnlyLinear", "quantize_for_inference",
    "quantized_all_reduce_arrays", "quantized_all_gather_arrays",
    "DEFAULT_CHUNK",
    "quantize_absmax_arrays", "dequantize_arrays", "quantized_matmul_arrays",
    "pack_int4_arrays", "unpack_int4_arrays", "qmax_for_bits",
]
