"""EQuARX-style quantized collectives (PAPERS.md): int8 on the wire,
exact integer reduction, optional error feedback.

The trick that keeps a quantized ALL-REDUCE exact-in-int: every member
must quantize with the SAME scale, or the integer sum is meaningless.  So
each chunk's abs-max scale is itself pmax-ed over the axis first (a tiny
[n_chunks] f32 collective), every member requantizes against the shared
scale, and the int32 psum of codes then dequantizes as
``sum_q * shared_scale`` — the only lossy step is the local round, whose
residual ``x − q·s`` feeds the optional error-feedback buffer
(next call adds it back, the DGC/EF-SGD convergence argument).

Wire accounting: the payload drops from 4 bytes/element to 1 byte (int8
codes) + 4/chunk (shared scales); ``lowbit/comm_bytes{mode=raw|compressed}``
counters and the ``lowbit/comm_compression_ratio`` gauge record it per
trace.

These are jnp/array-level functions usable inside any shard_map region;
`paddle_tpu.distributed.all_reduce(..., compress="int8")` and the fleet
``int8_allreduce`` strategy flag (meta_optimizers.QuantAllReduceOptimizer)
are the Tensor-level entry points.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .. import monitor
from ..ops.lowbit import qmax_for_bits, quantize_with_scale_arrays

__all__ = ["quantized_all_reduce_arrays", "quantized_all_gather_arrays",
           "DEFAULT_CHUNK"]

DEFAULT_CHUNK = 256


def _count_comm(kind, n_elems, itemsize, bits, n_chunks):
    if not monitor.enabled():
        return
    raw = int(n_elems) * int(itemsize)
    compressed = (int(n_elems) if bits == 8 else (int(n_elems) + 1) // 2) \
        + 4 * int(n_chunks)
    monitor.counter("lowbit/comm_bytes").labels(
        kind=kind, mode="raw").add(raw)
    monitor.counter("lowbit/comm_bytes").labels(
        kind=kind, mode="compressed").add(compressed)
    monitor.gauge("lowbit/comm_compression_ratio",
                  "raw / compressed payload bytes").labels(kind=kind).set(
        raw / max(compressed, 1))


def _to_chunks(a, chunk):
    """Flatten to [n_chunks, chunk] (zero-padded tail)."""
    flat = jnp.ravel(a)
    n = flat.shape[0]
    n_chunks = -(-n // chunk)
    pad = n_chunks * chunk - n
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat.reshape(n_chunks, chunk), n


def _quantize_shared(chunks, axis_name, bits):
    """Per-chunk abs-max scale, pmax-shared over the axis; returns
    (codes int8 [n_chunks, chunk], shared scale f32 [n_chunks, 1])."""
    qmax = qmax_for_bits(bits)
    amax = jnp.max(jnp.abs(chunks), axis=1, keepdims=True)
    scale = jax.lax.pmax(amax.astype(jnp.float32), axis_name) / qmax
    return quantize_with_scale_arrays(chunks.astype(jnp.float32),
                                      scale, qmax), scale


def quantized_all_reduce_arrays(a, axis_name, bits=8, chunk=DEFAULT_CHUNK,
                                residual=None, average=False):
    """Quantized all-reduce(SUM/AVG) of `a` over a live mesh axis.

    residual: optional error-feedback buffer (same shape as `a`); it is
    ADDED to the input before quantization and the new local rounding
    error comes back as the second return value — thread it into the next
    call and the quantization noise becomes a delayed, not lost, signal.
    Returns (reduced array in a's dtype, new_residual or None).
    """
    dt = a.dtype
    x = a.astype(jnp.float32)
    if residual is not None:
        x = x + residual.astype(jnp.float32)
    chunks, n = _to_chunks(x, chunk)
    q, scale = _quantize_shared(chunks, axis_name, bits)
    _count_comm("all_reduce", n, np.dtype(dt).itemsize, bits,
                chunks.shape[0])
    total = jax.lax.psum(q.astype(jnp.int32), axis_name)
    out = total.astype(jnp.float32) * scale
    if average:
        out = out / jax.lax.psum(1, axis_name)
    new_res = None
    if residual is not None:
        # local quantization error: what THIS member failed to inject
        new_res = (chunks - q.astype(jnp.float32) * scale).reshape(-1)[:n] \
            .reshape(a.shape).astype(residual.dtype)
    return out.reshape(-1)[:n].reshape(a.shape).astype(dt), new_res


def quantized_all_gather_arrays(a, axis_name, bits=8, chunk=DEFAULT_CHUNK):
    """Quantized all-gather: each member ships int8 codes + its own
    per-chunk scales; every member dequantizes every shard.  Returns
    [world, *a.shape] in a's dtype (tiled=False layout, matching
    `jax.lax.all_gather`)."""
    qmax = qmax_for_bits(bits)
    dt = a.dtype
    chunks, n = _to_chunks(a.astype(jnp.float32), chunk)
    amax = jnp.max(jnp.abs(chunks), axis=1, keepdims=True)
    scale = amax.astype(jnp.float32) / qmax
    q = quantize_with_scale_arrays(chunks, scale, qmax)
    _count_comm("all_gather", n, np.dtype(dt).itemsize, bits,
                chunks.shape[0])
    gq = jax.lax.all_gather(q, axis_name, tiled=False)
    gs = jax.lax.all_gather(scale, axis_name, tiled=False)
    deq = gq.astype(jnp.float32) * gs
    world = deq.shape[0]
    return deq.reshape(world, -1)[:, :n].reshape(
        (world,) + tuple(a.shape)).astype(dt)
