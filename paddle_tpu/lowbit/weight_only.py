"""Weight-only quantized inference: swap `nn.Linear` → `WeightOnlyLinear`.

The quantization kit's QAT/PTQ paths are *fake*-quant — every tensor
stays fp32, nothing shrinks.  This module is the real thing: weights are
STORED as int8 (or int4, packed two-per-byte) with per-channel float32
scales, and the matmul dequantizes in-kernel with fp32 accumulation
(`ops.lowbit.quantized_matmul_arrays`).  Activations stay in the model
dtype — weight-only is the serving sweet spot (decode is weight-bandwidth
bound; halving/quartering weight bytes is a direct tokens/s and
HBM-capacity win, PAPERS.md low-bit serving line).

Accuracy: per-channel abs-max int8 is near-lossless on trained linears
(each output channel gets its own dynamic range); int4 costs real
precision and is for capacity emergencies — tests/test_lowbit.py pins
both tolerance envelopes.
"""
from __future__ import annotations

import copy
import warnings

import jax.numpy as jnp

from .. import monitor
from ..core.tensor import Tensor
from ..core.dispatch import apply
from ..nn.layer import Layer
from ..nn.common import Linear
from ..ops.lowbit import (pack_int4_arrays, qmax_for_bits,
                          quantize_absmax_arrays, quantize_with_scale_arrays,
                          quantized_bytes, quantized_matmul_arrays)

__all__ = ["WeightOnlyLinear", "quantize_for_inference"]

_BITS = {"int8": 8, "int4": 4}


class WeightOnlyLinear(Layer):
    """Inference-only Linear over packed low-bit weights.

    Storage (registered buffers, so state_dict round-trips them):

    - ``qweight`` — int8 [in, out] codes, or uint8 [ceil(in/2), out]
      packed nibbles for int4;
    - ``scale``  — float32 [out] per-channel (or scalar per-tensor);
    - ``bias``   — the original bias, untouched.

    Forward = ``(x @ q) * scale + b`` with fp32 accumulation; gradients
    are not defined through the integer weight (inference only — wrap
    QAT around the fp original if you need to train).
    """

    def __init__(self, in_features, out_features, weight_dtype="int8",
                 per_channel=True):
        super().__init__()
        if weight_dtype not in _BITS:
            raise ValueError(
                f"weight_dtype must be one of {sorted(_BITS)}, got "
                f"{weight_dtype!r}")
        self.in_features = int(in_features)
        self.out_features = int(out_features)
        self.weight_dtype = weight_dtype
        self.bits = _BITS[weight_dtype]
        self.per_channel = bool(per_channel)
        rows = (self.in_features + 1) // 2 if self.bits == 4 \
            else self.in_features
        cdtype = jnp.uint8 if self.bits == 4 else jnp.int8
        self.register_buffer(
            "qweight", Tensor(jnp.zeros((rows, self.out_features), cdtype)))
        scale_shape = (self.out_features,) if per_channel else ()
        self.register_buffer(
            "scale", Tensor(jnp.zeros(scale_shape, jnp.float32)))
        self.bias = None

    @classmethod
    def from_linear(cls, layer, weight_dtype="int8",
                    per_channel=True, scale=None):
        """Quantize a linear-shaped layer's live weight (anything holding
        a [in, out] `weight` and optional `bias` — nn.Linear, or the mp
        layers at degree 1).  `scale` overrides the abs-max-derived scale
        (QAT/PTQ convert passes the calibrated quanter scale through
        here — already in dequant-ready ``absmax/qmax`` form)."""
        in_features, out_features = layer.weight.shape
        m = cls(in_features, out_features,
                weight_dtype=weight_dtype, per_channel=per_channel)
        w = layer.weight._data
        if scale is not None:
            s = jnp.asarray(scale, jnp.float32)
            q = quantize_with_scale_arrays(w, s, qmax_for_bits(m.bits))
        else:
            q, s = quantize_absmax_arrays(w, bits=m.bits,
                                          axis=0 if per_channel else None)
        if m.bits == 4:
            q = pack_int4_arrays(q)
        m.qweight._data = q
        m.scale._data = jnp.broadcast_to(
            s, m.scale.shape if m.per_channel else ()).astype(jnp.float32)
        if layer.bias is not None:
            m.bias = layer.bias
        return m

    def forward(self, x):
        args = (x, self.qweight, self.scale)
        if self.bias is not None:
            return apply(
                lambda a, q, s, b: quantized_matmul_arrays(
                    a, q, s, bits=self.bits,
                    in_features=self.in_features) + b,
                *args, self.bias, name="weight_only_linear")
        return apply(
            lambda a, q, s: quantized_matmul_arrays(
                a, q, s, bits=self.bits, in_features=self.in_features),
            *args, name="weight_only_linear")

    # -- accounting ---------------------------------------------------------

    @property
    def packed_bytes(self) -> int:
        return quantized_bytes((self.in_features, self.out_features),
                               self.bits, self.scale._data.size)

    @property
    def dense_bytes(self) -> int:
        return self.in_features * self.out_features * 4

    def extra_repr(self):
        return (f"in_features={self.in_features}, "
                f"out_features={self.out_features}, "
                f"weight_dtype={self.weight_dtype}, "
                f"per_channel={self.per_channel}")


def quantize_for_inference(model, weight_dtype="int8", per_channel=True,
                           inplace=False):
    """Swap every `nn.Linear` in `model` for a `WeightOnlyLinear` holding
    packed low-bit codes of its current weight.  Returns the (copied
    unless `inplace`) model in eval mode.

    Emits ``lowbit/bytes_saved{wing=weights}`` (fp32 bytes − packed
    bytes) and ``lowbit/weight_layers`` to the monitor.
    """
    if weight_dtype not in _BITS:
        raise ValueError(
            f"weight_dtype must be one of {sorted(_BITS)}, got "
            f"{weight_dtype!r}")
    if not inplace:
        model = copy.deepcopy(model)
    saved = [0]
    swapped = [0]

    def _quantable(sub):
        if isinstance(sub, Linear):
            return True
        # the tensor-parallel linears are plain y = xW (+ b) when the
        # 'mp' axis has degree 1 (their sharding constraints are
        # identities) — the common serving shape.  At real mp degree the
        # sharded weight layout is NOT weight-only-quantizable here.
        from ..parallel.mesh import axis_size
        from ..parallel.mp_layers import (ColumnParallelLinear,
                                          RowParallelLinear)

        if isinstance(sub, (ColumnParallelLinear, RowParallelLinear)):
            if axis_size("mp") == 1:
                return True
            warnings.warn(
                f"quantize_for_inference: skipping {type(sub).__name__} — "
                "weight-only quantization of mp-sharded weights is not "
                "supported (mp degree > 1)")
        return False

    def _swap(layer):
        for name, sub in list(layer._sub_layers.items()):
            if _quantable(sub):
                wol = WeightOnlyLinear.from_linear(
                    sub, weight_dtype=weight_dtype, per_channel=per_channel)
                # setattr, not a bare _sub_layers[name] write: Layer's
                # __setattr__ mirrors sublayers into __dict__, and a
                # forward that says `self.fc` reads THAT copy
                setattr(layer, name, wol)
                saved[0] += wol.dense_bytes - wol.packed_bytes
                swapped[0] += 1
            else:
                _swap(sub)

    _swap(model)
    if monitor.enabled():
        monitor.counter("lowbit/bytes_saved",
                        "storage bytes removed by low-bit packing").labels(
            wing="weights").add(saved[0])
        monitor.counter("lowbit/weight_layers",
                        "Linears swapped to WeightOnlyLinear").add(swapped[0])
    model.eval()
    return model
