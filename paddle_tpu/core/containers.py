"""Auxiliary tensor containers (reference: phi/core/selected_rows.h and
phi/core/string_tensor.h — the non-dense tensor types in the phi type
system; SURVEY §2.1).

TPU-native positions:

- SelectedRows is the reference's sparse-row gradient container (embedding
  grads touch few vocab rows). XLA consumes dense arrays, so here it is a
  host-side accumulation structure: rows+values pairs that merge cheaply
  (the lookup_table_grad "merge duplicate rows" step) and densify once at
  the optimizer boundary — O(touched rows) memory until the update.
- StringTensor is host-side by definition (strings never reach the MXU);
  it wraps a numpy object array with tensor-shaped indexing so string
  pipelines (tokenizer feeds) have the reference's container surface.
"""
from __future__ import annotations

from typing import Sequence, Union

import numpy as np
import jax.numpy as jnp

from .tensor import Tensor

__all__ = ["SelectedRows", "StringTensor"]


class SelectedRows:
    """Sparse row set over a [height, ...row_shape] dense space."""

    def __init__(self, rows, values, height: int):
        rows = np.asarray(rows, np.int64).reshape(-1)
        vals = values._data if isinstance(values, Tensor) else jnp.asarray(values)
        if vals.shape[0] != rows.shape[0]:
            raise ValueError(
                f"rows ({rows.shape[0]}) and values ({vals.shape[0]}) differ")
        if rows.size and (rows.min() < 0 or rows.max() >= height):
            raise ValueError(f"row ids out of range [0, {height})")
        self.rows = rows
        self.values = vals
        self.height = int(height)

    @property
    def shape(self):
        return (self.height,) + tuple(self.values.shape[1:])

    def merge(self) -> "SelectedRows":
        """Sum duplicate rows (reference MergeAdd functor)."""
        uniq, inv = np.unique(self.rows, return_inverse=True)
        merged = jnp.zeros((len(uniq),) + self.values.shape[1:],
                           self.values.dtype)
        merged = merged.at[jnp.asarray(inv)].add(self.values)
        return SelectedRows(uniq, merged, self.height)

    def to_dense(self) -> Tensor:
        out = jnp.zeros(self.shape, self.values.dtype)
        out = out.at[jnp.asarray(self.rows)].add(self.values)
        return Tensor(out)

    def __repr__(self):
        return (f"SelectedRows(height={self.height}, "
                f"nnz_rows={len(self.rows)}, row_shape={self.values.shape[1:]})")


class StringTensor:
    """Host-side string array with tensor-shaped metadata."""

    def __init__(self, data: Union[Sequence, np.ndarray], name: str = None):
        self._data = np.asarray(data, dtype=object)
        self.name = name

    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def ndim(self):
        return self._data.ndim

    def numpy(self):
        return self._data

    def tolist(self):
        return self._data.tolist()

    def __getitem__(self, idx):
        out = self._data[idx]
        if isinstance(out, np.ndarray):
            return StringTensor(out)
        return out

    def __len__(self):
        return self._data.shape[0] if self._data.ndim else 1

    def __repr__(self):
        return f"StringTensor(shape={self.shape}, {self._data!r})"
