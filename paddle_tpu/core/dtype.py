"""Dtype registry.

TPU-native analog of the reference's dtype inventory
(paddle/phi/common/data_type.h, platform/bfloat16.h — see SURVEY §8.12):
fp32/fp64/fp16/bf16, complex64/128, int8..64, uint8, bool. We use numpy/jax
dtypes directly as the canonical representation; bfloat16 comes from ml_dtypes
via jax. fp64 is supported only when jax x64 is enabled (off by default —
TPU-first means fp32/bf16 discipline).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

bool_ = jnp.bool_.dtype if hasattr(jnp.bool_, "dtype") else np.dtype("bool")
bool_ = np.dtype("bool")
uint8 = np.dtype("uint8")
int8 = np.dtype("int8")
int16 = np.dtype("int16")
int32 = np.dtype("int32")
int64 = np.dtype("int64")
float16 = np.dtype("float16")
bfloat16 = jnp.bfloat16.dtype
float32 = np.dtype("float32")
float64 = np.dtype("float64")
complex64 = np.dtype("complex64")
complex128 = np.dtype("complex128")

_STR2DTYPE = {
    "bool": bool_,
    "uint8": uint8,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "float16": float16,
    "fp16": float16,
    "bfloat16": bfloat16,
    "bf16": bfloat16,
    "float32": float32,
    "fp32": float32,
    "float64": float64,
    "fp64": float64,
    "complex64": complex64,
    "complex128": complex128,
}

_FLOATING = {float16, bfloat16, float32, float64}
_INTEGER = {uint8, int8, int16, int32, int64}
_COMPLEX = {complex64, complex128}


def convert_dtype(dtype):
    """Normalize str/np.dtype/jnp dtype-like into a canonical np.dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        try:
            return _STR2DTYPE[dtype]
        except KeyError:
            raise ValueError(f"Unknown dtype string: {dtype!r}")
    return np.dtype(dtype)


def is_floating_point(dtype) -> bool:
    return convert_dtype(dtype) in _FLOATING


def is_integer(dtype) -> bool:
    return convert_dtype(dtype) in _INTEGER


def is_complex(dtype) -> bool:
    return convert_dtype(dtype) in _COMPLEX
