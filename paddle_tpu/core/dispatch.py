"""Eager op dispatch.

The TPU-native replacement for the reference's entire dispatch stack
(_C_ops → pybind eager_op_function.cc → *_ad_func → phi::KernelFactory →
kernel launch; SURVEY §3.1). Every framework op is defined once as a pure
jax-traceable function; `apply` runs it eagerly (XLA compiles + caches per
shape/dtype, playing the role of the reference's KernelKey-indexed kernel
cache) and, when any input requires grad, records a tape Node holding the
jax.vjp pullback — this single generic path replaces the YAML→codegen'd
per-op forward/GradNode pairs (eager_gen.py).
"""
from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp

from .tensor import Tensor
from ..autograd import tape

__all__ = ["apply", "defop", "unwrap", "wrap"]


def unwrap(x):
    return x._data if isinstance(x, Tensor) else x


def wrap(x, stop_gradient=True):
    return Tensor(x, stop_gradient=stop_gradient)


def apply(fn: Callable, *tensor_args, n_outs=None, name=None, **static_kwargs):
    """Run `fn(*arrays, **static_kwargs)` eagerly with autograd recording.

    tensor_args: Tensors (or array-likes) — the differentiable positional args.
    static_kwargs: non-differentiable attrs (ints, strings, shapes...).
    Returns Tensor or tuple of Tensors mirroring fn's output structure.
    """
    ts = [t if isinstance(t, Tensor) else Tensor(jnp.asarray(t)) for t in tensor_args]
    arrays = [t._data for t in ts]
    if static_kwargs:
        fn_c = functools.partial(fn, **static_kwargs)
    else:
        fn_c = fn

    # AMP O1/O2 autocast (reference: eager_amp_auto_cast.h applied inside
    # every generated *_ad_func). The cast lives INSIDE the op function so
    # (a) jax.vjp differentiates through it — cotangents arrive back in the
    # params' own dtype, exactly like the reference's recorded cast op —
    # and (b) under a jit trace the autocast state is captured at trace
    # time, the analog of amp attrs baked into a static program.
    amp_state = _amp_state if _amp_state is not None else _bind_amp()
    if amp_state.enabled:
        plan = _amp_plan(name or getattr(fn, "__name__", "op"), arrays)
        if plan is not None:
            inner_fn = fn_c

            def fn_c(*arrs, __inner=inner_fn, __plan=plan):
                return __inner(*[a.astype(d) if d is not None else a
                                 for a, d in zip(arrs, __plan)])

    needs = [
        (not t.stop_gradient) and jnp.issubdtype(t._data.dtype, jnp.inexact)
        for t in ts
    ]
    trace_grad = tape.is_grad_enabled() and any(needs)

    # Eager forward runs WITHOUT jax.vjp: linearization tracing costs ~5x
    # the op itself on eager dispatch (measured 4295us vs 776us for a 256^2
    # matmul chain on CPU), so the tape stores the pure forward and
    # materializes the pullback lazily at backward time (tape.Node
    # .ensure_vjp) — forwards that never reach a backward (eval loops
    # without no_grad, the SURVEY §7 "eager overhead" hard part) no
    # longer pay for gradients. UNDER A JIT TRACE the pullback is taken
    # up front instead: the lazy path would re-trace the forward into the
    # same jaxpr a second time, and XLA does not reliably CSE the
    # duplicate across Pallas custom-call boundaries (measured -23%
    # tokens/sec on the GPT-2 bench when the flash forward ran twice).
    if trace_grad and any(isinstance(a, jax.core.Tracer) for a in arrays):
        out, vjp_fn = jax.vjp(fn_c, *arrays)
    else:
        out, vjp_fn = fn_c(*arrays), None

    multi = isinstance(out, (tuple, list))
    outs = list(out) if multi else [out]
    out_ts = [Tensor(o) for o in outs]

    if trace_grad:
        tape.record(vjp_fn, ts, needs, out_ts,
                    name=name or getattr(fn, "__name__", "op"), fwd_fn=fn_c)

    prog = _static_recording()
    if prog is not None:
        prog._record_op(fn_c, ts, out_ts,
                        name=name or getattr(fn, "__name__", "op"),
                        attrs=static_kwargs)

    if _nan_check_enabled():
        _check_nan_inf(outs, name or getattr(fn, "__name__", "op"))

    return tuple(out_ts) if multi else out_ts[0]


_amp_state = None
_amp_plan = None


def _bind_amp():
    """Lazy one-time bind of the amp thread-local (amp imports after core
    during package init; a module-top import would cycle)."""
    global _amp_state, _amp_plan
    from .. import amp as _amp_mod

    _amp_state = _amp_mod._state
    _amp_plan = _amp_mod.cast_plan
    return _amp_state


def _static_recording():
    """Program under construction when enable_static() + program building
    is active (static/__init__.py) — the append_op hook."""
    from ..static import _recording_program

    return _recording_program()


def _nan_check_enabled():
    from ..framework import core_

    return bool(core_._flags.get("FLAGS_check_nan_inf", False))


def _check_nan_inf(outs, op_name):
    """FLAGS_check_nan_inf analog (reference: operator.cc:1608 +
    eager/nan_inf_utils.cc — per-op output scan). Eager-only: inside a jit
    trace outputs are tracers and the scan is skipped (the reference's
    static-graph checker is likewise a debug mode)."""
    for i, o in enumerate(outs):
        if isinstance(o, jax.core.Tracer):
            return
        if jnp.issubdtype(o.dtype, jnp.inexact):
            bad = int(jnp.sum(~jnp.isfinite(o)))
            if bad:
                raise FloatingPointError(
                    f"Operator {op_name!r} output {i} contains {bad} "
                    f"NaN/Inf values (shape {tuple(o.shape)}, dtype {o.dtype}); "
                    f"FLAGS_check_nan_inf is enabled")


def defop(n_tensor_args=None, name=None):
    """Decorator: turn a pure jax function into an eager framework op.

    The wrapped function takes Tensors first, then static keyword attrs:

        @defop()
        def relu(x):
            return jnp.maximum(x, 0)
    """

    def deco(fn):
        op_name = name or fn.__name__

        @functools.wraps(fn)
        def op(*args, **kwargs):
            return apply(fn, *args, name=op_name, **kwargs)

        op._jax_fn = fn
        return op

    return deco
