"""Global RNG state.

Reference keeps per-generator state (python/paddle/fluid/framework.py seed,
mp-rank RNGStatesTracker fleet/layers/mpu/random.py:35). TPU-native design:
a counter-split jax PRNG key stack. `next_key()` works both eagerly (concrete
key) and inside a jit trace (a traced base key pushed by the compiler path),
so dropout/random ops are usable under whole-graph compilation without
baking constants.
"""
from __future__ import annotations

import threading

import jax

__all__ = ["seed", "next_key", "get_state", "set_state", "key_scope"]


class _RngState(threading.local):
    def __init__(self):
        # created on FIRST USE, not at import: PRNGKey(0) materializes a
        # device array, which initializes the XLA backend — and
        # jax.distributed.initialize (multi-host bring-up) must run before
        # any backend init. `import paddle_tpu` has to stay backend-free.
        self.stack = None


_state = _RngState()


def _stack():
    if _state.stack is None:
        _state.stack = [jax.random.PRNGKey(0)]
    return _state.stack


def seed(s: int):
    """paddle.seed equivalent: reset the root key."""
    _stack()[-1] = jax.random.PRNGKey(int(s))
    return s


def next_key():
    st = _stack()
    cur = st[-1]
    new, sub = jax.random.split(cur)
    st[-1] = new
    return sub


def get_state():
    return _stack()[-1]


def set_state(key):
    _stack()[-1] = key


class key_scope:
    """Push a (possibly traced) base key — used by jit tracing and by the
    mp-rank RNG tracker (parallel/random.py)."""

    def __init__(self, key):
        self._key = key

    def __enter__(self):
        _stack().append(self._key)
        return self

    def __exit__(self, *exc):
        _stack().pop()
        return False
