"""Global RNG state.

Reference keeps per-generator state (python/paddle/fluid/framework.py seed,
mp-rank RNGStatesTracker fleet/layers/mpu/random.py:35). TPU-native design:
a counter-split jax PRNG key stack. `next_key()` works both eagerly (concrete
key) and inside a jit trace (a traced base key pushed by the compiler path),
so dropout/random ops are usable under whole-graph compilation without
baking constants.
"""
from __future__ import annotations

import threading

import jax

__all__ = ["seed", "next_key", "get_state", "set_state", "key_scope"]


class _RngState(threading.local):
    def __init__(self):
        self.stack = [jax.random.PRNGKey(0)]


_state = _RngState()


def seed(s: int):
    """paddle.seed equivalent: reset the root key."""
    _state.stack[-1] = jax.random.PRNGKey(int(s))
    return s


def next_key():
    cur = _state.stack[-1]
    new, sub = jax.random.split(cur)
    _state.stack[-1] = new
    return sub


def get_state():
    return _state.stack[-1]


def set_state(key):
    _state.stack[-1] = key


class key_scope:
    """Push a (possibly traced) base key — used by jit tracing and by the
    mp-rank RNG tracker (parallel/random.py)."""

    def __init__(self, key):
        self._key = key

    def __enter__(self):
        _state.stack.append(self._key)
        return self

    def __exit__(self, *exc):
        _state.stack.pop()
        return False
