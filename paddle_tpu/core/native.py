"""Loader for the native runtime library (csrc/ — TCP store, shm ring).

The reference ships its runtime as one big pybind'd C++ tree; here the
native pieces are a small C-ABI shared library consumed via ctypes, built
lazily on first use (`make` in csrc/) and cached. Components degrade to
pure-Python fallbacks when no toolchain is available, so the framework
stays importable everywhere.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_CSRC = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), "csrc")
_LIB_PATH = os.path.join(_CSRC, "build", "libpaddle_tpu_native.so")

_lock = threading.Lock()
_lib = None
_tried = False


def _needs_build():
    if not os.path.exists(_LIB_PATH):
        return True
    lib_mtime = os.path.getmtime(_LIB_PATH)
    for f in os.listdir(_CSRC):
        # the Makefile counts: CXXFLAGS / source-list edits must trigger a
        # rebuild too, or a stale library is dlopened and the missing-symbol
        # fallback silently disables every native path
        if (f.endswith((".cc", ".h")) or f == "Makefile") and os.path.getmtime(
                os.path.join(_CSRC, f)) > lib_mtime:
            return True
    return False


def _build():
    subprocess.run(
        ["make", "-s", "-C", _CSRC],
        check=True,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        timeout=300,
    )


def load():
    """Return the loaded native library, building it if needed; None when
    unavailable (no sources / no toolchain)."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        try:
            if not os.path.isdir(_CSRC):
                return None
            # decide staleness BEFORE the first dlopen: reloading after a
            # rebuild cannot work in-process (dlopen dedupes by pathname
            # and ctypes never dlcloses), so a stale handle would stick
            if _needs_build():
                _build()
            lib = ctypes.CDLL(_LIB_PATH)
        except (OSError, subprocess.SubprocessError):
            return None

        # -- bindings: a missing symbol (stale .so that make could not
        # refresh) must degrade to the pure-Python fallbacks, not crash
        # every native consumer --
        try:
            _bind(lib)
        except AttributeError:
            return None
        _lib = lib
        return _lib


def _bind(lib):
    # -- tcp store --
    lib.pts_server_start.restype = ctypes.c_int64
    lib.pts_server_start.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.pts_server_stop.argtypes = [ctypes.c_int64]
    lib.pts_connect.restype = ctypes.c_int64
    lib.pts_connect.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
    lib.pts_close.argtypes = [ctypes.c_int64]
    lib.pts_set.restype = ctypes.c_int
    lib.pts_set.argtypes = [ctypes.c_int64, ctypes.c_char_p,
                                ctypes.c_char_p, ctypes.c_int64]
    lib.pts_get.restype = ctypes.c_int64
    lib.pts_get.argtypes = [ctypes.c_int64, ctypes.c_char_p,
                                ctypes.c_void_p, ctypes.c_int64, ctypes.c_int]
    lib.pts_add.restype = ctypes.c_int
    lib.pts_add.argtypes = [ctypes.c_int64, ctypes.c_char_p,
                                ctypes.c_int64,
                                ctypes.POINTER(ctypes.c_int64)]
    lib.pts_wait.restype = ctypes.c_int
    lib.pts_wait.argtypes = [ctypes.c_int64, ctypes.c_char_p, ctypes.c_int]
    lib.pts_delete_key.restype = ctypes.c_int
    lib.pts_delete_key.argtypes = [ctypes.c_int64, ctypes.c_char_p]
    lib.pts_cas.restype = ctypes.c_int64
    lib.pts_cas.argtypes = [ctypes.c_int64, ctypes.c_char_p,
                            ctypes.c_char_p, ctypes.c_int64,
                            ctypes.c_char_p, ctypes.c_int64,
                            ctypes.c_void_p, ctypes.c_int64]

    # -- shm ring --
    lib.shm_ring_create.restype = ctypes.c_int64
    lib.shm_ring_create.argtypes = [ctypes.c_char_p, ctypes.c_int64]
    lib.shm_ring_attach.restype = ctypes.c_int64
    lib.shm_ring_attach.argtypes = [ctypes.c_char_p]
    lib.shm_ring_close.argtypes = [ctypes.c_int64, ctypes.c_int]
    lib.shm_ring_push.restype = ctypes.c_int
    lib.shm_ring_push.argtypes = [ctypes.c_int64, ctypes.c_char_p,
                                  ctypes.c_int64, ctypes.c_int]
    lib.shm_ring_pop_len.restype = ctypes.c_int64
    lib.shm_ring_pop_len.argtypes = [ctypes.c_int64, ctypes.c_int]
    lib.shm_ring_pop.restype = ctypes.c_int64
    lib.shm_ring_pop.argtypes = [ctypes.c_int64, ctypes.c_void_p,
                                 ctypes.c_int64]

    # -- wordpiece tokenizer --
    lib.wp_vocab_new.restype = ctypes.c_int64
    lib.wp_vocab_new.argtypes = [ctypes.c_int32, ctypes.c_int32]
    lib.wp_vocab_add.restype = ctypes.c_int
    lib.wp_vocab_add.argtypes = [ctypes.c_int64, ctypes.c_char_p,
                                 ctypes.c_int32]
    lib.wp_vocab_free.argtypes = [ctypes.c_int64]
    lib.wp_encode.restype = ctypes.c_int32
    lib.wp_encode.argtypes = [ctypes.c_int64, ctypes.c_char_p,
                              ctypes.c_int32,
                              ctypes.POINTER(ctypes.c_int32),
                              ctypes.c_int32]


def available():
    return load() is not None
