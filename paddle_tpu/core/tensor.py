"""Eager Tensor.

TPU-native analog of `paddle.Tensor` (reference: phi::DenseTensor
paddle/phi/core/dense_tensor.h:38 + pybind eager_method.cc). The device
buffer, layout, sharding and async execution are all delegated to a
`jax.Array` — XLA's runtime already provides what the reference builds by
hand in paddle/fluid/memory/ (stream-safe allocation, async dispatch) — so
this class only adds the *framework* state: stop_gradient, .grad, the
autograd node pointer, and the method surface.

Methods are attached by `paddle_tpu.ops` at import time (same pattern as the
reference's `python/paddle/tensor/__init__.py` monkey-patching).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .dtype import convert_dtype, is_floating_point

__all__ = ["Tensor", "to_tensor", "TracedValueError"]


class TracedValueError(TypeError):
    """A traced tensor was used where a concrete Python value is required
    (float()/int()/bool()/.item() under jit). Subclasses TypeError so
    generic numeric-coercion handlers keep working."""


class Tensor:
    __slots__ = (
        "_data_",
        "stop_gradient",
        "grad",
        "_grad_node",
        "_out_index",
        "name",
        "persistable",
        "_hooks",
        "_version",
        "__weakref__",
    )

    def __init__(self, data, stop_gradient=True, name=None):
        # inplace-version counter (reference: eager/tensor_wrapper.h
        # inplace_version check): the _data setter bumps it on EVERY
        # rebind, so no mutation path can forget; the tape snapshots it at
        # record time and errors on backward if a saved input was mutated
        # after the forward ran (backward replays the forward lazily —
        # dispatch.apply — so a missed bump would mean silently wrong
        # gradients, not just a stale-aliasing nicety).
        self._version = 0
        self._data = data  # jax.Array (or tracer under jit)
        self.stop_gradient = stop_gradient
        self.grad = None
        self._grad_node = None
        self._out_index = 0
        self.name = name
        self.persistable = False
        self._hooks = None

    @property
    def _data(self):
        return self._data_

    @_data.setter
    def _data(self, value):
        self._data_ = value
        self._version += 1

    def _bump_version(self):
        self._version += 1

    # -- basic metadata ----------------------------------------------------
    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def place(self):
        try:
            devs = self._data.devices()
            return next(iter(devs))
        except Exception:
            return None

    @property
    def is_leaf(self):
        return self._grad_node is None

    def numel(self):
        return self.size

    def dim(self):
        return self.ndim

    # -- host transfer -----------------------------------------------------
    def numpy(self):
        a = self._data
        if isinstance(a, jax.core.Tracer):
            raise TracedValueError(
                "this Tensor is a TRACED value (inside jit / staged "
                "control flow), so a concrete host value is not "
                "available to numpy()/item()/float()/int()/bool()/"
                "tolist(). Values carried out of staged loops or "
                "branches (e.g. a loop index after a converted `break` "
                "loop) are tensors — keep them in tensor arithmetic, or "
                "restructure so the concrete use happens outside the "
                "traced region.")
        if (hasattr(a, "is_fully_addressable")
                and not a.is_fully_addressable
                and (not getattr(a, "is_fully_replicated", False)
                     or not len(a.addressable_shards))):
            # multi-process mesh and this process cannot read the value:
            # either genuinely sharded onto other processes, or committed
            # to a sub-mesh this rank does not touch (e.g. a mesh smaller
            # than the job). jax's np.asarray handles the replicated-with-
            # local-copy case itself (with caching) — this branch only
            # upgrades the error for the unreadable ones.
            raise RuntimeError(
                "Tensor.numpy() on a multi-process array whose shards "
                "live on other processes; use "
                "paddle.distributed.all_gather (or read "
                "._data.addressable_shards for the local part)")
        return np.asarray(a)

    def item(self):
        return self.numpy().item()

    def tolist(self):
        return self.numpy().tolist()

    def __array__(self, dtype=None):
        arr = self.numpy()
        return arr.astype(dtype) if dtype is not None else arr

    def __float__(self):
        return float(self.item())

    def __int__(self):
        return int(self.item())

    def __bool__(self):
        return bool(self.item())

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self.shape[0]

    def __iter__(self):
        """Iterate over the leading axis (reference: eager Tensor __iter__
        yields rows). Without this, Python's legacy __getitem__ iteration
        protocol never terminates: jnp indexing clamps out-of-range
        indices instead of raising IndexError."""
        if self.ndim == 0:
            raise TypeError("iteration over a 0-d tensor")
        for i in range(self.shape[0]):
            yield self[i]

    # -- autograd ----------------------------------------------------------
    @property
    def trainable(self):
        """Plain Tensors act as parameters when stop_gradient=False (the
        reference optimizers accept them); Parameter overrides this with
        its own slot."""
        return not self.stop_gradient

    def backward(self, grad_tensor=None, retain_graph=False):
        from ..autograd.tape import backward as _backward

        _backward([self], [grad_tensor], retain_graph=retain_graph)

    def _accumulate_grad(self, ct):
        if self._hooks:
            from ..autograd.tape import no_grad

            with no_grad():
                t = Tensor(ct)
                for hook in list(self._hooks.values()):
                    out = hook(t)
                    if out is not None:
                        t = out
                ct = t._data
        if self.grad is None:
            self.grad = Tensor(ct)
        else:
            self.grad = Tensor(self.grad._data + ct)

    def clear_grad(self):
        self.grad = None

    def clear_gradient(self, set_to_zero=False):
        if set_to_zero and self.grad is not None:
            self.grad = Tensor(jnp.zeros_like(self._data))
        else:
            self.grad = None

    def detach(self):
        t = Tensor(self._data, stop_gradient=True, name=self.name)
        return t

    def register_hook(self, hook):
        """Gradient hook (reference: egr hooks / Tensor.register_hook)."""
        if self._hooks is None:
            self._hooks = {}
        hid = max(self._hooks, default=-1) + 1
        self._hooks[hid] = hook

        class _Removable:
            def __init__(self, owner, key):
                self._owner, self._key = owner, key

            def remove(self):
                self._owner._hooks.pop(self._key, None)

        return _Removable(self, hid)

    # -- mutation (eager-only; used by optimizers / Layer.to) --------------
    def _set_data(self, arr):
        self._data = arr   # property setter bumps the inplace version

    def set_value(self, value):
        if isinstance(value, Tensor):
            arr = value._data
        else:
            arr = jnp.asarray(value)
        if tuple(arr.shape) != self.shape:
            raise ValueError(
                f"set_value shape mismatch: {tuple(arr.shape)} vs {self.shape}"
            )
        self._data = arr.astype(self.dtype)

    def copy_(self, other):
        self.set_value(other)
        return self

    # -- misc --------------------------------------------------------------
    def clone(self):
        from ..ops import assign

        return assign(self)

    def pin_memory(self):
        return self

    def cpu(self):
        return Tensor(jax.device_get(self._data), stop_gradient=self.stop_gradient)

    def block_until_ready(self):
        jax.block_until_ready(self._data)
        return self

    def __repr__(self):
        grad_info = "" if self.stop_gradient else ", stop_gradient=False"
        return (
            f"Tensor(shape={list(self.shape)}, dtype={self.dtype}{grad_info},\n"
            f"       {np.asarray(self._data)!r})"
        )

    def __hash__(self):
        return id(self)

    # NOTE: rich comparison / arithmetic operators are attached by
    # paddle_tpu.ops at import time.


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor equivalent."""
    dtype = convert_dtype(dtype)
    if isinstance(data, Tensor):
        arr = data._data
        if dtype is not None and arr.dtype != dtype:
            arr = arr.astype(dtype)
        return Tensor(arr, stop_gradient=stop_gradient)
    if isinstance(data, (jnp.ndarray, jax.Array)):
        arr = data
    else:
        arr = np.asarray(data)
        # Follow the reference's default dtype policy: python floats → fp32.
        if dtype is None and arr.dtype == np.float64:
            arr = arr.astype(np.float32)
    arr = jnp.asarray(arr, dtype=dtype)
    return Tensor(arr, stop_gradient=stop_gradient)
