"""paddle.dataset.mnist (reference: python/paddle/dataset/mnist.py —
train()/test() yielding (image[784] float32 in [-1, 1], label int))."""
from __future__ import annotations

import numpy as np

from ..vision.datasets import MNIST as _MNIST


def _reader(mode):
    ds = _MNIST(mode=mode)

    def rd():
        for i in range(len(ds)):
            img, label = ds[i]
            img = np.asarray(img, np.float32).reshape(-1)
            # reference normalizes to [-1, 1]
            if img.max() > 1.0:
                img = img / 127.5 - 1.0
            yield img, int(np.asarray(label).ravel()[0])

    return rd


def train():
    return _reader("train")


def test():
    return _reader("test")
