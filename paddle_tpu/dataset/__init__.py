"""Legacy `paddle.dataset` reader-style datasets (reference:
python/paddle/dataset/ — uci_housing, mnist, imdb, imikolov, cifar,
movielens, conll05, wmt14/16 as creator functions returning sample
GENERATORS, consumed through paddle.batch / paddle.reader decorators).

The modern path is paddle.io.Dataset + DataLoader (and the map-style
classes under vision.datasets / text.datasets); this module keeps the
legacy reader-function surface alive so reference scripts like

    train_reader = paddle.batch(
        paddle.reader.shuffle(paddle.dataset.uci_housing.train(), 500),
        batch_size=32)

run unchanged. Zero-egress environment: every creator yields a
deterministic synthetic sample stream with the reference's schema (the
map-style dataset classes these wrap carry a `.synthetic` flag).
"""
from __future__ import annotations

import numpy as np

from . import uci_housing, mnist, imdb, imikolov, cifar, movielens  # noqa: F401

__all__ = ["uci_housing", "mnist", "imdb", "imikolov", "cifar", "movielens"]
