"""paddle.dataset.imikolov (reference: python/paddle/dataset/imikolov.py —
n-gram LM tuples)."""
from __future__ import annotations

import numpy as np

from ..text.datasets import Imikolov as _Imikolov


def build_dict(min_word_freq=50):
    ds = _Imikolov(mode="train")
    return getattr(ds, "word_idx", {f"w{i}": i for i in range(2000)})


def _reader(mode, n):
    ds = _Imikolov(mode=mode, window_size=n)

    def rd():
        for i in range(len(ds)):
            yield tuple(int(v) for v in np.asarray(ds[i]).ravel())

    return rd


def train(word_idx=None, n=5):
    return _reader("train", n)


def test(word_idx=None, n=5):
    return _reader("test", n)
