"""paddle.dataset.cifar (reference: python/paddle/dataset/cifar.py —
train10/test10/train100/test100 yielding (image[3072] float32, label))."""
from __future__ import annotations

import numpy as np

from ..vision.datasets import Cifar10 as _Cifar10, Cifar100 as _Cifar100


def _reader(cls, mode):
    ds = cls(mode=mode)

    def rd():
        for i in range(len(ds)):
            img, label = ds[i]
            img = np.asarray(img, np.float32).reshape(-1)
            if img.max() > 1.0:
                img = img / 255.0
            yield img, int(np.asarray(label).ravel()[0])

    return rd


def train10():
    return _reader(_Cifar10, "train")


def test10():
    return _reader(_Cifar10, "test")


def train100():
    return _reader(_Cifar100, "train")


def test100():
    return _reader(_Cifar100, "test")
