"""paddle.dataset.uci_housing (reference: python/paddle/dataset/uci_housing.py
train()/test() reader creators yielding (feature[13] float32, price[1]))."""
from __future__ import annotations

import numpy as np

from ..text.datasets import UCIHousing as _UCIHousing

feature_names = [
    "CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE", "DIS", "RAD", "TAX",
    "PTRATIO", "B", "LSTAT",
]


def _reader(mode):
    ds = _UCIHousing(mode=mode)

    def rd():
        for i in range(len(ds)):
            x, y = ds[i]
            yield np.asarray(x, np.float32), np.asarray(y, np.float32)

    return rd


def train():
    return _reader("train")


def test():
    return _reader("test")
