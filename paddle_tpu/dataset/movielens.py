"""paddle.dataset.movielens (reference: python/paddle/dataset/movielens.py —
rating tuples for recommender examples)."""
from __future__ import annotations

import numpy as np

from ..text.datasets import Movielens as _Movielens


def _reader(mode):
    ds = _Movielens(mode=mode)

    def rd():
        for i in range(len(ds)):
            yield tuple(np.asarray(v).ravel()[0] if np.asarray(v).size == 1
                        else np.asarray(v) for v in ds[i])

    return rd


def train():
    return _reader("train")


def test():
    return _reader("test")


def max_user_id():
    return getattr(_Movielens(mode="train"), "max_user_id", 944)


def max_movie_id():
    return getattr(_Movielens(mode="train"), "max_movie_id", 1683)
