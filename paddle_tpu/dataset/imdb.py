"""paddle.dataset.imdb (reference: python/paddle/dataset/imdb.py —
word_dict() + train(word_dict)/test(word_dict) yielding (ids, label))."""
from __future__ import annotations

import numpy as np

from ..text.datasets import Imdb as _Imdb


def word_dict():
    return _Imdb(mode="train").word_idx


def _reader(mode, w=None):
    ds = _Imdb(mode=mode)

    def rd():
        for i in range(len(ds)):
            ids, label = ds[i]
            yield np.asarray(ids, np.int64), int(label)

    return rd


def train(word_idx=None):
    return _reader("train", word_idx)


def test(word_idx=None):
    return _reader("test", word_idx)
