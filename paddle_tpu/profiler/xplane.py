"""Minimal XSpace/XPlane protobuf reader + per-op statistics.

Reference analog: paddle/fluid/platform/profiler/event_node.cc +
profiler_statistic.py — the reference walks its own CUPTI event tree into
operator/kernel summary tables. On TPU the device trace is the xplane
protobuf emitted by jax.profiler (tsl/profiler/protobuf/xplane.proto);
rather than depending on tensorflow to decode it, this module parses the
few fields the tables need straight from the protobuf wire format
(varint / length-delimited), ~schema:

  XSpace   { repeated XPlane planes = 1; }
  XPlane   { int64 id=1; string name=2; repeated XLine lines=3;
             map<int64, XEventMetadata> event_metadata=4; }
  XLine    { int64 id=1; string name=2; int64 timestamp_ns=3;
             repeated XEvent events=4; }
  XEvent   { int64 metadata_id=1; int64 offset_ps=2; int64 duration_ps=3; }
  XEventMetadata { int64 id=1; string name=2; }
"""
from __future__ import annotations

import dataclasses
import glob
import os
from typing import Dict, List

__all__ = ["parse_xspace", "find_xplane_files", "op_stats",
           "format_op_table", "XPlane", "XLine", "XEvent"]


# -- protobuf wire-format primitives ----------------------------------------

def _varint(buf, pos):
    out = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7


def _fields(buf):
    """Yield (field_number, wire_type, value) over a message buffer.
    Length-delimited values come back as memoryview slices."""
    pos, n = 0, len(buf)
    while pos < n:
        tag, pos = _varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire == 0:                         # varint
            val, pos = _varint(buf, pos)
        elif wire == 1:                       # fixed64
            val = int.from_bytes(buf[pos:pos + 8], "little")
            pos += 8
        elif wire == 2:                       # length-delimited
            ln, pos = _varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wire == 5:                       # fixed32
            val = int.from_bytes(buf[pos:pos + 4], "little")
            pos += 4
        else:                                 # groups: not in this schema
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, val


# -- the slices of the schema the tables need --------------------------------

@dataclasses.dataclass
class XEvent:
    metadata_id: int = 0
    offset_ps: int = 0
    duration_ps: int = 0


@dataclasses.dataclass
class XLine:
    id: int = 0
    name: str = ""
    timestamp_ns: int = 0
    events: List[XEvent] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class XPlane:
    id: int = 0
    name: str = ""
    lines: List[XLine] = dataclasses.field(default_factory=list)
    event_names: Dict[int, str] = dataclasses.field(default_factory=dict)


def _parse_event(buf):
    e = XEvent()
    for field, _, val in _fields(buf):
        if field == 1:
            e.metadata_id = val
        elif field == 2:
            e.offset_ps = val
        elif field == 3:
            e.duration_ps = val
    return e


def _parse_line(buf):
    ln = XLine()
    for field, wire, val in _fields(buf):
        if field == 1:
            ln.id = val
        elif field == 2 and wire == 2:
            ln.name = bytes(val).decode("utf-8", "replace")
        elif field == 3:
            ln.timestamp_ns = val
        elif field == 4 and wire == 2:
            ln.events.append(_parse_event(val))
    return ln


def _parse_metadata_entry(buf):
    """map<int64, XEventMetadata> entry -> (id, name)."""
    key, name = 0, ""
    for field, wire, val in _fields(buf):
        if field == 1:
            key = val
        elif field == 2 and wire == 2:           # XEventMetadata
            for f2, w2, v2 in _fields(val):
                if f2 == 1:
                    key = v2 or key
                elif f2 == 2 and w2 == 2:
                    name = bytes(v2).decode("utf-8", "replace")
    return key, name


def _parse_plane(buf):
    p = XPlane()
    for field, wire, val in _fields(buf):
        if field == 1:
            p.id = val
        elif field == 2 and wire == 2:
            p.name = bytes(val).decode("utf-8", "replace")
        elif field == 3 and wire == 2:
            p.lines.append(_parse_line(val))
        elif field == 4 and wire == 2:
            k, name = _parse_metadata_entry(val)
            p.event_names[k] = name
    return p


def parse_xspace(path) -> List[XPlane]:
    with open(path, "rb") as f:
        buf = memoryview(f.read())
    planes = []
    for field, wire, val in _fields(buf):
        if field == 1 and wire == 2:
            planes.append(_parse_plane(val))
    return planes


def find_xplane_files(trace_dir) -> List[str]:
    """jax.profiler writes <dir>/plugins/profile/<run>/<host>.xplane.pb."""
    return sorted(glob.glob(os.path.join(
        trace_dir, "plugins", "profile", "*", "*.xplane.pb")))


# -- aggregation (reference profiler_statistic.py operator/kernel tables) ----

def op_stats(planes: List[XPlane], plane_filter=None) -> Dict[str, dict]:
    """Aggregate event durations per op name across the selected planes.
    plane_filter: predicate on plane name; default = device planes
    (TPU/GPU/axon) falling back to every non-empty plane (CPU runs)."""
    def is_device(name):
        return any(k in name for k in ("TPU", "GPU", "/device:", "axon"))

    chosen = [p for p in planes
              if (plane_filter(p.name) if plane_filter else is_device(p.name))]
    if not chosen:
        chosen = planes
    out: Dict[str, dict] = {}
    for plane in chosen:
        for line in plane.lines:
            for ev in line.events:
                name = plane.event_names.get(ev.metadata_id,
                                             f"#{ev.metadata_id}")
                s = out.setdefault(name, {
                    "calls": 0, "total_ps": 0, "min_ps": float("inf"),
                    "max_ps": 0})
                s["calls"] += 1
                s["total_ps"] += ev.duration_ps
                s["min_ps"] = min(s["min_ps"], ev.duration_ps)
                s["max_ps"] = max(s["max_ps"], ev.duration_ps)
    for s in out.values():
        s["avg_ps"] = s["total_ps"] / max(s["calls"], 1)
    return out


def format_op_table(stats: Dict[str, dict], top=30, time_unit="ms") -> str:
    div = {"ms": 1e9, "us": 1e6, "ns": 1e3, "ps": 1.0}[time_unit]
    total = sum(s["total_ps"] for s in stats.values()) or 1
    lines = [f"{'device op':52s} {'calls':>7s} {f'total_{time_unit}':>12s} "
             f"{f'avg_{time_unit}':>10s} {'ratio':>7s}"]
    ranked = sorted(stats.items(), key=lambda kv: -kv[1]["total_ps"])
    for name, s in ranked[:top]:
        lines.append(
            f"{name[:52]:52s} {s['calls']:7d} {s['total_ps']/div:12.3f} "
            f"{s['avg_ps']/div:10.3f} {s['total_ps']/total:6.1%}")
    if len(ranked) > top:
        rest = sum(s["total_ps"] for _, s in ranked[top:])
        lines.append(f"{'… %d more' % (len(ranked) - top):52s} "
                     f"{'':7s} {rest/div:12.3f} {'':10s} {rest/total:6.1%}")
    return "\n".join(lines)
