"""Profiler (reference: python/paddle/profiler/profiler.py:344 +
paddle/fluid/platform/profiler/ HostTracer/CudaTracer).

TPU-native: host spans use a lightweight in-process tracer (chrome-trace
exportable, the HostTracer analog); device side delegates to jax.profiler
(XLA xplane capture, viewable in TensorBoard/Perfetto — the CUPTI analog).
"""
from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager

__all__ = [
    "Profiler", "RecordEvent", "ProfilerTarget", "ProfilerState",
    "make_scheduler", "export_chrome_tracing", "load_profiler_result",
]


class ProfilerTarget:
    CPU = "cpu"
    GPU = "gpu"
    CUSTOM_DEVICE = "tpu"
    TPU = "tpu"


class ProfilerState:
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class _HostTracer(threading.local):
    def __init__(self):
        self.events = []
        self.enabled = False


_tracer = _HostTracer()


class RecordEvent:
    """Host span annotation (reference: platform::RecordEvent,
    profiler/event_tracing.h:49). Also emits a jax TraceAnnotation so spans
    appear in xplane captures."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._jax_ctx = None
        self._t0 = None

    def begin(self):
        self._t0 = time.perf_counter_ns()
        try:
            import jax.profiler

            self._jax_ctx = jax.profiler.TraceAnnotation(self.name)
            self._jax_ctx.__enter__()
        except Exception:
            self._jax_ctx = None

    def end(self):
        if self._jax_ctx is not None:
            self._jax_ctx.__exit__(None, None, None)
        if _tracer.enabled and self._t0 is not None:
            _tracer.events.append(
                {
                    "name": self.name,
                    "ph": "X",
                    "ts": self._t0 / 1000.0,
                    "dur": (time.perf_counter_ns() - self._t0) / 1000.0,
                    "pid": os.getpid(),
                    "tid": threading.get_ident() % 1_000_000,
                }
            )

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


def make_scheduler(closed=0, ready=0, record=1, repeat=0, skip_first=0):
    def scheduler(step):
        step -= skip_first
        if step < 0:
            return ProfilerState.CLOSED
        cycle = closed + ready + record
        if repeat and step >= repeat * cycle:
            return ProfilerState.CLOSED
        pos = step % cycle if cycle else 0
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def export_chrome_tracing(dir_name, worker_name=None):
    def handler(prof):
        os.makedirs(dir_name, exist_ok=True)
        fname = os.path.join(
            dir_name, f"{worker_name or 'paddle_tpu'}_{int(time.time())}.json"
        )
        prof._export_chrome(fname)
        return fname

    return handler


class Profiler:
    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, record_shapes=False, profile_memory=False,
                 with_flops=False):
        self._scheduler = scheduler if callable(scheduler) else None
        if isinstance(scheduler, (tuple, list)):
            lo, hi = scheduler
            self._scheduler = make_scheduler(closed=lo, ready=0, record=hi - lo, repeat=1)
        self._on_trace_ready = on_trace_ready
        self._step = 0
        self._xla_dir = None
        self._timer_only = timer_only
        self._step_times = []
        self._last_step_t = None
        self._profile_memory = profile_memory
        self._mem_samples = []  # (bytes_in_use, peak_bytes_in_use) per step
        self._last_trace_dir = None  # xplane dir of the finished capture

    def start(self):
        _tracer.enabled = True
        _tracer.events = []
        self._last_step_t = time.perf_counter()
        if not self._timer_only:
            try:
                import jax.profiler

                self._xla_dir = os.environ.get("PTPU_PROF_DIR", "/tmp/ptpu_profile")
                jax.profiler.start_trace(self._xla_dir)
            except Exception:
                self._xla_dir = None

    def stop(self):
        _tracer.enabled = False
        if self._xla_dir is not None:
            try:
                import jax.profiler

                jax.profiler.stop_trace()
                self._last_trace_dir = self._xla_dir
            except Exception:  # ptpu-check[silent-except]: stop_trace without a matching
                # start raises on some jax versions; profile teardown must not kill the run
                pass
            self._xla_dir = None
        if self._on_trace_ready is not None:
            self._on_trace_ready(self)

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._last_step_t is not None:
            self._step_times.append((now - self._last_step_t, num_samples))
        self._last_step_t = now
        self._step += 1
        if self._profile_memory:
            from .. import device as _device

            self._mem_samples.append((_device.memory_allocated(),
                                      _device.max_memory_allocated()))

    def _ips_samples(self):
        """Per-step ips for exactly the steps that reported num_samples —
        each sample paired with ITS OWN step duration (a positional
        times[-len(samples):] pairing mismatches whenever only some steps
        pass num_samples)."""
        return [n / t for t, n in self._step_times if n and t > 0]

    def step_info(self, unit="samples"):
        if not self._step_times:
            return ""
        import numpy as np

        times = np.array([t for t, _ in self._step_times])
        msg = f"avg step {times.mean()*1000:.2f} ms"
        ips = self._ips_samples()
        if ips:
            msg += f", ips {np.mean(ips):.1f} {unit}/s"
        return msg

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False, time_unit="ms"):
        by_name = {}
        for e in _tracer.events:
            agg = by_name.setdefault(e["name"], [0.0, 0])
            agg[0] += e["dur"] / 1000.0
            agg[1] += 1
        lines = [f"{'name':40s} {'calls':>8s} {'total_ms':>12s}"]
        for name, (tot, n) in sorted(by_name.items(), key=lambda kv: -kv[1][0]):
            lines.append(f"{name[:40]:40s} {n:8d} {tot:12.3f}")
        if self._mem_samples:
            # device-memory statistics column (reference:
            # profiler_statistic.py memory tables / memory/stats.h peaks)
            cur = [c for c, _ in self._mem_samples]
            peak = [p for _, p in self._mem_samples]
            mb = 1 / 2**20
            lines.append("")
            lines.append(
                f"{'device memory (MiB)':40s} {'current':>12s} {'peak':>12s}")
            lines.append(
                f"{'  last step':40s} {cur[-1]*mb:12.1f} {peak[-1]*mb:12.1f}")
            lines.append(
                f"{'  max over steps':40s} {max(cur)*mb:12.1f} "
                f"{max(peak)*mb:12.1f}")
        if op_detail:
            dev = self.device_op_summary(time_unit=time_unit)
            if dev:
                lines += ["", dev]
        # always-on stats layer (paddle_tpu.monitor): counters/gauges/
        # histograms recorded by the train/pipeline/MoE/autotune hot paths
        # share names with the RecordEvent spans above.
        from .. import monitor

        mon = monitor.render()
        if mon:
            lines += ["", mon]
        # perf attribution (paddle_tpu.monitor.perf): ranked MFU/roofline
        # table of every analyzed program and sub-step segment — the row
        # with the worst achieved-vs-optimal ratio is the next kernel to
        # optimize.  Empty unless PTPU_PERF accounting recorded anything.
        try:
            from ..monitor import perf as _mperf

            pa = _mperf.report()
        except ImportError:   # standalone monitor load — no perf module
            pa = ""
        if pa:
            lines += ["", pa]
        # training microscope (paddle_tpu.monitor.train): ranked per-layer
        # grad/param/update table from the PTPU_TRAIN_STATS sampled fused
        # reduction — empty unless the optimizer recorded a sample.
        try:
            from ..monitor import train as _mtrain

            ts = _mtrain.report()
        except ImportError:   # standalone monitor load — no train module
            ts = ""
        if ts:
            lines += ["", ts]
        return "\n".join(lines)

    def device_op_summary(self, top=30, time_unit="ms"):
        """Per-op device-time attribution table parsed from the xplane
        capture (reference: profiler_statistic.py operator/kernel
        statistics fed from the CUPTI event tree; here the jax.profiler
        xplane protobuf, decoded without a tensorflow dependency — see
        profiler/xplane.py). Empty string when no device trace exists
        (timer_only mode, or capture failed)."""
        if self._last_trace_dir is None:
            return ""
        from . import xplane

        files = xplane.find_xplane_files(self._last_trace_dir)
        if not files:
            return ""
        planes = []
        for f in files:
            try:
                planes.extend(xplane.parse_xspace(f))
            except (OSError, ValueError, IndexError):
                continue   # truncated/corrupt capture: skip that file
        stats = xplane.op_stats(planes) if planes else {}
        if not stats:
            return ""
        return xplane.format_op_table(stats, top=top, time_unit=time_unit)

    def _export_chrome(self, fname):
        # one timeline: RecordEvent host spans + monitor.trace framework
        # spans (same perf_counter_ns timebase, so Perfetto interleaves
        # them correctly; trace spans carry trace_id/span_id in args)
        from ..monitor import trace as _mtrace

        events = list(_tracer.events) + _mtrace.chrome_events()
        with open(fname, "w") as f:
            json.dump({"traceEvents": events}, f)

    def export(self, path, format="json"):
        self._export_chrome(path)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


def load_profiler_result(path):
    """Load an exported trace: chrome-trace JSON, or the pickled raw host
    event list written by export_protobuf (.pkl)."""
    if path.endswith((".pkl", ".pb.pkl")):
        import pickle

        with open(path, "rb") as f:
            return pickle.load(f)
    with open(path) as f:
        return json.load(f)


class SortedKeys:
    """Summary-table sort keys (reference profiler/profiler.py SortedKeys)."""

    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


class SummaryView:
    """Summary views (reference profiler/profiler.py SummaryView)."""

    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8


def export_protobuf(dir_name, worker_name=None):
    """on_trace_ready handler writing the raw trace records (reference
    export_protobuf; here the host-tracer event list is serialized with
    pickle next to the chrome trace — the xplane protobuf itself is
    produced by jax.profiler when the device tracer is active)."""
    import os
    import pickle
    import socket
    import time as _time

    def handler(prof):
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"host_{socket.gethostname()}"
        path = os.path.join(
            dir_name, f"{name}_{int(_time.time() * 1000)}.pb.pkl")
        # the raw records live on the module host tracer, not the Profiler
        # (a prior version pickled a nonexistent prof._events — always [])
        with open(path, "wb") as f:
            pickle.dump(list(_tracer.events), f)
        return path

    return handler


__all__ += ["SortedKeys", "SummaryView", "export_protobuf"]
