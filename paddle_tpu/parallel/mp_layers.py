"""Tensor-parallel layers (reference: fleet/layers/mpu/mp_layers.py:35,173,332,498
— VocabParallelEmbedding / ColumnParallelLinear / RowParallelLinear /
ParallelCrossEntropy, built on c_identity/c_allreduce PyLayers in mp_ops.py).

TPU-native: weights carry 'mp' axis annotations; forward adds GSPMD
sharding constraints. XLA inserts the all-reduce/all-gather the reference
codes by hand — and fuses/overlaps them. The layer *math* is identical, so
checkpoints and model defs port 1:1.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import apply
from ..nn.layer import Layer
from ..nn import functional as F
from ..nn.initializer import XavierNormal, Constant
from .mesh import axis_size
from .api import shard_parameter, constraint

__all__ = [
    "ColumnParallelLinear", "RowParallelLinear", "VocabParallelEmbedding",
    "ParallelCrossEntropy", "mp_allreduce", "mp_identity",
]


def mp_identity(x):
    """c_identity analog: identity fwd, allreduce bwd — under GSPMD this is
    just the replicated-activation constraint."""
    return constraint(x, [None] * x.ndim)


def mp_allreduce(x):
    """c_allreduce analog: force-replicate a partially-computed activation
    (GSPMD materializes the mp all-reduce at this boundary)."""
    return constraint(x, [None] * x.ndim)


class ColumnParallelLinear(Layer):
    """Y = X W, W:[in, out] sharded on columns ('mp' on dim 1)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.gather_output = gather_output
        self.world_size = axis_size("mp")
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            default_initializer=XavierNormal(),
        )
        shard_parameter(self.weight, (None, "mp"))
        if has_bias:
            self.bias = self.create_parameter(shape=[out_features], is_bias=True)
            shard_parameter(self.bias, ("mp",))
        else:
            self.bias = None

    def forward(self, x):
        y = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            y = constraint(y, [None] * y.ndim)
        else:
            y = constraint(y, [None] * (y.ndim - 1) + ["mp"])
        return y


class RowParallelLinear(Layer):
    """Y = X W, W:[in, out] sharded on rows ('mp' on dim 0); input arrives
    mp-sharded on its last dim, output needs the mp partial-sum reduced."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            default_initializer=XavierNormal(),
        )
        shard_parameter(self.weight, ("mp", None))
        if has_bias:
            self.bias = self.create_parameter(shape=[out_features], is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        if self.input_is_parallel:
            x = constraint(x, [None] * (x.ndim - 1) + ["mp"])
        y = F.linear(x, self.weight, None)
        # force the partial sums to be combined (mp all-reduce) and output replicated
        y = constraint(y, [None] * y.ndim)
        if self.bias is not None:
            y = y + self.bias
        return y


class VocabParallelEmbedding(Layer):
    """Embedding with vocab dim sharded on 'mp' (reference mp_layers.py:35 —
    c_embedding op masks out-of-shard ids then allreduces; GSPMD derives the
    same from a gather on a sharded operand)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            shape=[num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=XavierNormal(),
        )
        shard_parameter(self.weight, ("mp", None))

    def forward(self, x):
        y = F.embedding(x, self.weight)
        return constraint(y, [None] * y.ndim)


class ParallelCrossEntropy(Layer):
    """Cross entropy over mp-sharded logits (reference:
    c_softmax_with_cross_entropy_op.cu — shard-local max/sum + allreduce;
    GSPMD derives the identical schedule from softmax on a sharded axis)."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        logits = constraint(input, [None] * (input.ndim - 1) + ["mp"])
        return F.cross_entropy(
            logits, label, reduction="none", ignore_index=self.ignore_index
        )
