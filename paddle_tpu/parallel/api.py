"""Sharding annotation API.

Reference analog: auto_parallel shard_tensor + dist_attr (ProcessMesh,
dims_mapping — completion.py propagates them through the graph). Here the
same information is (a) `Parameter._sharding_axes` consumed when building
the compiled step's in_shardings, and (b) in-graph
`with_sharding_constraint` hints; propagation is XLA GSPMD's job, not a
hand-written Completer.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import NamedSharding, PartitionSpec

from ..core.tensor import Tensor
from ..core.dispatch import apply
from .mesh import get_mesh, axis_size

__all__ = [
    "shard_parameter", "shard_tensor", "sharding_of", "param_sharding",
    "constraint", "replicated", "place_model",
]


def _filter_spec(axes):
    """Drop axes of size 1 so single-degree configs stay replicated."""
    out = []
    for a in axes:
        if a is None:
            out.append(None)
        elif isinstance(a, (tuple, list)):
            kept = tuple(x for x in a if axis_size(x) > 1)
            out.append(kept if kept else None)
        else:
            out.append(a if axis_size(a) > 1 else None)
    return tuple(out)


def shard_parameter(param, axes: Sequence[Optional[str]]):
    """Annotate a Parameter with per-dim mesh axes, e.g. (None, 'mp')."""
    if len(axes) != len(param.shape):
        raise ValueError(f"axes {axes} rank != param rank {len(param.shape)}")
    param._sharding_axes = tuple(axes)
    return param


def param_sharding(param):
    """NamedSharding for a Parameter (replicated if unannotated)."""
    mesh = get_mesh()
    axes = getattr(param, "_sharding_axes", None)
    if axes is None:
        return NamedSharding(mesh, PartitionSpec())
    return NamedSharding(mesh, PartitionSpec(*_filter_spec(axes)))


def sharding_of(*axes):
    return NamedSharding(get_mesh(), PartitionSpec(*_filter_spec(axes)))


def replicated():
    return NamedSharding(get_mesh(), PartitionSpec())


def shard_tensor(x, axes, mesh=None):
    """Place (or re-place) a Tensor onto the mesh with the given per-dim axes.
    Eager: jax.device_put; inside a trace: a sharding constraint."""
    sh = sharding_of(*axes)
    if isinstance(x, Tensor):
        arr = x._data
        if hasattr(arr, "aval") and not isinstance(arr, jax.Array):
            return constraint(x, axes)
        try:
            x._data = jax.device_put(arr, sh)
        except Exception:
            x._data = jax.lax.with_sharding_constraint(arr, sh)
        return x
    return jax.device_put(x, sh)


def place_model(model):
    """Device_put every parameter/buffer of a Layer onto the mesh per its
    annotation (replicated when unannotated). The TPU-native analog of the
    reference's per-group param broadcast at distributed_model() time
    (meta_parallel/tensor_parallel.py:27)."""
    for p in model.parameters():
        p._data = jax.device_put(p._data, param_sharding(p))
    for b in model.buffers():
        b._data = jax.device_put(b._data, param_sharding(b))
    return model


def _divisible_spec(axes, shape):
    """Drop axes whose degree doesn't divide the dim (GSPMD requires even
    splits; undivisible dims stay replicated, e.g. tiny eager batches)."""
    out = []
    for a, d in zip(axes, shape):
        if a is None:
            out.append(None)
            continue
        parts = a if isinstance(a, (tuple, list)) else (a,)
        deg = 1
        for p in parts:
            deg *= axis_size(p)
        out.append(a if d % deg == 0 else None)
    return tuple(out)


def constraint(x, axes):
    """In-graph sharding hint (GSPMD boundary) — differentiable."""
    sh = sharding_of(*_divisible_spec(axes, x.shape))
    return apply(
        lambda a: jax.lax.with_sharding_constraint(a, sh), x, name="sharding_constraint"
    )
