"""Device mesh management.

Reference analog: fleet/base/topology.py:56 CommunicateTopology — a
cartesian rank topology over axes ["data","pipe","sharding","model"] with an
NCCL group per axis slice. Here the same topology is ONE
jax.sharding.Mesh; "groups" are named axes and XLA compiles collectives
onto the physical ICI torus (device order comes from jax.devices(), which
is already topology-sorted for TPU).
"""
from __future__ import annotations

import threading
from typing import Dict, Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

_state = threading.local()

AXIS_ORDER = ("dp", "sharding", "pp", "ep", "sp", "mp")


def _current():
    return getattr(_state, "mesh", None)


def init_mesh(dp=1, mp=1, pp=1, sharding=1, sp=1, ep=1, devices=None) -> Mesh:
    """Build + install the global hybrid-parallel mesh.

    Axis order puts dp outermost and mp innermost so tensor-parallel
    collectives ride the fastest ICI links (reference fleet orders
    [data, pipe, sharding, model] for the same reason — topology.py:56).
    """
    devices = list(devices if devices is not None else jax.devices())
    need = dp * mp * pp * sharding * sp * ep
    if need > len(devices):
        raise ValueError(
            f"mesh {dp}x{sharding}x{pp}x{ep}x{sp}x{mp}={need} exceeds {len(devices)} devices"
        )
    devices = devices[:need]
    arr = np.array(devices).reshape(dp, sharding, pp, ep, sp, mp)
    mesh = Mesh(arr, ("dp", "sharding", "pp", "ep", "sp", "mp"))
    _state.mesh = mesh
    return mesh


def set_mesh(mesh: Mesh):
    _state.mesh = mesh


def get_mesh() -> Optional[Mesh]:
    m = _current()
    if m is None:
        # default: trivial 1-axis mesh over all devices on 'dp'
        devs = np.array(jax.devices()).reshape(-1, 1, 1, 1, 1, 1)
        m = Mesh(devs, ("dp", "sharding", "pp", "ep", "sp", "mp"))
        _state.mesh = m
    return m


def mesh_axes():
    return get_mesh().axis_names


def axis_size(name: str) -> int:
    mesh = get_mesh()
    if name not in mesh.axis_names:
        return 1
    return mesh.shape[name]


def has_axis(name: str) -> bool:
    return axis_size(name) > 1


def shard_map_compat(f, *, mesh, in_specs, out_specs, axis_names=None,
                     check_vma=False):
    """jax.shard_map across jax versions. Newer jax exposes
    `jax.shard_map(..., axis_names=<manual axes>, check_vma=...)`; 0.4.x
    only has `jax.experimental.shard_map.shard_map(..., auto=<NON-manual
    axes>, check_rep=...)`. Same partial-manual semantics, inverted axis
    selector — this wrapper accepts the new-style kwargs and translates
    when running on the old API."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            axis_names=frozenset(axis_names) if axis_names else None,
            check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map

    auto = frozenset()
    if axis_names:
        # size-1 axes are semantically identical manual or auto (one shard
        # holds the full extent); folding them into the manual set empties
        # `auto` on single-parallelism meshes, dodging the partial-manual
        # constructs old XLA can't partition on some backends
        # ("PartitionId instruction is not supported for SPMD").
        auto = frozenset(a for a in mesh.axis_names
                         if a not in axis_names and mesh.shape[a] > 1)
    if auto and jax.default_backend() == "cpu":
        # True partial-manual on 0.4.x XLA-CPU is a minefield: lowering
        # hits "PartitionId instruction is not supported for SPMD
        # partitioning" or fatally aborts the process in the
        # float-normalization pass. Refuse loudly rather than crash
        # (accelerator backends are left to try the `auto=` path).
        raise NotImplementedError(
            f"shard_map over manual axes {sorted(axis_names)} with live "
            f"auto axes {sorted(auto)} needs jax >= 0.6 (jax.shard_map "
            f"with axis_names); this jax ({jax.__version__}) only "
            "partitions single-parallelism meshes reliably. Collapse the "
            "mesh to the manual axes or upgrade jax.")
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma, auto=auto)


class MeshGuard:
    def __init__(self, mesh: Mesh):
        self.mesh = mesh

    def __enter__(self):
        self._prev = _current()
        _state.mesh = self.mesh
        return self.mesh

    def __exit__(self, *exc):
        _state.mesh = self._prev
        return False
