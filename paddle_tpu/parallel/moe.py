"""Expert parallelism — capacity-factor token dispatch over the 'ep' axis.

Reference analog: incubate/distributed/models/moe/moe_layer.py:260 (MoELayer:
gate -> global_scatter all-to-all dispatch -> local experts -> global_gather)
with the collective ops paddle/fluid/operators/collective/global_scatter_op.cu.cc
and global_gather_op.cu.cc.

TPU-native design (GShard-style, SPMD):
- top-k gating with a static capacity C = ceil(cf * k * tokens / E): static
  shapes keep XLA happy; overflow tokens are dropped (their combine weight
  is zero) exactly like the reference's capacity overflow.
- dispatch/combine are one-hot einsums (MXU-friendly, no scatter),
- the global_scatter/global_gather pair is ONE `lax.all_to_all` each over
  the 'ep' mesh axis inside shard_map: shard i sends its per-expert queues
  to the shard owning those experts and receives every shard's queue for
  its local experts. Per-token expert FLOPs are k*cf*H*M — independent of
  num_experts (the dense-MoE einsum this replaces was O(E) per token).
"""
from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .mesh import get_mesh, axis_size, shard_map_compat
from .. import monitor
from ..profiler import RecordEvent

__all__ = ["moe_mlp_arrays", "moe_capacity"]


def _maybe_record_routing(dispatch, n_tokens, top_k):
    """Expert-routing telemetry from the concrete dispatch tensor [N,E,C].
    Only observable on the eager path (tracers carry no values); under jit
    the aux load-balance loss remains the in-graph signal. Forces the
    dispatch computation, which the eager caller pays anyway."""
    if not monitor.enabled() or isinstance(dispatch, jax.core.Tracer):
        return
    import numpy as np

    tokens_per_expert = np.asarray(jnp.sum(dispatch, axis=(0, 2)))  # [E]
    hist = monitor.histogram("moe/tokens_per_expert")
    for c in tokens_per_expert:
        hist.observe(float(c))
    kept = float(tokens_per_expert.sum())
    monitor.counter("moe/dropped_tokens").add(
        max(0.0, n_tokens * top_k - kept))
    mean = float(tokens_per_expert.mean())
    if mean > 0:
        monitor.gauge("moe/imbalance").set(
            float(tokens_per_expert.max()) / mean)


def moe_capacity(num_tokens, num_experts, top_k, capacity_factor):
    """Static per-expert queue length (tokens beyond it overflow)."""
    return max(1, math.ceil(capacity_factor * top_k * num_tokens / num_experts))


def _routing(logits, num_experts, top_k, capacity):
    """[N, E] gate logits -> (dispatch [N,E,C] 0/1, combine [N,E,C] fp32,
    aux_loss scalar). Top-k routing with in-expert positions assigned
    choice-major (all first choices before any second choice, GShard
    priority) and capacity overflow dropped."""
    n = logits.shape[0]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)      # [N, E]
    topv, topi = jax.lax.top_k(probs, top_k)                          # [N, k]
    topv = topv / jnp.sum(topv, axis=-1, keepdims=True)

    onehot = jax.nn.one_hot(topi, num_experts, dtype=jnp.int32)       # [N,k,E]
    # queue position of each (token, choice): count earlier slots routed to
    # the same expert, choice-major so primary routes win capacity
    flat = jnp.swapaxes(onehot, 0, 1).reshape(top_k * n, num_experts)
    pos_flat = jnp.cumsum(flat, axis=0) - flat
    pos = jnp.swapaxes(
        jnp.sum(pos_flat.reshape(top_k, n, num_experts) *
                jnp.swapaxes(onehot, 0, 1), axis=-1), 0, 1)           # [N, k]

    keep = pos < capacity                                             # [N, k]
    oh_e = onehot.astype(jnp.float32) * keep[..., None].astype(jnp.float32)
    oh_c = jax.nn.one_hot(pos, capacity, dtype=jnp.float32)           # [N,k,C]
    dispatch = jnp.einsum("nke,nkc->nec", oh_e, oh_c)
    combine = jnp.einsum("nke,nkc,nk->nec", oh_e, oh_c, topv)

    # GShard aux load-balance loss: E * mean_e(frac_tokens_e * mean_prob_e)
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(onehot[:, 0].astype(jnp.float32), axis=0)           # top-1 counts
    aux = num_experts * jnp.sum(me * ce)
    return dispatch, combine, aux


def _expert_ffn(expert_in, w_in, w_out):
    """[E_l, C', H] x [E_l, H, M] -> gelu -> [E_l, C', H]."""
    hidden = jnp.einsum("ech,ehm->ecm", expert_in, w_in)
    hidden = jax.nn.gelu(hidden, approximate=True)
    return jnp.einsum("ecm,emh->ech", hidden, w_out)


def _moe_single(x, logits, w_in, w_out, *, top_k, capacity_factor):
    """No expert parallelism: route + run all experts locally."""
    b, s, h = x.shape
    e = w_in.shape[0]
    xf = x.reshape(b * s, h)
    cap = moe_capacity(b * s, e, top_k, capacity_factor)
    dispatch, combine, aux = _routing(logits.reshape(b * s, e), e, top_k, cap)
    _maybe_record_routing(dispatch, b * s, top_k)
    expert_in = jnp.einsum("nec,nh->ech", dispatch.astype(x.dtype), xf)
    out = _expert_ffn(expert_in, w_in, w_out)
    y = jnp.einsum("nec,ech->nh", combine.astype(out.dtype), out)
    return y.reshape(b, s, h).astype(x.dtype), aux


def _moe_sharded(x, logits, w_in, w_out, *, axis_name, top_k, capacity_factor):
    """Per-shard body (inside shard_map over 'ep'): x/logits hold the local
    token slice [B_l, S, H]; w_in/w_out hold the local experts [E_l, H, M].
    The two all_to_alls are the reference's global_scatter / global_gather.
    NOTE: the eager telemetry replay in _moe_mlp_dispatch mirrors this
    body's token slicing and capacity — keep the two in lockstep."""
    ep = jax.lax.psum(1, axis_name)
    b_l, s, h = x.shape
    e = w_in.shape[0] * ep                          # global expert count
    xf = x.reshape(b_l * s, h)
    cap = moe_capacity(b_l * s, e, top_k, capacity_factor)
    dispatch, combine, aux = _routing(
        logits.reshape(b_l * s, e), e, top_k, cap)

    # local per-expert queues [E, C, H]
    expert_in = jnp.einsum("nec,nh->ech", dispatch.astype(x.dtype), xf)
    # global_scatter: shard i keeps experts [i*E_l, (i+1)*E_l) and receives
    # every shard's queues for them -> [E_l, ep*C, H]
    expert_in = jax.lax.all_to_all(
        expert_in, axis_name, split_axis=0, concat_axis=1, tiled=True)
    out = _expert_ffn(expert_in, w_in, w_out)
    # global_gather: route outputs back to the owning token shards
    out = jax.lax.all_to_all(
        out, axis_name, split_axis=1, concat_axis=0, tiled=True)
    y = jnp.einsum("nec,ech->nh", combine.astype(out.dtype), out)
    # aux loss is a mean over local tokens; average across the ep group
    aux = jax.lax.pmean(aux, axis_name)
    return y.reshape(b_l, s, h).astype(x.dtype), aux


def moe_mlp_arrays(x, gate_logits, w_in, w_out, top_k=2, capacity_factor=1.25,
                   axis="ep"):
    """Array-level MoE FFN. x: [B, S, H]; gate_logits: [B, S, E];
    w_in: [E, H, M]; w_out: [E, M, H]. Returns (y [B,S,H], aux_loss).

    With axis size > 1, tokens (batch dim) are sharded over 'ep' and experts
    dispatched via all_to_all; otherwise everything is local.
    """
    with RecordEvent("moe/ffn"):
        return _moe_mlp_dispatch(x, gate_logits, w_in, w_out, top_k,
                                 capacity_factor, axis)


def _moe_mlp_dispatch(x, gate_logits, w_in, w_out, top_k, capacity_factor,
                      axis):
    ep = axis_size(axis)
    if ep > 1 and x.shape[0] % ep != 0:
        # loud fallback: every shard gets every expert's weights and no
        # all_to_all dispatch happens — an invisible capacity/perf cliff
        # if silent (VERDICT r2 weak #5)
        import warnings

        warnings.warn(
            f"MoE: global batch {x.shape[0]} is not divisible by the "
            f"'{axis}' mesh axis ({ep}) — falling back to LOCAL DENSE "
            f"routing (all experts replicated on every shard, no expert-"
            f"parallel dispatch). Pad the batch to a multiple of {ep} to "
            f"engage expert parallelism.", stacklevel=2)
    if ep <= 1 or x.shape[0] % ep != 0:
        return _moe_single(x, gate_logits, w_in, w_out,
                           top_k=top_k, capacity_factor=capacity_factor)
    if monitor.enabled() and not isinstance(gate_logits, jax.core.Tracer):
        # The sharded dispatch below is opaque to host telemetry (the
        # dispatch tensor only exists inside shard_map, as a tracer).
        # On the eager path, replay ONE shard's routing — same _routing,
        # same local token slice and capacity as _moe_sharded — purely to
        # record tokens_per_expert/dropped/imbalance as a per-shard
        # SAMPLE. One extra routing pass (not ep), eager-only and
        # monitor-gated; compiled runs skip entirely.
        b, s, _ = x.shape
        e = w_in.shape[0]
        b_l = b // ep
        cap = moe_capacity(b_l * s, e, top_k, capacity_factor)
        d_0, _, _ = _routing(
            jnp.asarray(gate_logits[:b_l]).reshape(b_l * s, e),
            e, top_k, cap)
        _maybe_record_routing(d_0, b_l * s, top_k)
    mesh = get_mesh()
    body = partial(_moe_sharded, axis_name=axis, top_k=top_k,
                   capacity_factor=capacity_factor)
    fn = shard_map_compat(
        body, mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis), P(axis)),
        out_specs=(P(axis), P()),
        axis_names=frozenset({axis}), check_vma=False,
    )
    # partial-manual shard_map (only 'ep' manual, dp/mp auto) requires a
    # surrounding jit in this jax version; jax.jit inlines when already
    # inside a trace, so this is a no-op on the blessed compiled path
    return jax.jit(fn)(x, gate_logits, w_in, w_out)
