"""Ring attention — sequence/context parallelism over the 'sp' mesh axis.

Capability gap the reference snapshot leaves open (SURVEY §5.7: no ring
attention / context parallel / Ulysses anywhere; long sequences are handled
only by recompute). Built natively here because long-context GPT pretrain
is table stakes for the north-star config: the sequence stays sharded
through attention, and K/V blocks rotate around the 'sp' ring via
`lax.ppermute` (one ICI hop per step) while each device accumulates its
queries' output with an online (flash-style) softmax. Peak memory per chip
is O(S/n · S/n) attention scores instead of O(S · S), and compute/comm
overlap rides XLA's latency-hiding scheduler.

Layouts match ops/pallas_ops.py: q, k, v are [B, S, H, D].
"""
from __future__ import annotations

import math
import warnings
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.dispatch import apply
from .mesh import get_mesh, axis_size, shard_map_compat

__all__ = ["ring_attention", "ring_attention_arrays", "zigzag_sequence_perm"]


def _online_block_update(carry, q_scaled, qpos, k_blk, v_blk, kpos,
                         qseg=None, kseg=None):
    """One flash-style online-softmax accumulation of a K/V block against
    scaled queries (shared by the contiguous and zigzag ring bodies — the
    numerically delicate part lives exactly once). kpos=None means no
    causal mask for this block; qseg/kseg ([B, Sq]/[B, Sk] int32) add
    packed-segment masking (positions attend iff ids match — safe with
    the diagonal-first visit order: a row's own position always matches
    its own segment, so m turns finite before foreign blocks arrive)."""
    o, m, l = carry
    s = jnp.einsum("bqhd,bkhd->bhqk", q_scaled, k_blk.astype(jnp.float32))
    if kpos is not None:
        s = jnp.where(kpos[None, None, None, :]
                      > qpos[None, None, :, None], -jnp.inf, s)
    if qseg is not None:
        s = jnp.where(qseg[:, None, :, None] == kseg[:, None, None, :],
                      s, -jnp.inf)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    # rows whose running max is still -inf (every block seen so far fully
    # masked — segment masking can order a fully-masked pair before the
    # diagonal one) must contribute exact zeros, not exp(-inf - -inf)=NaN
    p = jnp.where(jnp.isneginf(m_new)[..., None], 0.0,
                  jnp.exp(s - m_new[..., None]))
    corr = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - m_new))
    l_new = l * corr + jnp.sum(p, axis=-1)
    o_new = o * corr[..., None] + jnp.einsum(
        "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32))
    return o_new, m_new, l_new


def _ring_attn_local(q, k, v, seg=None, *, axis_name, causal, scale):
    """Per-shard body (inside shard_map): q/k/v hold the local sequence
    chunk [B, Sq, H, D]; returns the local output chunk. seg: optional
    local packed-segment ids [B, Sq] — the k-side ids ride the SAME ring
    rotation as their k/v block."""
    n = jax.lax.psum(1, axis_name)  # static: axis size
    my = jax.lax.axis_index(axis_name)
    b, sq, h, d = q.shape
    qpos = my * sq + jnp.arange(sq)
    qf = q.astype(jnp.float32) * scale
    perm = [(j, (j + 1) % n) for j in range(n)]

    def attend(o, m, l, k_blk, v_blk, kseg_blk, i):
        """Accumulate the block that originated at ring position
        (my - i) % n."""
        src = (my - i) % n
        kpos = (src * sq + jnp.arange(sq)) if causal else None
        return _online_block_update((o, m, l), qf, qpos, k_blk, v_blk, kpos,
                                    qseg=seg, kseg=kseg_blk)

    o0 = jnp.zeros((b, h, sq, d), jnp.float32)
    # step 0 visits the device's own (diagonal) block, which under a causal
    # mask has unmasked entries — so m turns finite before any fully masked
    # future block arrives and exp(-inf - finite) stays 0, not NaN.
    m0 = jnp.full((b, h, sq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    o, m, l = attend(o0, m0, l0, k, v, seg, 0)
    if n > 1:
        # permute-at-top so the ring does n-1 rotations, not n (the block a
        # final rotation would produce is never attended).
        kseg0 = seg if seg is not None else jnp.zeros((b, sq), jnp.int32)

        def step(carry, i):
            o, m, l, k_blk, v_blk, kseg_blk = carry
            k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
            v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
            kseg_blk = jax.lax.ppermute(kseg_blk, axis_name, perm)
            o, m, l = attend(o, m, l, k_blk, v_blk,
                             kseg_blk if seg is not None else None, i)
            return (o, m, l, k_blk, v_blk, kseg_blk), None

        (o, m, l, _, _, _), _ = jax.lax.scan(
            step, (o, m, l, k, v, kseg0), jnp.arange(1, n))
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def _ring_attn_zigzag(q, k, v, seg=None, *, axis_name, scale):
    """Causal ring attention over the ZIGZAG layout: the local sequence
    rows are half-chunks (j, 2n-1-j) of the 2n global half-chunks, so
    every device owns an equal mix of early and late positions. Each ring
    step considers 4 (q-half, k-half) pairs and computes a pair ONLY when
    its k-chunk index <= its q-chunk index (lax.cond on a per-device
    scalar — pure compute, no collectives inside the branch, so
    non-uniform branching across the ring is legal). Per-device work is
    exactly 2n+1 half-pairs for every rank — the balanced version of the
    contiguous ring where rank n-1 computes n full blocks while rank 0
    masks away all but one (the TODO this replaces); ~2x causal
    throughput at large n."""
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    b, sq, h, d = q.shape
    hsq = sq // 2
    cl, ch = my, 2 * n - 1 - my              # local half-chunk indices
    qf = q.astype(jnp.float32) * scale
    q_halves = (qf[:, :hsq], qf[:, hsq:])
    q_chunks = (cl, ch)
    qpos = tuple(c * hsq + jnp.arange(hsq) for c in q_chunks)
    perm = [(j, (j + 1) % n) for j in range(n)]

    qseg_halves = (None, None)
    if seg is not None:
        qseg_halves = (seg[:, :hsq], seg[:, hsq:])

    def attend_pair(carry, k_half, v_half, kseg_half, qh_idx, kc):
        kpos = kc * hsq + jnp.arange(hsq)
        return _online_block_update(carry, q_halves[qh_idx], qpos[qh_idx],
                                    k_half, v_half, kpos,
                                    qseg=qseg_halves[qh_idx], kseg=kseg_half)

    def visit(carries, k_blk, v_blk, kseg_blk, src):
        """Process both k-halves of the block that originated at `src`
        against both local q-halves, skipping fully-masked pairs."""
        k_halves = (k_blk[:, :hsq], k_blk[:, hsq:])
        v_halves = (v_blk[:, :hsq], v_blk[:, hsq:])
        kseg_halves = ((kseg_blk[:, :hsq], kseg_blk[:, hsq:])
                       if seg is not None else (None, None))
        k_chunks = (src, 2 * n - 1 - src)
        new = []
        for qh in range(2):
            carry = carries[qh]
            for kh in range(2):
                kc = k_chunks[kh]
                carry = jax.lax.cond(
                    kc <= q_chunks[qh],
                    lambda c, kh=kh, qh=qh, kc=kc: attend_pair(
                        c, k_halves[kh], v_halves[kh], kseg_halves[kh],
                        qh, kc),
                    lambda c: c,
                    carry)
            new.append(carry)
        return tuple(new)

    def init_carry():
        return (jnp.zeros((b, h, hsq, d), jnp.float32),
                jnp.full((b, h, hsq), -jnp.inf, jnp.float32),
                jnp.zeros((b, h, hsq), jnp.float32))

    carries = (init_carry(), init_carry())
    carries = visit(carries, k, v, seg, my)  # own block first (diagonal)
    if n > 1:
        kseg0 = seg if seg is not None else jnp.zeros((b, sq), jnp.int32)

        def step(state, i):
            carries, k_blk, v_blk, kseg_blk = state
            k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
            v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
            kseg_blk = jax.lax.ppermute(kseg_blk, axis_name, perm)
            carries = visit(carries, k_blk, v_blk,
                            kseg_blk if seg is not None else None,
                            (my - i) % n)
            return (carries, k_blk, v_blk, kseg_blk), None

        (carries, _, _, _), _ = jax.lax.scan(
            step, (carries, k, v, kseg0), jnp.arange(1, n))

    outs = []
    for o, m, l in carries:
        outs.append(jnp.transpose(o / jnp.maximum(l, 1e-30)[..., None],
                                  (0, 2, 1, 3)))
    return jnp.concatenate(outs, axis=1).astype(q.dtype)


def zigzag_sequence_perm(s, n):
    """Global permutation natural -> zigzag (device j holds half-chunks
    j and 2n-1-j); returns (perm, inverse). Public: models that permute
    the token stream ONCE (embedding output in, logits out) pay one
    gather each way per STEP instead of four per attention layer — pair
    with layout="zigzag_pre"."""
    import numpy as np

    hsq = s // (2 * n)
    order = []
    for j in range(n):
        order.extend(range(j * hsq, (j + 1) * hsq))
        order.extend(range((2 * n - 1 - j) * hsq, (2 * n - j) * hsq))
    perm = np.asarray(order)
    inv = np.empty_like(perm)
    inv[perm] = np.arange(s)
    return perm, inv


def ring_attention_arrays(q, k, v, is_causal=True, scale=None, axis="sp",
                          layout="contiguous", segment_ids=None):
    """Array-level ring attention: [B,S,H,D] with S sharded over `axis`.

    layout="zigzag" (causal only) rebalances the ring: the sequence is
    permuted so each device holds an early+late half-chunk pair, every
    rank does identical work, and fully-masked pairs are skipped —
    ~2x causal throughput at large axis sizes for one gather each way.
    Falls back to the single-shard flash path when the axis is degenerate.

    segment_ids: optional [B, S] int32 packed-sequence ids (same layout
    as the token stream — for zigzag_pre that means ALREADY permuted);
    the k-side ids ride the ring rotation with their k/v blocks, so
    packed long-context batches keep context parallelism.
    """
    from ..ops.pallas_ops import flash_attention_arrays

    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    seg = None
    if segment_ids is not None:
        seg = jnp.asarray(segment_ids, jnp.int32)
    n = axis_size(axis)
    if n <= 1:
        return flash_attention_arrays(q, k, v, None, is_causal, scale,
                                      segment_ids=seg)
    if q.shape[1] % n != 0:
        warnings.warn(
            f"ring_attention: seq len {q.shape[1]} not divisible by {axis} axis "
            f"size {n}; falling back to full-sequence attention (peak memory "
            f"O(S^2) per chip instead of O((S/n)^2))."
        )
        return flash_attention_arrays(q, k, v, None, is_causal, scale,
                                      segment_ids=seg)

    mesh = get_mesh()
    # Only 'sp' is manual; batch/head dims stay in GSPMD-auto mode so dp/mp
    # sharding (and an enclosing pp pipeline) keep composing.
    spec = P(None, axis, None, None)
    seg_spec = P(None, axis)
    zig_ok = is_causal and q.shape[1] % (2 * n) == 0 and n > 1
    if layout in ("zigzag", "zigzag_pre") and not zig_ok:
        warnings.warn(
            "ring_attention: zigzag layout needs causal attention and seq "
            "divisible by 2*axis_size; using the contiguous ring instead.")
        layout = "contiguous"

    def mapped(body):
        if seg is None:
            fn = shard_map_compat(
                body, mesh=mesh, in_specs=(spec, spec, spec),
                out_specs=spec, axis_names=frozenset({axis}),
                check_vma=False)
            return lambda a, b_, c: fn(a, b_, c)
        fn = shard_map_compat(
            body, mesh=mesh, in_specs=(spec, spec, spec, seg_spec),
            out_specs=spec, axis_names=frozenset({axis}), check_vma=False)
        return fn

    if layout == "zigzag_pre":
        # caller already permuted the sequence into zigzag order (one
        # model-level gather instead of per-layer ones); segment_ids
        # arrive in the same permuted order
        body = partial(_ring_attn_zigzag, axis_name=axis, scale=scale)
        fn = mapped(body)
        return fn(q, k, v, seg) if seg is not None else fn(q, k, v)
    if layout == "zigzag":
        perm, inv = zigzag_sequence_perm(q.shape[1], n)
        qz, kz, vz = (jnp.take(t, jnp.asarray(perm), axis=1)
                      for t in (q, k, v))
        segz = (jnp.take(seg, jnp.asarray(perm), axis=1)
                if seg is not None else None)
        body = partial(_ring_attn_zigzag, axis_name=axis, scale=scale)
        fn = mapped(body)
        out = fn(qz, kz, vz, segz) if seg is not None else fn(qz, kz, vz)
        return jnp.take(out, jnp.asarray(inv), axis=1)
    body = partial(_ring_attn_local, axis_name=axis, causal=is_causal, scale=scale)
    fn = mapped(body)
    return fn(q, k, v, seg) if seg is not None else fn(q, k, v)


def ring_attention(query, key, value, is_causal=True, scale=None, axis="sp",
                   layout="contiguous", name=None, segment_ids=None):
    """Tensor-level context-parallel attention (the long-context answer:
    seq stays sharded over 'sp' end to end — no all-gather of
    activations). layout="zigzag" load-balances the causal ring;
    segment_ids pack multiple documents per row (see
    ring_attention_arrays)."""
    seg_arr = None
    if segment_ids is not None:
        seg_arr = (segment_ids._data if hasattr(segment_ids, "_data")
                   else jnp.asarray(segment_ids))

    def fn(q, k, v):
        return ring_attention_arrays(q, k, v, is_causal, scale, axis,
                                     layout=layout, segment_ids=seg_arr)

    return apply(fn, query, key, value, name=name or "ring_attention")
