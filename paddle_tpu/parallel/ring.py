"""Ring attention — sequence/context parallelism over the 'sp' mesh axis.

Capability gap the reference snapshot leaves open (SURVEY §5.7: no ring
attention / context parallel / Ulysses anywhere; long sequences are handled
only by recompute). Built natively here because long-context GPT pretrain
is table stakes for the north-star config: the sequence stays sharded
through attention, and K/V blocks rotate around the 'sp' ring via
`lax.ppermute` (one ICI hop per step) while each device accumulates its
queries' output with an online (flash-style) softmax. Peak memory per chip
is O(S/n · S/n) attention scores instead of O(S · S), and compute/comm
overlap rides XLA's latency-hiding scheduler.

Layouts match ops/pallas_ops.py: q, k, v are [B, S, H, D].
"""
from __future__ import annotations

import math
import warnings
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.dispatch import apply
from .mesh import get_mesh, axis_size

__all__ = ["ring_attention", "ring_attention_arrays"]


def _ring_attn_local(q, k, v, *, axis_name, causal, scale):
    """Per-shard body (inside shard_map): q/k/v hold the local sequence
    chunk [B, Sq, H, D]; returns the local output chunk."""
    n = jax.lax.psum(1, axis_name)  # static: axis size
    my = jax.lax.axis_index(axis_name)
    b, sq, h, d = q.shape
    qpos = my * sq + jnp.arange(sq)
    qf = q.astype(jnp.float32) * scale
    perm = [(j, (j + 1) % n) for j in range(n)]

    # TODO(perf): causal masking leaves blocks from src > my fully masked;
    # a zig-zag layout (device holds chunks i and 2n-1-i) would balance the
    # ring and recover ~2x attention throughput at large n.
    def attend(o, m, l, k_blk, v_blk, i):
        """Online-softmax accumulate the block that originated at ring
        position (my - i) % n."""
        src = (my - i) % n
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, k_blk.astype(jnp.float32))
        if causal:
            kpos = src * sq + jnp.arange(sq)
            s = jnp.where(kpos[None, None, None, :] > qpos[None, None, :, None],
                          -jnp.inf, s)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        o_new = o * corr[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32)
        )
        return o_new, m_new, l_new

    o0 = jnp.zeros((b, h, sq, d), jnp.float32)
    # step 0 visits the device's own (diagonal) block, which under a causal
    # mask has unmasked entries — so m turns finite before any fully masked
    # future block arrives and exp(-inf - finite) stays 0, not NaN.
    m0 = jnp.full((b, h, sq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    o, m, l = attend(o0, m0, l0, k, v, 0)
    if n > 1:
        # permute-at-top so the ring does n-1 rotations, not n (the block a
        # final rotation would produce is never attended).
        def step(carry, i):
            o, m, l, k_blk, v_blk = carry
            k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
            v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
            o, m, l = attend(o, m, l, k_blk, v_blk, i)
            return (o, m, l, k_blk, v_blk), None

        (o, m, l, _, _), _ = jax.lax.scan(step, (o, m, l, k, v), jnp.arange(1, n))
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def ring_attention_arrays(q, k, v, is_causal=True, scale=None, axis="sp"):
    """Array-level ring attention: [B,S,H,D] with S sharded over `axis`.

    Falls back to the single-shard flash path when the axis is degenerate.
    """
    from ..ops.pallas_ops import flash_attention_arrays

    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    n = axis_size(axis)
    if n <= 1:
        return flash_attention_arrays(q, k, v, None, is_causal, scale)
    if q.shape[1] % n != 0:
        warnings.warn(
            f"ring_attention: seq len {q.shape[1]} not divisible by {axis} axis "
            f"size {n}; falling back to full-sequence attention (peak memory "
            f"O(S^2) per chip instead of O((S/n)^2))."
        )
        return flash_attention_arrays(q, k, v, None, is_causal, scale)

    mesh = get_mesh()
    # Only 'sp' is manual; batch/head dims stay in GSPMD-auto mode so dp/mp
    # sharding (and an enclosing pp pipeline) keep composing.
    spec = P(None, axis, None, None)
    body = partial(_ring_attn_local, axis_name=axis, causal=is_causal, scale=scale)
    fn = jax.shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        axis_names=frozenset({axis}), check_vma=False,
    )
    return fn(q, k, v)


def ring_attention(query, key, value, is_causal=True, scale=None, axis="sp", name=None):
    """Tensor-level context-parallel attention (the long-context answer:
    seq stays sharded over 'sp' end to end — no all-gather of activations)."""

    def fn(q, k, v):
        return ring_attention_arrays(q, k, v, is_causal, scale, axis)

    return apply(fn, query, key, value, name=name or "ring_attention")
