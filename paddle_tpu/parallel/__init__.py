"""paddle_tpu.parallel — SPMD mesh engine.

The TPU-native replacement for the reference's entire multi-process
parallelism stack (HybridCommunicateGroup topology.py, ProcessGroupNCCL,
EagerReducer, mp_ops c_* collectives, pipeline p2p — SURVEY §2.4): one
device Mesh with named axes

    dp       data parallel        (batch dim)
    sharding ZeRO weight-update sharding (optimizer state dim 0)
    pp       pipeline parallel    (stacked-layer scan + collective-permute)
    mp       tensor parallel      (hidden/head dims)
    sp       sequence/context parallel (sequence dim; ring attention)
    ep       expert parallel      (MoE expert dim, rides mp/dp axes)

Parameters carry per-dim logical axes (`Parameter._sharding_axes`); the
compiled train step (paddle_tpu.jit + this engine) turns them into
jax.sharding.NamedSharding placements and XLA GSPMD inserts all
collectives over ICI/DCN.
"""
from .mesh import (
    init_mesh, get_mesh, set_mesh, mesh_axes, axis_size, has_axis, MeshGuard,
)
from .api import (
    shard_parameter, shard_tensor, sharding_of, param_sharding, constraint,
    replicated, place_model,
)
from .mp_layers import (
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
    ParallelCrossEntropy, mp_allreduce, mp_identity,
)
from .random_ import RNGStatesTracker, get_rng_state_tracker, model_parallel_random_seed
from .ring import ring_attention, ring_attention_arrays

__all__ = [
    "init_mesh", "get_mesh", "set_mesh", "mesh_axes", "axis_size", "has_axis",
    "MeshGuard", "shard_parameter", "shard_tensor", "sharding_of",
    "param_sharding", "constraint", "replicated", "place_model",
    "ColumnParallelLinear",
    "RowParallelLinear", "VocabParallelEmbedding", "ParallelCrossEntropy",
    "RNGStatesTracker", "get_rng_state_tracker", "model_parallel_random_seed",
    "ring_attention", "ring_attention_arrays",
]
