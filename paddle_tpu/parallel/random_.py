"""Model-parallel RNG state tracker (reference:
fleet/layers/mpu/random.py:35 RNGStatesTracker — distinct dropout seeds
inside vs outside tensor-parallel regions so replicated activations get
identical masks while mp-sharded ones get per-shard masks)."""
from __future__ import annotations

import threading

import jax

from ..core import random as _rng

__all__ = ["RNGStatesTracker", "get_rng_state_tracker", "model_parallel_random_seed"]


class RNGStatesTracker:
    def __init__(self):
        self.states = {}

    def reset(self):
        self.states = {}

    def add(self, name, seed):
        if name in self.states:
            raise ValueError(f"rng state {name} already exists")
        self.states[name] = jax.random.PRNGKey(int(seed))

    def rng_state(self, name="model_parallel_rng"):
        from contextlib import contextmanager

        if name not in self.states:
            raise ValueError(f"rng state {name} not added")

        @contextmanager
        def guard():
            with _rng.key_scope(self.states[name]):
                try:
                    yield
                finally:
                    self.states[name] = _rng.get_state()

        return guard()


_TRACKER = RNGStatesTracker()


def get_rng_state_tracker():
    return _TRACKER


def model_parallel_random_seed(seed=None):
    import random as pyrandom

    # ptpu-check[determinism]: reference-parity default — fleet draws a
    # random seed when none is given; deterministic runs pass seed=
    seed = seed or (1024 + pyrandom.randint(0, 100000))
    _TRACKER.reset()
    _TRACKER.add("global_seed", seed)
    _TRACKER.add("model_parallel_rng", seed + 1)
