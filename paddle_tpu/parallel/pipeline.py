"""Pipeline parallelism over the 'pp' mesh axis.

Reference analog: fleet/meta_parallel/pipeline_parallel.py:31 (1F1B
micro-batch schedule) + pp_utils/p2p_communication.py:298 (send_v2/recv_v2
NCCL p2p with tensor-meta handshakes) + pp_layers.py:209 (LayerDesc
segmentation).

TPU-native re-design: there is no host-driven schedule and no p2p
handshake. The whole pipeline — every micro-batch, every stage hop — is ONE
compiled XLA program:

- stage weights live in stacked arrays with a leading stage dim sharded on
  'pp' (each device group holds only its stage's slice),
- the micro-batch rotation is a `lax.scan` whose carry hops stages via
  `lax.ppermute` over ICI (the collective-permute the reference emulates
  with NCCL send/recv),
- the schedule is GPipe-shaped (M + pp - 1 ticks); XLA's latency-hiding
  scheduler overlaps the permute DMA with the next tick's compute, which is
  what hand-written 1F1B overlap achieves in the reference,
- only 'pp' is manual (shard_map axis_names={'pp'}); dp/mp/sp/ep stay in
  GSPMD-auto mode so tensor-parallel constraints inside the stage body
  keep working.

Functions here are array-level (jnp in, jnp out); `apply`-wrapped use lives
in models (GPTStackedBlocks) and meta_parallel.
"""
from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .mesh import get_mesh, axis_size

__all__ = ["pipeline_apply", "scan_blocks"]


def scan_blocks(block_fn: Callable, stacked_params: Any, x, unroll: int = 1):
    """Apply L stacked blocks sequentially via lax.scan (single-stage path;
    compile time O(1) in depth — the TPU answer to the reference's per-layer
    Program ops)."""

    def body(h, p):
        return block_fn(p, h), None

    out, _ = jax.lax.scan(body, x, stacked_params, unroll=unroll)
    return out


def pipeline_apply(
    block_fn: Callable,
    stacked_params: Any,
    x,
    n_microbatches: int | None = None,
    axis: str = "pp",
):
    """Run x through a pp-stage GPipe pipeline inside one XLA program.

    block_fn(params_leaf_slice, h) -> h : one transformer block.
    stacked_params: pytree, every leaf [L, ...] with L = total blocks,
        L % pp == 0; leading dim sharded on 'pp' outside this call.
    x: [B, ...] activations; split into M micro-batches along dim 0.
    """
    mesh = get_mesh()
    pp = axis_size(axis)
    if pp == 1:
        return scan_blocks(block_fn, stacked_params, x)

    B = x.shape[0]
    M = n_microbatches or pp
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible into {M} micro-batches")
    leaves = jax.tree_util.tree_leaves(stacked_params)
    L = leaves[0].shape[0]
    if L % pp != 0:
        raise ValueError(f"{L} blocks not divisible by pp={pp}")

    xs = x.reshape((M, B // M) + x.shape[1:])

    def stage_fn(params, h):
        # params leaves: [k, ...] — this stage's k blocks, scanned.
        return scan_blocks(block_fn, params, h)

    @functools.partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(axis), P()),
        out_specs=P(),
        axis_names=frozenset({axis}),
        check_vma=False,
    )
    def run(params, xs):
        # each shard sees leaf [1, k, ...] — drop the stage dim
        params = jax.tree_util.tree_map(lambda a: a[0], params)
        stage = jax.lax.axis_index(axis)
        mb = jnp.zeros_like(xs[0])
        outs = jnp.zeros_like(xs)
        fwd_perm = [(i, i + 1) for i in range(pp - 1)]

        def tick(carry, t):
            mb, outs = carry
            # stage 0 ingests micro-batch t (clipped when draining)
            inp = jnp.where(stage == 0, xs[jnp.clip(t, 0, M - 1)], mb)
            out = stage_fn(params, inp)
            # last stage retires micro-batch t-(pp-1)
            j = t - (pp - 1)
            write = (stage == pp - 1) & (j >= 0)
            outs = jnp.where(
                write,
                jax.lax.dynamic_update_index_in_dim(
                    outs, out, jnp.clip(j, 0, M - 1), 0
                ),
                outs,
            )
            # hop to the next stage over ICI
            mb = jax.lax.ppermute(out, axis, fwd_perm)
            return (mb, outs), None

        (mb, outs), _ = jax.lax.scan(
            tick, (mb, outs), jnp.arange(M + pp - 1)
        )
        # outs is populated only on the last stage; all-reduce over the pp
        # axis broadcasts it (zeros elsewhere).
        outs = jnp.where(stage == pp - 1, outs, jnp.zeros_like(outs))
        return jax.lax.psum(outs, axis)

    # params arrive stage-major: leaf [L, ...] -> [pp, k, ...] so the shard_map
    # slice along dim 0 hands each stage its k blocks.
    staged = jax.tree_util.tree_map(
        lambda a: a.reshape((pp, L // pp) + a.shape[1:]), stacked_params
    )
    out = run(staged, xs)
    return out.reshape((B,) + x.shape[1:])
