"""Pipeline parallelism over the 'pp' mesh axis.

Reference analog: fleet/meta_parallel/pipeline_parallel.py:31 (1F1B
micro-batch schedule) + pp_utils/p2p_communication.py:298 (send_v2/recv_v2
NCCL p2p with tensor-meta handshakes) + pp_layers.py:209 (LayerDesc
segmentation).

TPU-native re-design: there is no host-driven schedule and no p2p
handshake. The whole pipeline — every micro-batch, every stage hop — is ONE
compiled XLA program:

- stage weights live in stacked arrays with a leading stage dim sharded on
  'pp' (each device group holds only its stage's slice),
- the micro-batch rotation is a `lax.scan` whose carry hops stages via
  `lax.ppermute` over ICI (the collective-permute the reference emulates
  with NCCL send/recv),
- the schedule is GPipe-shaped (M + pp - 1 ticks); XLA's latency-hiding
  scheduler overlaps the permute DMA with the next tick's compute, which is
  what hand-written 1F1B overlap achieves in the reference,
- only 'pp' is manual (shard_map axis_names={'pp'}); dp/mp/sp/ep stay in
  GSPMD-auto mode so tensor-parallel constraints inside the stage body
  keep working.

Functions here are array-level (jnp in, jnp out); `apply`-wrapped use lives
in models (GPTStackedBlocks) and meta_parallel.
"""
from __future__ import annotations

import functools
import os
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from .mesh import get_mesh, axis_size, shard_map_compat
from .. import monitor
from ..profiler import RecordEvent

__all__ = ["pipeline_apply", "pipeline_1f1b", "scan_blocks"]


def scan_blocks(block_fn: Callable, stacked_params: Any, x,
                unroll: int | None = None, aux: Any = None):
    """Apply L stacked blocks sequentially via lax.scan (single-stage path;
    compile time O(1) in depth — the TPU answer to the reference's per-layer
    Program ops).

    aux: optional pytree of per-token metadata (e.g. packed-sequence
    segment ids) passed unchanged to every block as a third argument:
    block_fn(params_slice, h, aux). Constant across layers, so it rides
    the scan closure, not the carry.

    Default unroll policy (override with PTPU_SCAN_UNROLL=<n>, 0 = full):
    FULLY unroll when depth <= 32, else keep the rolled scan. Measured on
    v5e (GPT-2 124M, batch 8 x seq 1024): full unroll 108.3k tokens/sec vs
    92k rolled (+18%) — XLA schedules DMA prefetch and fusion across block
    boundaries that a scan body boundary forbids. PARTIAL unroll is a trap
    (unroll=2: 65k, unroll=4: 60k — worse than rolled) and is never chosen
    automatically. Deep stacks keep O(1)-in-depth compile time. Pipeline
    stage bodies pass an explicit unroll=1: they already sit inside the
    scanned pipeline tick loop, where replicating the stage body would
    multiply the pipeline program's size per tick (unmeasured, and the
    bench above only covers the single-stage path)."""

    def _depth():
        return jax.tree_util.tree_leaves(stacked_params)[0].shape[0]

    if unroll is None:
        env = os.environ.get("PTPU_SCAN_UNROLL")
        unroll = int(env) if env is not None else (
            _depth() if _depth() <= 32 else 1)
    if unroll <= 0:
        unroll = _depth()

    if aux is None:
        def body(h, p):
            return block_fn(p, h), None
    else:
        def body(h, p):
            return block_fn(p, h, aux), None

    out, _ = jax.lax.scan(body, x, stacked_params, unroll=max(1, unroll))
    return out


def _pipeline_telemetry(schedule, pp, M, v, ticks, t0, sample):
    """Host-side schedule telemetry. `sample` is any array flowing through
    the schedule: when it is a tracer the call sits inside an outer jit
    trace, where wall-clock numbers would measure tracing, not execution —
    skip. On the recorded (eager) path the timed window spans trace +
    compile + run of the fused XLA program — each eager call builds a
    fresh closure, so compile dominates and the series is a smoke/debug
    signal, not a perf ruler; production per-step numbers come from the
    profiler's xplane capture, and bubble_fraction (analytic) is exact
    everywhere."""
    if not monitor.enabled() or isinstance(sample, jax.core.Tracer):
        return
    jax.block_until_ready(sample)   # time the run, not just the dispatch
    dt = time.perf_counter() - t0
    # per-tick time ~ per-stage per-microbatch slot time
    monitor.histogram("pipeline/stage_time").labels(
        schedule=schedule).observe(dt / max(1, ticks))
    # warm-up/drain bubble of the schedule: pp-1 idle slots out of
    # M*v + pp - 1 total (v = virtual stages per device; 1F1B has the
    # same fraction over its doubled fwd+bwd slot count)
    monitor.gauge("pipeline/bubble_fraction").labels(schedule=schedule).set(
        (pp - 1) / (M * v + pp - 1))
    monitor.counter("pipeline/microbatches").labels(schedule=schedule).add(M)


_LOW_FLOAT = ("bfloat16", "float16")


def _cpu_lowp() -> bool:
    return jax.default_backend() == "cpu"


def _widen_boundary(tree):
    """CPU-only workaround for the partial-manual bf16 psum bug (see
    _psum_safe): REPLICATED (P()) low-precision inputs to a partial-manual
    shard_map get a JAX-inserted psum over the manual axis on their
    cotangent in the backward pass — in the input dtype, which is the
    crashing construct. Feed such inputs through the boundary as f32 and
    narrow back to the original dtype inside the region (returned as the
    second element, a dtype tree for _narrow_boundary). No-op off-CPU."""
    dtypes = jax.tree_util.tree_map(lambda a: a.dtype, tree)
    if not _cpu_lowp():
        return tree, dtypes
    widened = jax.tree_util.tree_map(
        lambda a: a.astype(jnp.float32) if str(a.dtype) in _LOW_FLOAT else a,
        tree)
    return widened, dtypes


def _narrow_boundary(tree, dtypes):
    return jax.tree_util.tree_map(
        lambda a, dt: a.astype(dt) if a.dtype != dt else a, tree, dtypes)


def _psum_safe(x, axis):
    """psum that survives XLA-CPU's float-normalization bug: a bf16/f16
    all-reduce inside a PARTIAL-manual shard_map region (axis_names a
    strict subset of the mesh) hits `Invalid binary instruction opcode
    copy` (fatal) on the CPU backend — minimal repro in
    tests/test_pipeline.py::test_partial_manual_bf16_psum. Shared
    implementation: distributed.collective._reduce_safe (f32 reduce on
    CPU; TPU keeps the native dtype on the wire, half the ICI bytes)."""
    from ..distributed.collective import _reduce_safe

    return _reduce_safe(jax.lax.psum, x, axis)


def pipeline_apply(
    block_fn: Callable,
    stacked_params: Any,
    x,
    n_microbatches: int | None = None,
    axis: str = "pp",
    num_chunks: int = 1,
    aux: Any = None,
):
    """Run x through a pp-stage GPipe pipeline inside one XLA program.

    block_fn(params_leaf_slice, h) -> h : one transformer block.
    stacked_params: pytree, every leaf [L, ...] with L = total blocks,
        L % pp == 0; leading dim sharded on 'pp' outside this call.
    x: [B, ...] activations; split into M micro-batches along dim 0.
    aux: optional pytree of PER-TOKEN metadata (packed-sequence segment
        ids, [B, S]-leading leaves) split into the same M micro-batches as
        x. Unlike activations, aux does NOT hop stages over ICI: every
        stage holds the replicated [M, B/M, ...] table and indexes the
        micro-batch it is currently computing (stage s works on
        micro-batch t - s at tick t), so the id rows stay paired with
        their activations through the whole schedule. When given,
        block_fn is called as block_fn(params, h, aux_mb). This is the
        TPU answer to the reference's p2p meta handshake carrying
        attention masks with activations (pp_utils/p2p_communication.py).

    num_chunks > 1 selects the INTERLEAVED schedule (reference
    meta_parallel/pipeline_parallel.py:461 PipelineParallelWithInterleave):
    each device hosts `num_chunks` non-adjacent layer chunks (virtual
    stage vs hosts layers [vs*k, (vs+1)*k) on device vs % pp), shrinking
    the warm-up/drain bubble from (pp-1)/(M+pp-1) of the step to
    (pp-1)/(M*v+pp-1). See _pipeline_interleaved for the SPMD slot clock.
    """
    mesh = get_mesh()
    pp = axis_size(axis)
    if pp == 1:
        return scan_blocks(block_fn, stacked_params, x, aux=aux)
    if num_chunks > 1:
        return _pipeline_interleaved(block_fn, stacked_params, x,
                                     n_microbatches, axis, num_chunks,
                                     aux=aux)

    B = x.shape[0]
    M = n_microbatches or pp
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible into {M} micro-batches")
    leaves = jax.tree_util.tree_leaves(stacked_params)
    L = leaves[0].shape[0]
    if L % pp != 0:
        raise ValueError(f"{L} blocks not divisible by pp={pp}")

    xs = x.reshape((M, B // M) + x.shape[1:])
    has_aux = aux is not None
    aux_xs = _split_aux(aux, M) if has_aux else ()

    def stage_fn(params, h, amb):
        # params leaves: [k, ...] — this stage's k blocks, scanned rolled:
        # this body repeats inside the pipeline tick loop, so unrolling it
        # would multiply program size per tick.
        return scan_blocks(block_fn, params, h, unroll=1,
                           aux=amb if has_aux else None)

    @functools.partial(
        shard_map_compat,
        mesh=mesh,
        in_specs=(P(axis), P(), P()),
        out_specs=P(),
        axis_names=frozenset({axis}),
        check_vma=False,
    )
    def run(params, xs, axs):
        # each shard sees leaf [1, k, ...] — drop the stage dim
        params = jax.tree_util.tree_map(lambda a: a[0], params)
        xs = _narrow_boundary(xs, xs_dtype)
        stage = jax.lax.axis_index(axis)
        mb = jnp.zeros_like(xs[0])
        outs = jnp.zeros_like(xs)
        fwd_perm = [(i, i + 1) for i in range(pp - 1)]

        def tick(carry, t):
            mb, outs = carry
            # stage 0 ingests micro-batch t (clipped when draining)
            inp = jnp.where(stage == 0, xs[jnp.clip(t, 0, M - 1)], mb)
            # stage s computes micro-batch t - s: its metadata rows come
            # from the replicated table, not the ICI hop
            cur = jnp.clip(t - stage, 0, M - 1)
            amb = jax.tree_util.tree_map(lambda a: a[cur], axs)
            out = stage_fn(params, inp, amb)
            # last stage retires micro-batch t-(pp-1)
            j = t - (pp - 1)
            write = (stage == pp - 1) & (j >= 0)
            outs = jnp.where(
                write,
                jax.lax.dynamic_update_index_in_dim(
                    outs, out, jnp.clip(j, 0, M - 1), 0
                ),
                outs,
            )
            # hop to the next stage over ICI
            mb = jax.lax.ppermute(out, axis, fwd_perm)
            return (mb, outs), None

        (mb, outs), _ = jax.lax.scan(
            tick, (mb, outs), jnp.arange(M + pp - 1)
        )
        # outs is populated only on the last stage; all-reduce over the pp
        # axis broadcasts it (zeros elsewhere).
        outs = jnp.where(stage == pp - 1, outs, jnp.zeros_like(outs))
        return _psum_safe(outs, axis)

    # params arrive stage-major: leaf [L, ...] -> [pp, k, ...] so the shard_map
    # slice along dim 0 hands each stage its k blocks.
    staged = jax.tree_util.tree_map(
        lambda a: a.reshape((pp, L // pp) + a.shape[1:]), stacked_params
    )
    xs, xs_dtype = _widen_boundary(xs)
    # partial-manual shard_map validates specs only under jit; eager calls
    # (plain apply without jit.compile) need the wrapper — it inlines when
    # already inside a trace
    t0 = time.perf_counter()
    with RecordEvent("pipeline/gpipe"):
        out = jax.jit(run)(staged, xs, aux_xs)
    _pipeline_telemetry("gpipe", pp, M, 1, M + pp - 1, t0, out)
    return out.reshape((B,) + x.shape[1:])


def _split_aux(aux, M):
    """Reshape every aux leaf [B, ...] -> [M, B/M, ...] (the same
    micro-batch split as the activations)."""
    def split(a):
        if a.shape[0] % M != 0:
            raise ValueError(
                f"aux leading dim {a.shape[0]} not divisible into {M} "
                "micro-batches (must match the activation batch)")
        return a.reshape((M, a.shape[0] // M) + a.shape[1:])

    return jax.tree_util.tree_map(split, aux)


def _pipeline_interleaved(block_fn, stacked_params, x, n_microbatches,
                          axis, v, aux: Any = None):
    """Interleaved (virtual-stage) pipeline forward in one XLA program.

    The reference drives interleave from the host with a per-rank unit
    ordering (pipeline_parallel.py:461); the SPMD re-derivation used here:
    enumerate per-device work units k = g*(pp*v) + c*pp + j — group g of
    pp micro-batches, chunk c, member j — and run unit k on device s at
    slot u = k + s. Then every dependency arrives exactly one slot early:
    within a chunk, producer (same k, device s-1) finished at u-1; across
    the chunk boundary, device pp-1's unit for chunk c-1 finished at
    (k-pp) + (pp-1) = u-1 and the SAME wraparound ppermute
    [(i, (i+1) % pp)] delivers it. One uniform hop per slot, no
    double-booked devices, bubble = pp-1 slots out of M*v + pp - 1.

    Autodiff-transparent: XLA derives the mirrored backward schedule by
    transposing the scan (activations for all M*v units stay live through
    backward — the memory/bubble trade vs pipeline_1f1b, whose stash ring
    is bounded; the reference's interleave has the same appetite).

    Deliberately NOT merged with the gpipe scan above even though v=1
    degenerates to it: the gpipe body indexes this stage's params
    statically, while this schedule selects the chunk with a traced
    per-slot index — folding gpipe into the v=1 case would put a dynamic
    gather on the hot path of every pp>1 model for no benefit. Fixes to
    either scan body should be mirrored in the other.
    """
    mesh = get_mesh()
    pp = axis_size(axis)
    B = x.shape[0]
    M = n_microbatches or pp
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible into {M} micro-batches")
    if M % pp != 0:
        raise ValueError(
            f"interleaved schedule needs micro-batches ({M}) divisible by "
            f"pp ({pp}) — units advance in groups of pp")
    leaves = jax.tree_util.tree_leaves(stacked_params)
    L = leaves[0].shape[0]
    V = pp * v
    if L % V != 0:
        raise ValueError(f"{L} blocks not divisible by pp*num_chunks={V}")
    k_layers = L // V
    units = M * v
    U = units + pp - 1

    xs = x.reshape((M, B // M) + x.shape[1:])
    has_aux = aux is not None
    aux_xs = _split_aux(aux, M) if has_aux else ()

    @functools.partial(
        shard_map_compat,
        mesh=mesh,
        in_specs=(P(axis), P(), P()),
        out_specs=P(),
        axis_names=frozenset({axis}),
        check_vma=False,
    )
    def run(params, xs, axs):
        # leaf [1, v, k, ...] -> [v, k, ...]: this device's v chunks
        params = jax.tree_util.tree_map(lambda a: a[0], params)
        xs = _narrow_boundary(xs, xs_dtype)
        stage = jax.lax.axis_index(axis)
        wrap_perm = [(i, (i + 1) % pp) for i in range(pp)]
        mb_shape = xs.shape[1:]

        def tick(carry, u):
            h_recv, outs = carry
            ku = jnp.clip(u - stage, 0, units - 1)
            c = (ku % (pp * v)) // pp
            f = (ku % pp) + pp * (ku // (pp * v))
            chunk_params = jax.tree_util.tree_map(lambda a: a[c], params)
            first = (stage == 0) & (c == 0)
            h_in = jnp.where(first, xs[f], h_recv)
            # metadata for micro-batch f from the replicated table (ids do
            # not hop the ring; the unit->micro-batch map is exact)
            amb = jax.tree_util.tree_map(lambda a: a[f], axs)
            out = scan_blocks(block_fn, chunk_params, h_in, unroll=1,
                              aux=amb if has_aux else None)
            retire = (stage == pp - 1) & (c == v - 1) & (u - stage >= 0) \
                & (u - stage < units)
            outs = jnp.where(
                retire,
                jax.lax.dynamic_update_index_in_dim(outs, out, f, 0),
                outs)
            h_recv = jax.lax.ppermute(out, axis, wrap_perm)
            return (h_recv, outs), None

        carry0 = (jnp.zeros(mb_shape, x.dtype),
                  jnp.zeros((M,) + mb_shape, x.dtype))
        (h, outs), _ = jax.lax.scan(tick, carry0, jnp.arange(U))
        outs = jnp.where(stage == pp - 1, outs, jnp.zeros_like(outs))
        return _psum_safe(outs, axis)

    # layer l lives on virtual stage l // k_layers = c*pp + s: reshape
    # [L,...] -> [V, k, ...] -> [v, pp, k, ...] -> device-major
    # [pp, v, k, ...]
    def stage_major(a):
        rest = a.shape[1:]
        return a.reshape((v, pp, k_layers) + rest).transpose(
            (1, 0, 2) + tuple(range(3, 3 + len(rest))))

    staged = jax.tree_util.tree_map(stage_major, stacked_params)
    xs, xs_dtype = _widen_boundary(xs)
    t0 = time.perf_counter()
    with RecordEvent("pipeline/interleave"):
        out = jax.jit(run)(staged, xs, aux_xs)
    _pipeline_telemetry("interleave", pp, M, v, U, t0, out)
    return out.reshape((B,) + x.shape[1:])


def _label_cotangent(y):
    """Zero cotangent for a (possibly integer) label pytree leaf."""
    if jnp.issubdtype(jnp.result_type(y), jnp.inexact):
        return jnp.zeros_like(y)
    return np.zeros(jnp.shape(y), dtype=jax.dtypes.float0)


def pipeline_1f1b(
    block_fn: Callable,
    loss_fn: Callable,
    stacked_params: Any,
    tail_params: Any,
    x,
    y,
    n_microbatches: int | None = None,
    axis: str = "pp",
    aux: Any = None,
):
    """1F1B (PipeDream-flush) pipelined training loss in ONE XLA program.

    Reference analog: fleet/meta_parallel/pipeline_parallel.py:230 — the
    1F1B steady state where each stage alternates one forward and one
    backward micro-batch so at most `pp - stage` activation stashes are
    live, vs GPipe's M. The reference drives this schedule from the host
    with NCCL p2p; here the whole schedule is a `lax.scan` over global
    "slots" inside one `shard_map`:

    - slot clock: stage s runs forward of micro-batch f at slot `s + 2f`
      and backward of micro-batch b at slot `2*pp - 1 - s + 2b`. The two
      are parity-disjoint, so each slot is one `lax.cond` per stage; in
      steady state every stage computes every slot (no idle beyond the
      pp-1 warmup/drain bubble — the same bubble the reference has).
    - stages stash only their micro-batch INPUT in a pp-deep ring and
      recompute the stage forward under `jax.vjp` at the backward slot
      (activation recompute, the standard large-model 1F1B pairing).
      In-flight memory is O(pp * microbatch), not O(M * activations).
    - hops ride `lax.ppermute` both directions each slot (activations
      s->s+1, cotangents s->s-1) — the p2p_communication.py:298 analog.

    The function is autodiff-transparent: a `jax.custom_vjp` whose primal
    computes loss AND grads in the fused schedule, saving the grads as
    residuals; the outer `jax.grad` then just scales them. `loss_fn`
    consumes `tail_params` on the LAST stage (final norm / lm head /
    criterion), so head grads flow too:

        loss_fn(tail_params, h_out, y_microbatch) -> scalar mean loss

    Returns the scalar mean loss over micro-batches. Grads flow to
    `stacked_params`, `tail_params`, and `x`.

    aux: optional per-token metadata pytree ([B, ...]-leading leaves, e.g.
    packed segment ids) split with the activation micro-batches; when
    given, block_fn is called as block_fn(params, h, aux_mb) — both the
    forward slot (micro-batch f) and the recompute-backward slot
    (micro-batch b) read the right id rows from the replicated table.
    """
    mesh = get_mesh()
    pp = axis_size(axis)
    if pp == 1:
        # Degenerate pipeline: plain differentiable compute (outer autodiff
        # handles grads; no schedule needed).
        out = scan_blocks(block_fn, stacked_params, x, aux=aux)
        return loss_fn(tail_params, out, y)
    return _pipeline_1f1b_vjp(
        block_fn, loss_fn, n_microbatches, axis, stacked_params,
        tail_params, x, y, aux,
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3))
def _pipeline_1f1b_vjp(block_fn, loss_fn, n_microbatches, axis,
                       stacked_params, tail_params, x, y, aux):
    loss, _ = _pipeline_1f1b_impl(
        block_fn, loss_fn, n_microbatches, axis, stacked_params,
        tail_params, x, y, aux,
    )
    return loss


def _pipeline_1f1b_fwd(block_fn, loss_fn, n_microbatches, axis,
                       stacked_params, tail_params, x, y, aux):
    loss, grads = _pipeline_1f1b_impl(
        block_fn, loss_fn, n_microbatches, axis, stacked_params,
        tail_params, x, y, aux,
    )
    return loss, (grads, y, aux)


def _pipeline_1f1b_bwd(block_fn, loss_fn, n_microbatches, axis, res, gbar):
    (dparams, dtail, dx), y, aux = res
    # keep each cotangent's dtype: a bare `a * gbar` would promote bf16
    # leaves to f32 and fail custom_vjp's aval check on bf16 models
    scale = lambda t: jax.tree_util.tree_map(
        lambda a: (a * gbar).astype(a.dtype), t)
    dy = jax.tree_util.tree_map(_label_cotangent, y)
    daux = jax.tree_util.tree_map(_label_cotangent, aux)
    return scale(dparams), scale(dtail), (dx * gbar).astype(dx.dtype), dy, daux


_pipeline_1f1b_vjp.defvjp(_pipeline_1f1b_fwd, _pipeline_1f1b_bwd)


def _pipeline_1f1b_impl(block_fn, loss_fn, n_microbatches, axis,
                        stacked_params, tail_params, x, y, aux=None):
    """Fused forward+backward 1F1B schedule. Returns
    (mean_loss, (d_stacked_params, d_tail_params, dx))."""
    mesh = get_mesh()
    pp = axis_size(axis)
    B = x.shape[0]
    M = n_microbatches or pp
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible into {M} micro-batches")
    leaves = jax.tree_util.tree_leaves(stacked_params)
    L = leaves[0].shape[0]
    if L % pp != 0:
        raise ValueError(f"{L} blocks not divisible by pp={pp}")
    R = min(pp, M)                       # stash ring depth (1F1B bound)
    U = 2 * M + 2 * pp - 2               # total schedule slots

    xs = x.reshape((M, B // M) + x.shape[1:])
    ys = jax.tree_util.tree_map(
        lambda a: a.reshape((M, a.shape[0] // M) + a.shape[1:]), y)
    has_aux = aux is not None
    aux_xs = _split_aux(aux, M) if has_aux else ()

    @functools.partial(
        shard_map_compat,
        mesh=mesh,
        in_specs=(P(axis), P(), P(), P(), P()),
        out_specs=(P(), (P(axis), P(), P())),
        axis_names=frozenset({axis}),
        check_vma=False,
    )
    def run(params, tail, xs, ys, axs):
        params = jax.tree_util.tree_map(lambda a: a[0], params)
        tail = _narrow_boundary(tail, tail_dtype)
        xs = _narrow_boundary(xs, xs_dtype)
        stage = jax.lax.axis_index(axis)
        is_last = stage == pp - 1
        fwd_perm = [(i, i + 1) for i in range(pp - 1)]
        bwd_perm = [(i + 1, i) for i in range(pp - 1)]

        def stage_full(p, tl, h, ymb, amb):
            out = scan_blocks(block_fn, p, h, unroll=1,
                              aux=amb if has_aux else None)
            loss = jax.lax.cond(
                is_last,
                lambda: loss_fn(tl, out, ymb).astype(jnp.float32),
                lambda: jnp.float32(0.0),
            )
            return out, loss

        mb_shape = xs.shape[1:]
        f32 = lambda t: jax.tree_util.tree_map(
            lambda a: jnp.zeros(a.shape, jnp.float32), t)

        carry0 = dict(
            h_recv=jnp.zeros(mb_shape, x.dtype),
            g_recv=jnp.zeros(mb_shape, jnp.float32),
            stash=jnp.zeros((R,) + mb_shape, x.dtype),
            gacc=f32(params),
            tacc=f32(tail),
            dxs=jnp.zeros((M,) + mb_shape, jnp.float32),
            loss_sum=jnp.float32(0.0),
        )

        def slot(carry, u):
            rel_f = u - stage
            do_f = (rel_f >= 0) & (rel_f % 2 == 0) & (rel_f < 2 * M)
            f = jnp.clip(rel_f // 2, 0, M - 1)
            rel_b = u - (2 * pp - 1 - stage)
            do_b = (rel_b >= 0) & (rel_b % 2 == 0) & (rel_b < 2 * M)
            b = jnp.clip(rel_b // 2, 0, M - 1)

            y_f = jax.tree_util.tree_map(lambda a: a[f], ys)
            y_b = jax.tree_util.tree_map(lambda a: a[b], ys)
            aux_f = jax.tree_util.tree_map(lambda a: a[f], axs)
            aux_b = jax.tree_util.tree_map(lambda a: a[b], axs)
            h_in = jnp.where(stage == 0, xs[f], carry["h_recv"])

            def fwd_slot(c):
                out, loss = stage_full(params, tail, h_in, y_f, aux_f)
                return dict(
                    c,
                    stash=jax.lax.dynamic_update_index_in_dim(
                        c["stash"], h_in, f % R, 0),
                    loss_sum=c["loss_sum"] + loss,
                ), out, jnp.zeros(mb_shape, jnp.float32)

            def bwd_slot(c):
                h_stash = c["stash"][b % R]
                g_out = jnp.where(
                    is_last, jnp.zeros(mb_shape, jnp.float32),
                    c["g_recv"]).astype(h_stash.dtype)
                g_loss = jnp.where(is_last, jnp.float32(1.0 / M),
                                   jnp.float32(0.0))
                _, vjp_fn = jax.vjp(
                    lambda p, tl, h: stage_full(p, tl, h, y_b, aux_b),
                    params, tail, h_stash)
                dp, dtl, dh = vjp_fn((g_out, g_loss))
                add = lambda acc, g: jax.tree_util.tree_map(
                    lambda a, b_: a + b_.astype(jnp.float32), acc, g)
                dh32 = dh.astype(jnp.float32)
                dxs = jnp.where(
                    stage == 0,
                    jax.lax.dynamic_update_index_in_dim(c["dxs"], dh32, b, 0),
                    c["dxs"])
                return dict(
                    c,
                    gacc=add(c["gacc"], dp),
                    tacc=add(c["tacc"], dtl),
                    dxs=dxs,
                ), jnp.zeros(mb_shape, x.dtype), dh32

            def idle(c):
                return c, jnp.zeros(mb_shape, x.dtype), \
                    jnp.zeros(mb_shape, jnp.float32)

            c, send_h, send_g = jax.lax.cond(
                do_f, fwd_slot,
                lambda c: jax.lax.cond(do_b, bwd_slot, idle, c),
                carry)
            c = dict(
                c,
                h_recv=jax.lax.ppermute(send_h, axis, fwd_perm),
                g_recv=jax.lax.ppermute(send_g, axis, bwd_perm),
            )
            return c, None

        carry, _ = jax.lax.scan(slot, carry0, jnp.arange(U))

        loss = jax.lax.psum(carry["loss_sum"], axis) / M  # f32 scalar
        # tail/dx live on one stage (zeros elsewhere) — psum broadcasts.
        tacc = jax.tree_util.tree_map(
            lambda a: _psum_safe(a, axis), carry["tacc"])
        dxs = _psum_safe(carry["dxs"], axis)
        gacc = jax.tree_util.tree_map(lambda a: a[None], carry["gacc"])
        return loss, (gacc, tacc, dxs)

    staged = jax.tree_util.tree_map(
        lambda a: a.reshape((pp, L // pp) + a.shape[1:]), stacked_params
    )
    tail_params, tail_dtype = _widen_boundary(tail_params)
    xs, xs_dtype = _widen_boundary(xs)
    # see pipeline_apply: jit makes eager invocation legal (inlines in-trace)
    t0 = time.perf_counter()
    with RecordEvent("pipeline/1f1b"):
        loss, (gacc, tacc, dxs) = jax.jit(run)(
            staged, tail_params, xs, ys, aux_xs)
    _pipeline_telemetry("1f1b", pp, M, 1, U, t0, loss)
    dparams = jax.tree_util.tree_map(
        lambda g, p: g.reshape((L,) + g.shape[2:]).astype(p.dtype),
        gacc, stacked_params)
    dtail = jax.tree_util.tree_map(
        lambda g, dt: g.astype(dt), tacc, tail_dtype)
    dx = dxs.reshape((B,) + x.shape[1:]).astype(x.dtype)
    return loss, (dparams, dtail, dx)
