"""Inference API (reference: paddle/fluid/inference/ — AnalysisConfig +
AnalysisPredictor (api/analysis_predictor.h:95): load a saved model, run an
optimization pass pipeline, execute with zero-copy input/output handles;
TensorRT subgraphs for deployment).

TPU-native design: the "analysis pass pipeline + TensorRT engine" role is
played by XLA itself — `save_inference_model` traces the layer into a
StableHLO module via jax.export and serializes it next to the weights;
`create_predictor` deserializes and AOT-compiles it once. Input/output
handles mirror the reference's Tensor handle API (copy_from_cpu /
copy_to_cpu)."""
from __future__ import annotations

import os
import pickle

import numpy as np
import jax
import jax.numpy as jnp
from jax import export as jax_export

from ..core.tensor import Tensor
from ..nn.layer import Layer
from ..static import InputSpec

__all__ = [
    "Config", "Predictor", "create_predictor", "DistConfig",
    "save_inference_model", "load_inference_model",
]

_MODEL_SUFFIX = ".pdmodel"
_PARAMS_SUFFIX = ".pdiparams"


def save_inference_model(path_prefix: str, layer: Layer, input_spec=None,
                         example_inputs=None):
    """Trace `layer.forward` on the given specs and serialize:
    <prefix>.pdmodel = serialized StableHLO (jax.export), <prefix>.pdiparams
    = weights (reference: paddle.static.save_inference_model / jit.save)."""
    was_training = layer.training
    layer.eval()
    try:
        return _save_inference_model(path_prefix, layer, input_spec,
                                     example_inputs)
    finally:
        if was_training:
            layer.train()


def _save_inference_model(path_prefix, layer, input_spec, example_inputs):
    params, buffers = layer.state_arrays()

    if example_inputs is not None:
        specs = [jax.ShapeDtypeStruct(np.asarray(x._data if isinstance(x, Tensor) else x).shape,
                                      np.asarray(x._data if isinstance(x, Tensor) else x).dtype)
                 for x in example_inputs]
    else:
        if input_spec is None:
            raise ValueError("pass input_spec or example_inputs")
        specs = []
        sym_count = 0
        scope = jax_export.SymbolicScope()
        for s in input_spec:
            shape, dtype = (s.shape, np.dtype(s.dtype)) if isinstance(s, InputSpec) \
                else (tuple(s), np.dtype("float32"))
            dims = []
            for d in shape:
                if d is None or (isinstance(d, int) and d < 0):
                    # dynamic dim -> real symbolic dimension in the export
                    dims.append(jax_export.symbolic_shape(
                        f"_dyn{sym_count}", scope=scope)[0])
                    sym_count += 1
                else:
                    dims.append(int(d))
            specs.append(jax.ShapeDtypeStruct(tuple(dims), dtype))

    from ..autograd import no_grad

    def fn(params, buffers, *inputs):
        backup = layer.state_arrays()
        try:
            layer.load_state_arrays(params, buffers)
            with no_grad():
                out = layer(*[Tensor(x) for x in inputs])
            if isinstance(out, (list, tuple)):
                return tuple(o._data if isinstance(o, Tensor) else o for o in out)
            return out._data if isinstance(out, Tensor) else out
        finally:
            layer.load_state_arrays(*backup)

    exported = jax_export.export(jax.jit(fn))(
        jax.tree.map(lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), params),
        jax.tree.map(lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), buffers),
        *specs,
    )
    dirname = os.path.dirname(path_prefix)
    if dirname:
        os.makedirs(dirname, exist_ok=True)
    with open(path_prefix + _MODEL_SUFFIX, "wb") as f:
        f.write(exported.serialize())
    with open(path_prefix + _PARAMS_SUFFIX, "wb") as f:
        pickle.dump(
            {
                "params": {k: np.asarray(v) for k, v in params.items()},
                "buffers": {k: np.asarray(v) for k, v in buffers.items()},
                "n_inputs": len(specs),
            },
            f,
        )
    return path_prefix


def load_inference_model(path_prefix: str, params_file: str = None):
    """Returns (exported_fn, params, buffers, n_inputs)."""
    with open(path_prefix + _MODEL_SUFFIX, "rb") as f:
        exported = jax_export.deserialize(f.read())
    with open(params_file or (path_prefix + _PARAMS_SUFFIX), "rb") as f:
        blob = pickle.load(f)
    return exported, blob["params"], blob["buffers"], blob["n_inputs"]


class DistConfig:
    """Distributed-serving config (reference: paddle_infer DistConfig
    feeding DistModel on fleet_executor,
    paddle/fluid/distributed/fleet_executor/dist_model.cc).

    TPU-native re-design: the reference shards one model across ranks and
    runs a carrier/interceptor runtime between them; here the sharded
    model is ONE SPMD executable over a device mesh — ranks/endpoints
    become mesh axes, the message bus becomes XLA collectives. Configure
    the mesh (e.g. set_mesh(dp=2, mp=4)); inputs shard over the batch
    axis ('dp'), parameters shard per `set_param_shard_fn(fn)` where
    fn(name, array) returns a PartitionSpec-compatible tuple (e.g.
    (None, 'mp') to column-split a weight) or None to replicate."""

    def __init__(self):
        self._enable = True
        self._mesh_axes = {}
        self._shard_fn = None
        self._batch_axis = "dp"
        # accepted for reference API parity (no multi-process bootstrap
        # is needed for single-controller SPMD serving)
        self._nranks, self._rank = 1, 0
        self._endpoints, self._current_endpoint = [], ""

    def enable_dist_model(self, flag=True):
        self._enable = bool(flag)

    def set_mesh(self, **axes):
        self._mesh_axes = {k: int(v) for k, v in axes.items() if int(v) > 1}

    def set_param_shard_fn(self, fn):
        self._shard_fn = fn

    def set_batch_axis(self, axis):
        self._batch_axis = axis

    def set_ranks(self, nranks, rank):
        self._nranks, self._rank = int(nranks), int(rank)

    def set_endpoints(self, endpoints, current_endpoint):
        self._endpoints = list(endpoints)
        self._current_endpoint = current_endpoint

    def set_comm_init_config(self, path):
        self._comm_init_config = path


class Config:
    """AnalysisConfig analog (subset: model paths + device + toggles that
    map to XLA; unknown toggles are accepted and recorded)."""

    def __init__(self, model_dir=None, prog_file=None, params_file=None):
        if model_dir and not prog_file:
            # directory layout: <dir>/inference.pdmodel etc.; an explicitly
            # passed params_file always wins over the convention
            for name in ("inference", "model", "__model__"):
                if os.path.exists(os.path.join(model_dir, name + _MODEL_SUFFIX)):
                    prog_file = os.path.join(model_dir, name + _MODEL_SUFFIX)
                    if params_file is None:
                        params_file = os.path.join(model_dir, name + _PARAMS_SUFFIX)
                    break
        self._prefix = None
        self._params_file = params_file
        if prog_file:
            self._prefix = prog_file[: -len(_MODEL_SUFFIX)] if prog_file.endswith(_MODEL_SUFFIX) else prog_file
        self._device = "tpu"
        self._memory_pool_init_size_mb = 0
        self._enable_log = True
        self._flags = {}
        self._dist = None

    def set_dist_config(self, dist_config: "DistConfig"):
        """Serve the model sharded over a device mesh (reference:
        Config.set_dist_config routing to DistModel)."""
        self._dist = dist_config

    def set_prog_file(self, path):
        self._prefix = path[: -len(_MODEL_SUFFIX)] if path.endswith(_MODEL_SUFFIX) else path

    def set_model(self, prog_or_dir, params_file=None):
        """Bind a model without clobbering other settings; an explicit
        params_file overrides the <prefix>.pdiparams convention."""
        if os.path.isdir(prog_or_dir):
            for name in ("inference", "model", "__model__"):
                cand = os.path.join(prog_or_dir, name + _MODEL_SUFFIX)
                if os.path.exists(cand):
                    self.set_prog_file(cand)
                    break
        else:
            self.set_prog_file(prog_or_dir)
        if params_file:
            self._params_file = params_file

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._device = "tpu"  # device selection is jax-level; accepted for parity

    def disable_gpu(self):
        self._device = "cpu"

    def enable_memory_optim(self):
        self._flags["memory_optim"] = True  # XLA does buffer reuse natively

    def switch_ir_optim(self, on=True):
        self._flags["ir_optim"] = on  # XLA fusion always on

    def disable_glog_info(self):
        self._enable_log = False

    def enable_tensorrt_engine(self, **kwargs):
        # TRT's role = AOT-compiled XLA executable; accepted for API parity
        self._flags["trt"] = kwargs

    def model_dir(self):
        return self._prefix


class _IOHandle:
    """Zero-copy-style tensor handle (reference: paddle_infer Tensor —
    copy_from_cpu / copy_to_cpu / shape)."""

    def __init__(self):
        self._value = None

    def copy_from_cpu(self, arr):
        self._value = jnp.asarray(np.ascontiguousarray(arr))

    def reshape(self, shape):
        pass  # shapes come from the bound array

    def copy_to_cpu(self):
        return np.asarray(self._value)

    def shape(self):
        return list(np.asarray(self._value).shape)


class Predictor:
    def __init__(self, config: Config):
        if config._prefix is None:
            raise ValueError("Config has no model path")
        self._exported, params, buffers, n_inputs = load_inference_model(
            config._prefix, config._params_file)
        self._params = jax.tree.map(jnp.asarray, params)
        self._buffers = jax.tree.map(jnp.asarray, buffers)
        # the serialized StableHLO is compiled for fixed input dtypes; a
        # weights file stored in reduced precision (convert_to_mixed_
        # precision artifacts) casts back to the module's expected avals
        # at load — halved storage, unchanged executable
        try:
            avals = list(self._exported.in_avals)
            p_flat, p_tree = jax.tree_util.tree_flatten(self._params)
            b_flat, b_tree = jax.tree_util.tree_flatten(self._buffers)
            n_state = len(p_flat) + len(b_flat)
            exp = avals[:n_state]
            cast = [a.astype(e.dtype) if a.dtype != e.dtype else a
                    for a, e in zip(p_flat + b_flat, exp)]
            self._params = jax.tree_util.tree_unflatten(
                p_tree, cast[:len(p_flat)])
            self._buffers = jax.tree_util.tree_unflatten(
                b_tree, cast[len(p_flat):])
        except Exception:  # ptpu-check[silent-except]: aval introspection is best-effort;
            # call() validates
            pass   # aval introspection is best-effort; call() validates
        self._n_inputs = n_inputs
        self._inputs = [_IOHandle() for _ in range(n_inputs)]
        self._outputs = []
        self._mesh = None
        self._batch_sharding = None
        self._call = None
        dist = getattr(config, "_dist", None)
        if dist is not None and dist._enable and dist._mesh_axes:
            self._init_dist(dist)

    def _init_dist(self, dist: DistConfig):
        """Shard the loaded weights over a mesh and compile the exported
        module as one SPMD program (the DistModel capability: a TP/DP-
        sharded model served with a host loop; dist_model.cc analog)."""
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        # a LOCAL mesh: serving must not clobber the process-global
        # training mesh (parallel.init_mesh), and axis names are free-form
        axes = dict(dist._mesh_axes)
        need = int(np.prod(list(axes.values()))) if axes else 1
        devs = jax.devices()
        if need > len(devs):
            raise ValueError(
                f"DistConfig mesh {axes} needs {need} devices, "
                f"{len(devs)} available")
        mesh = Mesh(
            np.array(devs[:need]).reshape(tuple(axes.values())),
            tuple(axes.keys()))
        self._mesh = mesh
        shard_fn = dist._shard_fn

        def place(tree):
            out = {}
            for name, arr in tree.items():
                spec = shard_fn(name, arr) if shard_fn is not None else None
                spec = P(*spec) if spec is not None else P()
                out[name] = jax.device_put(arr, NamedSharding(mesh, spec))
            return out

        self._params = place(self._params)
        self._buffers = place(self._buffers)
        if dist._batch_axis in mesh.axis_names:
            self._batch_sharding = NamedSharding(
                mesh, P(dist._batch_axis))
        exported = self._exported
        # jit around the exported module: XLA propagates the param/input
        # shardings through the inlined StableHLO and inserts collectives
        self._call = jax.jit(
            lambda p, b, *xs: exported.call(p, b, *xs))

    def get_input_names(self):
        return [f"input_{i}" for i in range(self._n_inputs)]

    def get_input_handle(self, name):
        return self._inputs[int(name.rsplit("_", 1)[1]) if isinstance(name, str) else name]

    def run(self, inputs=None):
        """Either bind handles then run(), or pass arrays directly —
        returns list of numpy outputs either way."""
        if inputs is not None:
            for h, a in zip(self._inputs, inputs):
                h.copy_from_cpu(np.asarray(a._data) if isinstance(a, Tensor) else a)
        args = [h._value for h in self._inputs]
        if self._call is not None:   # distributed (mesh-sharded) serving
            if self._batch_sharding is not None:
                n = self._batch_sharding.mesh.shape[
                    self._batch_sharding.spec[0]]
                placed = []
                for a in args:
                    if a.ndim >= 1 and a.shape[0] % n == 0:
                        placed.append(jax.device_put(a, self._batch_sharding))
                    else:
                        placed.append(a)   # indivisible batch: replicate
                args = placed
            out = self._call(self._params, self._buffers, *args)
        else:
            out = self._exported.call(self._params, self._buffers, *args)
        outs = list(out) if isinstance(out, (tuple, list)) else [out]
        self._outputs = outs
        return [np.asarray(o) for o in outs]

    def get_output_names(self):
        return [f"output_{i}" for i in range(len(self._outputs) or 1)]

    def get_output_handle(self, name):
        h = _IOHandle()
        idx = int(name.rsplit("_", 1)[1]) if isinstance(name, str) else name
        h._value = self._outputs[idx]
        return h


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


class DataType:
    """Predictor IO dtypes (reference paddle_infer_declare.h PD_DataType)."""

    FLOAT32 = 0
    INT64 = 1
    INT32 = 2
    UINT8 = 3
    INT8 = 4
    FLOAT16 = 5
    BFLOAT16 = 6


class PlaceType:
    """Predictor placement (reference PD_PlaceType). On this backend every
    accelerator place routes to the active XLA device."""

    UNK = -1
    CPU = 0
    GPU = 1
    XPU = 2
    NPU = 3
    CUSTOM = 4


class PrecisionType:
    """Analysis-config precision (reference AnalysisConfig::Precision)."""

    Float32 = 0
    Half = 1
    Int8 = 2
    Bfloat16 = 3


def get_version():
    from .. import __version__

    return f"version: {__version__}"


def get_num_bytes_of_data_type(dtype):
    sizes = {DataType.FLOAT32: 4, DataType.INT64: 8, DataType.INT32: 4,
             DataType.UINT8: 1, DataType.INT8: 1, DataType.FLOAT16: 2,
             DataType.BFLOAT16: 2}
    return sizes[dtype]


def get_trt_compile_version():
    """No TensorRT on this stack — XLA is the compiled-inference engine
    (SURVEY §2.5.15); reference returns (0, 0, 0) when built without TRT."""
    return (0, 0, 0)


def get_trt_runtime_version():
    return (0, 0, 0)


def _get_phi_kernel_name(op_name):
    """Op -> kernel-name mapping hook (reference pybind helper). Kernel
    naming is 1:1 here (no fluid-op alias table to consult)."""
    return op_name


class PredictorPool:
    """Thread-serving predictor pool (reference PredictorPool): ONE model
    load; the size-1 clones share the main predictor's weight arrays
    (jax arrays are immutable, so sharing is safe)."""

    def __init__(self, config, size=1):
        main = Predictor(config)
        self._predictors = [main]
        for _ in range(max(1, size) - 1):
            clone = object.__new__(Predictor)
            clone.__dict__.update(main.__dict__)   # shares _params/_buffers
            # ...but NOT the IO handles: each pool slot serves its own
            # thread with independent input/output bindings
            clone._inputs = [_IOHandle() for _ in range(main._n_inputs)]
            clone._outputs = []
            self._predictors.append(clone)

    def retrieve(self, idx):
        return self._predictors[idx]


def convert_to_mixed_precision(model_file, params_file, mixed_model_file,
                               mixed_params_file, mixed_precision=None,
                               backend=None, keep_io_types=True,
                               black_list=None, **kwargs):
    """Cast a saved inference model's weights to bf16/fp16 (reference
    convert_to_mixed_precision pass). Loads the exported artifact's
    params, casts floating weights, re-saves alongside the model file."""
    import pickle
    import shutil

    import numpy as np

    prec = mixed_precision if mixed_precision is not None else PrecisionType.Half
    target = {PrecisionType.Half: np.float16,
              PrecisionType.Bfloat16: "bfloat16",
              PrecisionType.Float32: np.float32}[prec]
    with open(params_file, "rb") as f:
        blob = pickle.load(f)

    import ml_dtypes

    tgt = ml_dtypes.bfloat16 if target == "bfloat16" else target

    def cast_tree(v):
        # recurse: save_inference_model writes {"params": {...},
        # "buffers": {...}, "n_inputs": int}; flat dicts also accepted
        if isinstance(v, dict):
            return {k: cast_tree(x) for k, x in v.items()}
        a = np.asarray(v)
        return a.astype(tgt) if a.dtype.kind == "f" else v

    with open(mixed_params_file, "wb") as f:
        pickle.dump(cast_tree(blob), f)
    if model_file != mixed_model_file:
        shutil.copy(model_file, mixed_model_file)


__all__ += ["DataType", "PlaceType", "PrecisionType", "PredictorPool",
            "get_version", "get_num_bytes_of_data_type",
            "get_trt_compile_version", "get_trt_runtime_version",
            "_get_phi_kernel_name", "convert_to_mixed_precision"]


# -- generative serving entry point (paddle_tpu.serving) --------------------
# The continuous-batching engine is the generative-model counterpart of the
# Predictor above.  Re-exported lazily (PEP 562): `import paddle_tpu` pulls
# this module at package init, and the engine's model-side imports must not
# tax every non-serving process.
_SERVING_EXPORTS = ("LLMEngine", "EngineConfig", "SamplingParams",
                    "BlockKVCache", "Scheduler", "Request")
__all__ += list(_SERVING_EXPORTS)


def __getattr__(name):
    if name in _SERVING_EXPORTS:
        from .. import serving

        return getattr(serving, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
