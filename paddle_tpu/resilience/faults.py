"""Deterministic fault injection (`PTPU_FAULTS`) so recovery paths are
*testable*, not just written.  The reference framework proves its NaN
trap with FLAGS_check_nan_inf unit fixtures; here every resilience layer
(atomic checkpoints, NaN rollback, retry) gets a switchable failure.

Syntax — semicolon-separated fault specs, each ``kind@key=value,...``::

    PTPU_FAULTS="ckpt_crash@step=4;conn_error@site=store.connect,times=2"
    PTPU_FAULTS="nan_grad@step=5"
    PTPU_FAULTS="ckpt_crash@step=4,hard=1"     # SIGKILL mid-save (kill -9)

Keys:

- ``step``  — fire only when the call site reports this step number.
- ``site``  — fire only at this named injection site (e.g. ``store.get``).
- ``times`` — how many firings before the fault burns out (default 1;
  ``times=0`` means unlimited).
- ``hard``  — for ``ckpt_crash``: 1 = kill the process with SIGKILL
  (uncatchable, the true "power loss mid-write"), 0 = raise
  :class:`InjectedCrash` (catchable, for in-process tests).
- ``secs``  — for ``stall``: how long the injected hang sleeps
  (default 2.0).

Kinds wired into the framework:

- ``ckpt_crash`` — consulted by `CheckpointManager.save` and
  `distributed.checkpoint.save_state_dict` AFTER array data is written
  but BEFORE the atomic rename, i.e. the worst moment.
- ``conn_error`` — consulted by TCPStore connect/get and rpc dial; fires
  as a transient ``ConnectionError``.
- ``nan_grad``   — consulted by `StepGuard` right after the wrapped step:
  the updated params are poisoned with NaN, simulating an optimizer
  update driven by non-finite gradients.
- ``stall``      — consulted by `LLMEngine.step` (site
  ``engine.step``): the step blocks for ``secs`` without completing any
  span, the deterministic "distributed hang" that
  `monitor.watchdog` must catch (tests/test_trace.py).

Everything is inert (one None check) when ``PTPU_FAULTS`` is unset.
"""
from __future__ import annotations

import os
import signal
import threading
import time
from typing import Optional

from .. import monitor

__all__ = ["FaultPlan", "InjectedCrash", "InjectedFault", "get_plan",
           "set_plan", "should_fire", "maybe_raise", "maybe_crash",
           "maybe_stall"]


class InjectedFault(Exception):
    """Base for injected failures (never raised by real code paths)."""


class InjectedCrash(InjectedFault):
    """A simulated process death during a checkpoint write."""


class _Fault:
    __slots__ = ("kind", "step", "site", "times", "hard", "secs", "fired")

    def __init__(self, kind, step=None, site=None, times=1, hard=0,
                 secs=2.0):
        self.kind = kind
        self.step = step
        self.site = site
        self.times = times      # 0 = unlimited
        self.hard = hard
        self.secs = secs
        self.fired = 0

    def matches(self, kind, site, step):
        if kind != self.kind:
            return False
        if self.times and self.fired >= self.times:
            return False
        if self.site is not None and site != self.site:
            return False
        if self.step is not None and (step is None or int(step) != self.step):
            return False
        return True

    def __repr__(self):
        return (f"_Fault({self.kind}, step={self.step}, site={self.site}, "
                f"times={self.times}, hard={self.hard}, fired={self.fired})")


class FaultPlan:
    """A parsed PTPU_FAULTS spec with per-fault firing budgets."""

    def __init__(self, spec: str = ""):
        self.spec = spec or ""
        self._lock = threading.Lock()
        self._faults = []
        for part in self.spec.split(";"):
            part = part.strip()
            if not part:
                continue
            kind, _, opts = part.partition("@")
            kw = {}
            for item in filter(None, (o.strip() for o in opts.split(","))):
                k, _, v = item.partition("=")
                if k in ("step", "times", "hard"):
                    kw[k] = int(v)
                elif k == "secs":
                    kw[k] = float(v)
                elif k == "site":
                    kw[k] = v
                else:
                    raise ValueError(
                        f"PTPU_FAULTS: unknown key {k!r} in {part!r} "
                        "(known: step, site, times, hard, secs)")
            self._faults.append(_Fault(kind.strip(), **kw))
        self._ctr = monitor.counter("resilience/faults_injected",
                                    "deterministic injected failures")

    @classmethod
    def from_env(cls) -> "FaultPlan":
        return cls(os.environ.get("PTPU_FAULTS", ""))

    def __bool__(self):
        return bool(self._faults)

    def should_fire(self, kind: str, site: str = None, step=None) -> bool:
        """True (and consumes one firing) when a fault matches."""
        with self._lock:
            for f in self._faults:
                if f.matches(kind, site, step):
                    f.fired += 1
                    self._ctr.labels(kind=kind).inc()
                    return True
        return False

    def _find(self, kind, site=None, step=None) -> Optional[_Fault]:
        with self._lock:
            for f in self._faults:
                if f.matches(kind, site, step):
                    return f
        return None

    def maybe_raise(self, kind: str, site: str = None, step=None,
                    exc=ConnectionError, msg: str = None) -> None:
        """Raise `exc` when a matching fault fires (transient failures)."""
        if self.should_fire(kind, site=site, step=step):
            raise exc(msg or f"injected {kind} at {site or step}")

    def maybe_crash(self, site: str = "checkpoint", step=None) -> None:
        """ckpt_crash: die mid-write.  hard=1 SIGKILLs the process (the
        kill -9 test); soft raises InjectedCrash.  A spec with ``site=``
        matches only the named injection site (``CheckpointManager.save``
        or ``save_state_dict``); without it, any site fires."""
        f = self._find("ckpt_crash", site=site, step=step)
        if f is None:
            return
        with self._lock:
            f.fired += 1
        self._ctr.labels(kind="ckpt_crash").inc()
        if f.hard:
            os.kill(os.getpid(), signal.SIGKILL)
        raise InjectedCrash(f"injected checkpoint crash in {site} "
                            f"(step={step})")

    def maybe_stall(self, site: str = None, step=None) -> None:
        """stall: block for the fault's ``secs`` without completing any
        span/step — the deterministic distributed-hang the
        `monitor.watchdog` post-mortem path is proven against."""
        f = self._find("stall", site=site, step=step)
        if f is None:
            return
        with self._lock:
            f.fired += 1
        self._ctr.labels(kind="stall").inc()
        time.sleep(f.secs)


# -- process-wide plan ------------------------------------------------------
_plan: Optional[FaultPlan] = None
_plan_lock = threading.Lock()


def get_plan() -> Optional[FaultPlan]:
    """The active plan, or None when PTPU_FAULTS is unset/empty (the
    common case: one global read, no parsing)."""
    global _plan
    if _plan is None and os.environ.get("PTPU_FAULTS"):
        with _plan_lock:
            if _plan is None:
                _plan = FaultPlan.from_env()
    return _plan


def set_plan(plan: Optional[FaultPlan]) -> None:
    """Install a plan programmatically (tests); None clears."""
    global _plan
    _plan = plan


# -- call-site helpers (inert one-liner when no plan) ----------------------
def should_fire(kind, site=None, step=None) -> bool:
    p = get_plan()
    return False if p is None else p.should_fire(kind, site=site, step=step)


def maybe_raise(kind, site=None, step=None, exc=ConnectionError, msg=None):
    p = get_plan()
    if p is not None:
        p.maybe_raise(kind, site=site, step=step, exc=exc, msg=msg)


def maybe_crash(site="checkpoint", step=None):
    p = get_plan()
    if p is not None:
        p.maybe_crash(site=site, step=step)


def maybe_stall(site=None, step=None):
    p = get_plan()
    if p is not None:
        p.maybe_stall(site=site, step=step)
