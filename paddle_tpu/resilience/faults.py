"""Deterministic fault injection (`PTPU_FAULTS`) so recovery paths are
*testable*, not just written.  The reference framework proves its NaN
trap with FLAGS_check_nan_inf unit fixtures; here every resilience layer
(atomic checkpoints, NaN rollback, retry, the rpc transport) gets a
switchable failure.

Syntax — semicolon-separated fault specs, each ``kind@key=value,...``::

    PTPU_FAULTS="ckpt_crash@step=4;conn_error@site=store.connect,times=2"
    PTPU_FAULTS="nan_grad@step=5"
    PTPU_FAULTS="ckpt_crash@step=4,hard=1"     # SIGKILL mid-save (kill -9)
    PTPU_FAULTS="net_drop@site=rpc.dial,peer=r0,times=0"
    PTPU_FAULTS="net_delay@site=rpc.send,secs=0.2,p=0.5,seed=7"

Keys (validated PER KIND at parse time — an unknown key, or a key that
is not valid for its kind, raises ``ValueError`` instead of passing
silently as a dead knob):

- ``step``  — fire only when the call site reports this step number.
- ``site``  — fire only at this named injection site (e.g. ``store.get``).
- ``times`` — how many firings before the fault burns out.  Default 1;
  ``times=0`` is pinned as "never burns out — fire on EVERY match"
  (tests/test_chaos.py), the spelling every long-lived partition uses.
- ``hard``  — for ``ckpt_crash``: 1 = kill the process with SIGKILL
  (uncatchable, the true "power loss mid-write"), 0 = raise
  :class:`InjectedCrash` (catchable, for in-process tests).
- ``secs``  — for ``stall``: how long the injected hang sleeps
  (default 2.0).  For ``net_delay``: how long the byte trickle takes;
  for ``net_partition``: how long the blackhole blocks before the
  caller's injected timeout (default 0.05 — tests should not pay real
  partition walls).
- ``peer``  — ``net_*`` only: fire only when the transport names this
  remote worker.  Caller-side rpc passes the dial target, so
  ``net_partition@peer=r2`` is a ONE-directional blackhole: calls *to*
  r2 die, calls *from* r2 are untouched.
- ``p``     — ``net_*`` only: fire probabilistically with this chance,
  drawn from the fault's own seeded RNG.  A draw is consumed on every
  structural match (fired or not), so the same spec + seed + call
  sequence replays the identical fire/no-fire pattern bit-for-bit.
- ``seed``  — ``net_*`` only: RNG seed for ``p=`` rolls (default
  ``PTPU_CHAOS_SEED`` env, else 0).  Each fault's stream is derived
  arithmetically from (seed, spec position) — never ``hash()`` — so
  replays are independent of PYTHONHASHSEED.

Kinds wired into the framework:

- ``ckpt_crash`` — consulted by `CheckpointManager.save` and
  `distributed.checkpoint.save_state_dict` AFTER array data is written
  but BEFORE the atomic rename, i.e. the worst moment.
- ``conn_error`` — consulted by TCPStore connect/get and rpc dial; fires
  as a transient ``ConnectionError``.
- ``nan_grad``   — consulted by `StepGuard` right after the wrapped step:
  the updated params are poisoned with NaN, simulating an optimizer
  update driven by non-finite gradients.
- ``stall``      — consulted by `LLMEngine.step` (site
  ``engine.step``): the step blocks for ``secs`` without completing any
  span, the deterministic "distributed hang" that
  `monitor.watchdog` must catch (tests/test_trace.py).
- ``net_drop`` / ``net_delay`` / ``net_partition`` / ``net_garble`` —
  the network-fault family, consulted by `distributed.rpc` at its three
  choke points (sites ``rpc.dial`` / ``rpc.send`` / ``rpc.recv``) via
  :meth:`FaultPlan.net_fire`.  drop = connection refused/reset, delay =
  slow byte trickle, partition = one-directional blackhole (the caller
  sees only a timeout), garble = truncated/corrupted frame.  What each
  kind *does* lives in rpc.py; this module only decides *whether* it
  fires, deterministically.

Every fire increments ``resilience/faults_injected{kind}`` and drops a
``fault/injected`` breadcrumb on the flight ring, so a chaos run's fire
sequence is auditable post-mortem.  Everything is inert (one global
read) when ``PTPU_FAULTS`` is unset.
"""
from __future__ import annotations

import os
import random
import signal
import threading
import time
from typing import Optional

from .. import monitor
from ..monitor import flight as _flight

__all__ = ["FaultPlan", "InjectedCrash", "InjectedFault", "NET_KINDS",
           "get_plan", "set_plan", "should_fire", "maybe_raise",
           "maybe_crash", "maybe_stall", "net_fire"]

NET_KINDS = ("net_drop", "net_delay", "net_partition", "net_garble")

# per-kind key vocabulary — parse-time contract, not a runtime filter
_COMMON_KEYS = ("step", "site", "times")
_NET_KEYS = _COMMON_KEYS + ("peer", "p", "seed")
_KIND_KEYS = {
    "ckpt_crash": _COMMON_KEYS + ("hard",),
    "conn_error": _COMMON_KEYS,
    "nan_grad": _COMMON_KEYS,
    "stall": _COMMON_KEYS + ("secs",),
    "net_drop": _NET_KEYS,
    "net_delay": _NET_KEYS + ("secs",),
    "net_partition": _NET_KEYS + ("secs",),
    "net_garble": _NET_KEYS,
}


class InjectedFault(Exception):
    """Base for injected failures (never raised by real code paths)."""


class InjectedCrash(InjectedFault):
    """A simulated process death during a checkpoint write."""


class _Fault:
    __slots__ = ("kind", "step", "site", "peer", "times", "hard", "secs",
                 "p", "fired", "_rng")

    def __init__(self, kind, index=0, step=None, site=None, peer=None,
                 times=1, hard=0, secs=None, p=None, seed=None):
        self.kind = kind
        self.step = step
        self.site = site
        self.peer = peer
        self.times = times      # 0 = unlimited: fire on every match
        self.hard = hard
        self.secs = (0.05 if kind == "net_partition" else 2.0) \
            if secs is None else secs
        self.p = p
        self.fired = 0
        if seed is None:
            seed = int(os.environ.get("PTPU_CHAOS_SEED", "0") or 0)
        # arithmetic stream derivation (seed, spec position) — hash() of
        # a tuple would make replays PYTHONHASHSEED-dependent
        self._rng = random.Random(seed * 1000003 + index)

    def matches(self, kind, site, step, peer=None):
        if kind != self.kind:
            return False
        if self.times and self.fired >= self.times:
            return False
        if self.site is not None and site != self.site:
            return False
        if self.peer is not None and peer != self.peer:
            return False
        if self.step is not None and (step is None or int(step) != self.step):
            return False
        return True

    def roll(self) -> bool:
        """One p= draw; always True when p is unset.  Call exactly once
        per structural match so the stream position tracks the match
        sequence, making fire/no-fire replay bit-identical."""
        if self.p is None:
            return True
        return self._rng.random() < self.p

    def __repr__(self):
        return (f"_Fault({self.kind}, step={self.step}, site={self.site}, "
                f"peer={self.peer}, times={self.times}, hard={self.hard}, "
                f"p={self.p}, fired={self.fired})")


class FaultPlan:
    """A parsed PTPU_FAULTS spec with per-fault firing budgets."""

    def __init__(self, spec: str = ""):
        self.spec = spec or ""
        self._lock = threading.Lock()
        self._faults = []
        for part in self.spec.split(";"):
            part = part.strip()
            if not part:
                continue
            kind, _, opts = part.partition("@")
            kind = kind.strip()
            valid = _KIND_KEYS.get(kind)
            if valid is None:
                raise ValueError(
                    f"PTPU_FAULTS: unknown fault kind {kind!r} in {part!r} "
                    f"(known: {', '.join(sorted(_KIND_KEYS))})")
            kw = {}
            for item in filter(None, (o.strip() for o in opts.split(","))):
                k, _, v = item.partition("=")
                if k not in valid:
                    raise ValueError(
                        f"PTPU_FAULTS: unknown key {k!r} for kind "
                        f"{kind!r} in {part!r} "
                        f"(valid: {', '.join(valid)})")
                if k in ("step", "times", "hard"):
                    kw[k] = int(v)
                elif k == "seed":
                    kw[k] = int(v)
                elif k in ("secs", "p"):
                    kw[k] = float(v)
                else:            # site / peer
                    kw[k] = v
            self._faults.append(
                _Fault(kind, index=len(self._faults), **kw))
        self._ctr = monitor.counter("resilience/faults_injected",
                                    "deterministic injected failures")

    @classmethod
    def from_env(cls) -> "FaultPlan":
        return cls(os.environ.get("PTPU_FAULTS", ""))

    def __bool__(self):
        return bool(self._faults)

    def _record(self, f: _Fault, site, peer, step) -> None:
        # caller holds self._lock; counter + flight ring are themselves
        # thread-safe and never call back into faults
        f.fired += 1
        self._ctr.labels(kind=f.kind).inc()
        _flight.note("fault/injected", fault=f.kind, site=site,
                     peer=peer, step=step, fired=f.fired)

    def should_fire(self, kind: str, site: str = None, step=None,
                    peer=None) -> bool:
        """True (and consumes one firing) when a fault matches."""
        with self._lock:
            for f in self._faults:
                if f.matches(kind, site, step, peer):
                    if not f.roll():
                        continue    # draw consumed, fault held its fire
                    self._record(f, site, peer, step)
                    return True
        return False

    def net_fire(self, site: str = None, peer=None, step=None,
                 kinds=NET_KINDS) -> Optional[_Fault]:
        """First ``net_*`` fault that fires at this transport point, or
        None.  Specs are consulted in plan order (the spec author sets
        precedence); the returned fault carries ``kind`` and ``secs``
        for the transport to act on.  ``kinds`` restricts the scan to
        the kinds meaningful at this choke point (a garble spec can't
        fire at dial — there is no payload to corrupt — and must not
        burn budget there)."""
        with self._lock:
            for f in self._faults:
                if f.kind not in kinds:
                    continue
                if not f.matches(f.kind, site, step, peer):
                    continue
                if not f.roll():
                    continue
                self._record(f, site, peer, step)
                return f
        return None

    def _find(self, kind, site=None, step=None) -> Optional[_Fault]:
        with self._lock:
            for f in self._faults:
                if f.matches(kind, site, step):
                    return f
        return None

    def maybe_raise(self, kind: str, site: str = None, step=None,
                    exc=ConnectionError, msg: str = None) -> None:
        """Raise `exc` when a matching fault fires (transient failures)."""
        if self.should_fire(kind, site=site, step=step):
            raise exc(msg or f"injected {kind} at {site or step}")

    def maybe_crash(self, site: str = "checkpoint", step=None) -> None:
        """ckpt_crash: die mid-write.  hard=1 SIGKILLs the process (the
        kill -9 test); soft raises InjectedCrash.  A spec with ``site=``
        matches only the named injection site (``CheckpointManager.save``
        or ``save_state_dict``); without it, any site fires."""
        f = self._find("ckpt_crash", site=site, step=step)
        if f is None:
            return
        with self._lock:
            self._record(f, site, None, step)
        if f.hard:
            os.kill(os.getpid(), signal.SIGKILL)
        raise InjectedCrash(f"injected checkpoint crash in {site} "
                            f"(step={step})")

    def maybe_stall(self, site: str = None, step=None) -> None:
        """stall: block for the fault's ``secs`` without completing any
        span/step — the deterministic distributed-hang the
        `monitor.watchdog` post-mortem path is proven against."""
        f = self._find("stall", site=site, step=step)
        if f is None:
            return
        with self._lock:
            self._record(f, site, None, step)
        time.sleep(f.secs)


# -- process-wide plan ------------------------------------------------------
# The disabled hot path (every rpc send/recv, every engine step) must be
# ONE global read: `_plan` holds the sentinel until the first get_plan()
# resolves it from the env — to None when PTPU_FAULTS is unset — and
# from then on the fast path never touches environ or the lock.
# set_plan(None) restores the sentinel so tests that clear the plan and
# then set PTPU_FAULTS see the new env (the pre-existing contract).
_UNRESOLVED = object()
_plan = _UNRESOLVED
_plan_lock = threading.Lock()


def get_plan() -> Optional[FaultPlan]:
    """The active plan, or None when PTPU_FAULTS is unset/empty (the
    common case: one global read, no parsing)."""
    p = _plan
    if p is _UNRESOLVED:
        p = _resolve()
    return p


def _resolve() -> Optional[FaultPlan]:
    global _plan
    with _plan_lock:
        if _plan is _UNRESOLVED:
            spec = os.environ.get("PTPU_FAULTS", "")
            _plan = FaultPlan(spec) if spec else None
        return _plan


def set_plan(plan: Optional[FaultPlan]) -> None:
    """Install a plan programmatically (tests); None clears (and re-arms
    env resolution on the next get_plan)."""
    global _plan
    with _plan_lock:
        _plan = _UNRESOLVED if plan is None else plan


# -- call-site helpers (inert one-liner when no plan) ----------------------
def should_fire(kind, site=None, step=None, peer=None) -> bool:
    p = get_plan()
    return False if p is None else p.should_fire(kind, site=site, step=step,
                                                 peer=peer)


def maybe_raise(kind, site=None, step=None, exc=ConnectionError, msg=None):
    p = get_plan()
    if p is not None:
        p.maybe_raise(kind, site=site, step=step, exc=exc, msg=msg)


def maybe_crash(site="checkpoint", step=None):
    p = get_plan()
    if p is not None:
        p.maybe_crash(site=site, step=step)


def maybe_stall(site=None, step=None):
    p = get_plan()
    if p is not None:
        p.maybe_stall(site=site, step=step)


def net_fire(site=None, peer=None, step=None, kinds=NET_KINDS
             ) -> Optional[_Fault]:
    """Module-level transport hook: one global read when chaos is off."""
    p = _plan
    if p is _UNRESOLVED:
        p = _resolve()
    return None if p is None else p.net_fire(site=site, peer=peer, step=step,
                                             kinds=kinds)
