"""`StepGuard` — NaN/Inf-guarded training steps with rollback (reference:
FLAGS_check_nan_inf at operator.cc:1608 *detects*; this layer *recovers*).

The guard wraps an arbitrary train step — eager or `jit.compile`d — that
updates `model`/`optimizer` in place and returns the loss.  Per step:

1. snapshot params / optimizer slots / master weights / step counter.
   The snapshot COPIES every array (one device-side copy of model+opt
   state per step): the optimizer's jitted update donates its input
   buffers, so a reference-only snapshot would hold deleted buffers the
   moment the step runs.  On-device copy rides HBM bandwidth — cheap
   next to the step itself — and is the entire price of rollback;
2. run the step;
3. health check: one fused device-side reduction
   ``isfinite(loss) & all(isfinite(param) for params)`` — a single
   boolean crosses to the host, there is no per-array sync.  Checking
   the *post-update params* (not just the loss) is what catches a
   NaN-gradient update whose loss was still finite;
4. on a bad step: restore the pre-step snapshot INCLUDING any attached
   `amp.GradScaler`'s scale/counters (the update is skipped), optionally
   re-run the same step (`max_retries_per_step` — a transient fault
   retried from truly identical pre-state, scaler included, reproduces
   the unfaulted trajectory bit-for-bit), back off the scaler only once
   the step is finally given up on, and after `rollback_after`
   CONSECUTIVE bad steps restore the last *good snapshot* (taken every
   `snapshot_every` good steps), covering slow corruption the per-step
   skip can't.

Monitor: ``resilience/skipped_steps``, ``resilience/rollbacks``,
``resilience/bad_step_streak`` (gauge), ``train/step_time`` (gauge, the
per-rank straggler signal), plus the v6 divergence forensics below.

Divergence forensics (ISSUE 13): a bad step no longer just *counts* —
before the restore wipes the evidence, the grad/param pytree is scanned
in one batched device reduction (``resilience.forensics``) and the
offending layer paths are named in ``resilience/nonfinite{layer,which}``
counters, a flight-ring breadcrumb, and — when ``PTPU_FLIGHT_DIR`` is
set — a ``bad_step`` flight dump carrying per-layer non-finite counts
and abs-max stats.  On healthy steps an EWMA loss-spike detector
(``monitor.train.LossSpikeDetector``) drops pre-divergence warnings
into the flight ring *before* the NaN lands, so the post-mortem shows
the climb, not just the crater.

Scope: rollback restores params, optimizer slots, master weights, the
optimizer step counter, and GradScaler scale/counters.  Host-side state
the step mutates itself (dataloader position, python RNG) is the
caller's to manage — with `max_retries_per_step > 0` the retried step
re-runs with the SAME arguments, so feed the batch in as arguments
rather than pulling it inside the step.
"""
from __future__ import annotations

import time
from typing import Optional

import numpy as np
import jax.numpy as jnp

from .. import monitor
from ..monitor import flight as mflight
from ..monitor import trace as mtrace
from ..monitor import train as mtrain
from . import faults, forensics

__all__ = ["StepGuard", "GuardedStepInfo"]


class GuardedStepInfo:
    """What happened to one guarded step.  `loss` holds the extracted
    loss ARRAY (first element of a tuple-returning step, unwrapped from
    Tensor), not the step's raw return value."""

    __slots__ = ("ok", "loss", "retries", "skipped", "rolled_back")

    def __init__(self, ok, loss, retries=0, skipped=False, rolled_back=False):
        self.ok = ok
        self.loss = loss
        self.retries = retries
        self.skipped = skipped
        self.rolled_back = rolled_back

    def __repr__(self):
        return (f"GuardedStepInfo(ok={self.ok}, retries={self.retries}, "
                f"skipped={self.skipped}, rolled_back={self.rolled_back})")


def _loss_array(result):
    """Extract the loss array from a step's return value (Tensor, array,
    or a tuple whose first element is the loss)."""
    if isinstance(result, (tuple, list)) and result:
        result = result[0]
    return getattr(result, "_data", result)


class StepGuard:
    def __init__(self, model=None, optimizer=None, scaler=None, *,
                 params=None, rollback_after: int = 3,
                 snapshot_every: int = 1, max_retries_per_step: int = 0,
                 check_params: bool = True):
        if params is not None:
            self._params = list(params)
            self._names = [getattr(p, "name", None) or f"param_{i}"
                           for i, p in enumerate(self._params)]
        elif model is not None:
            # named_parameters gives the layer PATHS ("0.weight", ...) —
            # what the forensics dump names; parameters() is derived from
            # the same walk, so order matches
            named = list(model.named_parameters())
            self._params = [p for _, p in named]
            self._names = [n for n, _ in named]
        elif optimizer is not None:
            self._params = list(optimizer._parameter_list)
            self._names = [getattr(p, "name", None) or f"param_{i}"
                           for i, p in enumerate(self._params)]
        else:
            raise ValueError("StepGuard needs a model, optimizer, or "
                             "an explicit params list")
        self._opt = optimizer
        self._scaler = scaler
        self.rollback_after = int(rollback_after)
        self.snapshot_every = max(1, int(snapshot_every))
        self.max_retries_per_step = int(max_retries_per_step)
        self.check_params = bool(check_params)
        self._step_index = 0
        self._bad_streak = 0
        self._good_steps = 0
        self._good_snap = None
        self._m_skipped = monitor.counter("resilience/skipped_steps",
                                          "non-finite steps skipped")
        self._m_rollbacks = monitor.counter(
            "resilience/rollbacks",
            "rollbacks to the last good snapshot")
        self._m_streak = monitor.gauge("resilience/bad_step_streak")
        self._m_nonfinite = monitor.counter(
            "resilience/nonfinite",
            "layers found non-finite by bad-step forensics")
        self._m_forensics_err = monitor.counter(
            "resilience/forensics_errors",
            "bad-step forensic scans that failed")
        self._m_step_time = monitor.gauge(
            "train/step_time",
            "train step seconds — the per-rank straggler signal")
        self._spike = mtrain.LossSpikeDetector()

    # -- snapshot / restore -------------------------------------------------

    @staticmethod
    def _copy(a):
        """A buffer the optimizer's donating update can't invalidate."""
        return jnp.array(a, copy=True)

    def _capture(self):
        snap = {
            "params": [self._copy(p._data) for p in self._params],
        }
        if self._opt is not None:
            snap["states"] = {k: {s: self._copy(a) for s, a in v.items()}
                              for k, v in self._opt._states.items()}
            snap["masters"] = {k: self._copy(a) for k, a in
                               self._opt._master_weights.items()}
            snap["step_count"] = self._opt._step_count
        if self._scaler is not None:
            snap["scaler"] = self._scaler.state_dict()
        return snap

    def _restore(self, snap, restore_scaler=False):
        # copies on the way OUT as well: the next step will donate what we
        # install here, and the same snapshot (the good snapshot) may be
        # restored again later
        for p, data in zip(self._params, snap["params"]):
            p._data = self._copy(data)
        if self._opt is not None:
            self._opt._states = {k: {s: self._copy(a) for s, a in v.items()}
                                 for k, v in snap["states"].items()}
            self._opt._master_weights = {k: self._copy(a) for k, a in
                                         snap["masters"].items()}
            self._opt._step_count = snap["step_count"]
        if restore_scaler and self._scaler is not None \
                and "scaler" in snap:
            self._scaler.load_state_dict(snap["scaler"])

    # -- health -------------------------------------------------------------

    def _healthy(self, loss_arr) -> bool:
        """One device-side AND-reduction over loss (and params); a single
        bool() sync at the end."""
        ok = jnp.all(jnp.isfinite(jnp.asarray(loss_arr, jnp.float32)))
        if self.check_params:
            for p in self._params:
                d = p._data
                if jnp.issubdtype(d.dtype, jnp.floating):
                    ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(d)))
        return bool(ok)

    # -- divergence forensics (ISSUE 13 wing a) -----------------------------

    def _observe_loss(self, result, step):
        """Feed the healthy step's loss to the EWMA spike detector."""
        try:
            val = float(np.mean(np.asarray(_loss_array(result))))
        except (TypeError, ValueError):
            return
        self._spike.observe(val, step=step)

    def _forensics(self, step, result):
        """Name the offending layers of a bad step: one batched device
        scan of the grad/param pytree → counters, a flight breadcrumb,
        and (when PTPU_FLIGHT_DIR is set) a ``bad_step`` dump with
        per-layer non-finite counts and abs-max stats."""
        try:
            params = [(n, p._data)
                      for n, p in zip(self._names, self._params)]
            grads = [(n, p.grad._data)
                     for n, p in zip(self._names, self._params)
                     if getattr(p, "grad", None) is not None]
            report = forensics.nonfinite_report(
                params=params, grads=grads, loss=_loss_array(result))
        except Exception:   # ptpu-check[silent-except]: forensics must never turn a
            # recoverable bad step into a crash — failures are counted
            self._m_forensics_err.inc()
            return
        report["step"] = step
        for b in report["bad"]:
            self._m_nonfinite.labels(layer=b["layer"],
                                     which=b["which"]).inc()
        mflight.note("resilience/nonfinite", step=step,
                     first_bad=report["first_bad"],
                     layers=[b["layer"] for b in report["bad"]][:16])
        mflight.maybe_dump("bad_step", extra={"forensics": report})

    # -- the guarded step ---------------------------------------------------

    def step(self, step_fn, *args, **kwargs):
        """Run ``step_fn(*args, **kwargs)`` under the guard.  Returns
        ``(result, info)`` where `result` is the step's return value (the
        last attempt's, even when skipped) and `info` a
        :class:`GuardedStepInfo`."""
        self._step_index += 1
        step = self._step_index
        retries = 0
        # ONE pre-step snapshot, reused across retries: _restore installs
        # fresh copies, so `pre` itself stays valid for another restore —
        # re-capturing after a restore would just copy the same state again
        pre = self._capture()
        while True:
            t0 = time.perf_counter() if monitor.enabled() else 0.0
            result = step_fn(*args, **kwargs)
            # injected "optimizer update from NaN gradients": poison the
            # updated params so the health check sees what a real
            # non-finite gradient step produces
            if faults.should_fire("nan_grad", step=step):
                p0 = self._params[0]
                p0._data = p0._data * jnp.float32(jnp.nan)
            if self._healthy(_loss_array(result)):
                self._bad_streak = 0
                self._m_streak.set(0)
                self._good_steps += 1
                if self._good_steps % self.snapshot_every == 0:
                    # post-step state of a verified-healthy step
                    self._good_snap = self._capture()
                if monitor.enabled():
                    # the health check just synced the step, so the wall
                    # is real (not dispatch time) and the loss transfer
                    # is a cheap ready-scalar read; the EWMA detector
                    # files pre-divergence breadcrumbs off it
                    self._m_step_time.set(time.perf_counter() - t0)
                    self._observe_loss(result, step)
                mtrace.heartbeat()   # watchdog liveness: a step completed
                return result, GuardedStepInfo(True, _loss_array(result),
                                               retries=retries)
            # -- bad step ---------------------------------------------------
            self._m_skipped.inc()
            if retries == 0:
                # first bad attempt of this step: forensic scan BEFORE
                # the restore wipes the evidence (cold path — a bad step
                # already pays a full state restore)
                self._forensics(step, result)
            # skip the update entirely — scaler included, so a retried
            # step runs from EXACTLY the unfaulted pre-state (the
            # bit-for-bit parity property)
            with mtrace.span("resilience/step_restore", step=step,
                             attempt=retries):
                self._restore(pre, restore_scaler=True)
            # a bad step that restored IS forward progress — without this
            # beat a NaN storm under a watchdog (tracing off, so no span
            # ends fire) would read as a stall and spew false dumps
            mtrace.heartbeat()
            if retries < self.max_retries_per_step:
                retries += 1
                continue
            # the step is given up on: NOW the scaler backs off (a
            # transient fault that retried clean never touches it)
            if self._scaler is not None:
                self._scaler.backoff()
            self._bad_streak += 1
            self._m_streak.set(self._bad_streak)
            rolled = False
            if self.rollback_after > 0 and \
                    self._bad_streak >= self.rollback_after and \
                    self._good_snap is not None:
                with mtrace.span("resilience/rollback", step=step,
                                 bad_streak=self._bad_streak):
                    self._restore(self._good_snap, restore_scaler=True)
                self._m_rollbacks.inc()
                self._bad_streak = 0
                self._m_streak.set(0)
                rolled = True
            return result, GuardedStepInfo(False, _loss_array(result),
                                           retries=retries, skipped=True,
                                           rolled_back=rolled)

    __call__ = step
