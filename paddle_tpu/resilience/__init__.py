"""`paddle_tpu.resilience` — fault-tolerant training/serving runtime.

Four independently-testable layers (ISSUE 3; reference capabilities:
fleet/elastic/manager.py relaunch + FLAGS_check_nan_inf detection,
completed here with the *recovery* half):

- :mod:`.checkpoint_manager` — atomic auto-resume checkpoints (tmp dir +
  fsynced checksummed manifest + rename), rotation, async save, and
  `restore_latest()` falling back to the newest intact checkpoint;
- :mod:`.guard` — `StepGuard`: NaN/Inf-guarded train steps that skip the
  bad update, retry or roll back to the last good snapshot, and back off
  an attached `amp.GradScaler`; bad steps run the :mod:`.forensics`
  layer scan (ISSUE 13) so the flight dump NAMES the diverged layer;
- :mod:`.forensics` — per-layer non-finite/abs-max scan of the
  grad/param pytree in one batched device reduction (the "where did the
  NaN come from" half of the NaN trap);
- :mod:`.retry` — `retry()` backoff policy, shared `Deadline` budget, and
  the SIGTERM/SIGINT `PreemptionHandler` (checkpoint at the next step
  boundary, exit clean);
- :mod:`.faults` — the `PTPU_FAULTS` deterministic fault-injection plan
  the tests use to prove every recovery path.

All recovery events land in the PR-1 monitor as ``resilience/*`` series.
"""
from . import checkpoint_manager, faults, forensics, guard
from .checkpoint_manager import CheckpointError, CheckpointManager
from .faults import FaultPlan, InjectedCrash, InjectedFault
from .guard import GuardedStepInfo, StepGuard
# NOTE: binds the package attribute `retry` to the FUNCTION (shadowing the
# module of the same name); import the module explicitly as
# `paddle_tpu.resilience.retry` when needed.
from .retry import Deadline, PreemptionHandler, retry

__all__ = [
    "CheckpointManager", "CheckpointError", "StepGuard", "GuardedStepInfo",
    "retry", "Deadline", "PreemptionHandler", "FaultPlan", "InjectedCrash",
    "InjectedFault", "faults", "forensics", "guard", "checkpoint_manager",
]
