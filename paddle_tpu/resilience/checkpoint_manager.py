"""Atomic, auto-resume checkpointing (reference capability:
fleet checkpoint auto-save + elastic relaunch resume; the array IO rides
`distributed/checkpoint.py`'s orbax path — this layer adds the crash
contract on top).

Layout under ``directory``::

    step_00000010/            # one intact checkpoint
        arrays/               # orbax payload (save_state_dict)
        manifest.json         # step + per-array {shape, dtype, crc32}
    step_00000020/
    .tmp_step_00000030-<pid>/ # an in-flight (or crashed) save

Crash contract: a checkpoint becomes visible ONLY via the final
``os.rename(tmp, step_N)`` — a process killed at any earlier point (the
``kill -9`` acceptance test) leaves a ``.tmp_*`` remnant and the
previous intact checkpoints untouched.  The manifest is fsynced before
the rename and carries a crc32 per array, so `restore_latest()` can
verify a candidate end-to-end and fall back to the newest *intact* one
when the latest is truncated or bit-rotted.

Monitor: ``resilience/saves``, ``resilience/restores``,
``resilience/corrupt_ckpts_skipped``, gauge ``resilience/last_saved_step``.
"""
from __future__ import annotations

import json
import os
import queue
import shutil
import threading
import zlib
from typing import Dict, Optional, Tuple

import numpy as np

from .. import monitor
from ..monitor import trace as mtrace
from . import faults

__all__ = ["CheckpointManager", "CheckpointError"]

_MANIFEST = "manifest.json"
_ARRAYS = "arrays"
_STEP_PREFIX = "step_"
_TMP_PREFIX = ".tmp_"
_OLD_PREFIX = ".old_"
MANIFEST_FORMAT = 1


class CheckpointError(RuntimeError):
    """No intact checkpoint could be restored."""


def _fsync_path(path: str) -> None:
    """fsync a file or directory so the rename that follows is durable."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:   # some filesystems reject dir fsync; rename still atomic
        pass
    finally:
        os.close(fd)


def _to_numpy(v) -> np.ndarray:
    data = getattr(v, "_data", v)          # Tensor → jax.Array
    return np.asarray(data)


def _crc32(arr: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).tobytes()) & 0xFFFFFFFF


class CheckpointManager:
    """Atomic save / verified restore / rotation over a flat state dict.

    `state_dict` values may be paddle Tensors, jax arrays, or numpy
    arrays; restore returns paddle Tensors (whatever
    `distributed.checkpoint.load_state_dict` yields).
    """

    def __init__(self, directory: str, keep_last_n: int = 3,
                 async_save: bool = False):
        self.directory = os.path.abspath(directory)
        self.keep_last_n = int(keep_last_n)
        os.makedirs(self.directory, exist_ok=True)
        self._m_saves = monitor.counter("resilience/saves",
                                        "checkpoints committed")
        self._m_restores = monitor.counter("resilience/restores",
                                           "checkpoints restored")
        self._m_corrupt = monitor.counter(
            "resilience/corrupt_ckpts_skipped",
            "checkpoints rejected by verification during restore")
        self._m_last = monitor.gauge("resilience/last_saved_step")
        self._async = bool(async_save)
        self._worker = None
        self._q: "queue.Queue" = queue.Queue(maxsize=1)
        self._async_error: Optional[BaseException] = None
        # pending-save accounting under one condition variable (NOT an
        # event toggled from the drain thread: empty()-then-set races a
        # producer that enqueues between the check and the set, making
        # wait_until_finished() return with a save still pending)
        self._pending = 0
        self._cv = threading.Condition()
        self._clean_stale_tmp()

    # -- save ---------------------------------------------------------------

    def save(self, step: int, state_dict: Dict, wait: bool = True) -> str:
        """Commit `state_dict` as checkpoint `step`.  With
        ``async_save=True`` and ``wait=False`` the arrays are snapshotted
        to host memory NOW and written by a background thread; any
        background failure re-raises on the next save()/wait call."""
        step = int(step)
        if self._async and not wait:
            self._raise_async_error()
            host = {k: _to_numpy(v) for k, v in state_dict.items()}
            self._ensure_worker()
            with self._cv:
                self._pending += 1
            self._q.put((step, host))
            return self._final_dir(step)
        self.wait_until_finished()
        return self._save_sync(step, state_dict)

    def _save_sync(self, step: int, state_dict: Dict) -> str:
        with mtrace.span("resilience/ckpt_save", step=step,
                         arrays=len(state_dict)):
            return self._save_sync_body(step, state_dict)

    def _save_sync_body(self, step: int, state_dict: Dict) -> str:
        from ..distributed import checkpoint as dckpt

        final = self._final_dir(step)
        tmp = os.path.join(self.directory,
                           f"{_TMP_PREFIX}{_STEP_PREFIX}{step:08d}-{os.getpid()}")
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        # a failure anywhere below leaves the tmp dir behind (swept by the
        # next manager's _clean_stale_tmp) and the previous checkpoints
        # untouched — the commit is the os.rename at the end, nothing else
        arrays = {k: _to_numpy(v) for k, v in state_dict.items()}
        dckpt.save_state_dict(arrays, os.path.join(tmp, _ARRAYS),
                              _atomic=False)
        manifest = {
            "format": MANIFEST_FORMAT,
            "step": step,
            "arrays": {
                k: {"shape": list(a.shape), "dtype": str(a.dtype),
                    "crc32": _crc32(a)}
                for k, a in arrays.items()
            },
        }
        # the worst-moment injection point: data written, nothing
        # committed (hard=1 SIGKILLs right here — the kill -9 test)
        faults.maybe_crash(site="CheckpointManager.save", step=step)
        mpath = os.path.join(tmp, _MANIFEST)
        with open(mpath, "w") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())
        _fsync_path(tmp)
        if os.path.exists(final):
            # re-save of the same step: two-rename swap, never rmtree the
            # committed dir before its replacement is in place (a kill in
            # between would lose BOTH — the old via rmtree, the new via
            # the next manager's tmp sweep); _clean_stale_tmp rolls an
            # orphaned .old_ back when the final is missing
            old = os.path.join(
                self.directory,
                f"{_OLD_PREFIX}{_STEP_PREFIX}{step:08d}-{os.getpid()}")
            if os.path.exists(old):
                shutil.rmtree(old)
            os.rename(final, old)
            os.rename(tmp, final)   # the commit point
            shutil.rmtree(old, ignore_errors=True)
        else:
            os.rename(tmp, final)   # the commit point
        _fsync_path(self.directory)
        self._m_saves.inc()
        self._m_last.set(step)
        self._rotate()
        return final

    # -- restore ------------------------------------------------------------

    def restore_latest(self, strict_checksums: bool = True
                       ) -> Optional[Tuple[int, Dict]]:
        """Newest checkpoint that passes verification, as
        ``(step, state_dict)``; None when the directory holds none.
        A candidate failing ANY check (missing/unreadable manifest,
        orbax restore error, shape/dtype/crc mismatch) is skipped with
        ``resilience/corrupt_ckpts_skipped += 1`` and the next newest is
        tried — the auto-resume path after an unclean death."""
        for step in sorted(self.all_steps(), reverse=True):
            state = self._try_restore(step, strict_checksums)
            if state is not None:
                self._m_restores.inc()
                return step, state
        return None

    def restore(self, step: int, strict_checksums: bool = True) -> Dict:
        state = self._try_restore(int(step), strict_checksums)
        if state is None:
            raise CheckpointError(
                f"checkpoint step {step} in {self.directory} is missing or "
                "failed verification")
        self._m_restores.inc()
        return state

    def _try_restore(self, step: int, strict: bool) -> Optional[Dict]:
        with mtrace.span("resilience/ckpt_restore", step=step):
            return self._try_restore_body(step, strict)

    def _try_restore_body(self, step: int, strict: bool) -> Optional[Dict]:
        from ..distributed import checkpoint as dckpt

        path = self._final_dir(step)
        try:
            with open(os.path.join(path, _MANIFEST)) as f:
                manifest = json.load(f)
            expected = manifest["arrays"]
            state = dckpt.load_state_dict(os.path.join(path, _ARRAYS))
            if set(state) != set(expected):
                raise CheckpointError(
                    f"array set mismatch: manifest has {len(expected)}, "
                    f"payload has {len(state)}")
            for k, meta in expected.items():
                a = _to_numpy(state[k])
                if list(a.shape) != list(meta["shape"]) or \
                        str(a.dtype) != meta["dtype"]:
                    raise CheckpointError(
                        f"{k}: shape/dtype mismatch "
                        f"({a.shape}/{a.dtype} vs manifest)")
                if strict and _crc32(a) != meta["crc32"]:
                    raise CheckpointError(f"{k}: crc32 mismatch")
            return state
        except Exception as e:  # ptpu-check[silent-except]: orbax raises backend-specific
            # errors for truncated/corrupt payloads; ANY failure here means
            # "this candidate is not intact", which is exactly the event
            # restore_latest() recovers from (counted, warned, skipped)
            import warnings

            self._m_corrupt.inc()
            warnings.warn(
                f"checkpoint step {step} at {path} failed verification and "
                f"was skipped: {type(e).__name__}: {e}")
            return None

    # -- introspection ------------------------------------------------------

    def all_steps(self):
        steps = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return steps
        for n in names:
            if n.startswith(_STEP_PREFIX):
                try:
                    steps.append(int(n[len(_STEP_PREFIX):]))
                except ValueError:
                    continue
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def _final_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"{_STEP_PREFIX}{step:08d}")

    # -- housekeeping -------------------------------------------------------

    def _rotate(self) -> None:
        if self.keep_last_n <= 0:
            return
        steps = self.all_steps()
        for step in steps[:-self.keep_last_n]:
            shutil.rmtree(self._final_dir(step), ignore_errors=True)

    def _clean_stale_tmp(self) -> None:
        """Sweep crash remnants.  A ``.old_step_N`` whose ``step_N`` is
        MISSING marks a re-save killed between its two swap renames —
        roll the old one back before sweeping, so an intact checkpoint
        always survives.  Everything else (.tmp_*, leftover .old_* with
        a live final) was never/no-longer committed and is garbage by
        construction (the crash contract above)."""
        try:
            names = os.listdir(self.directory)
        except OSError:
            return
        for n in names:
            if not n.startswith(_OLD_PREFIX):
                continue
            # ".old_step_NNNNNNNN-pid" → "step_NNNNNNNN"
            stem = n[len(_OLD_PREFIX):].rsplit("-", 1)[0]
            final = os.path.join(self.directory, stem)
            path = os.path.join(self.directory, n)
            if stem.startswith(_STEP_PREFIX) and not os.path.exists(final):
                os.rename(path, final)
            else:
                shutil.rmtree(path, ignore_errors=True)
        for n in names:
            if n.startswith(_TMP_PREFIX):
                shutil.rmtree(os.path.join(self.directory, n),
                              ignore_errors=True)

    # -- async worker -------------------------------------------------------

    def _ensure_worker(self):
        if self._worker is None or not self._worker.is_alive():
            self._worker = threading.Thread(target=self._drain, daemon=True)
            self._worker.start()

    def _drain(self):
        while True:
            # ptpu-check[blocking-in-handler]: idle-state block of a
            # daemon consumer — the blocking get() IS the worker's
            # parked state between saves (None would be a shutdown
            # sentinel if one were ever sent; the thread is daemon and
            # dies with the process).  A timeout would only add
            # spurious wakeups between checkpoints.
            item = self._q.get()
            if item is None:
                return
            step, host = item
            try:
                self._save_sync(step, host)
            except BaseException as e:  # ptpu-check[silent-except]: surfaced to the caller
                # on the next save()/wait_until_finished() — an async save
                # failure must not die silently on a daemon thread
                self._async_error = e
            finally:
                with self._cv:
                    self._pending -= 1
                    self._cv.notify_all()
                self._q.task_done()

    def wait_until_finished(self, timeout: Optional[float] = None) -> None:
        """Block until every queued/in-flight async save committed;
        re-raise its failure if it crashed.  Raises TimeoutError when
        `timeout` expires with saves still pending — returning silently
        there would let a shutdown path exit believing the checkpoint
        committed while the daemon worker dies mid-write."""
        with self._cv:
            done = self._cv.wait_for(lambda: self._pending == 0, timeout)
        self._raise_async_error()
        if not done:
            raise TimeoutError(
                f"async checkpoint save still pending after {timeout}s")

    def _raise_async_error(self):
        if self._async_error is not None:
            e, self._async_error = self._async_error, None
            raise e
