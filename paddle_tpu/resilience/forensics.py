"""Divergence forensics — the device-side half of the v6 training
microscope (ISSUE 13 wing a).

`StepGuard._healthy` answers *whether* a step went bad with one fused
boolean; this module answers *where*: given the named grad/param pytree
it computes, per layer, the non-finite element count and the absolute
max — all in ONE batched device computation with a SINGLE host
transfer (the same sync discipline as the health check itself: the
reductions are dispatched together and one stacked array crosses to
the host).  The result names the first-NaN layer path and ranks the
finite-but-hot suspects, and is what StepGuard writes into the
``resilience/nonfinite{layer,which}`` counters, the flight-ring
breadcrumb, and the ``bad_step`` flight dump.

This runs ONLY on the bad-step path (cold by definition — a bad step
already pays a restore), so there is no gate here; the per-step hot
path never reaches this module.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

__all__ = ["layer_health", "nonfinite_report"]

# layers listed in full in a report/dump; beyond this only the bad and
# hottest layers are named (a 10k-layer model must not write a 10k-row
# dump on every bad step)
_MAX_SUSPECTS = 8


def layer_health(named_arrays):
    """One batched device scan of ``[(name, array), ...]``.

    Returns ``[(name, nonfinite_count, absmax, size), ...]`` for every
    float array (non-float entries are skipped — integers can't go
    non-finite).  All per-layer reductions are dispatched together and
    materialized with ONE host transfer; ``absmax`` is over the finite
    elements only, so a single NaN doesn't mask which layer was
    *growing* before it died."""
    names, rows, sizes = [], [], []
    for name, a in named_arrays:
        if a is None or not jnp.issubdtype(a.dtype, jnp.floating) \
                or a.size == 0:
            continue
        af = a.astype(jnp.float32)
        finite = jnp.isfinite(af)
        # integer reduction, cast AFTER: a float32 accumulator saturates
        # at 2^24 and would report a fully-finite 200M-element embedding
        # as non-finite (size - ~1.7e7 > 0)
        n_bad = jnp.sum(jnp.logical_not(finite),
                        dtype=jnp.int32).astype(jnp.float32)
        amax = jnp.max(jnp.abs(jnp.where(finite, af, 0.0)))
        names.append(name)
        rows.append(jnp.stack([n_bad, amax]))
        sizes.append(int(a.size))
    if not rows:
        return []
    stats = np.asarray(jnp.stack(rows))   # the ONE host transfer
    return [(name, int(stats[i, 0]), float(stats[i, 1]), sizes[i])
            for i, name in enumerate(names)]


def nonfinite_report(params=None, grads=None, loss=None) -> dict:
    """The bad-step post-mortem document.

    ``params`` / ``grads``: ``[(layer_path, array), ...]`` (grads may be
    absent — a step that already ran ``clear_grad()`` only has params
    to examine).  ``loss``: the step's loss array, checked alongside.

    Returns::

        {"checked": n_layers_scanned,
         "first_bad": "layer (which)" | None,   # first in param order
         "bad": [{"layer", "which", "nonfinite", "size", "frac",
                  "absmax"}, ...],              # every non-finite layer
         "suspects": [{"layer", "which", "absmax"}, ...],  # hottest
         "loss_finite": bool | None}

    ``suspects`` ranks the finite layers by abs-max — the "who was
    about to blow up" list the loss-spike breadcrumbs pair with."""
    entries = []
    for which, named in (("param", params or ()), ("grad", grads or ())):
        for name, a in named:
            entries.append((which, name, a))
    scanned = layer_health([(f"{which}\0{name}", a)
                            for which, name, a in entries])
    bad, finite_rows = [], []
    for key, n_bad, amax, size in scanned:
        which, name = key.split("\0", 1)
        if n_bad:
            bad.append({"layer": name, "which": which,
                        "nonfinite": n_bad, "size": size,
                        "frac": n_bad / size, "absmax": amax})
        else:
            finite_rows.append({"layer": name, "which": which,
                                "absmax": amax})
    finite_rows.sort(key=lambda r: -r["absmax"])
    report = {
        "checked": len(scanned),
        "first_bad": (f"{bad[0]['layer']} ({bad[0]['which']})"
                      if bad else None),
        "bad": bad,
        "suspects": finite_rows[:_MAX_SUSPECTS],
    }
    if loss is not None:
        try:
            report["loss_finite"] = bool(np.isfinite(
                np.asarray(loss)).all())
        except (TypeError, ValueError):
            report["loss_finite"] = None
    return report
