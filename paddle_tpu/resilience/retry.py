"""Retry/backoff, deadlines, and preemption handling (reference analog:
fleet/elastic/manager.py restarts ranks on transient failures; the etcd
client retries leases — here transient-failure policy is one shared
primitive instead of ad-hoc loops at each call site).

Design: stdlib-only (importable from the store/rpc bootstrap path before
jax exists), monitor-instrumented (`resilience/retries` counter labeled
by site), and deterministic enough to test (the sleeper is injectable
and jitter is a bounded multiplier, not an unbounded resample).
"""
from __future__ import annotations

import os
import random
import signal
import threading
import time
from typing import Callable, Optional, Tuple, Type

from .. import monitor
from ..monitor import flight as _flight

__all__ = ["retry", "Deadline", "PreemptionHandler", "DEFAULT_RETRYABLE"]

DEFAULT_RETRYABLE: Tuple[Type[BaseException], ...] = (
    ConnectionError, TimeoutError, OSError)

# Private RNG for jitter: drawing from the process-global `random` module
# would perturb seeded streams the framework depends on for determinism
# (reader.shuffle order, dy2static probes) every time a background retry
# fires mid-training.
_jitter_rng = random.Random(0x5EED)


class Deadline:
    """A wall-clock budget that several operations can share.

    `Deadline(None)` never expires — call sites can thread an optional
    deadline without branching.  Monotonic clock: a host NTP step during
    a long rendezvous must not spuriously expire every worker at once.
    """

    __slots__ = ("seconds", "_expires")

    def __init__(self, seconds: Optional[float]):
        self.seconds = seconds
        self._expires = None if seconds is None else time.monotonic() + seconds

    @classmethod
    def after(cls, seconds: Optional[float]) -> "Deadline":
        return cls(seconds)

    @property
    def expired(self) -> bool:
        return self._expires is not None and time.monotonic() >= self._expires

    def remaining(self) -> Optional[float]:
        """Seconds left (>= 0), or None for an infinite deadline."""
        if self._expires is None:
            return None
        return max(0.0, self._expires - time.monotonic())

    def remaining_ms(self, cap: int = 2**31 - 1) -> Optional[int]:
        r = self.remaining()
        return None if r is None else min(cap, max(0, int(r * 1000)))

    def check(self, what: str = "operation") -> None:
        if self.expired:
            raise TimeoutError(f"deadline exceeded ({self.seconds}s) in {what}")

    def __repr__(self):
        return f"Deadline(remaining={self.remaining()})"


def retry(fn: Callable = None, *, retries: int = 5, backoff: float = 0.05,
          max_backoff: float = 5.0, jitter: float = 0.1,
          retryable: Tuple[Type[BaseException], ...] = DEFAULT_RETRYABLE,
          deadline: Optional[Deadline] = None, site: str = "",
          on_retry: Callable = None, sleep: Callable = time.sleep):
    """Exponential-backoff retry wrapper.

    Two spellings::

        result = retry(lambda: store.get(k), retries=3, site="store.get")()
        @retry(retries=3)
        def connect(): ...

    Policy: attempt `fn`; on a `retryable` exception sleep
    ``backoff * 2**i`` (capped at `max_backoff`, stretched by up to
    ``+jitter`` fractionally so a fleet of workers doesn't thunder-herd
    the master) and re-attempt, up to `retries` extra attempts or until
    `deadline` expires — whichever is first.  The LAST underlying
    exception is re-raised unwrapped, so call sites keep their existing
    except clauses.  Each re-attempt increments
    ``resilience/retries{site=...}``.
    """
    if fn is None:
        def deco(f):
            return retry(f, retries=retries, backoff=backoff,
                         max_backoff=max_backoff, jitter=jitter,
                         retryable=retryable, deadline=deadline,
                         site=site or getattr(f, "__name__", ""),
                         on_retry=on_retry, sleep=sleep)
        return deco

    ctr = monitor.counter("resilience/retries",
                          "transient-failure re-attempts")

    def wrapped(*args, **kwargs):
        attempt = 0
        while True:
            try:
                return fn(*args, **kwargs)
            except retryable as e:
                remaining = None if deadline is None else deadline.remaining()
                out_of_time = remaining is not None and remaining <= 0
                if attempt >= retries or out_of_time:
                    raise
                # exponent clamped: long deadline-governed loops (retries
                # in the thousands) must not hit float overflow at 2**1024
                delay = min(backoff * (2.0 ** min(attempt, 62)), max_backoff)
                if jitter:
                    delay *= 1.0 + _jitter_rng.uniform(0.0, jitter)
                if remaining is not None:
                    delay = min(delay, remaining)
                attempt += 1
                ctr.labels(site=site or getattr(fn, "__name__", "?")).inc()
                if on_retry is not None:
                    on_retry(attempt, e, delay)
                sleep(delay)

    wrapped.__name__ = getattr(fn, "__name__", "retry_wrapped")
    return wrapped


class PreemptionHandler:
    """SIGTERM/SIGINT → "checkpoint at the next step boundary, then exit".

    The training loop polls `triggered` once per step; when set it saves
    through its CheckpointManager and exits cleanly (the pattern of the
    reference's elastic relaunch: the *loop* decides when state is
    consistent, the signal only requests it).  A second SIGINT falls
    through to the previous handler so an interactive ^C ^C still kills
    a wedged run.
    """

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self._signals = tuple(signals)
        self._event = threading.Event()
        self._prev = {}
        self._installed = False
        self._ctr = monitor.counter("resilience/preemptions",
                                    "preemption signals received")

    def install(self) -> "PreemptionHandler":
        if self._installed:
            return self
        if threading.current_thread() is not threading.main_thread():
            raise RuntimeError(
                "PreemptionHandler.install() must run on the main thread "
                "(signal module restriction)")
        for sig in self._signals:
            self._prev[sig] = signal.signal(sig, self._on_signal)
        self._installed = True
        return self

    def _on_signal(self, signum, frame):
        if self._event.is_set():
            # second signal: restore + re-deliver so a stuck loop dies
            self.uninstall()
            os.kill(os.getpid(), signum)
            return
        self._ctr.inc()
        self._event.set()
        # post-mortem breadcrumb trail: with PTPU_FLIGHT_DIR set, the
        # last spans/notes are on disk even if the grace period runs out
        # before the step-boundary checkpoint lands (signal-safe form:
        # helper thread + bounded join, never inline lock acquisition)
        _flight.dump_from_signal("preemption", extra={"signal": int(signum)})

    @property
    def triggered(self) -> bool:
        return self._event.is_set()

    def reset(self) -> None:
        self._event.clear()

    def uninstall(self) -> None:
        if not self._installed:
            return
        for sig, prev in self._prev.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, TypeError):   # non-main thread teardown
                pass
        self._prev.clear()
        self._installed = False

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False
