"""Custom autograd function (reference: paddle.autograd.PyLayer,
python/paddle/autograd/py_layer.py:244 + pybind/eager_py_layer.cc).

The TPU-native twist: forward/backward run through the same eager op layer,
and the recorded Node simply calls the user's static backward. Used by
recompute and MoE exactly like the reference.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..autograd import tape


class PyLayerContext:
    def __init__(self):
        self._saved = []
        self.materialize_grads = True

    def save_for_backward(self, *tensors):
        self._saved = list(tensors)

    def saved_tensor(self):
        return list(self._saved)

    # paddle alias
    saved_tensors = property(lambda self: list(self._saved))


class PyLayerMeta(type):
    pass


class PyLayer(metaclass=PyLayerMeta):
    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        tensor_inputs = [a for a in args if isinstance(a, Tensor)]
        with tape.no_grad():
            outputs = cls.forward(ctx, *args, **kwargs)

        multi = isinstance(outputs, (tuple, list))
        out_list = list(outputs) if multi else [outputs]
        out_tensors = [o for o in out_list if isinstance(o, Tensor)]

        needs = [not t.stop_gradient for t in tensor_inputs]
        if tape.is_grad_enabled() and any(needs):

            def vjp_fn(cts):
                if not isinstance(cts, (tuple, list)):
                    cts = (cts,)
                grad_in = [Tensor(c) for c in cts]
                with tape.no_grad():
                    res = cls.backward(ctx, *grad_in)
                if not isinstance(res, (tuple, list)):
                    res = (res,)
                out = []
                i = 0
                for a in tensor_inputs:
                    if i < len(res):
                        g = res[i]
                        out.append(g._data if isinstance(g, Tensor) else g)
                    else:
                        out.append(None)
                    i += 1
                return tuple(out)

            # fresh output tensors so recording doesn't alias forward's internals
            wrapped = [Tensor(t._data) for t in out_tensors]
            tape.record(vjp_fn, tensor_inputs, needs, wrapped, name=cls.__name__)
            it = iter(wrapped)
            out_list = [next(it) if isinstance(o, Tensor) else o for o in out_list]

        return tuple(out_list) if multi else out_list[0]


class LegacyPyLayer(PyLayer):
    pass
