"""Functional higher-order autodiff (reference: paddle.incubate.autograd
vjp/jvp/Jacobian/Hessian, python/paddle/incubate/autograd/functional.py).

TPU-native: these are direct jax transforms over a Tensor-level callable —
higher-order derivatives (double/triple grad in the reference's
backward.yaml) come for free from composing jax.vjp/jvp instead of
hand-written *_double_grad kernels.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..autograd import tape

__all__ = ["vjp", "jvp", "jacobian", "hessian", "functionalize"]


def _wrap_fn(func):
    """Lift a Tensor→Tensor python callable to an array→array function."""

    def array_fn(*arrays):
        with tape.no_grad():
            ins = [Tensor(a) for a in arrays]
            out = func(*ins)
        if isinstance(out, (tuple, list)):
            return tuple(o._data for o in out)
        return out._data

    return array_fn


functionalize = _wrap_fn


def vjp(func, xs, v=None):
    xs_list = xs if isinstance(xs, (list, tuple)) else [xs]
    arrays = [x._data for x in xs_list]
    fn = _wrap_fn(func)
    out, vjp_fn = jax.vjp(fn, *arrays)
    if v is None:
        seed = jax.tree_util.tree_map(jnp.ones_like, out)
    else:
        v_list = v if isinstance(v, (list, tuple)) else [v]
        seed = tuple(t._data for t in v_list)
        if not isinstance(out, tuple):
            seed = seed[0]
    grads = vjp_fn(seed)
    outs = (
        [Tensor(o) for o in out] if isinstance(out, tuple) else Tensor(out)
    )
    gs = [Tensor(g) for g in grads]
    return outs, gs if len(gs) > 1 else gs[0]


def jvp(func, xs, v=None):
    xs_list = xs if isinstance(xs, (list, tuple)) else [xs]
    arrays = [x._data for x in xs_list]
    fn = _wrap_fn(func)
    if v is None:
        tangents = tuple(jnp.ones_like(a) for a in arrays)
    else:
        v_list = v if isinstance(v, (list, tuple)) else [v]
        tangents = tuple(t._data for t in v_list)
    out, jv = jax.jvp(fn, tuple(arrays), tangents)
    outs = [Tensor(o) for o in out] if isinstance(out, tuple) else Tensor(out)
    jvs = [Tensor(j) for j in jv] if isinstance(jv, tuple) else Tensor(jv)
    return outs, jvs


def jacobian(func, xs):
    xs_list = xs if isinstance(xs, (list, tuple)) else [xs]
    arrays = [x._data for x in xs_list]
    fn = _wrap_fn(func)
    jac = jax.jacrev(fn, argnums=tuple(range(len(arrays))))(*arrays)
    if len(arrays) == 1:
        jac = jac[0] if isinstance(jac, tuple) else jac
        return Tensor(jac)
    return [Tensor(j) for j in jac]


def hessian(func, xs):
    xs_list = xs if isinstance(xs, (list, tuple)) else [xs]
    arrays = [x._data for x in xs_list]
    fn = _wrap_fn(func)
    h = jax.hessian(fn)(*arrays)
    if len(arrays) == 1:
        return Tensor(h)
    return h
