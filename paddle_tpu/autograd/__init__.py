"""Autograd public API (reference: python/paddle/autograd/)."""
from .tape import (
    backward,
    saved_tensors_hooks,
    grad,
    no_grad,
    enable_grad,
    is_grad_enabled,
    set_grad_enabled,
)
from .py_layer import PyLayer, PyLayerContext
from . import functional

__all__ = [
    "backward",
    "saved_tensors_hooks",
    "grad",
    "no_grad",
    "enable_grad",
    "is_grad_enabled",
    "set_grad_enabled",
    "PyLayer",
    "PyLayerContext",
    "functional",
]
