"""Eager autograd engine.

TPU-native re-design of the reference's eager autograd
(paddle/fluid/eager/backward.cc:383 `egr::Backward`,
grad_node_info.h:168 `GradNodeBase`): instead of per-op hand-written C++
GradNodes generated from YAML, every eager op records a single `Node` holding
the `jax.vjp` pullback of its (pure, jittable) forward function. Backward is
the same reverse-topological cotangent walk, but each node's backward *is* an
XLA-compiled pullback — there is no per-op gradient kernel library to
maintain, because jax.vjp derives it from the forward definition.

Gradient accumulation for leaves mirrors the reference's
GradTensorHolder/accumulation nodes (paddle/fluid/eager/grad_tensor_holder.h).
"""
from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp

__all__ = [
    "Node",
    "saved_tensors_hooks",
    "no_grad",
    "enable_grad",
    "is_grad_enabled",
    "set_grad_enabled",
    "record",
    "backward",
    "grad",
]


class _GradState(threading.local):
    def __init__(self):
        self.enabled = True
        self.saved_tensors_hooks = None   # (pack, unpack) or None


_state = _GradState()


def is_grad_enabled() -> bool:
    return _state.enabled


def set_grad_enabled(mode: bool):
    _state.enabled = bool(mode)


class _GradModeGuard:
    def __init__(self, mode: bool):
        self._mode = mode
        self._prev = None

    def __enter__(self):
        self._prev = _state.enabled
        _state.enabled = self._mode
        return self

    def __exit__(self, *exc):
        _state.enabled = self._prev
        return False

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with _GradModeGuard(self._mode):
                return fn(*args, **kwargs)

        return wrapper


def no_grad():
    """Context manager / decorator disabling tape recording (paddle.no_grad)."""
    return _GradModeGuard(False)


def enable_grad():
    return _GradModeGuard(True)


class Node:
    """One autograd-graph node: the vjp pullback of a single eager op.

    Analog of a generated GradNode subclass in the reference (eager_gen.py
    templates) — but generic over any jax-traceable forward.
    """

    __slots__ = (
        "vjp_fn",
        "inputs",
        "input_needs_grad",
        "out_avals",
        "n_outs",
        "name",
        "fwd_fn",
        "input_versions",
        "saved_packed",
        "unpack_hook",
        "__weakref__",
    )

    def __init__(self, vjp_fn, inputs, input_needs_grad, out_avals, name="",
                 fwd_fn=None):
        self.vjp_fn = vjp_fn
        self.inputs = inputs  # list of input Tensors (kept alive for leaf accumulation)
        self.input_needs_grad = input_needs_grad
        self.out_avals = out_avals  # list of (shape, dtype) for each output
        self.n_outs = len(out_avals)
        self.name = name
        # pure forward (arrays -> arrays), kept for create_graph=True: the
        # recorded backward re-runs jax.vjp(fwd_fn, *primals) so the pullback
        # is differentiable wrt BOTH cotangents and primals (the reference's
        # double-grad GradNodes from backward.yaml play this role).
        self.fwd_fn = fwd_fn
        # inplace-version snapshot (reference tensor_wrapper.h): backward
        # errors if a saved input was mutated after this forward recorded.
        self.input_versions = [getattr(t, "_version", 0) for t in inputs]
        # saved_tensors_hooks (reference autograd/saved_tensors_hooks):
        # pack() transforms what the node saves, unpack() restores it at
        # backward — the pullback replays from unpack(packed) instead of
        # the live tensor's data.
        hooks = _state.saved_tensors_hooks
        if hooks is not None:
            pack, self.unpack_hook = hooks
            self.saved_packed = [pack(t) for t in inputs]
        else:
            self.saved_packed = None
            self.unpack_hook = None

    def check_versions(self):
        for t, v in zip(self.inputs, self.input_versions):
            cur = getattr(t, "_version", 0)
            if cur != v:
                raise RuntimeError(
                    f"one of the tensors needed for the backward of "
                    f"{self.name!r} was modified in place after the forward "
                    f"ran (saved version {v}, current {cur}); gradients "
                    f"would be wrong. Clone the tensor before mutating it, "
                    f"or re-run the forward."
                )

    def ensure_vjp(self):
        """Materialize the pullback lazily (dispatch.apply records only the
        pure forward — see the eager-overhead note there). Valid because
        check_versions has confirmed the saved inputs are unmutated."""
        if self.vjp_fn is None:
            if self.fwd_fn is None:
                raise RuntimeError(
                    f"node {self.name!r} has neither a pullback nor a "
                    "replayable forward")
            _, self.vjp_fn = jax.vjp(self.fwd_fn, *self.saved_data())
        return self.vjp_fn

    def saved_data(self):
        """Primal input arrays for the pullback: unpacked through the
        saved_tensors_hooks when the node recorded under one."""
        if self.saved_packed is not None:
            import jax.numpy as _jnp

            def _arr(v):
                from ..core.tensor import Tensor as _T

                return v._data if isinstance(v, _T) else _jnp.asarray(v)

            return [_arr(self.unpack_hook(p)) for p in self.saved_packed]
        return [t._data for t in self.inputs]

    def __repr__(self):
        return f"<GradNode {self.name} n_outs={self.n_outs}>"


class saved_tensors_hooks:
    """Context manager installing (pack, unpack) hooks on tensors saved for
    backward (reference python/paddle/autograd/saved_tensors_hooks.py).
    pack(tensor) -> object runs at record time; unpack(object) -> tensor/
    array runs when the node's pullback materializes, and the pullback
    replays from the UNPACKED data (both plain and create_graph backward).

    Memory note: the graph also keeps the input Tensor handles for
    topology/accumulation, so a pack hook reduces device memory only for
    buffers the hook itself releases (e.g. by re-materializing on unpack);
    it always controls WHAT data first-order backward sees —
    quantize/dequantize or recompute-from-cheap-state hooks work as in
    the reference. create_graph=True backward replays from the LIVE saved
    tensors instead (a host unpack is opaque to second-order tracing);
    value-identical for round-tripping hooks like host offload.
    """

    def __init__(self, pack_hook, unpack_hook):
        self._hooks = (pack_hook, unpack_hook)

    def __enter__(self):
        self._prev = _state.saved_tensors_hooks
        _state.saved_tensors_hooks = self._hooks
        return self

    def __exit__(self, *exc):
        _state.saved_tensors_hooks = self._prev
        return False


def record(vjp_fn, inputs, input_needs_grad, outputs, name="", fwd_fn=None):
    """Attach a Node to `outputs` (Tensors) produced from `inputs` (Tensors)."""
    out_avals = [(o.shape, o.dtype) for o in outputs]
    node = Node(vjp_fn, list(inputs), list(input_needs_grad), out_avals, name,
                fwd_fn=fwd_fn)
    for i, o in enumerate(outputs):
        o._grad_node = node
        o._out_index = i
        o.stop_gradient = False
    return node


def _topo_order(root_nodes: Sequence[Node]) -> List[Node]:
    """Reverse-topological order over the node DAG (iterative DFS postorder)."""
    visited = set()
    order: List[Node] = []
    stack: List[tuple] = [(n, False) for n in root_nodes]
    while stack:
        node, processed = stack.pop()
        if processed:
            order.append(node)
            continue
        if id(node) in visited:
            continue
        visited.add(id(node))
        stack.append((node, True))
        for t in node.inputs:
            n = getattr(t, "_grad_node", None)
            if n is not None and id(n) not in visited:
                stack.append((n, False))
    order.reverse()  # roots first → walk producers after consumers
    return order


def _accum(slot, value):
    return value if slot is None else slot + value


def _node_backward_recorded(node, ct_tensors):
    """Run one node's pullback THROUGH the dispatch layer so the backward
    computation is itself taped (create_graph=True; reference analog: the
    double/triple-grad GradNodes generated from backward.yaml).

    The recorded op is `jax.vjp(fwd_fn, *primals) pullback(cts)` — a pure
    function of (cotangents, primal inputs), so second-order cotangents
    flow to both. Returns input cotangents (Tensors) for the needs-grad
    inputs, positionally aligned with node.inputs (None where not needed).
    """
    from ..core.dispatch import apply

    if node.fwd_fn is None:
        raise RuntimeError(
            f"create_graph=True through node {node.name!r} which recorded no "
            "replayable forward (PyLayer nodes do not support double "
            "backward; use autograd.functional transforms)"
        )
    m = node.n_outs
    needs = list(node.input_needs_grad)

    def bwd_fn(*args):
        cts, prims = args[:m], args[m:]
        _, pull = jax.vjp(node.fwd_fn, *prims)
        out = pull(tuple(cts) if m > 1 else cts[0])
        kept = tuple(o for o, n in zip(out, needs) if n)
        # a 1-tuple output would desync apply's multi-output bookkeeping
        # from the tape's n_outs cotangent call convention
        return kept[0] if len(kept) == 1 else kept

    res = apply(bwd_fn, *ct_tensors, *node.inputs,
                name=(node.name or "op") + "_grad")
    res = list(res) if isinstance(res, tuple) else [res]
    full = []
    for n in needs:
        full.append(res.pop(0) if n else None)
    return full


def backward(tensors, grad_tensors=None, retain_graph=False, capture=None,
             create_graph=False):
    """Reverse-mode walk accumulating `.grad` on leaf tensors.

    Mirrors egr::Backward (reference backward.cc:383): seed cotangents on the
    root outputs, walk nodes in reverse topological order, run each node's
    pullback, scatter cotangents to producer nodes or leaf tensors.

    `capture`: optional dict id(tensor)→tensor (GeneralGrad mode, used by
    paddle.grad). When given, cotangents arriving at captured tensors (leaf
    OR intermediate) are collected into the returned dict and leaf `.grad`
    fields are NOT touched.

    `create_graph`: run every pullback through the dispatch layer so the
    produced gradients carry their own grad graph (higher-order autograd
    from the eager API; implies retain_graph). Cotangents are then Tensors
    and leaf `.grad` accumulation is a recorded add (gradient hooks are
    bypassed on this path).
    """
    from ..core.tensor import Tensor

    retain_graph = retain_graph or create_graph
    captured = {} if capture is not None else None

    def _take(t, ct):
        key = id(t)
        captured[key] = ct if key not in captured else captured[key] + ct

    if not isinstance(tensors, (list, tuple)):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif not isinstance(grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]

    # cotangents[(id(node), out_idx)] = accumulated cotangent
    # (jnp arrays normally; Tensors under create_graph so sums are taped)
    cotangents = {}
    roots = []
    for t, g in zip(tensors, grad_tensors):
        if t.stop_gradient:
            continue
        if g is None:
            if t.size != 1:
                raise RuntimeError(
                    "grad can be implicitly created only for scalar outputs; "
                    f"got shape {t.shape}"
                )
            seed = jnp.ones(t.shape, t.dtype)
        else:
            seed = g._data if isinstance(g, Tensor) else jnp.asarray(g)
        if create_graph:
            seed = g if isinstance(g, Tensor) else Tensor(seed)
        node = getattr(t, "_grad_node", None)
        if capture is not None and id(t) in capture:
            _take(t, seed)
            if node is None:
                continue
        elif node is None:
            # Root is itself a leaf.
            if capture is None:
                if create_graph:
                    _accumulate_grad_recorded(t, seed)
                else:
                    t._accumulate_grad(seed)
            continue
        key = (id(node), t._out_index)
        cotangents[key] = _accum(cotangents.get(key), seed)
        roots.append(node)

    order = _topo_order(roots)
    node_by_id = {id(n): n for n in order}

    for node in order:
        cts = []
        any_ct = False
        for i, (shape, dtype) in enumerate(node.out_avals):
            ct = cotangents.pop((id(node), i), None)
            if ct is None:
                zero = jnp.zeros(shape, dtype)
                ct = Tensor(zero) if create_graph else zero
            else:
                any_ct = True
            cts.append(ct)
        if not any_ct:
            continue
        node.check_versions()
        if create_graph:
            in_cts = _node_backward_recorded(node, cts)
        else:
            vjp_fn = node.ensure_vjp()
            in_cts = vjp_fn(tuple(cts) if node.n_outs > 1 else cts[0])
        for t, needs, ct in zip(node.inputs, node.input_needs_grad, in_cts):
            if not needs or ct is None:
                continue
            if capture is not None and id(t) in capture:
                _take(t, ct)
            producer = getattr(t, "_grad_node", None)
            if producer is not None and id(producer) in node_by_id:
                key = (id(producer), t._out_index)
                cotangents[key] = _accum(cotangents.get(key), ct)
            elif producer is None and not t.stop_gradient and capture is None:
                if create_graph:
                    _accumulate_grad_recorded(t, ct)
                else:
                    t._accumulate_grad(ct)
        if not retain_graph:
            node.vjp_fn = _used_up

    # Free graph references so intermediate activations can be collected.
    if not retain_graph:
        for t in tensors:
            _release_graph(t)
    return captured


def _accumulate_grad_recorded(t, ct):
    """Leaf .grad accumulation keeping ct's grad graph (create_graph path)."""
    t.grad = ct if t.grad is None else t.grad + ct


def _used_up(*_):
    raise RuntimeError(
        "Trying to backward through the graph a second time. "
        "Pass retain_graph=True if you need to."
    )


def _release_graph(root):
    node = getattr(root, "_grad_node", None)
    stack = [node] if node is not None else []
    seen = set()
    while stack:
        n = stack.pop()
        if n is None or id(n) in seen:
            continue
        seen.add(id(n))
        for t in n.inputs:
            p = getattr(t, "_grad_node", None)
            if p is not None:
                stack.append(p)
            t._grad_node = None
        n.inputs = []


def grad(
    outputs,
    inputs,
    grad_outputs=None,
    retain_graph=False,
    create_graph=False,
    allow_unused=False,
):
    """paddle.grad equivalent (reference: egr::GeneralGrad, general_grad.h).

    Computes grads of `outputs` w.r.t. `inputs` without touching `.grad`
    fields. create_graph=True runs every pullback back through the dispatch
    layer (tape-recorded backward — the analog of the 58+74 double/triple
    grad entries in paddle/phi/api/yaml/backward.yaml being themselves
    differentiable ops), so the returned grads can be differentiated again
    with another paddle.grad / .backward call.
    """
    from ..core.tensor import Tensor  # noqa: F401 (used for wrapping results)

    if not isinstance(outputs, (list, tuple)):
        outputs = [outputs]
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]

    # GeneralGrad mode: cotangents are captured for exactly `inputs` (leaf or
    # intermediate); no tensor's `.grad` field is touched.
    capture = {id(t): t for t in inputs}
    captured = backward(outputs, grad_outputs, retain_graph=retain_graph,
                        capture=capture, create_graph=create_graph)
    results = []
    for t in inputs:
        ct = captured.get(id(t))
        if ct is None and not allow_unused:
            raise RuntimeError(
                "One of the differentiated tensors appears to not have "
                "been used in the graph. Set allow_unused=True if this is "
                "the desired behavior."
            )
        if ct is None:
            results.append(None)
        else:
            results.append(ct if isinstance(ct, Tensor) else Tensor(ct))
    return results
