"""Utilities (reference: python/paddle/utils/)."""
from __future__ import annotations

import functools
import importlib
import warnings

__all__ = ["deprecated", "try_import", "run_check", "unique_name", "cpp_extension"]

from . import cpp_extension  # noqa: E402


def deprecated(update_to="", since="", reason="", level=0):
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            warnings.warn(
                f"{fn.__name__} is deprecated since {since}: {reason}. "
                f"Use {update_to} instead.",
                DeprecationWarning,
            )
            return fn(*args, **kwargs)

        return wrapper

    return deco


def try_import(module_name, err_msg=None):
    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(err_msg or f"Cannot import {module_name}.")


def run_check():
    """Smoke-check the TPU runtime (reference: paddle.utils.run_check)."""
    import jax
    import jax.numpy as jnp

    devs = jax.devices()
    x = jnp.ones((128, 128))
    y = (x @ x).block_until_ready()
    print(f"paddle_tpu works! devices={devs}, matmul checksum={float(y.sum()):.1f}")


class _UniqueNameGenerator:
    def __init__(self):
        import collections

        self._counters = collections.defaultdict(int)

    def generate(self, prefix="tmp"):
        n = self._counters[prefix]
        self._counters[prefix] += 1
        return f"{prefix}_{n}"


unique_name = _UniqueNameGenerator()


def require_version(min_version, max_version=None):
    """Check the installed framework version (reference
    utils/__init__.py require_version): raises if this build's version
    falls outside [min_version, max_version]."""
    from .. import __version__

    def key(v):
        parts = [int(p) for p in str(v).split(".")[:3] if p.isdigit()]
        return tuple(parts + [0] * (3 - len(parts)))   # zero-pad: 0.1 == 0.1.0

    cur = key(__version__)
    if key(min_version) > cur:
        raise Exception(
            f"version {__version__} is below required {min_version}")
    if max_version is not None and key(max_version) < cur:
        raise Exception(
            f"version {__version__} is above supported {max_version}")
    return True


if "__all__" in globals():
    __all__ += ["require_version"]
