"""Custom C++ op extensions (reference: python/paddle/utils/cpp_extension/ —
JIT-compiles user C++/CUDA ops against paddle/extension.h and registers
them; fluid/framework/custom_operator.cc).

TPU-native shape: a user C++ kernel is built into a shared library (same
lazy-make flow as the framework's own csrc/) and invoked as a host
callback inside the XLA program via jax.pure_callback — the custom-call
extension point. Device-side custom kernels are written in Pallas instead
(pure Python, no toolchain needed)."""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile

import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor

__all__ = ["load", "CppExtension", "get_build_directory", "custom_host_op",
           "register_custom_op", "get_custom_op"]

_BUILD_ROOT = os.path.join(tempfile.gettempdir(), "paddle_tpu_extensions")


def get_build_directory():
    os.makedirs(_BUILD_ROOT, exist_ok=True)
    return _BUILD_ROOT


class CppExtension:
    """Declarative extension spec (sources + flags), mirroring the
    reference's setuptools Extension shim."""

    def __init__(self, sources, extra_compile_args=None, extra_link_args=None,
                 include_dirs=None):
        self.sources = list(sources)
        self.extra_compile_args = list(extra_compile_args or [])
        self.extra_link_args = list(extra_link_args or [])
        self.include_dirs = list(include_dirs or [])


def load(name, sources, extra_cxx_cflags=None, extra_include_paths=None,
         build_directory=None, verbose=False, **kwargs):
    """Compile `sources` into lib<name>.so and return the ctypes handle
    (reference: cpp_extension.load JIT path)."""
    build_dir = build_directory or get_build_directory()
    os.makedirs(build_dir, exist_ok=True)
    tag = hashlib.sha1(
        ("".join(sorted(sources)) + str(extra_cxx_cflags)).encode()).hexdigest()[:10]
    out = os.path.join(build_dir, f"lib{name}_{tag}.so")
    if not os.path.exists(out):
        cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17", "-o", out]
        for inc in extra_include_paths or []:
            cmd += ["-I", inc]
        cmd += list(sources)
        cmd += extra_cxx_cflags or []
        if verbose:
            print("building:", " ".join(cmd))
        subprocess.run(cmd, check=True, capture_output=not verbose)
    return ctypes.CDLL(out)


def custom_host_op(fn, out_shape_fn=None, name=None):
    """Wrap a host function (numpy in/out — e.g. a ctypes call into a
    compiled extension) as a framework op usable inside jitted programs
    via XLA custom-call (jax.pure_callback).

    fn: (*numpy arrays) -> numpy array (or tuple)
    out_shape_fn: (*ShapeDtypeStruct-like inputs) -> jax.ShapeDtypeStruct
        or list thereof; defaults to same-shape-as-first-input.
    """

    def op(*tensors, **attrs):
        def jfn(*arrays):
            if out_shape_fn is not None:
                result_shape = out_shape_fn(*arrays)
            else:
                result_shape = jax.ShapeDtypeStruct(arrays[0].shape,
                                                    arrays[0].dtype)
            call = lambda *a: fn(*[np.asarray(x) for x in a], **attrs)
            return jax.pure_callback(call, result_shape, *arrays,
                                     vmap_method="sequential")

        return apply(jfn, *tensors, name=name or getattr(fn, "__name__", "custom_op"))

    return op


# ---------------------------------------------------------------------------
# Device-side custom ops (reference: custom_operator.cc PD_BUILD_OP —
# user kernels registered as first-class framework ops with autograd)
# ---------------------------------------------------------------------------

_CUSTOM_OPS = {}


def register_custom_op(name, fn, backward=None, override=False):
    """Register a DEVICE-side custom op: `fn` is any jax-traceable
    function over arrays (jnp code or a Pallas kernel — the TPU-native
    analog of the reference's PD_BUILD_OP C++/CUDA kernels). Returns a
    Tensor-level op that runs eagerly and inside jit.compile, with
    autograd:

    - backward=None: differentiated by jax autodiff through `fn`.
    - backward=(fn): custom gradient (the PD_BUILD_GRAD_OP analog) —
      called as backward(*forward_inputs, out_cotangent, **attrs) with
      whatever keyword attrs the op call carried, returning one
      cotangent per forward INPUT (attrs get none).

    Duplicate names raise (reference PD_BUILD_OP rejects re-registration)
    unless override=True. The op is retrievable via get_custom_op(name).
    """
    if name in _CUSTOM_OPS and not override:
        raise ValueError(
            f"custom op {name!r} is already registered; pass "
            "override=True to replace it")

    def op(*tensors, **attrs):
        # attrs bind BEFORE custom_vjp so they are compile-time config,
        # not primals — the backward contract stays one-cotangent-per-
        # tensor-input regardless of attrs
        if backward is not None:
            core = jax.custom_vjp(lambda *arrays: fn(*arrays, **attrs))

            def _fwd(*args):
                return fn(*args, **attrs), args

            def _bwd(res, ct):
                out = backward(*res, ct, **attrs)
                return (tuple(out) if isinstance(out, (list, tuple))
                        else (out,))

            core.defvjp(_fwd, _bwd)
        else:
            core = lambda *arrays: fn(*arrays, **attrs)
        return apply(core, *tensors, name=name)

    op.__name__ = name
    _CUSTOM_OPS[name] = op
    return op


def get_custom_op(name):
    """Look up a previously registered custom op (reference: custom ops
    appearing under paddle.* after load)."""
    try:
        return _CUSTOM_OPS[name]
    except KeyError:
        raise KeyError(
            f"no custom op named {name!r} is registered — call "
            "register_custom_op first (registered: "
            f"{sorted(_CUSTOM_OPS)})") from None
