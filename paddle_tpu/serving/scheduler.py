"""Continuous-batching scheduler (the MPK lesson from PAPERS.md applied to
serving: scheduling lives OUTSIDE the compiled step, so one jitted decode
program serves an ever-changing request mix).

Per engine step the scheduler picks ONE of:

- a **prefill** for the head of the waiting queue (prefill-priority, the
  classic continuous-batching policy: new requests join the decode batch
  at the earliest step), chunked to the token budget
  (`max_num_batched_tokens`), admitted only when the KV pool can hold the
  chunk;
- a **decode** over every RUNNING request, after reserving each row's next
  slot — reservation failures trigger **preemption by eviction**: the
  youngest running request is swapped out (host snapshot, blocks freed,
  re-queued at the FRONT of the waiting queue so arrival order is
  preserved) until the rest fit.  Evicting the youngest minimizes wasted
  work — the oldest requests are closest to finishing.

Multi-tenant policy (ISSUE 19): requests carry a `tenant` and a
`priority` class.  Admission candidates are ordered by (priority class,
weighted tenant service, arrival) — a deficit-style fair share where every
prefill chunk and decode slot charges `tokens / weight` against the
tenant's running total, so a burst tenant's normalized service grows and
its queued requests yield the admission head to under-served tenants.
Preemption evicts lowest-priority-youngest first.  With default params
(no tenant, one priority) every ordering degenerates to the original
FIFO/youngest policy bit-for-bit.

The scheduler owns request state machines and the block accounting calls;
it never touches device math — that is `engine.LLMEngine`'s half.
"""
from __future__ import annotations

import dataclasses
import os
from collections import deque
from typing import Optional

__all__ = ["SamplingParams", "Request", "Scheduler", "SchedulerOutput",
           "PRIORITIES", "priority_rank", "tenant_weights", "should_shed",
           "worst_fast_burn"]

# Priority classes, best first.  Admission prefers lower rank; eviction
# victimizes higher rank.  Unknown strings rank with "best-effort" so a
# typo'd class degrades service instead of jumping the queue.
PRIORITIES = ("interactive", "batch", "best-effort")
_PRIORITY_RANK = {name: i for i, name in enumerate(PRIORITIES)}

# Shed threshold: best-effort traffic is shed once the worst fast-window
# SLO burn rate reaches this multiple of budget burn (2.0 = burning error
# budget at twice the sustainable rate).
_SHED_DEFAULT_BURN = 2.0


def priority_rank(priority) -> int:
    """Rank of a priority class — lower is better; unknown ranks worst."""
    return _PRIORITY_RANK.get(priority, len(PRIORITIES) - 1)


def tenant_weights(spec: Optional[str] = None) -> dict:
    """Parse a ``name:weight,name:weight`` spec (default: the
    ``PTPU_TENANT_WEIGHTS`` env var).  Unlisted tenants weigh 1.0; zero,
    negative, or malformed weights are dropped rather than raising — a
    bad env var must not take the serving loop down."""
    if spec is None:
        spec = os.environ.get("PTPU_TENANT_WEIGHTS", "")
    out: dict = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, raw = part.partition(":")
        try:
            weight = float(raw) if raw else 1.0
        except ValueError:
            continue
        if name.strip() and weight > 0:
            out[name.strip()] = weight
    return out


def worst_fast_burn(report=None) -> float:
    """Worst fast-window burn rate across all SLO objectives, 0.0 when
    the SLO engine is off (shedding never triggers without live SLOs)."""
    if report is None:
        from ..monitor import slo as mslo
        report = mslo.report()
    if not report or not report.get("enabled"):
        return 0.0
    worst = 0.0
    for obj in report.get("objectives", ()):
        rate = (obj.get("burn_rate") or {}).get("fast")
        if rate is not None:
            worst = max(worst, float(rate))
    return worst


def should_shed(priority, burn: Optional[float] = None) -> bool:
    """SLO-aware admission control: shed `priority`-class work right now?

    Only "best-effort" is ever shed — interactive and batch classes defer
    (stay queued) rather than drop.  The decision input is the worst
    fast-window burn rate from the live `monitor.slo` engine (injectable
    via `burn` for tests), against the `PTPU_SHED_BURN` threshold."""
    if priority_rank(priority) < priority_rank("best-effort"):
        return False
    if burn is None:
        burn = worst_fast_burn()
    try:
        threshold = float(os.environ.get("PTPU_SHED_BURN",
                                         _SHED_DEFAULT_BURN))
    except ValueError:
        threshold = _SHED_DEFAULT_BURN
    return burn >= threshold


@dataclasses.dataclass
class SamplingParams:
    """Per-request sampling controls — field-for-field the knobs of
    `GPTForCausalLM.generate` (the parity oracle)."""

    max_new_tokens: int = 16
    do_sample: bool = False
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    eos_token_id: Optional[int] = None
    seed: Optional[int] = None
    # wall-clock budget from admission; an expired request is aborted at
    # the next engine step via release_request() (resilience.Deadline —
    # None = no deadline).  Not a sampling knob, so absent from the dense
    # generate() oracle surface.
    deadline_s: Optional[float] = None
    # -- multi-tenant scheduling (ISSUE 19) --------------------------------
    # Tenant for weighted fair-share accounting (None = the shared default
    # pool) and priority class ("interactive" | "batch" | "best-effort").
    # Router-wire-safe: params_from_wire drops fields older peers don't
    # declare, so mixed-version fleets fall back to default-pool FIFO.
    tenant: Optional[str] = None
    priority: str = "interactive"


class Request:
    """One in-flight generation: prompt, sampling state, and progress."""

    WAITING, RUNNING, PREEMPTED, FINISHED = range(4)

    def __init__(self, req_id, prompt_ids, params: SamplingParams):
        self.req_id = req_id
        self.prompt_ids = list(int(t) for t in prompt_ids)
        self.params = params
        self.state = Request.WAITING
        self.output_ids: list = []         # generated tokens (incl. eos)
        self.num_computed = 0              # prompt tokens prefilled so far
        self.key = None                    # per-request PRNG key (engine)
        self.swap = None                   # host KV snapshot while evicted
        self.prefix_keys = None            # chained block keys (engine;
        #                                    set only with prefix caching)
        self.prefix_hit_tokens = 0         # prompt tokens adopted cached
        self.arrival = None                # admission tiebreak (set by add)
        self.deadline = None               # resilience.Deadline (engine)
        # -- observability (engine-owned; monitor.trace v2) ----------------
        self.trace = None                  # root Span, or None (trace off)
        self.queue_span = None             # open queue-wait child Span
        self.arrival_t = None              # perf_counter at add_request
        self.first_token_t = None          # perf_counter of token 1 (TTFT)
        self.last_token_t = None           # perf_counter of latest token
        # -- request-plane wide event (engine-owned; ISSUE 16) -------------
        self.arrival_ts = None             # wall clock at add_request
        self.queue_wait_s = None           # arrival to first compute
        self.tpot_max = None               # worst inter-token gap, seconds
        self.prefill_chunks = 0            # prefill passes this prompt took
        self.num_preemptions = 0           # times evicted mid-flight
        self.peak_kv_blocks = 0            # high-water KV blocks held
        self.spec_proposed = 0             # draft tokens proposed (this req)
        self.spec_accepted = 0             # draft tokens accepted (this req)
        self.finish_reason = None          # stop|abort|deadline|released|
        #                                    shed, set exactly once at finish

    # -- derived ------------------------------------------------------------

    @property
    def prompt_len(self) -> int:
        return len(self.prompt_ids)

    @property
    def total_len(self) -> int:
        return self.prompt_len + len(self.output_ids)

    @property
    def prefill_done(self) -> bool:
        return self.num_computed >= self.prompt_len

    @property
    def finished(self) -> bool:
        return self.state == Request.FINISHED

    def record_token(self, tok: int) -> None:
        self.output_ids.append(int(tok))
        p = self.params
        if len(self.output_ids) >= p.max_new_tokens or (
                p.eos_token_id is not None and int(tok) == p.eos_token_id):
            self.state = Request.FINISHED

    def __repr__(self):
        names = {0: "WAITING", 1: "RUNNING", 2: "PREEMPTED", 3: "FINISHED"}
        return (f"Request({self.req_id}, state={names[self.state]}, "
                f"prompt={self.prompt_len}, out={len(self.output_ids)})")


@dataclasses.dataclass
class SchedulerOutput:
    """What the engine must run this step."""

    kind: str                      # "prefill" | "decode" | "idle"
    prefill_request: Optional[Request] = None
    chunk_start: int = 0           # prefill: first prompt position of chunk
    chunk_len: int = 0
    decode_requests: tuple = ()    # decode: rows of the batch
    preempted: tuple = ()          # requests evicted while scheduling


class Scheduler:
    def __init__(self, cache, max_num_seqs=8, max_num_batched_tokens=2048,
                 spec_tokens=0, max_model_len=None, weights=None):
        self.cache = cache
        self.max_num_seqs = int(max_num_seqs)
        self.max_num_batched_tokens = int(max_num_batched_tokens)
        # deficit-style weighted fair share (ISSUE 19): normalized service
        # per tenant (tokens / weight), charged at prefill-chunk emission
        # and per decode slot.  `weights` overrides the env knob for
        # tests; None = PTPU_TENANT_WEIGHTS.
        self.tenant_weights = (dict(weights) if weights is not None
                               else tenant_weights())
        self.tenant_served: dict = {}
        # speculative decoding (ISSUE 15): a decode step may write up to
        # `spec_tokens` draft positions past each row's last token, so
        # the decode branch reserves blocks for that extent up front (the
        # engine rolls the table back to the ACCEPTED length after the
        # step).  Clamped per row so no write position ever reaches
        # max_model_len.
        self.spec_tokens = max(0, int(spec_tokens))
        self.max_model_len = (None if max_model_len is None
                              else int(max_model_len))
        self.waiting: deque = deque()
        self.running: list = []
        self._arrival = 0
        # ISSUE 20 memory microscope: plain-int pressure ledger the
        # engine's eviction-storm detector reads per-step deltas of
        # (always counted — two int adds per rare event, no gate)
        self.num_evictions = 0
        self.num_swap_ins = 0

    def _decode_reserve_len(self, req) -> int:
        """Token coverage the decode step needs for `req`: total_len (the
        non-spec write of position total_len-1) plus the row's REAL draft
        budget — the same clamp the engine's proposer applies, so rows
        that can never carry drafts (sampling rows, rows within one token
        of max_new_tokens or max_model_len) reserve nothing extra and
        can't evict a neighbour for blocks nobody will write."""
        extra = self.spec_tokens
        if extra:
            p = req.params
            if p.do_sample:
                extra = 0
            else:
                extra = min(extra,
                            p.max_new_tokens - len(req.output_ids) - 1)
                if self.max_model_len is not None:
                    extra = min(extra, self.max_model_len - req.total_len)
        return req.total_len + max(0, extra)

    # -- request lifecycle --------------------------------------------------

    def add(self, req: Request) -> None:
        req.arrival = self._arrival
        self._arrival += 1
        self.waiting.append(req)

    def has_work(self) -> bool:
        return bool(self.waiting or self.running)

    # -- multi-tenant fair share (ISSUE 19) --------------------------------

    @staticmethod
    def _tenant_of(req) -> str:
        return getattr(req.params, "tenant", None) or "default"

    def _charge(self, req, tokens: int) -> None:
        """Charge `tokens` of service against the request's tenant,
        normalized by its configured weight — a weight-3 tenant pays a
        third of the fair-share price per token, so it sustains 3x the
        throughput before yielding the admission head."""
        if tokens <= 0:
            return
        tenant = self._tenant_of(req)
        weight = self.tenant_weights.get(tenant, 1.0)
        self.tenant_served[tenant] = (self._served_of(tenant)
                                      + tokens / weight)

    def _served_of(self, tenant) -> float:
        got = self.tenant_served.get(tenant)
        if got is None:
            # a never-seen tenant starts at the current minimum, not 0 —
            # starting from zero would let a late joiner monopolize
            # admission until it "caught up" with incumbents' history
            got = min(self.tenant_served.values(), default=0.0)
        return got

    def _admission_key(self, req):
        """Candidate ordering for admission: priority class first, then
        least normalized tenant service, then arrival.  With default
        params every key collapses to (0, served, arrival) with `served`
        shared by all — exact FIFO."""
        return (priority_rank(getattr(req.params, "priority", None)),
                self._served_of(self._tenant_of(req)),
                req.arrival)

    # -- the policy ---------------------------------------------------------

    def schedule(self) -> SchedulerOutput:
        preempted = []
        # 1) continue a partially-prefilled running request (chunked
        #    prefill spans several steps; it must finish before decoding)
        part = next((r for r in self.running if not r.prefill_done), None)
        if part is not None:
            if self._ensure_blocks(
                    part, min(part.prompt_len,
                              part.num_computed
                              + self.max_num_batched_tokens),
                    preempted, protect=part):
                return self._emit_prefill(part, preempted)
            return SchedulerOutput(kind="idle", preempted=tuple(preempted))
        # 2) admit / resume from the waiting queue (no eviction on behalf
        #    of admission — preemption exists to keep RUNNING work
        #    progressing, not to thrash between queued requests).  The
        #    admission head is the best (priority, fair-share, arrival)
        #    candidate — plain FIFO when every request carries defaults —
        #    and the deque itself is never reordered; when the head is
        #    blocked and NOTHING is running, any other schedulable entry
        #    (e.g. a forked child already holding shared blocks whose
        #    completion will free them) is tried before declaring the
        #    pool too small.
        if self.waiting and len(self.running) < self.max_num_seqs:
            order = sorted(self.waiting, key=self._admission_key)
            got = self._admit_or_resume(order[0], preempted)
            if isinstance(got, SchedulerOutput):
                return got
            if got is None and not self.running:
                for req in order[1:]:
                    got = self._admit_or_resume(req, preempted)
                    if isinstance(got, SchedulerOutput):
                        return got
                    if got:
                        break
                else:
                    head = self.waiting[0]
                    if head.swap is not None:
                        raise RuntimeError(
                            "KV cache too small: an evicted request can "
                            "never be restored "
                            f"(free={self.cache.num_free_blocks} blocks, "
                            f"needs {len(head.swap['k'][0])})")
                    raise RuntimeError(
                        "KV cache too small: cannot hold a single request "
                        f"(free={self.cache.num_free_blocks} blocks, "
                        "prompt chunk needs "
                        f"{self.cache.blocks_needed(min(head.prompt_len, self.max_num_batched_tokens))})")
            # got is True: a swap-resume landed in running with no step to
            # emit (mid-prefill resumes continue via branch 1 next call)
        # 3) decode every running request, reserving one slot per row
        if self.running:
            rows = []
            for req in list(self.running):   # oldest first
                if req.state != Request.RUNNING or not req.prefill_done:
                    continue                 # evicted mid-loop / mid-prefill
                # this step writes position total_len - 1 (the last
                # sampled token's K/V) — coverage of total_len tokens is
                # exactly enough (one more would take a block a step
                # early) — plus the speculative draft extent when spec
                # decoding is on (rolled back to the accepted length by
                # the engine after the step)
                reserve = self._decode_reserve_len(req)
                if not self._ensure_blocks(req, reserve, preempted,
                                           protect=req):
                    continue                 # req itself was evicted
                self.cache.grow_to(req.req_id, reserve)
                rows.append(req)
            # a LATER row's reservation may have evicted an EARLIER row
            # that already made it into the batch — a preempted row's
            # table is gone, so it must not reach the engine
            rows = [r for r in rows if r.state == Request.RUNNING]
            if rows:
                for r in rows:       # one decode slot = one token served
                    self._charge(r, 1)
                return SchedulerOutput(kind="decode",
                                       decode_requests=tuple(rows),
                                       preempted=tuple(preempted))
        return SchedulerOutput(kind="idle", preempted=tuple(preempted))

    def _admit_or_resume(self, req, preempted):
        """Try to start `req`: returns a SchedulerOutput to emit (a
        prefill step), True when a swap-resume landed in `running` with
        no step to emit, or None when it cannot start right now."""
        if req.swap is not None:
            if not self._can_swap_in(req):
                return None
            self.waiting.remove(req)
            self.cache.swap_in(req.req_id, req.swap)
            req.swap = None
            req.state = Request.RUNNING
            self.running.append(req)
            self.num_swap_ins += 1
            return True
        start = req.num_computed    # >0 only for forked children, which
        #                             already hold (shared) prefix blocks.
        forked = req.req_id in self.cache._tables
        # Automatic prefix caching (ISSUE 15): a fresh request first
        # matches its chained block keys against the prefix index and
        # adopts the longest cached run by refcount bump — capped below
        # the full prompt (the last prompt token must be recomputed for
        # its logits) and block-aligned (only full, never-rewritten
        # blocks are shared).  Adoption happens ONLY when the remaining
        # chunk also fits, so a failed admission holds no blocks.
        hit_blocks = 0
        if (not forked and start == 0 and req.prefix_keys
                and not req.prefix_hit_tokens):
            hit_blocks = self.cache.match_prefix(
                req.prefix_keys,
                max_blocks=(req.prompt_len - 1) // self.cache.block_size)
        # The prefill-chunking token budget counts only UNCACHED tokens:
        # a prefix-hit request's chunk starts at the first uncached
        # token, so a hot request admits its real remaining work instead
        # of being under-batched by its (already-paid) cached prefix.
        hit_tokens = hit_blocks * self.cache.block_size
        chunk = min(req.prompt_len - start - hit_tokens,
                    self.max_num_batched_tokens)
        target = start + hit_tokens + chunk
        if hit_blocks:
            need = self.cache.blocks_needed(target) - hit_blocks
            fits = need <= self.cache.adoptable_free_blocks(
                req.prefix_keys, hit_blocks)
        elif forked:
            fits = self.cache.can_grow_to(req.req_id, target)
        else:
            fits = (self.cache.blocks_needed(target)
                    <= self.cache.num_free_blocks)
        if not fits:
            return None
        self.waiting.remove(req)
        if hit_blocks:
            req.prefix_hit_tokens = self.cache.adopt_prefix(
                req.req_id, req.prefix_keys, hit_blocks)
            req.num_computed = req.prefix_hit_tokens
            start = req.num_computed
            self.cache.grow_to(req.req_id, target)
        elif forked:
            self.cache.grow_to(req.req_id, target)
        else:
            self.cache.allocate(req.req_id, target)
        req.state = Request.RUNNING
        self.running.append(req)
        self._charge(req, chunk)
        return SchedulerOutput(kind="prefill", prefill_request=req,
                               chunk_start=start, chunk_len=chunk,
                               preempted=tuple(preempted))

    def _emit_prefill(self, req, preempted) -> SchedulerOutput:
        start = req.num_computed
        chunk = min(req.prompt_len - start, self.max_num_batched_tokens)
        self.cache.grow_to(req.req_id, start + chunk)
        self._charge(req, chunk)
        return SchedulerOutput(
            kind="prefill", prefill_request=req, chunk_start=start,
            chunk_len=chunk, preempted=tuple(preempted))

    # -- eviction -----------------------------------------------------------

    def _can_swap_in(self, req) -> bool:
        return len(req.swap["k"][0]) <= self.cache.num_free_blocks

    def _ensure_blocks(self, req, target_len, preempted, protect=None) -> bool:
        """Make the pool able to cover `target_len` for `req`, evicting
        youngest-first as needed.  Returns False if `req` itself had to be
        evicted (nothing younger was left to take)."""
        while not self.cache.can_grow_to(req.req_id, target_len):
            victim = self._pick_victim(exclude=protect)
            if victim is None:
                # self-eviction only helps when someone ELSE still holds
                # blocks (e.g. forked children in the waiting queue); a
                # request that cannot fit in the EMPTY pool would evict
                # itself, swap back in, and livelock forever — raise
                need = self.cache.blocks_needed(target_len) + (
                    1 if self.cache._needs_cow(req.req_id, target_len)
                    else 0)
                if need > self.cache.num_blocks:
                    raise RuntimeError(
                        "KV cache too small: request needs "
                        f"{self.cache.blocks_needed(target_len)} blocks "
                        f"for {target_len} tokens but the pool holds only "
                        f"{self.cache.num_blocks}; raise "
                        "EngineConfig.num_blocks or lower max_new_tokens")
                if protect is not None and protect in self.running:
                    self._evict(protect, preempted)
                    return False
                raise RuntimeError(
                    "KV cache too small: cannot hold a single request "
                    f"(free={self.cache.num_free_blocks} blocks, request "
                    f"needs {self.cache.blocks_needed(target_len)})")
            self._evict(victim, preempted)
        return True

    def _pick_victim(self, exclude=None):
        # lowest priority class first, then youngest ARRIVAL — not list
        # position: swap-ins re-append resumed (older) requests at the
        # tail, so list order is not age order.  One priority class in
        # play reduces this to the original youngest-arrival pick.
        victims = [r for r in self.running if r is not exclude]
        if not victims:
            return None
        return max(victims, key=lambda r: (
            priority_rank(getattr(r.params, "priority", None)), r.arrival))

    def _evict(self, req, preempted) -> None:
        req.swap = self.cache.swap_out(req.req_id)
        req.state = Request.PREEMPTED
        self.running.remove(req)
        self.waiting.appendleft(req)             # keeps arrival order
        preempted.append(req)
        self.num_evictions += 1

    # -- completion ---------------------------------------------------------

    def retire_finished(self) -> tuple:
        done = tuple(r for r in self.running if r.finished)
        for req in done:
            self.cache.free(req.req_id)
            self.running.remove(req)
        return done
