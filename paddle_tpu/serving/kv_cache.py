"""Block-paged KV cache with a free-list allocator (the vLLM/Ragged-Paged-
Attention memory model, re-grown for this stack; PAPERS.md).

The dense decode path (`GPTForCausalLM.init_caches`) allocates a
``[B, S_max, H*D]`` ring per request — O(S_max) HBM per request no matter
how short the request actually is.  `BlockKVCache` instead pools K/V in
fixed-size physical blocks

    k_blocks[l], v_blocks[l] : [num_blocks, block_size, H, D]   per layer

and gives each sequence a *block table* (list of physical block ids), so
a request holds exactly ``ceil(len / block_size)`` blocks and frees them
the moment it finishes.  The device arrays are plain jax buffers owned by
this object; the engine's jitted step takes them donated and returns the
updated pool.

Allocator design (host-side, O(1) per op):

- **free list** — LIFO stack of physical ids; `Block` objects carry a
  refcount.
- **copy-on-fork** — `fork(parent, child)` shares every parent block by
  bumping refcounts (shared-prompt serving: N continuations of one prompt
  pay its KV once).  The first append into a SHARED last block triggers
  copy-on-write: a fresh block is allocated and the shared content copied
  device-side (`_copy_block`).
- **preemption by eviction** — `swap_out(seq)` snapshots the sequence's
  block contents to host numpy and frees the blocks; `swap_in(seq)`
  restores them bit-exactly into freshly allocated blocks.  Bit-exact
  restore is what makes "preempted requests resume with identical
  output" a guarantee instead of a tolerance (a recompute-from-prompt
  resume would re-run prefill over a different chunk length and shift
  last-ulp floats).

Every transition asserts the refcount/free-list invariants — the
allocator can never hand out a block that is still referenced
(tests/test_serving.py fuzzes this).

**Automatic prefix caching** (ISSUE 15 — the engine opts in via
``EngineConfig(enable_prefix_caching=True)``; with nobody registering,
nothing below changes behaviour):

- **prefix index** — a hash-keyed map over FULL blocks.  Keys are
  *chained* content digests (`prefix_block_keys`): block j's key hashes
  (key_{j-1}, tokens_of_block_j), so one key identifies an entire
  block-aligned token prefix — the radix-trie-equivalent over block
  hashes.  sha1 digests, not python ``hash()``: a collision would adopt
  WRONG KV silently, and int-tuple hashes are also what PYTHONHASHSEED
  reseeding taught PR 2 to distrust.
- **adoption** — `match_prefix` walks the chain to the longest indexed
  prefix; `adopt_prefix` builds a new sequence's table from those
  physical blocks by refcount bump — N requests sharing a system prompt
  pay its prefill ONCE.  Only FULL blocks are ever indexed/adopted (a
  full block is never written again while referenced, so sharing needs
  no CoW), and adoption is capped below the full prompt by the caller
  (the last prompt token must be recomputed for its logits).
- **LRU parking** — a block whose refcount drops to 0 while indexed is
  PARKED on an LRU instead of the free list: its content stays adoptable
  and it is reclaimed LAST (`_take` drains the free list first, then
  evicts the least-recently-used parked block, dropping its index
  entry).  Parked blocks count as allocatable capacity
  (`num_free_blocks`) but NOT as free for the utilization gauges
  (`blocks_in_use` includes them — they hold live, reusable bytes).
- observability: `serving/prefix_hits` / `prefix_hit_tokens` /
  `prefix_evictions` counters (monitor-gated no-ops when PTPU_MONITOR
  is off) plus the plain-int twins on the instance.  The memory
  microscope (ISSUE 20) adds a per-pool lifecycle ledger
  (``self.acct``, `monitor.memory.KVAccounting`): every transition —
  alloc/free/fork/cow/park/adopt/evict/swap_out/swap_in — counts under
  ``serving/kv_blocks{event}``, parked blocks carry their park
  timestamp (the residency-age forensics), and every capacity view
  (`num_free_blocks` / `num_parked_blocks` / `blocks_in_use` /
  `utilization`) derives from the ONE `counts()` source so the
  utilization gauge and the admission budget can never drift apart.

**Speculative-decode rollback** (`truncate_to`): the verify step
reserves blocks for up to k draft positions; rejected drafts roll the
table back by releasing the surplus blocks — slots inside kept blocks
that held rejected K/V are re-written by later real tokens before any
mask lets a query read them.

**Quantized mode** (``kv_quant="int8"``, the `paddle_tpu.lowbit` KV
wing): pools store int8 codes plus per-block-per-head float32 scales
(``k_scales[l], v_scales[l] : [num_blocks, num_heads]``, value =
code·scale).  A block costs ``block_size·H·D + 4·H`` bytes instead of
``block_size·H·D·itemsize`` — ~¼ of fp32, ~½ of bf16 — so the same pool
byte budget holds ~2–4× the blocks (`block_bytes` does the accounting;
the engine sizes the default pool by BYTES, not block count).  Scales
ride every block operation: copied on CoW, saved/restored through
swap_out/swap_in (bit-stable in the quantized domain), and zeroed when a
block is reallocated (`_reset_scales`).
"""
from __future__ import annotations

import hashlib
import struct
import time
from collections import OrderedDict

import numpy as np
import jax.numpy as jnp

from .. import monitor
from ..monitor import memory as mmemory

__all__ = ["BlockKVCache", "BlockAllocatorError", "prefix_block_keys"]


class BlockAllocatorError(RuntimeError):
    pass


def prefix_block_keys(token_ids, block_size) -> list:
    """Chained content keys for every FULL block of `token_ids`.

    key_j = sha1(key_{j-1} || tokens[j*bs:(j+1)*bs]) — equal keys imply
    equal block-aligned token prefixes, so a single dict lookup per block
    walks the radix-trie-equivalent.  Deterministic across processes
    (PYTHONHASHSEED-free) and collision-safe in practice (adopting on a
    collision would serve another prompt's KV)."""
    bs = int(block_size)
    keys = []
    prev = b""
    for j in range(len(token_ids) // bs):
        block = token_ids[j * bs:(j + 1) * bs]
        prev = hashlib.sha1(
            prev + struct.pack(f"<{bs}q", *[int(t) for t in block])
        ).digest()
        keys.append(prev)
    return keys


class _Block:
    __slots__ = ("idx", "ref")

    def __init__(self, idx):
        self.idx = idx
        self.ref = 0


class BlockKVCache:
    def __init__(self, num_layers, num_blocks, block_size, num_heads,
                 head_dim, dtype=jnp.float32, kv_quant=None):
        if kv_quant not in (None, "int8"):
            raise ValueError(
                f'kv_quant must be None or "int8", got {kv_quant!r}')
        self.num_layers = int(num_layers)
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.dtype = dtype
        self.kv_quant = kv_quant
        shape = (self.num_blocks, self.block_size, self.num_heads,
                 self.head_dim)
        pool_dtype = jnp.int8 if kv_quant else dtype
        self.k_blocks = [jnp.zeros(shape, pool_dtype)
                         for _ in range(num_layers)]
        self.v_blocks = [jnp.zeros(shape, pool_dtype)
                         for _ in range(num_layers)]
        if kv_quant:
            # per-block-per-head abs-max scales: value = code * scale
            sshape = (self.num_blocks, self.num_heads)
            self.k_scales = [jnp.zeros(sshape, jnp.float32)
                             for _ in range(num_layers)]
            self.v_scales = [jnp.zeros(sshape, jnp.float32)
                             for _ in range(num_layers)]
        else:
            self.k_scales = self.v_scales = None
        self._blocks = [_Block(i) for i in range(self.num_blocks)]
        self._free = list(range(self.num_blocks - 1, -1, -1))  # LIFO
        self._tables: dict = {}        # seq_id -> [physical ids]
        self._lengths: dict = {}       # seq_id -> token count covered
        self.peak_blocks_in_use = 0
        # ISSUE 20 memory microscope: per-pool lifecycle ledger
        # (serving/kv_blocks{event} + parked-residency histogram) — one
        # module-global check per hook when PTPU_MEMOBS is off
        self.acct = mmemory.KVAccounting()
        # -- prefix cache (ISSUE 15; inert until register_prefix) ----------
        self._prefix_index: dict = {}  # chain key (bytes) -> physical id
        self._block_key: dict = {}     # physical id -> chain key
        self._chain_of: dict = {}      # physical id -> chain id (the
        #                                register_prefix registration it
        #                                was indexed under — groups the
        #                                /kv "parked chains" view)
        self._lru: "OrderedDict" = OrderedDict()   # parked id ->
        #                                monotonic park timestamp, LRU
        #                                first (the timestamp feeds the
        #                                residency-age forensics)
        self.prefix_hits = 0
        self.prefix_hit_tokens = 0
        self.prefix_evictions = 0
        self._m_hits = monitor.counter(
            "serving/prefix_hits", "requests that adopted cached prefix "
            "blocks at admission")
        self._m_hit_toks = monitor.counter(
            "serving/prefix_hit_tokens",
            "prompt tokens whose prefill was paid by a cached prefix")
        self._m_evict = monitor.counter(
            "serving/prefix_evictions",
            "parked prefix blocks reclaimed for fresh allocations")

    # -- introspection ------------------------------------------------------

    @staticmethod
    def block_bytes(block_size, num_heads, head_dim, dtype=jnp.float32,
                    kv_quant=None) -> int:
        """Bytes ONE physical block costs per layer (K + V pools, plus the
        per-block-per-head f32 scales when quantized)."""
        per_tok = int(num_heads) * int(head_dim)
        if kv_quant == "int8":
            return 2 * (int(block_size) * per_tok + 4 * int(num_heads))
        return 2 * int(block_size) * per_tok * np.dtype(dtype).itemsize

    @property
    def bytes_per_block(self) -> int:
        """Bytes one block costs across all layers."""
        return self.num_layers * self.block_bytes(
            self.block_size, self.num_heads, self.head_dim, self.dtype,
            self.kv_quant)

    @property
    def pool_bytes(self) -> int:
        return self.num_blocks * self.bytes_per_block

    @property
    def num_slots(self) -> int:
        """Total physical token slots — also the ragged kernel's
        "dropped write" sentinel: a slot id >= num_slots marks a padding
        / evicted row whose write must be discarded, never clamped.
        (The per-row true lengths the kernel bounds its block stream by
        come from the engine's Request state — `req.total_len` is the
        authoritative value at decode time.)"""
        return self.num_blocks * self.block_size

    def counts(self) -> dict:
        """The ONE accounting source every capacity view derives from
        (ISSUE 20 satellite: the utilization gauge and the admission-
        capacity view were computed in two places and could drift).
        Invariants: ``free + in_use == total`` and
        ``allocatable == free + parked`` — parked prefix blocks are
        allocatable (reclaimed last by `_take`) but IN-USE for the
        utilization view (they hold live, reusable bytes)."""
        free = len(self._free)
        parked = len(self._lru)
        return {
            "total": self.num_blocks,
            "free": free,
            "parked": parked,
            "allocatable": free + parked,
            "in_use": self.num_blocks - free,
            "referenced": self.num_blocks - free - parked,
            "peak_in_use": self.peak_blocks_in_use,
        }

    @property
    def num_free_blocks(self) -> int:
        """ALLOCATABLE blocks: truly free plus LRU-parked prefix blocks
        (parked blocks are reclaimed — last — by `_take`), the number
        admission decisions budget against."""
        return self.counts()["allocatable"]

    @property
    def num_parked_blocks(self) -> int:
        """Unreferenced blocks held by the prefix index (adoptable AND
        reclaimable)."""
        return self.counts()["parked"]

    @property
    def blocks_in_use(self) -> int:
        """Blocks holding live bytes — referenced OR parked.  Parked
        prefix blocks are deliberately counted in-use: the utilization
        gauges must not report reusable-cache bytes as free capacity."""
        return self.counts()["in_use"]

    @property
    def utilization(self) -> float:
        """`serving/block_utilization`'s value, derived from the same
        `counts()` source as every other capacity view."""
        c = self.counts()
        return c["in_use"] / max(c["total"], 1)

    def block_table(self, seq_id):
        return list(self._tables[seq_id])

    def padded_table(self, seq_id, width):
        """Block table padded to `width` entries with num_blocks (an
        out-of-range id — `paged_gather` clips it, masks cover it)."""
        t = self._tables[seq_id]
        if len(t) > width:
            raise BlockAllocatorError(
                f"sequence {seq_id} spans {len(t)} blocks > table width "
                f"{width}")
        return t + [self.num_blocks] * (width - len(t))

    def slot(self, seq_id, position) -> int:
        """Physical slot of an (allocated) token position."""
        t = self._tables[seq_id]
        return t[position // self.block_size] * self.block_size \
            + position % self.block_size

    def blocks_needed(self, num_tokens) -> int:
        return -(-int(num_tokens) // self.block_size)

    # -- allocate / grow / free --------------------------------------------

    def _take(self) -> int:
        if self._free:
            i = self._free.pop()
        elif self._lru:
            # reclaimed LAST, least-recently-used first: the parked block
            # stops being adoptable the moment its bytes are handed out
            i, parked_ts = self._lru.popitem(last=False)
            self._drop_index(i)
            self.prefix_evictions += 1
            self._m_evict.inc()
            self.acct.on("evict")
            if parked_ts is not None:
                self.acct.observe_residency(
                    max(0.0, time.monotonic() - parked_ts))
        else:
            raise BlockAllocatorError("out of KV blocks")
        blk = self._blocks[i]
        assert blk.ref == 0, f"free list handed out a referenced block {i}"
        blk.ref = 1
        self.acct.on("alloc")
        self.peak_blocks_in_use = max(self.peak_blocks_in_use,
                                      self.blocks_in_use)
        return i

    def _release(self, idx):
        blk = self._blocks[idx]
        assert blk.ref > 0, f"double free of block {idx}"
        blk.ref -= 1
        if blk.ref == 0:
            if idx in self._block_key:
                # indexed prefix block: park (content stays adoptable)
                self._lru[idx] = time.monotonic()
                self._lru.move_to_end(idx)
                self.acct.on("park")
            else:
                self._free.append(idx)
                self.acct.on("free")

    def _drop_index(self, idx) -> None:
        key = self._block_key.pop(idx, None)
        if key is not None:
            self._prefix_index.pop(key, None)
        self._chain_of.pop(idx, None)

    def _needs_cow(self, seq_id, num_tokens) -> bool:
        """Will growing to `num_tokens` write into a SHARED partially-
        filled last block?  (A full shared block is never written again —
        new tokens land in fresh blocks — so it can stay shared.)"""
        t = self._tables.get(seq_id)
        old = self._lengths.get(seq_id, 0)
        return bool(t) and num_tokens > old \
            and old % self.block_size != 0 \
            and self._blocks[t[-1]].ref > 1

    def can_grow_to(self, seq_id, num_tokens) -> bool:
        """Enough free blocks (plus a possible copy-on-write block) to
        cover `num_tokens` for this sequence?"""
        have = len(self._tables.get(seq_id, ()))
        need = self.blocks_needed(num_tokens) - have
        if self._needs_cow(seq_id, num_tokens):
            need += 1              # CoW of the shared last block
        return need <= self.num_free_blocks

    def allocate(self, seq_id, num_tokens):
        """Register `seq_id` and give it blocks covering `num_tokens`."""
        if seq_id in self._tables:
            raise BlockAllocatorError(f"sequence {seq_id} already allocated")
        need = self.blocks_needed(num_tokens)
        if need > self.num_free_blocks:
            raise BlockAllocatorError("out of KV blocks")
        ids = [self._take() for _ in range(need)]
        self._tables[seq_id] = ids
        self._lengths[seq_id] = int(num_tokens)
        self._reset_scales(ids)

    def grow_to(self, seq_id, num_tokens):
        """Extend a sequence's table to cover `num_tokens` tokens,
        copy-on-writing a shared partially-filled last block first (the
        append target must be privately owned — forked siblings keep
        reading the original)."""
        t = self._tables[seq_id]
        if self._needs_cow(seq_id, num_tokens):
            self._cow_last_block(seq_id)
        new_ids = []
        while len(t) < self.blocks_needed(num_tokens):
            new_ids.append(self._take())
            t.append(new_ids[-1])
        self._lengths[seq_id] = max(self._lengths[seq_id], int(num_tokens))
        self._reset_scales(new_ids)

    def free(self, seq_id):
        for idx in self._tables.pop(seq_id):
            self._release(idx)
        self._lengths.pop(seq_id, None)

    def truncate_to(self, seq_id, num_tokens):
        """Shrink a sequence's table to cover exactly `num_tokens` tokens
        — the speculative-decode rollback: blocks reserved for rejected
        draft positions are released (decref — a shared block survives
        for its other holders).  Slots inside KEPT blocks that held
        rejected K/V are overwritten by later real tokens before any
        causal mask lets a query read them."""
        t = self._tables[seq_id]
        keep = self.blocks_needed(num_tokens)
        while len(t) > keep:
            self._release(t.pop())
        self._lengths[seq_id] = min(self._lengths[seq_id],
                                    int(num_tokens))

    # -- copy-on-fork -------------------------------------------------------

    def fork(self, parent_id, child_id):
        """Share the parent's blocks with a new sequence (refcount bump —
        no copy until one of them appends into the shared last block)."""
        if child_id in self._tables:
            raise BlockAllocatorError(f"sequence {child_id} already exists")
        t = self._tables[parent_id]
        for idx in t:
            self._blocks[idx].ref += 1
        self._tables[child_id] = list(t)
        self._lengths[child_id] = self._lengths[parent_id]
        self.acct.on("fork", len(t))

    def _reset_scales(self, ids):
        """Zero the quant scales of freshly (re)allocated blocks — a
        block's scale only grows while it is owned, so a reallocated
        block must not inherit the previous owner's dynamic range."""
        if not self.kv_quant or not ids:
            return
        idx = jnp.asarray(ids, jnp.int32)
        for l in range(self.num_layers):
            self.k_scales[l] = self.k_scales[l].at[idx].set(0.0)
            self.v_scales[l] = self.v_scales[l].at[idx].set(0.0)

    def _copy_block(self, src, dst):
        for l in range(self.num_layers):
            self.k_blocks[l] = self.k_blocks[l].at[dst].set(
                self.k_blocks[l][src])
            self.v_blocks[l] = self.v_blocks[l].at[dst].set(
                self.v_blocks[l][src])
            if self.kv_quant:
                self.k_scales[l] = self.k_scales[l].at[dst].set(
                    self.k_scales[l][src])
                self.v_scales[l] = self.v_scales[l].at[dst].set(
                    self.v_scales[l][src])

    def _cow_last_block(self, seq_id):
        t = self._tables[seq_id]
        src = t[-1]
        dst = self._take()
        self._copy_block(src, dst)
        t[-1] = dst
        self._release(src)
        self.acct.on("cow")

    # -- automatic prefix caching (ISSUE 15) --------------------------------

    def register_prefix(self, seq_id, keys, num_tokens) -> None:
        """Index `seq_id`'s fully-written leading blocks under their
        chain keys (`prefix_block_keys` of the prompt).  Only blocks
        wholly inside the first `num_tokens` computed tokens are indexed
        — a full block is never written again while referenced, so its
        content is final.  First writer wins: an existing key keeps
        pointing at the original block (dedup, not re-pointing)."""
        t = self._tables[seq_id]
        full = min(len(keys), int(num_tokens) // self.block_size, len(t))
        # chain id: the chain's FIRST key names the whole registration
        # (stable across re-registrations — first writer wins below), so
        # the /kv pool map can group parked blocks back into the prompt
        # chain they came from (ISSUE 20)
        chain = keys[0].hex()[:12] if full else None
        for j in range(full):
            key = keys[j]
            if key in self._prefix_index:
                continue
            idx = t[j]
            if idx in self._block_key:
                continue   # already indexed under another chain
            self._prefix_index[key] = idx
            self._block_key[idx] = key
            self._chain_of[idx] = chain

    def match_prefix(self, keys, max_blocks=None) -> int:
        """Longest indexed prefix of `keys`, in blocks.  Walks the chain
        in order and stops at the first miss; refreshes the recency of
        every parked block it matches."""
        limit = len(keys) if max_blocks is None else min(len(keys),
                                                        int(max_blocks))
        n = 0
        for j in range(limit):
            idx = self._prefix_index.get(keys[j])
            if idx is None:
                break
            if idx in self._lru:
                self._lru.move_to_end(idx)
            n += 1
        return n

    def adoptable_free_blocks(self, keys, n_blocks) -> int:
        """`num_free_blocks` minus the first `n_blocks` matched blocks
        that are currently PARKED — adopting those revives them, so an
        admission check must not count them as reclaimable capacity
        too (the double-count would admit a request that cannot fit)."""
        parked = sum(1 for key in keys[:n_blocks]
                     if self._prefix_index.get(key) in self._lru)
        return self.num_free_blocks - parked

    def adopt_prefix(self, seq_id, keys, n_blocks) -> int:
        """Start `seq_id` from the cached chain: its table begins with
        the `n_blocks` indexed physical blocks (refcount bump — parked
        blocks are revived off the LRU; no bytes move).  Returns the
        adopted token count, which the caller records as the sequence's
        already-computed prefix."""
        if seq_id in self._tables:
            raise BlockAllocatorError(f"sequence {seq_id} already exists")
        ids = []
        for key in keys[:n_blocks]:
            idx = self._prefix_index[key]
            blk = self._blocks[idx]
            if blk.ref == 0:
                self._lru.pop(idx, None)
            blk.ref += 1
            ids.append(idx)
        self._tables[seq_id] = ids
        hit_tokens = len(ids) * self.block_size
        self._lengths[seq_id] = hit_tokens
        self.acct.on("adopt", len(ids))
        self.peak_blocks_in_use = max(self.peak_blocks_in_use,
                                      self.blocks_in_use)
        if ids:
            self.prefix_hits += 1
            self.prefix_hit_tokens += hit_tokens
            self._m_hits.inc()
            self._m_hit_toks.inc(hit_tokens)
        return hit_tokens

    def privatize_last_block(self, seq_id):
        """Copy the sequence's last block now if it is shared.  A forked
        child RE-WRITES its final inherited position (it re-feeds the
        parent's last sampled token through its own prefill), and that
        slot must never land in a block the parent still reads — two
        jitted programs recomputing the same K/V may differ in the last
        ulp."""
        t = self._tables[seq_id]
        if t and self._blocks[t[-1]].ref > 1:
            self._cow_last_block(seq_id)

    # -- preemption swap ----------------------------------------------------

    def swap_out(self, seq_id):
        """Evict: host-snapshot the sequence's block contents and free its
        blocks.  Returns the opaque saved state for `swap_in`."""
        t = self._tables[seq_id]
        idx = np.asarray(t, np.int32)
        saved = {
            "len": self._lengths[seq_id],
            "k": [np.asarray(k[idx]) for k in self.k_blocks],
            "v": [np.asarray(v[idx]) for v in self.v_blocks],
        }
        if self.kv_quant:
            # codes alone are meaningless — the scales ARE the values'
            # exponents; saving both is what keeps the quantized domain
            # bit-stable across evict/restore
            saved["ks"] = [np.asarray(s[idx]) for s in self.k_scales]
            saved["vs"] = [np.asarray(s[idx]) for s in self.v_scales]
        self.acct.on("swap_out", len(t))
        self.free(seq_id)
        return saved

    def swap_in(self, seq_id, saved):
        """Restore an evicted sequence bit-exactly into fresh blocks."""
        n = len(saved["k"][0])
        if n > self.num_free_blocks:
            raise BlockAllocatorError("out of KV blocks")
        self.acct.on("swap_in", n)
        self._tables[seq_id] = [self._take() for _ in range(n)]
        self._lengths[seq_id] = saved["len"]
        idx = jnp.asarray(self._tables[seq_id], jnp.int32)
        for l in range(self.num_layers):
            self.k_blocks[l] = self.k_blocks[l].at[idx].set(
                jnp.asarray(saved["k"][l]))
            self.v_blocks[l] = self.v_blocks[l].at[idx].set(
                jnp.asarray(saved["v"][l]))
            if self.kv_quant:
                self.k_scales[l] = self.k_scales[l].at[idx].set(
                    jnp.asarray(saved["ks"][l]))
                self.v_scales[l] = self.v_scales[l].at[idx].set(
                    jnp.asarray(saved["vs"][l]))
