"""paddle_tpu.serving — continuous-batching LLM inference on a paged KV
cache (Ragged Paged Attention + MPK-style runtime scheduling; PAPERS.md).

Quickstart::

    from paddle_tpu.serving import LLMEngine, EngineConfig, SamplingParams
    from paddle_tpu.models import GPTForCausalLM, gpt_test_config

    model = GPTForCausalLM(gpt_test_config(stacked_blocks=True))
    engine = LLMEngine(model, EngineConfig(block_size=16))
    outs = engine.generate([prompt_a, prompt_b],
                           SamplingParams(max_new_tokens=32))

Layers (each its own module, each independently testable):

- `kv_cache.BlockKVCache` — block pool + free-list allocator, per-request
  block tables, copy-on-fork, bit-exact eviction swap, and the automatic
  prefix-cache index (chained block keys, LRU-parked unreferenced
  blocks; `prefix_block_keys`).
- `scheduler.Scheduler`  — waiting queue, token-budget admission (with
  longest-cached-prefix adoption), preemption-by-eviction;
  `SamplingParams` / `Request` state machines.
- `spec.propose_ngram`   — stdlib n-gram/prompt-lookup draft proposal
  for speculative decoding (no second model).
- `engine.LLMEngine`     — jitted prefill/decode/sample step programs over
  `ops.ragged_paged_attention` (default: ONE fixed-shape fused
  update+attend decode program; `ops.paged_attention` is the bucketed
  fallback), token-for-token equal to the dense
  `GPTForCausalLM.generate` (tests/test_serving.py pins it); with
  `EngineConfig(speculative_tokens=k)` a fixed-shape multi-token verify
  program emits several accepted tokens per decode step.
- `router.Router`        — the multi-replica tier (ISSUE 17):
  prefix-cache-aware sticky routing over N engine replicas, optional
  disaggregated prefill/decode (bit-exact KV handoff), drain/failover;
  `replica.ReplicaWorker` is the engine-owning worker half.
- `api.ApiServer`        — the OpenAI-compatible HTTP front door
  (ISSUE 19): /v1/completions + /v1/chat/completions with SSE token
  streaming, API-key → tenant mapping, deadline propagation and
  SLO-aware 429 shedding, over a local engine or the router.

The user-facing entry point also hangs off `paddle_tpu.inference`
(`inference.LLMEngine` etc.), next to the Predictor serving surface.
"""
from .kv_cache import (BlockAllocatorError, BlockKVCache,
                       prefix_block_keys)
from .scheduler import Request, SamplingParams, Scheduler, SchedulerOutput
from .spec import propose_ngram
from .engine import EngineConfig, LLMEngine
from .router import Router, RouterConfig, RpcReplicaClient
from .replica import ReplicaWorker
from .api import ApiServer, start_api_server

__all__ = [
    "ApiServer", "BlockAllocatorError", "BlockKVCache", "EngineConfig",
    "LLMEngine", "ReplicaWorker", "Request", "Router", "RouterConfig",
    "RpcReplicaClient", "SamplingParams", "Scheduler", "SchedulerOutput",
    "prefix_block_keys", "propose_ngram", "start_api_server",
]
