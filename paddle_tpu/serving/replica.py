"""Replica worker — the engine-owning half of the multi-replica tier.

`ReplicaWorker` wraps one `LLMEngine` behind the router protocol
(`serving/router.py` builds the frames; `monitor/wire.py` declares
them).  The split is thread-shaped: `distributed/rpc.py` delivers
`_remote_submit` / `_remote_adopt` / `_remote_poll` on its serve
threads, which only touch lock-guarded deques — admission, stepping,
export and harvest all happen in `pump()`, on whatever thread owns the
engine (jax programs are driven from exactly one place).  One `pump()`
is one cycle: drain check → admit inbox → `engine.step()` → harvest
(results, prefill handoffs, deadline expiries).

Roles (`RouterConfig.disaggregate` routes on them):

- ``both`` (default) — classic replica: prefill + decode locally.
- ``prefill`` — runs prompt prefills and samples the FIRST token, then
  exports the request (`LLMEngine.export_request`: evolved PRNG key +
  bit-exact `swap_out` KV snapshot) as a handoff frame the router
  forwards to a decode worker.  Absorbs the compile-heavy long-prompt
  program ladder.
- ``decode`` — only ever receives handoffs (`adopt_request` rides the
  scheduler's swap-resume path), so it dispatches exactly one
  fixed-shape ``ragged(max_num_seqs, 1)`` program, forever.

Drain (SIGTERM via `resilience.PreemptionHandler`, or `start_drain()`):
admission stops (`submit_local` returns False — the router re-routes),
never-computed WAITING requests are released with reason ``migrated``
and returned to the router as requeued submit frames, and the running
ones finish normally.  `serve_loop` exits once drained AND the router
has polled the last outbox — a drained worker never strands a result.

Fault hook: each pump crosses ``faults.maybe_crash(site="replica.step")``
so `PTPU_FAULTS="ckpt_crash@site=replica.step,hard=1"` kills a replica
mid-stream deterministically — the failover smoke's kill switch.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque

from ..monitor import trace as mtrace
from ..resilience import faults
from .router import (handoff_frame, params_from_wire, poll_frame,
                     result_frame, submit_frame)
from .scheduler import Request

__all__ = ["ReplicaWorker", "install", "current_worker",
           "_remote_submit", "_remote_adopt", "_remote_poll"]


class ReplicaWorker:
    """One engine behind the router protocol.  `handler` is an optional
    `PreemptionHandler` (or anything with a truthy ``triggered``) polled
    each pump; tests inject a stub, `serve_loop` installs the real
    one."""

    def __init__(self, engine, name: str = None, role: str = "both",
                 handler=None):
        if role not in ("both", "prefill", "decode"):
            raise ValueError(f"unknown replica role {role!r}")
        self.engine = engine
        self.name = name or os.environ.get("PTPU_REPLICA_ID") \
            or f"replica-{os.getpid()}"
        self.role = role
        self.handler = handler
        self._lock = threading.Lock()
        self._inbox: deque = deque()      # ("submit"|"adopt", frame)
        self._results: list = []          # result frames for the router
        self._handoffs: list = []         # handoff frames (prefill role)
        self._requeued: list = []         # submit frames (drain)
        self._owned: dict = {}            # engine rid -> original frame
        self._draining = False

    # -- rpc-thread surface (lock-guarded, never touches the engine) --------

    @staticmethod
    def _frame_ok(frame) -> bool:
        """Structural gate at the rpc boundary: a garbled frame that
        survived unpickling by luck must be refused HERE (the router
        re-routes on False), never enqueued where it would blow up
        `pump()` and wedge the one engine thread."""
        return (isinstance(frame, dict)
                and isinstance(frame.get("rid"), int)
                and isinstance(frame.get("prompt_ids"), (list, tuple)))

    def submit_local(self, frame) -> bool:
        """Accept a submit frame (False while draining — the router
        re-routes; no partial admission)."""
        if not self._frame_ok(frame):
            return False
        with self._lock:
            if self._draining:
                return False
            self._inbox.append(("submit", frame))
            return True

    def adopt_local(self, frame) -> bool:
        if not self._frame_ok(frame):
            return False
        with self._lock:
            if self._draining:
                return False
            self._inbox.append(("adopt", frame))
            return True

    def poll_local(self) -> dict:
        """Hand the router everything accumulated since its last poll
        (results, handoffs, drain requeues) in one frame."""
        with self._lock:
            doc = poll_frame(self.name, self._draining,
                             self._results, self._handoffs,
                             self._requeued)
            self._results = []
            self._handoffs = []
            self._requeued = []
        return doc

    # -- engine-thread pump --------------------------------------------------

    def pump(self) -> bool:
        """One worker cycle; returns True while there is (or may be)
        work.  Engine-owning thread only."""
        # deterministic mid-stream kill for the failover smoke
        faults.maybe_crash(site="replica.step")
        if not self._draining and self.handler is not None \
                and getattr(self.handler, "triggered", False):
            self.start_drain()
        self._admit()
        if self.engine.has_unfinished():
            self.engine.step()
        else:
            mtrace.heartbeat()   # idle pump still feeds the watchdog
        self._harvest()
        with self._lock:
            backlog = bool(self._inbox)
        return backlog or self.engine.has_unfinished()

    def _admit(self) -> None:
        with self._lock:
            batch = list(self._inbox)
            self._inbox.clear()
        for kind, frame in batch:
            if self._draining:
                # raced into the inbox as drain fired: bounce straight
                # back to the router, nothing was admitted
                with self._lock:
                    self._requeued.append(self._as_submit(frame))
                continue
            self._admit_one(kind, frame)

    def _admit_one(self, kind: str, frame: dict) -> None:
        # join the router's trace: the admit span carries the router-side
        # trace_id, so one trace spans router dispatch -> replica admit
        ctx = mtrace.extract(frame.get("trace"))
        sp = None
        if ctx is not None:
            sp = mtrace.start_span("replica/admit", parent=ctx,
                                   rid=frame.get("rid"), kind=kind,
                                   replica=self.name)
        try:
            # params decode is INSIDE the guard: a structurally-valid
            # frame with garbled params (wrong field types, non-dict)
            # must error this one request, not kill the pump
            params = params_from_wire(frame.get("params"))
            if kind == "adopt":
                erid = self.engine.adopt_request(
                    frame["prompt_ids"], params, frame["output_ids"],
                    frame["key"], frame["kv"])
            else:
                erid = self.engine.add_request(frame["prompt_ids"],
                                               params)
        except (ValueError, KeyError, TypeError, AttributeError) as e:
            # malformed request (empty/over-long prompt, spent handoff,
            # garbled field): a clean error result, not a wedged stream
            with self._lock:
                self._results.append(result_frame(
                    frame.get("rid"), self.name, ok=False,
                    finish_reason="abort", error=repr(e)))
            return
        finally:
            if sp is not None:
                sp.end()
        self._owned[erid] = frame

    def _harvest(self) -> None:
        out_results, out_handoffs = [], []
        for erid in list(self._owned):
            frame = self._owned[erid]
            req = self.engine._requests.get(erid)
            if req is None:
                # the engine's deadline sweep released it inside step()
                # — the only internal release path for an owned request
                out_results.append(result_frame(
                    frame["rid"], self.name, ok=False,
                    finish_reason="deadline",
                    error="deadline_s expired on the replica"))
                del self._owned[erid]
                continue
            if req.finished:
                out_results.append(result_frame(
                    frame["rid"], self.name, ok=True,
                    token_ids=self.engine.request_output(erid),
                    finish_reason="stop"))
                self.engine.release_request(erid)
                del self._owned[erid]
                continue
            if self.role == "prefill" and req.prefill_done \
                    and req.output_ids \
                    and req in self.engine.scheduler.running:
                # prefill half done (first token sampled): export for a
                # decode worker, KV block-for-block
                h = self.engine.export_request(erid)
                out_handoffs.append(handoff_frame(
                    frame["rid"], h["prompt_ids"], h["output_ids"],
                    frame.get("params"), h["key"], h["kv"],
                    trace=frame.get("trace")))
                del self._owned[erid]
        if out_results or out_handoffs:
            with self._lock:
                self._results.extend(out_results)
                self._handoffs.extend(out_handoffs)

    # -- drain ---------------------------------------------------------------

    def start_drain(self) -> None:
        """Stop admission and return never-computed waiting requests to
        the router (released locally with reason "migrated" — their
        terminal state HERE is a success elsewhere).  Running requests
        finish normally; idempotent."""
        with self._lock:
            if self._draining:
                return
            self._draining = True
            bounced = [self._as_submit(f) for _, f in self._inbox]
            self._inbox.clear()
        requeue = []
        for erid in list(self._owned):
            req = self.engine._requests.get(erid)
            if req is None or req.state != Request.WAITING:
                continue   # running/preempted requests run to completion
            frame = self._owned.pop(erid)
            self.engine.release_request(erid, reason="migrated")
            requeue.append(self._as_submit(frame))
        with self._lock:
            self._requeued.extend(bounced + requeue)

    @staticmethod
    def _as_submit(frame: dict) -> dict:
        """A requeueable submit frame from either a submit or a handoff
        frame (a bounced handoff resubmits from-prompt: its KV snapshot
        is forfeit, the tokens are not — generation is deterministic)."""
        return submit_frame(frame["rid"], frame["prompt_ids"],
                            frame.get("params"), trace=frame.get("trace"))

    def drained(self) -> bool:
        """True once draining AND nothing is left to run or hand back."""
        if not self._draining or self.engine.has_unfinished():
            return False
        with self._lock:
            return not (self._inbox or self._results
                        or self._handoffs or self._requeued)

    # -- process loop --------------------------------------------------------

    def serve_loop(self, idle_sleep_s: float = 0.005) -> None:
        """Pump until drained (the production loop).  Installs a
        `PreemptionHandler` when none was injected, so SIGTERM = drain;
        returns only after the router has polled the last outbox."""
        if self.handler is None:
            from ..resilience.retry import PreemptionHandler

            self.handler = PreemptionHandler().install()
        while True:
            busy = self.pump()
            if self.drained():
                return
            if not busy:
                time.sleep(idle_sleep_s)


# -- rpc entrypoints ----------------------------------------------------------
# rpc_sync ships the FUNCTION by reference; these resolve against the
# process-global worker the replica main installed.

_worker: "ReplicaWorker | None" = None


def install(worker: ReplicaWorker) -> ReplicaWorker:
    """Register `worker` as this process's rpc target."""
    global _worker
    _worker = worker
    return worker


def current_worker() -> "ReplicaWorker | None":
    return _worker


def _require() -> ReplicaWorker:
    if _worker is None:
        raise RuntimeError("no ReplicaWorker installed in this process "
                           "(call serving.replica.install(worker) first)")
    return _worker


def _remote_submit(frame) -> bool:
    return _require().submit_local(frame)


def _remote_adopt(frame) -> bool:
    return _require().adopt_local(frame)


def _remote_poll() -> dict:
    return _require().poll_local()
