"""Multi-replica serving router (ISSUE 17) — the process that turns N
single-process `LLMEngine` replicas into one serving tier.

The router owns the fleet-facing request queue and fans requests across
replica workers over `distributed/rpc.py` (the trace header already
rides that wire).  Three policies, in decision order:

- **prefix-cache-aware sticky routing** — each request's chained
  `kv_cache.prefix_block_keys` signature is matched against a bounded
  LRU map of *block key → replica that prefilled it*: the replica
  already holding the longest run of the request's leading blocks
  (parked on its prefix-cache LRU) gets the request, so N requests
  sharing a system prompt pay its prefill ONCE on ONE replica instead
  of once per replica the load balancer happened to spray them across
  (fleet-scale preservation of PR 13's hot-TTFT win).
- **least-loaded fallback** — no sticky match (or sticky replica
  ineligible): pick by the live `FleetAggregator.snapshot()` router
  feed, ordered by (router-tracked inflight + reported queue depth +
  waiting, worst SLO burn rate, -goodput tokens/s).  Replicas whose
  feed state is `stalled`/`down` are excluded and re-admitted the
  moment the feed reports them healthy again.
- **disaggregated prefill/decode** (`RouterConfig.disaggregate` /
  `PTPU_ROUTER_DISAGG`) — fresh prompts go to prefill-role workers
  (which absorb the compile-heavy long-prompt programs), and once a
  request is prefilled + has its first token, the worker exports it
  (`LLMEngine.export_request`: bit-exact `swap_out` KV snapshot + the
  row's evolved PRNG key) as a handoff frame the router forwards to a
  decode-role worker (`adopt_request`).  Decode workers therefore only
  ever dispatch the one fixed-shape `ragged(max_num_seqs, 1)` program
  — they never compile a prefill.  Token-identical to single-process
  serving for greedy AND seeded sampling (the key ships with the KV).

Lifecycle guarantees (drain / scale-down / failover):

- a SIGTERM'd replica (riding `resilience.PreemptionHandler`) stops
  admission, finishes its running requests, and returns its
  never-computed waiting requests as requeued submit frames — the
  router re-queues them at the FRONT in original arrival order;
- a replica going `stalled`/`down` on the feed (the `/fleet/healthz`
  state machine) triggers resubmission of its in-flight requests
  from-prompt — token-identical for greedy/seeded rows — bounded by
  `resubmit_limit`, beyond which the request errors cleanly.  Streams
  complete or error; they never hang.
- a request whose `SamplingParams.deadline_s` expires while still
  queued AT THE ROUTER is rejected locally (counted, reqlog reason
  "deadline") instead of being shipped to a replica that would only
  expire it after paying admission; a shipped request carries its
  REMAINING budget, so the clock does not restart on the replica.

Every frame this module speaks is declared in `monitor/wire.py`
(`ROUTER_SUBMIT_KEYS` / `ROUTER_RESULT_KEYS` / `ROUTER_HANDOFF_KEYS` /
`ROUTER_POLL_KEYS`, one `ROUTER_SCHEMA_VERSION`) and built HERE under
the matching ``# ptpu-wire: router-*`` anchors — drifting a frame
without registering it is a `wire-compat` lint failure, not a deploy
incident.  The router's metric names are pinned the same way
(`ROUTER_METRIC_NAMES`).

The `Router` itself is transport-agnostic and single-threaded by
design: `poll()` is the one pump (collect → failover/drain → dispatch),
driven by whoever owns the process loop.  Replica clients are
duck-typed (`name`, `role`, `submit(frame)`, `submit_handoff(frame)`,
`poll()`), so the fast-tier unit tests drive the full policy surface
with in-memory stubs — `RpcReplicaClient` is the production transport
(see `serving/replica.py` for the worker half and
`scripts/router_smoke.py` for the end-to-end proof).
"""
from __future__ import annotations

import dataclasses
import os
import time
from collections import OrderedDict, deque
from typing import Optional

from .. import monitor
from ..monitor import reqlog as mreqlog
from ..monitor import trace as mtrace
from ..monitor.wire import (ROUTER_HANDOFF_KEYS, ROUTER_POLL_KEYS,
                            ROUTER_RESULT_KEYS, ROUTER_SCHEMA_VERSION,
                            ROUTER_SUBMIT_KEYS)
from ..resilience.retry import Deadline
from .kv_cache import prefix_block_keys
from .scheduler import SamplingParams

__all__ = ["Router", "RouterConfig", "RpcReplicaClient",
           "submit_frame", "result_frame", "handoff_frame", "poll_frame",
           "sticky_signature"]

_PARAM_FIELDS = {f.name for f in dataclasses.fields(SamplingParams)}


def params_to_wire(params: SamplingParams) -> dict:
    """SamplingParams as a plain dict (the wire form: a replica running
    an older SamplingParams drops unknown fields instead of failing to
    unpickle a skewed class)."""
    return dataclasses.asdict(params)


def params_from_wire(d: dict) -> SamplingParams:
    return SamplingParams(**{k: v for k, v in (d or {}).items()
                             if k in _PARAM_FIELDS})


# -- canonical frame builders (keys pinned by ptpu-check wire-compat) -------

def submit_frame(rid, prompt_ids, params: dict, trace=None) -> dict:
    # ptpu-wire: router-submit
    return {
        "schema_version": ROUTER_SCHEMA_VERSION,
        "rid": int(rid),
        "prompt_ids": [int(t) for t in prompt_ids],
        "params": params,
        "trace": trace,
    }


def result_frame(rid, replica, ok, token_ids=None, finish_reason="stop",
                 error=None) -> dict:
    # ptpu-wire: router-result
    return {
        "schema_version": ROUTER_SCHEMA_VERSION,
        "rid": int(rid),
        "replica": replica,
        "ok": bool(ok),
        "token_ids": None if token_ids is None
        else [int(t) for t in token_ids],
        "finish_reason": finish_reason,
        "error": error,
    }


def handoff_frame(rid, prompt_ids, output_ids, params: dict, key, kv,
                  trace=None) -> dict:
    # ptpu-wire: router-handoff
    return {
        "schema_version": ROUTER_SCHEMA_VERSION,
        "rid": int(rid),
        "prompt_ids": [int(t) for t in prompt_ids],
        "output_ids": [int(t) for t in output_ids],
        "params": params,
        "key": key,
        "kv": kv,
        "trace": trace,
    }


def poll_frame(replica, draining, results, handoffs, requeued) -> dict:
    # ptpu-wire: router-poll
    return {
        "schema_version": ROUTER_SCHEMA_VERSION,
        "replica": replica,
        "draining": bool(draining),
        "results": list(results),
        "handoffs": list(handoffs),
        "requeued": list(requeued),
    }


def _check_frame(frame: dict, keys) -> dict:
    """Version + shape gate for a received frame: a FUTURE schema is
    rejected loudly (mis-parsing it would be worse), missing keys read
    None (accrete-only: an OLD peer's frame simply lacks the new
    fields)."""
    v = frame.get("schema_version")
    if v is not None and v > ROUTER_SCHEMA_VERSION:
        raise ValueError(
            f"router frame schema_version {v} is newer than this "
            f"process speaks ({ROUTER_SCHEMA_VERSION}) — upgrade me "
            "before the sender")
    del keys   # shape is advisory: accrete-only keys never hard-fail
    return frame


def sticky_signature(prompt_ids, block_size: int) -> tuple:
    """The request's routing signature: the chained content keys of its
    FULL prompt blocks (`kv_cache.prefix_block_keys` — sha1-chained, so
    stable across processes/PYTHONHASHSEED and collision-safe).  Two
    prompts share a leading signature run exactly when they share that
    prompt prefix block-for-block — the same identity the replica-side
    prefix cache indexes, which is what makes router-side stickiness
    predict replica-side cache hits."""
    return tuple(prefix_block_keys(list(prompt_ids), block_size))


@dataclasses.dataclass
class RouterConfig:
    # prefix-cache-aware sticky routing; None resolves from env
    # PTPU_ROUTER_STICKY ("0"/"false"/"off" disables), default ON
    sticky: Optional[bool] = None
    # disaggregated prefill/decode; None resolves from PTPU_ROUTER_DISAGG,
    # default OFF (requires prefill-/decode-role replicas)
    disaggregate: Optional[bool] = None
    # KV block size the replicas run (sticky signatures must chunk
    # prompts exactly like the replica prefix caches do)
    block_size: int = 16
    # sticky map capacity in block keys; None resolves from
    # PTPU_ROUTER_AFFINITY_CAP, default 4096 — bounded so a long-lived
    # router cannot grow an unbounded affinity map
    affinity_cap: Optional[int] = None
    # failover resubmissions per request before it errors cleanly; None
    # resolves from PTPU_ROUTER_RESUBMIT_LIMIT, default 1
    resubmit_limit: Optional[int] = None
    # per-replica circuit breaker: consecutive transport failures before
    # the breaker trips OPEN; None resolves from
    # PTPU_ROUTER_BREAKER_THRESHOLD, default 3
    breaker_threshold: Optional[int] = None
    # seconds an OPEN breaker cools down before the half-open probe;
    # doubles on every failed probe (capped 60s).  None resolves from
    # PTPU_ROUTER_BREAKER_COOLDOWN_S, default 1.0
    breaker_cooldown_s: Optional[float] = None
    # grace the router grants an INFLIGHT request past its deadline for
    # the replica's own deadline result to arrive before finishing it
    # ok=False locally (the no-hang bound under a partition); None
    # resolves from PTPU_ROUTER_DEADLINE_GRACE_S, default 0.25
    deadline_grace_s: Optional[float] = None

    def resolve(self) -> "RouterConfig":
        sticky = self.sticky
        if sticky is None:
            sticky = os.environ.get("PTPU_ROUTER_STICKY", "1").lower() \
                not in ("0", "false", "off")
        disagg = self.disaggregate
        if disagg is None:
            disagg = os.environ.get("PTPU_ROUTER_DISAGG", "0").lower() \
                in ("1", "true", "on")
        cap = self.affinity_cap
        if cap is None:
            cap = int(os.environ.get("PTPU_ROUTER_AFFINITY_CAP", "4096")
                      or 4096)
        limit = self.resubmit_limit
        if limit is None:
            limit = int(os.environ.get("PTPU_ROUTER_RESUBMIT_LIMIT", "1")
                        or 1)
        thresh = self.breaker_threshold
        if thresh is None:
            thresh = int(os.environ.get("PTPU_ROUTER_BREAKER_THRESHOLD",
                                        "3") or 3)
        cooldown = self.breaker_cooldown_s
        if cooldown is None:
            cooldown = float(os.environ.get(
                "PTPU_ROUTER_BREAKER_COOLDOWN_S", "1.0") or 1.0)
        grace = self.deadline_grace_s
        if grace is None:
            grace = float(os.environ.get(
                "PTPU_ROUTER_DEADLINE_GRACE_S", "0.25") or 0.25)
        return RouterConfig(sticky=bool(sticky), disaggregate=bool(disagg),
                            block_size=int(self.block_size),
                            affinity_cap=max(1, int(cap)),
                            resubmit_limit=max(0, int(limit)),
                            breaker_threshold=max(1, int(thresh)),
                            breaker_cooldown_s=max(1e-3, float(cooldown)),
                            deadline_grace_s=max(0.0, float(grace)))


class _Breaker:
    """Per-replica circuit breaker (single-threaded, pump-owned).

    CLOSED → `threshold` consecutive transport failures → OPEN (the
    replica is ejected from BOTH poll and dispatch, so a partitioned
    peer does not cost the pump a timeout per cycle) → after the
    cooldown, HALF_OPEN: the next `poll()` IS the probe — success
    re-admits (CLOSED, backoff reset), failure re-trips with the
    backoff doubled (capped).  The clock is injected so the state
    machine unit-tests run on a fake clock."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"
    MAX_BACKOFF_S = 60.0

    __slots__ = ("threshold", "cooldown", "clock", "state", "fails",
                 "trips", "opened_at", "backoff")

    def __init__(self, threshold: int, cooldown: float, clock):
        self.threshold = threshold
        self.cooldown = cooldown
        self.clock = clock
        self.state = self.CLOSED
        self.fails = 0          # consecutive transport failures
        self.trips = 0          # lifetime trips (exported on the feed)
        self.opened_at = 0.0
        self.backoff = cooldown  # current cooldown; doubles per re-trip

    def allow(self) -> bool:
        """May the pump talk to this replica this cycle?  OPEN → False
        until the cooldown elapses, then HALF_OPEN (probe granted)."""
        if self.state == self.OPEN:
            if self.clock() - self.opened_at < self.backoff:
                return False
            self.state = self.HALF_OPEN
        return True

    def record_success(self) -> None:
        self.fails = 0
        if self.state != self.CLOSED:
            self.state = self.CLOSED
            self.backoff = self.cooldown

    def record_failure(self) -> bool:
        """True when this failure TRIPS the breaker: threshold reached
        while CLOSED, or a failed HALF_OPEN probe (re-trip, doubled
        backoff)."""
        self.fails += 1
        if self.state == self.HALF_OPEN:
            self.backoff = min(self.backoff * 2.0, self.MAX_BACKOFF_S)
            self._open()
            return True
        if self.state == self.CLOSED and self.fails >= self.threshold:
            self._open()
            return True
        return False

    def _open(self) -> None:
        self.state = self.OPEN
        self.opened_at = self.clock()
        self.trips += 1
        self.fails = 0


class _RouterRequest:
    """Router-side request state (distinct from the replica Request)."""

    __slots__ = ("rid", "prompt_ids", "params", "sig", "deadline",
                 "kind", "state", "assigned", "resubmits", "result",
                 "handoff", "trace_id", "expired_at")

    QUEUED, INFLIGHT, DONE = "queued", "inflight", "done"

    def __init__(self, rid, prompt_ids, params: SamplingParams, sig):
        self.rid = rid
        self.prompt_ids = prompt_ids
        self.params = params
        self.sig = sig
        self.deadline = None if params.deadline_s is None \
            else Deadline(params.deadline_s)
        self.kind = "prompt"            # "prompt" | "handoff"
        self.state = _RouterRequest.QUEUED
        self.assigned = None            # replica name while INFLIGHT
        self.resubmits = 0              # failover resubmissions so far
        self.result = None              # ROUTER_RESULT_KEYS frame
        self.handoff = None             # pending handoff frame (disagg)
        self.trace_id = None
        self.expired_at = None          # clock() when first seen expired
        #                                 while INFLIGHT (grace window)


class Router:
    """submit() / poll() / result() over N replica clients.

    `clients` is an iterable of replica-client objects (duck-typed —
    see module docstring); `feed` is a zero-arg callable returning the
    `FleetAggregator.snapshot()` dict (name → router-feed record).
    Neither is owned: the caller runs the aggregator and the rpc
    world."""

    def __init__(self, clients, feed, config: Optional[RouterConfig] = None,
                 clock=time.monotonic):
        self.config = (config or RouterConfig()).resolve()
        self._clients = OrderedDict((c.name, c) for c in clients)
        self._feed = feed
        self._clock = clock
        self._breakers = {
            c.name: _Breaker(self.config.breaker_threshold,
                             self.config.breaker_cooldown_s, clock)
            for c in self._clients.values()}
        self._reqs: "dict[int, _RouterRequest]" = {}
        self._queue: deque = deque()          # rids awaiting dispatch
        self._next_rid = 0
        # block key -> replica that prefilled it (bounded LRU)
        self._block_home: OrderedDict = OrderedDict()
        self._draining: set = set()           # replicas mid-drain
        self._inflight: "dict[str, int]" = {}  # replica -> inflight count
        self.last_err = None                  # newest transport error
        m = monitor
        # ptpu-wire: router-metrics
        self._m = {
            "router/requests": m.counter(
                "router/requests", "requests accepted by the router"),
            "router/dispatched": m.counter(
                "router/dispatched", "requests shipped to a replica"),
            "router/sticky_hits": m.counter(
                "router/sticky_hits",
                "dispatches routed by prefix-cache affinity"),
            "router/deadline_rejected": m.counter(
                "router/deadline_rejected",
                "requests expired in the router queue, never shipped"),
            "router/failovers": m.counter(
                "router/failovers",
                "in-flight requests resubmitted off a stalled/down "
                "replica"),
            "router/requeued": m.counter(
                "router/requeued",
                "waiting requests returned by a draining replica"),
            "router/handoffs": m.counter(
                "router/handoffs",
                "prefill->decode KV handoffs forwarded"),
            "router/stale_results": m.counter(
                "router/stale_results",
                "results dropped from a replica no longer owning the "
                "request"),
            "router/errors": m.counter(
                "router/errors", "replica transport errors"),
            "router/queue_depth": m.gauge(
                "router/queue_depth", "requests queued at the router"),
            "router/inflight": m.gauge(
                "router/inflight", "requests in flight on replicas"),
            "router/breaker_trips": m.counter(
                "router/breaker_trips",
                "circuit-breaker trips (threshold reached or a failed "
                "half-open probe)"),
            "router/breaker_open": m.gauge(
                "router/breaker_open",
                "replicas currently ejected by an open breaker"),
            "router/deadline_inflight": m.counter(
                "router/deadline_inflight",
                "in-flight requests finished ok=False by the router "
                "after their deadline (+grace) passed unanswered"),
        }

    # -- request API --------------------------------------------------------

    def submit(self, prompt_ids, sampling_params=None) -> int:
        """Queue one request; returns the router-side request id.
        Dispatch happens on the next `poll()`."""
        params = sampling_params or SamplingParams()
        prompt = [int(t) for t in prompt_ids]
        if not prompt:
            raise ValueError("empty prompt")
        sig = sticky_signature(prompt, self.config.block_size) \
            if self.config.sticky else ()
        rreq = _RouterRequest(self._next_rid, prompt, params, sig)
        self._next_rid += 1
        if mtrace.enabled():
            sp = mtrace.current_span()
            rreq.trace_id = sp.trace_id if sp is not None else None
        self._reqs[rreq.rid] = rreq
        self._queue.append(rreq.rid)
        self._m["router/requests"].inc()
        return rreq.rid

    def result(self, rid) -> "dict | None":
        """The finished result frame, or None while pending."""
        return self._reqs[rid].result

    def release(self, rid) -> None:
        """Drop a finished request's router state (callers release after
        reading the result, like the engine's release_request)."""
        self._reqs.pop(rid, None)

    def wait(self, rid, timeout: float = 60.0,
             poll_s: float = 0.005) -> dict:
        """Pump poll() until `rid` finishes; TimeoutError past
        `timeout` (a bound, not a hang — failover/drain keep requests
        moving, so a healthy fleet finishes well inside it)."""
        deadline = Deadline(timeout)
        while True:
            self.poll()
            res = self._reqs[rid].result
            if res is not None:
                return res
            if deadline.expired:
                raise TimeoutError(f"router request {rid} not finished "
                                   f"after {timeout}s")
            time.sleep(poll_s)

    def pending(self) -> int:
        return sum(1 for r in self._reqs.values()
                   if r.state != _RouterRequest.DONE)

    # -- the pump -----------------------------------------------------------

    def poll(self) -> None:
        """One router cycle: feed-driven failover, breaker-gated replica
        poll absorption (results / handoffs / drain requeues), queue +
        in-flight expiry, dispatch."""
        snap = self._feed() or {}
        unavailable = set()
        for name in self._clients:
            state = (snap.get(name) or {}).get("state", "unknown")
            if state in ("stalled", "down"):
                unavailable.add(name)
                self._fail_over(name)
        for name, client in self._clients.items():
            if name in unavailable:
                continue   # never rpc a peer the feed says is gone
            br = self._breakers[name]
            if not br.allow():
                # OPEN and still cooling: ejected without an rpc — a
                # partitioned peer must not cost the pump one transport
                # timeout per cycle.  allow() past the cooldown flips
                # to HALF_OPEN and this poll IS the probe.
                unavailable.add(name)
                continue
            try:
                doc = _check_frame(client.poll(), ROUTER_POLL_KEYS)
            except (OSError, ConnectionError, TimeoutError,
                    RuntimeError) as e:
                # transport error: counted, surfaced, and fed to the
                # breaker — a trip ejects the replica and reroutes its
                # in-flight requests within this same cycle (the feed's
                # /fleet/healthz transition is the slower, authoritative
                # path; the breaker is the fast local one)
                self._m["router/errors"].inc()
                self.last_err = f"{name}: {e}"
                self._breaker_failure(name, unavailable)
                continue
            br.record_success()
            self._absorb(name, doc)
        self._expire_queue()
        self._expire_inflight()
        self._dispatch(snap, unavailable)
        self._m["router/queue_depth"].set(len(self._queue))
        self._m["router/inflight"].set(
            sum(self._inflight.values()))
        self._m["router/breaker_open"].set(
            sum(1 for b in self._breakers.values()
                if b.state == _Breaker.OPEN))

    def _breaker_failure(self, name: str, unavailable: set) -> None:
        """One transport failure against `name`: a resulting trip ejects
        it for this cycle AND reroutes its in-flight requests now (they
        re-dispatch in this cycle's _dispatch, sharing each request's
        ONE Deadline and resubmit budget)."""
        if self._breakers[name].record_failure():
            self._m["router/breaker_trips"].inc()
            unavailable.add(name)
            self._fail_over(name)

    def fleet_view(self) -> dict:
        """The fleet router feed overlaid with router-local breaker
        state — the aggregator cannot know it, so `ROUTER_FEED_KEYS`
        accretes breaker_state/breaker_trips and the aggregator-side
        builder reports them as None; this is where they get filled."""
        snap = {k: dict(v or {}) for k, v in (self._feed() or {}).items()}
        for name, br in self._breakers.items():
            rec = snap.setdefault(name, {})
            rec["breaker_state"] = br.state
            rec["breaker_trips"] = br.trips
        return snap

    # -- absorption ---------------------------------------------------------

    def _absorb(self, name: str, doc: dict) -> None:
        if doc.get("draining"):
            self._draining.add(name)
        else:
            self._draining.discard(name)
        for res in doc.get("results") or ():
            res = _check_frame(res, ROUTER_RESULT_KEYS)
            rreq = self._reqs.get(res.get("rid"))
            if rreq is None or rreq.state != _RouterRequest.INFLIGHT \
                    or rreq.assigned != name:
                # late completion from a replica we already failed away
                # from (or a released request): first owner wins
                self._m["router/stale_results"].inc()
                continue
            self._finish(rreq, res)
        for hof in doc.get("handoffs") or ():
            hof = _check_frame(hof, ROUTER_HANDOFF_KEYS)
            rreq = self._reqs.get(hof.get("rid"))
            if rreq is None or rreq.state != _RouterRequest.INFLIGHT \
                    or rreq.assigned != name:
                self._m["router/stale_results"].inc()
                continue
            # prefill half done: requeue as a decode handoff
            self._unassign(rreq)
            rreq.kind = "handoff"
            rreq.handoff = hof
            rreq.state = _RouterRequest.QUEUED
            self._queue.appendleft(rreq.rid)
            self._m["router/handoffs"].inc()
        requeued = [_check_frame(f, ROUTER_SUBMIT_KEYS)
                    for f in doc.get("requeued") or ()]
        if requeued:
            self._requeue_front(
                [r for f in requeued
                 if (r := self._reqs.get(f.get("rid"))) is not None
                 and r.state == _RouterRequest.INFLIGHT
                 and r.assigned == name],
                counter="router/requeued")

    def _finish(self, rreq: _RouterRequest, res: dict) -> None:
        self._unassign(rreq)
        rreq.state = _RouterRequest.DONE
        rreq.result = res

    def _unassign(self, rreq: _RouterRequest) -> None:
        if rreq.assigned is not None:
            n = self._inflight.get(rreq.assigned, 0) - 1
            self._inflight[rreq.assigned] = max(0, n)
            rreq.assigned = None

    def _requeue_front(self, rreqs, counter: str) -> None:
        """Put migrated requests back at the FRONT of the queue in
        original submission order (they are by construction older than
        anything still queued — dispatch preserved arrival order, so
        front insertion restores it exactly)."""
        for rreq in sorted(rreqs, key=lambda r: r.rid, reverse=True):
            self._unassign(rreq)
            rreq.kind = "prompt"      # any shipped KV died with the peer
            rreq.handoff = None
            rreq.state = _RouterRequest.QUEUED
            self._queue.appendleft(rreq.rid)
            self._m[counter].inc()

    # -- failover -----------------------------------------------------------

    def _fail_over(self, name: str) -> None:
        """The feed rolled `name` up as stalled/down: resubmit its
        in-flight requests from-prompt (token-identical for greedy and
        seeded rows — generation is a pure function of prompt + params
        + seed), bounded by resubmit_limit.  Idempotent: a request
        migrated once is no longer assigned here, so repeated polls
        while the replica stays down find nothing to do."""
        victims = [r for r in self._reqs.values()
                   if r.state == _RouterRequest.INFLIGHT
                   and r.assigned == name]
        if not victims:
            return
        retry, dead = [], []
        for rreq in victims:
            if rreq.resubmits < self.config.resubmit_limit:
                rreq.resubmits += 1
                retry.append(rreq)
            else:
                dead.append(rreq)
        for rreq in retry:
            # the first attempt's termination is a MIGRATION, not an
            # abort: logged distinctly so SLO error_rate stays clean
            self._emit_reqlog(rreq, "migrated")
        self._requeue_front(retry, counter="router/failovers")
        for rreq in dead:
            self._finish(rreq, result_frame(
                rreq.rid, name, ok=False, finish_reason="abort",
                error=f"replica {name} lost; resubmit limit "
                      f"({self.config.resubmit_limit}) reached"))
            self._emit_reqlog(rreq, "abort")
        # its parked prefix blocks died with it: forget the affinities
        # so new traffic re-warms a live replica instead
        for k in [k for k, v in self._block_home.items() if v == name]:
            del self._block_home[k]

    # -- dispatch -----------------------------------------------------------

    def _expire_queue(self) -> None:
        """Router-side deadline enforcement: reject queued requests
        whose budget expired before they were ever shipped."""
        expired = [rid for rid in self._queue
                   if (r := self._reqs[rid]).deadline is not None
                   and r.deadline.expired]
        for rid in expired:
            self._queue.remove(rid)
            rreq = self._reqs[rid]
            self._finish(rreq, result_frame(
                rid, None, ok=False, finish_reason="deadline",
                error="deadline_s expired in the router queue"))
            self._m["router/deadline_rejected"].inc()
            self._emit_reqlog(rreq, "deadline")

    def _expire_inflight(self) -> None:
        """The no-hang bound for shipped requests: a replica that went
        dark mid-request (partition, wedge) may never report back, and
        the feed can lag.  A request seen expired while INFLIGHT gets
        one grace window for the replica's own deadline result to
        arrive, then the ROUTER finishes it ok=False — a stream never
        outlives deadline + grace + one poll period."""
        now = self._clock()
        for rreq in list(self._reqs.values()):
            if rreq.state != _RouterRequest.INFLIGHT \
                    or rreq.deadline is None or not rreq.deadline.expired:
                continue
            if rreq.expired_at is None:
                rreq.expired_at = now
                continue
            if now - rreq.expired_at < self.config.deadline_grace_s:
                continue
            name = rreq.assigned
            self._finish(rreq, result_frame(
                rreq.rid, name, ok=False, finish_reason="deadline",
                error=f"deadline_s expired in flight on {name} "
                      "(no result within grace)"))
            self._m["router/deadline_inflight"].inc()
            self._emit_reqlog(rreq, "deadline")

    def _eligible(self, snap, unavailable, kind: str) -> list:
        """Replica names a `kind` ("prompt"|"handoff") dispatch may
        target right now: feed-healthy (or not yet scraped), not
        draining, and — under disaggregation — role-matched."""
        want = ("prefill", "both") if kind == "prompt" \
            else ("decode", "both")
        out = []
        for name, client in self._clients.items():
            if name in unavailable or name in self._draining:
                continue
            if self._breakers[name].state == _Breaker.OPEN:
                continue    # ejected: only the half-open probe may talk
            if self.config.disaggregate \
                    and getattr(client, "role", "both") not in want:
                continue
            out.append(name)
        return out

    def _sticky_choice(self, sig, eligible) -> "tuple[str, int] | None":
        """The replica holding the longest run of the request's leading
        prefix blocks, or None.  One full block (>= block_size shared
        tokens) is enough to beat a cold prefill."""
        if not sig:
            return None
        home = self._block_home.get(sig[0])
        if home is None:
            return None
        run = 1
        for k in sig[1:]:
            if self._block_home.get(k) != home:
                break
            run += 1
        return (home, run) if home in eligible else None

    def _load_score(self, name: str, snap: dict):
        rec = snap.get(name) or {}
        pending = (self._inflight.get(name, 0)
                   + (rec.get("queue_depth") or 0)
                   + (rec.get("waiting") or 0))
        burn = rec.get("slo_max_burn_rate") or 0.0
        goodput = rec.get("goodput_tokens_per_s") or 0.0
        return (pending, burn, -goodput, name)

    def _dispatch(self, snap: dict, unavailable: set) -> None:
        stuck = []
        while self._queue:
            rid = self._queue.popleft()
            if not self._dispatch_one(self._reqs[rid], snap,
                                      unavailable):
                stuck.append(rid)
        # parked requests keep their relative order at the queue front
        for rid in reversed(stuck):
            self._queue.appendleft(rid)

    def _dispatch_one(self, rreq: _RouterRequest, snap: dict,
                      unavailable: set) -> bool:
        while True:
            eligible = self._eligible(snap, unavailable, rreq.kind)
            if not eligible:
                return False
            sticky = None
            if rreq.kind == "prompt":
                sticky = self._sticky_choice(rreq.sig, eligible)
            if sticky is not None:
                name = sticky[0]
            else:
                name = min(eligible,
                           key=lambda n: self._load_score(n, snap))
            if self._ship(rreq, name, unavailable):
                if sticky is not None:
                    self._m["router/sticky_hits"].inc()
                for k in rreq.sig:
                    self._block_home[k] = name
                    self._block_home.move_to_end(k)
                while len(self._block_home) > self.config.affinity_cap:
                    self._block_home.popitem(last=False)
                return True
            # replica refused (drain race) or transport failed: exclude
            # it for the rest of this cycle and try the others
            unavailable.add(name)

    def _ship(self, rreq: _RouterRequest, name: str,
              unavailable: set) -> bool:
        client = self._clients[name]
        params = params_to_wire(rreq.params)
        if rreq.deadline is not None:
            # ship the REMAINING budget: the replica arms its own clock
            # at admission, and restarting it would grant queue time back
            params["deadline_s"] = max(1e-3, rreq.deadline.remaining())
        try:
            with mtrace.span("router/dispatch", rid=rreq.rid,
                             replica=name, kind=rreq.kind):
                hdr = mtrace.inject()
                if rreq.kind == "handoff":
                    frame = dict(rreq.handoff,
                                 params=params, trace=hdr)
                    ok = client.submit_handoff(frame)
                else:
                    frame = submit_frame(rreq.rid, rreq.prompt_ids,
                                         params, trace=hdr)
                    ok = client.submit(frame)
        except (OSError, ConnectionError, TimeoutError,
                RuntimeError) as e:
            self._m["router/errors"].inc()
            self.last_err = f"{name}: {e}"
            self._breaker_failure(name, unavailable)
            return False
        # the transport worked — an application-level refusal (ok=False,
        # e.g. a drain race) is not a breaker failure
        self._breakers[name].record_success()
        if not ok:
            return False
        rreq.state = _RouterRequest.INFLIGHT
        rreq.assigned = name
        self._inflight[name] = self._inflight.get(name, 0) + 1
        self._m["router/dispatched"].inc()
        return True

    # -- accounting ---------------------------------------------------------

    def _emit_reqlog(self, rreq: _RouterRequest, reason: str) -> None:
        if mreqlog.enabled():
            mreqlog.emit(mreqlog.event(
                rreq.rid, trace_id=rreq.trace_id,
                prompt_tokens=len(rreq.prompt_ids),
                finish_reason=reason))


class RpcReplicaClient:
    """The production replica client: each call is one `rpc_sync` to
    the worker process registered under `name` (see
    `serving/replica.py` for the remote half).  rpc already retries the
    dial and propagates the trace header; anything past the dial is
    NOT retried here — the router's failover path owns redelivery,
    keyed on the feed's health state, so a maybe-executed submit is
    never blindly re-sent."""

    def __init__(self, name: str, role: str = "both",
                 timeout: float = 60.0):
        self.name = name
        self.role = role
        self.timeout = timeout

    def _call(self, fn, *args):
        from ..distributed import rpc

        return rpc.rpc_sync(self.name, fn, args=args,
                            timeout=self.timeout)

    def submit(self, frame) -> bool:
        from . import replica

        return self._call(replica._remote_submit, frame)

    def submit_handoff(self, frame) -> bool:
        from . import replica

        return self._call(replica._remote_adopt, frame)

    def poll(self) -> dict:
        from . import replica

        return self._call(replica._remote_poll)
