"""n-gram / prompt-lookup draft proposal for speculative decoding
(ISSUE 15 — the "prompt lookup decoding" shape: no second model, no
extra device work; stdlib only).

The proposer guesses the next k tokens of a row from the row's OWN
history: take the longest recent n-gram (down to ``ngram_min`` tokens)
ending at the current position, find its most recent PREVIOUS occurrence
in the context, and propose the tokens that followed it.  On repetitive
text — shared boilerplate, code, lists, the degenerate cycles greedy
decoding falls into — the continuation after a repeated n-gram is very
often the same, so verification accepts several tokens per step.

Drafts are free to be wrong: verification scores them against the real
model in one fixed-shape multi-token call and accepts only the prefix
the model would have emitted anyway (token-identical greedy decoding —
the engine's parity bar), so a bad guess costs nothing but the padded
verify positions the program was already shaped for.
"""
from __future__ import annotations

__all__ = ["propose_ngram"]


def propose_ngram(context, k, ngram_max=3, ngram_min=1, window=1024) -> list:
    """Up to `k` draft tokens continuing `context` (a list of int token
    ids), from the most recent previous occurrence of the longest
    matching suffix n-gram; [] when nothing matches.

    Only the trailing `window` tokens are searched — proposal runs on
    the host inside the decode loop, so the scan must stay O(window)
    per row regardless of context length.
    """
    n = len(context)
    if n < 2 or k <= 0:
        return []
    lo = max(0, n - int(window))
    for size in range(min(int(ngram_max), n - 1), int(ngram_min) - 1, -1):
        tail = context[n - size:]
        # most recent prior occurrence: scan candidate start positions
        # right-to-left, excluding the suffix occurrence itself
        for start in range(n - size - 1, lo - 1, -1):
            if context[start:start + size] == tail:
                follow = context[start + size:start + size + int(k)]
                if follow:
                    return [int(t) for t in follow]
        # no occurrence at this size: a shorter n-gram may still match
    return []
