"""OpenAI-compatible HTTP front door (ISSUE 19, ROADMAP item 5).

A stdlib ``http.server`` tier that turns the Python-only serving stack
into something a load balancer can point at:

- ``POST /v1/completions``       — prompt in, tokens out; SSE streaming
  (``"stream": true`` — one chunk per engine step, fed straight from
  the step loop) or one non-streaming JSON body;
- ``POST /v1/chat/completions``  — same engine path with the chat
  request/response shapes (``messages`` in, ``delta``/``message`` out);
- ``GET  /v1/models``            — the one served model;
- structured error bodies (``{"error": {message, type, code, param}}``,
  the OpenAI client shape — declared in ``monitor/wire.py`` as
  ``API_ERROR_KEYS`` and lint-pinned here);
- API-key → tenant mapping: ``PTPU_API_KEYS="sk-a:acme:interactive,
  sk-b:free:best-effort"``.  With keys configured, a missing/unknown
  ``Authorization: Bearer`` is a 401; without, the server is open and
  the tenant falls back to the request's ``user`` field.

The server fronts either a local :class:`~.engine.LLMEngine` or the
multi-replica :class:`~.router.Router` — exactly one.  ONE daemon pump
thread owns the backend (HTTP handler threads never touch it): handlers
enqueue submissions and read per-request event queues the pump feeds,
so the engine's single-threaded step loop stays single-threaded no
matter how many sockets are open.

Request deadlines ride the existing path: a body ``deadline_s`` maps to
``SamplingParams.deadline_s``, the engine's deadline sweep aborts the
request at the next step, and the stream sees a clean
``finish_reason="deadline"`` event.  The HTTP side adds a backstop
timer (deadline + grace, or a fixed idle budget) so no stream EVER
hangs past its deadline — even a stalled pump answers with a timeout
error instead of silence.

SLO-aware admission (the scheduler's `should_shed`): when the live
``monitor/slo`` fast-window burn rate breaches ``PTPU_SHED_BURN``,
best-effort requests are answered 429 + ``finish_reason="shed"`` before
they ever reach the queue (the engine sheds already-queued best-effort
work the same way).  HTTP-level client errors (auth/parse) count as
``finish_reason="rejected"`` — both deliberate, both SLO-good.

Tokens in, tokens out: the framework ships no tokenizer, so ``prompt``
is a token-id array by default (OpenAI-legal for /v1/completions) and
string prompts/chat content need an ``encode=`` callable.  ``decode=``
renders emitted ids into the ``text``/``content`` fields (default:
space-separated ids); every choice also carries a ``token_ids``
extension field, which is what the parity tests assert against.
"""
from __future__ import annotations

import itertools
import json
import os
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .. import monitor
from ..monitor import reqlog as mreqlog
from .scheduler import SamplingParams, should_shed, worst_fast_burn

__all__ = ["ApiServer", "start_api_server", "api_error",
           "parse_api_keys"]

# HTTP backstop past the request's own deadline: the engine path
# finishes "deadline" well inside this; the grace only fires when the
# pump itself is wedged (fault injection, dead replica) and turns a
# would-be hang into a clean timeout body.
_DEADLINE_GRACE_S = 5.0
# budget for requests that set no deadline_s — generous, but a BOUND
_DEFAULT_BUDGET_S = 120.0
# handler wait granularity: how often a waiting handler rechecks its
# budget while the pump is quiet
_WAIT_SLICE_S = 1.0


def parse_api_keys(spec: Optional[str] = None) -> dict:
    """``key:tenant[:priority]`` comma list → ``{key: (tenant,
    priority)}`` (default: the ``PTPU_API_KEYS`` env var).  Malformed
    entries are dropped, not fatal — a typo'd key should fail ITS
    requests with 401, not take the server down."""
    if spec is None:
        spec = os.environ.get("PTPU_API_KEYS", "")
    out: dict = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        if not fields[0]:
            continue
        tenant = fields[1] if len(fields) > 1 and fields[1] else None
        priority = fields[2] if len(fields) > 2 and fields[2] else None
        out[fields[0]] = (tenant, priority)
    return out


def api_error(message: str, type: str = "invalid_request_error",
              code: Optional[str] = None,
              param: Optional[str] = None) -> dict:
    """THE canonical error-body builder: the inner object of every
    non-2xx response, lint-pinned to ``wire.API_ERROR_KEYS``."""
    # ptpu-wire: api-error
    err = {
        "message": message,
        "type": type,
        "code": code,
        "param": param,
    }
    return {"error": err}


def _default_decode(ids) -> str:
    """Space-separated token ids — honest output for a tokenizer-less
    framework; chunks concatenate cleanly (each starts with a space)."""
    return "".join(f" {int(t)}" for t in ids)


class _Stream:
    """One in-flight HTTP request's pump-side state + its event queue
    (the ONLY object both a handler thread and the pump touch; the
    queue is the synchronization)."""

    def __init__(self, prompt_ids, params):
        self.prompt_ids = list(prompt_ids)
        self.params = params
        self.q: "queue.Queue" = queue.Queue()
        self.rid = None            # backend id once the pump submits
        self.req = None            # engine-mode: the live Request object
        self.sent = 0              # generated tokens already pushed
        self.cancelled = False     # handler gone — pump must release


class ApiServer:
    """The HTTP tier.  ``engine`` XOR ``router``; ``port=0`` binds an
    ephemeral port (read ``.port``/``.url``).  ``api_keys`` overrides
    the ``PTPU_API_KEYS`` parse; ``encode``/``decode`` bridge strings
    to token ids and back."""

    def __init__(self, engine=None, router=None, host: str = "127.0.0.1",
                 port: int = 0, model_id: str = "paddle-tpu",
                 api_keys: Optional[dict] = None, encode=None,
                 decode=None, poll_s: float = 0.02):
        if (engine is None) == (router is None):
            raise ValueError("exactly one of engine/router")
        self.engine = engine
        self.router = router
        self.model_id = model_id
        self.api_keys = (dict(api_keys) if api_keys is not None
                         else parse_api_keys())
        self.encode = encode
        self.decode = decode or _default_decode
        self.poll_s = float(poll_s)
        self._submit_q: "queue.Queue" = queue.Queue()
        self._streams: dict = {}       # rid -> _Stream (pump-owned)
        self._ids = itertools.count()
        self._m_finish = monitor.counter(
            "serving/finish_reason",
            "finished requests by outcome "
            "(stop|abort|deadline|released|migrated|shed|rejected)")
        self._m_tenant_shed = monitor.counter(
            "serving/tenant_shed",
            "best-effort requests shed by SLO admission control, "
            "by tenant")
        self._m_http = monitor.counter(
            "serving/http_requests", "API requests by response class")
        self._stop = threading.Event()
        self._pump_thread = threading.Thread(
            target=self._pump, name="ptpu-api-pump", daemon=True)
        self._httpd = ThreadingHTTPServer((host, int(port)), _ApiHandler)
        self._httpd.daemon_threads = True
        self._httpd.api = self
        self.host, self.port = self._httpd.server_address[:2]
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, name="ptpu-api-http",
            daemon=True)
        self._pump_thread.start()
        self._http_thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._stop.set()
        self._pump_thread.join(timeout=5)
        self._http_thread.join(timeout=5)

    # -- handler-side API ---------------------------------------------------

    def submit(self, stream: _Stream) -> None:
        self._submit_q.put(stream)

    def live_burn(self) -> float:
        """Worst fast-window burn the shed decision reads: the local SLO
        engine when fronting an engine; the fleet feed's per-replica
        rollup when fronting a router."""
        if self.engine is not None:
            return worst_fast_burn()
        worst = worst_fast_burn()      # router-local SLOs, if any
        try:
            for rec in (self.router.fleet_view() or {}).values():
                b = rec.get("slo_max_burn_rate")
                if b is not None:
                    worst = max(worst, float(b))
        except Exception:   # ptpu-check[silent-except]: a fleet-feed
            # scrape race (replica mid-restart, stale snapshot) must
            # degrade to "no extra burn signal", never fail admission
            pass
        return worst

    # -- the pump (owns the backend; the ONLY backend-touching thread) ------

    def _pump(self) -> None:
        while not self._stop.is_set():
            try:
                self._pump_once()
            except Exception as e:   # a backend failure must surface as
                # clean per-stream errors, never a silent dead pump
                self._fail_all(repr(e))
                time.sleep(self.poll_s)   # no hot-spin on a wedged
                #                           backend that keeps raising

    def _pump_once(self) -> None:
        busy = bool(self._streams)
        self._drain_submits(block_s=0.0 if busy else self.poll_s)
        if self.engine is not None:
            if self.engine.has_unfinished():
                self.engine.step()
            self._push_engine_progress()
        else:
            self.router.poll()
            self._push_router_results()
            if self._streams:
                time.sleep(self.poll_s)

    def _drain_submits(self, block_s: float) -> None:
        try:
            first = self._submit_q.get(timeout=max(block_s, 0.001))
        except queue.Empty:
            return
        items = [first]
        while True:
            try:
                items.append(self._submit_q.get_nowait())
            except queue.Empty:
                break
        for st in items:
            self._handle_submit(st)

    def _handle_submit(self, st: _Stream) -> None:
        try:
            if self.engine is not None:
                st.rid = self.engine.add_request(st.prompt_ids, st.params)
                st.req = self.engine._requests[st.rid]
            else:
                st.rid = self.router.submit(st.prompt_ids, st.params)
        except ValueError as e:
            st.q.put(("reject", str(e)))
            return
        self._streams[st.rid] = st

    def _push_engine_progress(self) -> None:
        for rid, st in list(self._streams.items()):
            if st.cancelled:
                self.engine.release_request(rid)
                del self._streams[rid]
                continue
            new = st.req.output_ids[st.sent:]
            if new:
                st.sent += len(new)
                st.q.put(("tokens", list(new)))
            if st.req.finish_reason is not None:
                st.q.put(("end", st.req.finish_reason))
                self.engine.release_request(rid)
                del self._streams[rid]

    def _push_router_results(self) -> None:
        for rid, st in list(self._streams.items()):
            if st.cancelled:
                self.router.release(rid)
                del self._streams[rid]
                continue
            res = self.router.result(rid)
            if res is None:
                continue
            if res.get("ok"):
                toks = list(res.get("token_ids")
                            or [])[len(st.prompt_ids):]
                if toks:
                    st.q.put(("tokens", toks))
                st.q.put(("end", res.get("finish_reason") or "stop"))
            else:
                reason = res.get("finish_reason") or "abort"
                if reason == "deadline":
                    st.q.put(("end", reason))
                else:
                    st.q.put(("error",
                              res.get("error") or reason))
            self.router.release(rid)
            del self._streams[rid]

    def _fail_all(self, msg: str) -> None:
        for rid, st in list(self._streams.items()):
            st.q.put(("error", msg))
            del self._streams[rid]


class _ApiHandler(BaseHTTPRequestHandler):
    server_version = "ptpu-api/1"

    def log_message(self, *a):   # noqa: D102 — quiet by design; the
        pass                     # monitor counters are the access log

    # -- plumbing -----------------------------------------------------------

    def _send_json(self, code: int, doc: dict,
                   extra_headers=()) -> None:
        data = json.dumps(doc).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        for k, v in extra_headers:
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)
        self.server.api._m_http.labels(code=str(code)).inc()

    def _send_error_body(self, code: int, message: str,
                         type: str = "invalid_request_error",
                         err_code: Optional[str] = None,
                         param: Optional[str] = None,
                         extra_headers=()) -> None:
        if code in (400, 401, 404):
            # HTTP-level client rejection: never reached the scheduler,
            # counted in the finish mix (SLO-good — the client's fault)
            self.server.api._m_finish.labels(reason="rejected").inc()
        self._send_json(code, api_error(message, type=type,
                                        code=err_code, param=param),
                        extra_headers=extra_headers)

    # -- auth / parsing -----------------------------------------------------

    def _auth(self):
        """(tenant, priority) from the bearer key; (None, None) when no
        keys are configured; False after answering 401."""
        api = self.server.api
        if not api.api_keys:
            return (None, None)
        hdr = self.headers.get("Authorization", "")
        key = hdr[len("Bearer "):].strip() \
            if hdr.startswith("Bearer ") else ""
        ent = api.api_keys.get(key)
        if ent is None:
            self._send_error_body(
                401, "missing or unknown API key",
                type="authentication_error", err_code="invalid_api_key")
            return False
        return ent

    def _read_body(self):
        try:
            n = int(self.headers.get("Content-Length", "0"))
            raw = self.rfile.read(n) if n else b""
            doc = json.loads(raw or b"{}")
            if not isinstance(doc, dict):
                raise ValueError("body must be a JSON object")
            return doc
        except (ValueError, OSError) as e:
            self._send_error_body(400, f"invalid JSON body: {e}")
            return None

    def _encode_text(self, text, param):
        api = self.server.api
        if api.encode is None:
            self._send_error_body(
                400, "string prompts need a server-side tokenizer "
                     "(ApiServer(encode=...)); send token-id arrays",
                err_code="no_tokenizer", param=param)
            return None
        return [int(t) for t in api.encode(text)]

    def _prompt_ids(self, body):
        """Token ids from a /v1/completions ``prompt`` (ints, one
        nested int array, or a string via encode); None after 400."""
        prompt = body.get("prompt")
        if isinstance(prompt, str):
            return self._encode_text(prompt, "prompt")
        if isinstance(prompt, list) and prompt:
            if all(isinstance(t, int) for t in prompt):
                return list(prompt)
            if len(prompt) == 1 and isinstance(prompt[0], list) \
                    and all(isinstance(t, int) for t in prompt[0]):
                return list(prompt[0])
        self._send_error_body(
            400, "prompt must be a non-empty token-id array (or a "
                 "string with a server-side tokenizer)", param="prompt")
        return None

    def _chat_ids(self, body):
        """Token ids from ``messages`` — content as int arrays (the
        tokenizer-less extension) or strings via encode."""
        msgs = body.get("messages")
        if not isinstance(msgs, list) or not msgs:
            self._send_error_body(400, "messages must be a non-empty "
                                       "array", param="messages")
            return None
        ids: list = []
        for m in msgs:
            content = m.get("content") if isinstance(m, dict) else None
            if isinstance(content, list) \
                    and all(isinstance(t, int) for t in content):
                ids.extend(content)
            elif isinstance(content, str):
                got = self._encode_text(content, "messages")
                if got is None:
                    return None
                ids.extend(got)
            else:
                self._send_error_body(
                    400, "message content must be a string or a "
                         "token-id array", param="messages")
                return None
        if not ids:
            self._send_error_body(400, "messages encode to an empty "
                                       "prompt", param="messages")
        return ids or None

    def _params(self, body, tenant, priority):
        """SamplingParams from the request body.  OpenAI deviation,
        documented: sampling engages only when ``temperature`` is
        present and > 0 — the default is greedy, the parity oracle."""
        temp = body.get("temperature")
        do_sample = temp is not None and float(temp) > 0
        return SamplingParams(
            max_new_tokens=int(body.get("max_tokens", 16)),
            do_sample=do_sample,
            temperature=float(temp) if do_sample else 1.0,
            top_p=float(body.get("top_p", 1.0)),
            top_k=int(body.get("top_k", 0)),
            seed=(None if body.get("seed") is None
                  else int(body["seed"])),
            eos_token_id=(None if body.get("eos_token_id") is None
                          else int(body["eos_token_id"])),
            deadline_s=(None if body.get("deadline_s") is None
                        else float(body["deadline_s"])),
            tenant=tenant,
            priority=priority or "interactive",
        )

    # -- endpoints ----------------------------------------------------------

    def do_GET(self):   # noqa: N802 (http.server API)
        path = self.path.split("?", 1)[0].rstrip("/")
        if path == "/v1/models":
            api = self.server.api
            self._send_json(200, {
                "object": "list",
                "data": [{"id": api.model_id, "object": "model",
                          "owned_by": "paddle_tpu"}],
            })
        else:
            self._send_error_body(404, f"no route {path}",
                                  type="not_found_error")

    def do_POST(self):   # noqa: N802 (http.server API)
        path = self.path.split("?", 1)[0].rstrip("/")
        if path not in ("/v1/completions", "/v1/chat/completions"):
            self._send_error_body(404, f"no route {path}",
                                  type="not_found_error")
            return
        chat = path.endswith("/chat/completions")
        auth = self._auth()
        if auth is False:
            return
        body = self._read_body()
        if body is None:
            return
        model = body.get("model")
        api = self.server.api
        if model is not None and model != api.model_id:
            self._send_error_body(
                404, f"model {model!r} not found (serving "
                     f"{api.model_id!r})", type="not_found_error",
                err_code="model_not_found", param="model")
            return
        tenant = auth[0] or body.get("user") or None
        priority = body.get("priority") or auth[1]
        ids = self._chat_ids(body) if chat else self._prompt_ids(body)
        if ids is None:
            return
        try:
            params = self._params(body, tenant, priority)
        except (TypeError, ValueError) as e:
            self._send_error_body(400, f"bad sampling field: {e}")
            return
        # SLO-aware admission: shed best-effort work NOW, with a clean
        # 429, instead of queueing it to death (ISSUE 19)
        if should_shed(params.priority, burn=api.live_burn()):
            api._m_finish.labels(reason="shed").inc()
            if tenant:
                api._m_tenant_shed.labels(tenant=tenant).inc()
            if mreqlog.enabled():
                mreqlog.emit(mreqlog.event(
                    f"api-shed-{next(api._ids)}",
                    prompt_tokens=len(ids), finish_reason="shed",
                    tenant=tenant, priority=params.priority))
            self._send_error_body(
                429, "best-effort capacity shed (SLO burn-rate breach); "
                     "retry later", type="rate_limit_error",
                err_code="shed", extra_headers=(("Retry-After", "1"),))
            return
        st = _Stream(ids, params)
        api.submit(st)
        budget = (_DEFAULT_BUDGET_S if params.deadline_s is None
                  else params.deadline_s + _DEADLINE_GRACE_S)
        if body.get("stream"):
            self._respond_stream(st, chat, budget)
        else:
            self._respond_json(st, chat, budget)

    # -- response modes -----------------------------------------------------

    def _next_event(self, st, hard_deadline):
        """One pump event, or ("timeout", None) once the HTTP budget is
        spent — the no-hang backstop.  Never blocks more than
        _WAIT_SLICE_S per poll."""
        while True:
            remaining = hard_deadline - time.monotonic()
            if remaining <= 0:
                st.cancelled = True
                return ("timeout", None)
            try:
                return st.q.get(timeout=min(remaining, _WAIT_SLICE_S))
            except queue.Empty:
                continue

    def _respond_json(self, st, chat, budget):
        hard = time.monotonic() + budget
        toks: list = []
        while True:
            kind, val = self._next_event(st, hard)
            if kind == "tokens":
                toks.extend(val)
            elif kind == "end":
                self._send_completion(st, chat, toks, val)
                return
            elif kind == "reject":
                self._send_error_body(400, val)
                return
            elif kind == "error":
                self._send_error_body(500, val, type="api_error")
                return
            else:   # timeout
                self._send_error_body(
                    504, "request exceeded its deadline budget",
                    type="api_error", err_code="deadline")
                return

    def _send_completion(self, st, chat, toks, reason):
        api = self.server.api
        text = api.decode(toks)
        rid = next(api._ids)
        usage = {"prompt_tokens": len(st.prompt_ids),
                 "completion_tokens": len(toks),
                 "total_tokens": len(st.prompt_ids) + len(toks)}
        if chat:
            doc = {"id": f"chatcmpl-{rid}", "object": "chat.completion",
                   "model": api.model_id,
                   "choices": [{"index": 0,
                                "message": {"role": "assistant",
                                            "content": text},
                                "token_ids": toks,
                                "finish_reason": reason}],
                   "usage": usage}
        else:
            doc = {"id": f"cmpl-{rid}", "object": "text_completion",
                   "model": api.model_id,
                   "choices": [{"index": 0, "text": text,
                                "token_ids": toks,
                                "finish_reason": reason}],
                   "usage": usage}
        self._send_json(200, doc)

    def _respond_stream(self, st, chat, budget):
        """SSE: ``data: <chunk json>`` per pump event, ``data: [DONE]``
        terminator, close-delimited (HTTP/1.0 semantics — no length
        needed).  A mid-stream deadline/error becomes a final chunk
        with the finish reason, then [DONE]: the stream always
        terminates cleanly."""
        api = self.server.api
        rid = next(api._ids)
        started = False
        obj = "chat.completion.chunk" if chat else "text_completion"
        cid = f"chatcmpl-{rid}" if chat else f"cmpl-{rid}"

        def chunk(toks, reason):
            choice = {"index": 0, "token_ids": toks,
                      "finish_reason": reason}
            if chat:
                delta = {} if reason is not None and not toks else \
                    {"content": api.decode(toks)}
                if not started:
                    delta["role"] = "assistant"
                choice["delta"] = delta
            else:
                choice["text"] = api.decode(toks)
            return {"id": cid, "object": obj, "model": api.model_id,
                    "choices": [choice]}

        hard = time.monotonic() + budget
        try:
            while True:
                kind, val = self._next_event(st, hard)
                if kind == "reject" and not started:
                    self._send_error_body(400, val)
                    return
                if kind == "error" and not started:
                    self._send_error_body(500, val, type="api_error")
                    return
                if kind == "timeout" and not started:
                    self._send_error_body(
                        504, "request exceeded its deadline budget",
                        type="api_error", err_code="deadline")
                    return
                if not started:
                    self.send_response(200)
                    self.send_header("Content-Type", "text/event-stream")
                    self.send_header("Cache-Control", "no-cache")
                    self.end_headers()
                    api._m_http.labels(code="200").inc()
                if kind == "tokens":
                    self._sse(chunk(val, None))
                    started = True
                    continue
                # terminal: end / mid-stream error / timeout — one
                # final chunk naming the reason, then the terminator
                reason = val if kind == "end" else (
                    "deadline" if kind == "timeout" else "error")
                self._sse(chunk([], reason))
                self.wfile.write(b"data: [DONE]\n\n")
                self.wfile.flush()
                return
        except (BrokenPipeError, ConnectionResetError, OSError):
            st.cancelled = True   # client went away: pump releases the
            #                       backend request on its next cycle

    def _sse(self, doc: dict) -> None:
        self.wfile.write(b"data: " + json.dumps(doc).encode("utf-8")
                         + b"\n\n")
        self.wfile.flush()


def start_api_server(engine=None, router=None, port=None,
                     **kw) -> ApiServer:
    """Launch an :class:`ApiServer`; ``port`` defaults to
    ``PTPU_API_PORT`` (0 = ephemeral)."""
    if port is None:
        try:
            port = int(os.environ.get("PTPU_API_PORT", "0"))
        except ValueError:
            port = 0
    return ApiServer(engine=engine, router=router, port=port, **kw)
