"""`LLMEngine` — continuous-batching inference over a paged KV cache.

The dense path (`GPTForCausalLM.generate`) runs ONE fixed batch to
completion: no admission, no batching across arrivals, O(S_max) cache per
request.  This engine serves an ever-changing request mix through a small
set of jitted step programs of fixed padded shape (XLA recompiles only
per bucket), with the scheduler — waiting queue, token-budget admission,
preemption — living OUTSIDE the compiled step (the MPK structure from
PAPERS.md: runtime scheduling around static tensor programs).

Step programs (all array-level, weights threaded as inputs):

- ``prefill(P)``   — one request, exact prompt length, causal flash
  attention within the chunk + paged K/V writes.  Exact length (not
  bucketed) on purpose: it makes the prefill arithmetic *identical* to
  the dense path's flash prefill, which is what turns "paged decode
  matches dense generate" from a tolerance into token-for-token equality
  (tests/test_serving.py).  One compile per distinct prompt length — the
  prefill-compile price of exactness; decode, the steady-state loop, is
  ONE fixed-shape program (ragged) or bucketed (fallback).
- ``ragged(B, 1)`` — the decode workhorse (default,
  ``EngineConfig(attention_impl="ragged")`` / env ``PTPU_RAGGED``): per
  layer ONE fused `ops.ragged_paged_attention` call writes the new
  tokens' K/V to their slots and attends the ragged batch against the
  paged pools (int8 dequant folded into the block loads — no separate
  `quantized_gather_kv_arrays` pass).  B is pinned to ``max_num_seqs``,
  so ONE compiled program serves every batch composition — no
  power-of-2 bucket recompiles when the running-request count crosses a
  boundary.  ``ragged(1, C)`` serves chunked-prefill continuations.
- ``chunk(B, C)``  — the bucketed fallback
  (``attention_impl="bucketed"``): gather-blocks + masked attention via
  `ops.paged_attention` with the batch padded to power-of-two buckets
  (the PR-2 dispatch).  Padding rows scatter to a dropped slot and
  their outputs are ignored in both implementations.
- ``sample(B)``    — per-row replication of the dense `_sample_next`
  (greedy argmax / temperature / top-k / top-p + per-request PRNG key
  threading), vmapped so every request reproduces the sampling stream of
  its own solo `generate(seed=...)` call bit-for-bit.

Numerics contract: every op here mirrors the dense path's arithmetic
(same embedding takes, `_stacked_block_body` blocks, `F.layer_norm`
float32 stats, same LM-head einsum, -1e30 masks) so a mixed-length
continuous batch returns exactly the tokens of per-request solo runs.
Scope of the bit-exactness guarantee: it is pinned against the dense
path's masked-softmax DECODE REFERENCE (`cached_attention_arrays`' XLA
branch — the only decode path off-TPU, where the parity tests run).  On
a TPU host the dense oracle may route through the Pallas flash-decode
kernel, whose online-softmax reduction order differs in the last ulp —
there the two paths are mathematically identical but argmax ties can in
principle resolve differently; parity against the reference branch is
the invariant this module maintains.  Assumes AMP autocast is off
(serving is eval-mode; the dense generate path makes the same
assumption).

Monitor wiring (PR-1 StatRegistry): `serving/queue_depth`,
`serving/running`, `serving/waiting`, `serving/blocks_in_use`,
`serving/block_utilization`, `serving/prefill_tokens`,
`serving/decode_tokens`, `serving/prefill_tps`, `serving/decode_tps`,
`serving/preemptions`, `serving/requests_finished`, plus
`serving/step_time` histograms labeled by phase.  ISSUE-12 goodput and
launch accounting: `serving/kernels_per_step` (distinct compiled
programs one decode step dispatches — the mega-kernel before/after
number, flat across batch compositions on the ragged default),
`serving/padding_waste{kind=rows|tokens}` (padded fraction of the
fixed-shape decode program — rows and tokens diverge under speculative
decoding, where a row carries 1+drafts query positions),
`serving/goodput_tokens_per_s` (generated tokens over TOTAL engine step
wall time, prefill/idle included).  ISSUE 15:
`serving/prefix_hits`/`prefix_hit_tokens`/`prefix_evictions` (prefix
caching, counted by the cache) and
`serving/spec_proposed`/`spec_accepted`/`spec_accept_rate`
(speculative decoding).

Observability v2 (monitor.trace): with PTPU_TRACE=1 every request gets a
trace — root `serving/request` span with `serving/queue_wait`,
`serving/prefill` (one per chunk), and `serving/decode_step` children —
readable via `request_trace(rid)`, `/traces/<id>` on the live endpoint
(`EngineConfig(metrics_port=...)`), or `trace.export_chrome_trace()`.
Per-request latency decomposes into `serving/ttft` (arrival → first
token) and `serving/tpot` (inter-token) histograms, recorded whenever
the monitor is on (tracing not required); `serving/compiles{kind}`
counts step-program cache misses.

Request plane (ISSUE 16): `serving/queue_wait` (arrival → first
compute, monitor-gated — visible with tracing off) and
`serving/finish_reason{reason}` (stop/abort/deadline/released/migrated/
shed — the SLO error_rate numerator; "migrated" = handed off to another
replica and "shed" = dropped by SLO admission control, both counted
good) land alongside ttft/tpot; at finish the engine
emits ONE wide `monitor.reqlog` event per request (release time), ticks
`monitor.slo`'s burn-rate engine each step, stamps the request's
trace_id as a histogram exemplar on its ttft/tpot/queue_wait
observations, and marks SLO-violating traces `keep=True` for
tail-based sampling.  All default-off.
"""
from __future__ import annotations

import dataclasses
import os
import time
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from .. import monitor
from ..monitor import trace as mtrace
from ..monitor import perf as mperf
from ..monitor import reqlog as mreqlog
from ..monitor import slo as mslo
from ..monitor import memory as mmem
from ..resilience import faults
from ..resilience.retry import Deadline
from ..ops.paged_attention import (paged_attention_arrays,
                                   paged_cache_update_arrays,
                                   quantized_cache_update_arrays)
from ..ops.ragged_paged_attention import ragged_paged_attention_arrays
from .kv_cache import BlockKVCache, prefix_block_keys
from .scheduler import (Request, SamplingParams, Scheduler, priority_rank,
                        should_shed, worst_fast_burn)
from .spec import propose_ngram

__all__ = ["EngineConfig", "LLMEngine"]

_NEG_INF = -1e30


@dataclasses.dataclass
class EngineConfig:
    block_size: int = 16
    num_blocks: Optional[int] = None       # default: dense-equivalent pool
    max_num_seqs: int = 8
    # prefill token budget per step; None = whole prompt in one chunk
    # (the exact-parity path — chunked prefill is mathematically equal
    # but reassociates float reductions)
    max_num_batched_tokens: Optional[int] = None
    max_model_len: Optional[int] = None    # default: max_position_embeddings
    # "int8" stores the KV pools as int8 codes + per-block-per-head
    # scales (paddle_tpu.lowbit): same pool BYTES hold ~2× (bf16) / ~4×
    # (fp32) the blocks, at a documented decode tolerance vs fp — see
    # tests/test_lowbit.py.  None = full-precision pools (exact parity).
    kv_cache_dtype: Optional[str] = None
    # launch monitor.serve's live endpoint (/metrics, /healthz,
    # /traces/<id>) on this port when the engine boots; 0 = ephemeral
    # (read it back from engine.metrics_server.port), None = no server.
    metrics_port: Optional[int] = None
    # decode attention program (ISSUE 8): "ragged" runs ONE fixed-shape
    # fused program (ops.ragged_paged_attention — in-program cache update,
    # int8 dequant folded in, batch padded to max_num_seqs once) for every
    # batch composition; "bucketed" keeps the PR-2 power-of-2-bucketed
    # gather+attend dispatch as the fallback.  None resolves from env
    # PTPU_RAGGED ("0"/"false"/"off" -> bucketed); default ragged.
    attention_impl: Optional[str] = None
    # ISSUE 15 (a): automatic prefix caching — index full KV blocks by
    # chained content keys as prefill fills them; new requests adopt
    # their longest cached prefix by refcount bump and prefill only the
    # uncached tail (N requests sharing a system prompt pay its prefill
    # once).  Unreferenced prefix blocks park on an LRU and are
    # reclaimed last.  None resolves from env PTPU_PREFIX_CACHE;
    # default OFF (finished requests then pin pool blocks in the index,
    # which changes the blocks_in_use==0-at-idle invariant suites pin).
    enable_prefix_caching: Optional[bool] = None
    # ISSUE 15 (b): speculative decoding — k n-gram/prompt-lookup draft
    # tokens per greedy row, verified in ONE fixed-shape ragged
    # (max_num_seqs, k+1) multi-token program; the longest matching
    # greedy run (plus the correction token) is accepted per step.
    # Token-identical to dense greedy generate(); sampling rows get no
    # drafts (their PRNG stream is preserved exactly — documented
    # scope).  0 = off.  None resolves from env PTPU_SPEC_TOKENS.
    # Requires attention_impl="ragged".
    speculative_tokens: Optional[int] = None
    # n-gram proposer knobs: longest/shortest suffix n-gram tried, and
    # how far back the per-row host scan looks
    spec_ngram_max: int = 3
    spec_ngram_min: int = 1
    spec_lookup_window: int = 1024


class LLMEngine:
    """add_request() / step() / generate() over a stacked-blocks GPT."""

    def __init__(self, model, config: Optional[EngineConfig] = None):
        cfg = model.cfg
        if not cfg.stacked_blocks:
            raise ValueError(
                "LLMEngine serves the stacked-blocks GPT form "
                "(GPTConfig(stacked_blocks=True)) — per-layer Layer "
                "modules would re-trace one program per layer")
        self.model = model
        model.eval()
        self.cfg = cfg
        self.config = config or EngineConfig()
        c = self.config
        self.max_model_len = int(c.max_model_len
                                 or cfg.max_position_embeddings)
        # gathered view width mirrors the dense ring rounding
        # (init_caches: length rounds up to 128) so the decode softmax
        # reduces over the SAME padded extent as the dense oracle
        ring = -(-self.max_model_len // 128) * 128
        self.blocks_per_seq = -(-ring // c.block_size)
        nh = cfg.num_attention_heads
        hd = cfg.hidden_size // nh
        if c.kv_cache_dtype not in (None, "int8"):
            raise ValueError(
                f'kv_cache_dtype must be None or "int8", got '
                f'{c.kv_cache_dtype!r}')
        self._kv_quant = c.kv_cache_dtype
        impl = c.attention_impl
        if impl is None:
            impl = ("bucketed"
                    if os.environ.get("PTPU_RAGGED", "1").lower()
                    in ("0", "false", "off") else "ragged")
        if impl not in ("ragged", "bucketed"):
            raise ValueError(
                f'attention_impl must be "ragged" or "bucketed", got '
                f'{impl!r}')
        self.attention_impl = impl
        wdtype = model.gpt.embeddings.word_embeddings.weight.dtype
        fp_blocks = c.max_num_seqs * self.blocks_per_seq
        if c.num_blocks is not None:
            num_blocks = c.num_blocks
        elif self._kv_quant:
            # same BYTE budget as the full-precision default pool — the
            # whole point: halved/quartered bytes/block ⇒ ~2–4× blocks,
            # fewer preemptions under the same memory ceiling
            budget = fp_blocks * BlockKVCache.block_bytes(
                c.block_size, nh, hd, wdtype) * cfg.num_hidden_layers
            num_blocks = budget // (BlockKVCache.block_bytes(
                c.block_size, nh, hd, wdtype, self._kv_quant)
                * cfg.num_hidden_layers)
        else:
            num_blocks = fp_blocks
        pc = c.enable_prefix_caching
        if pc is None:
            pc = os.environ.get("PTPU_PREFIX_CACHE", "0").lower() in (
                "1", "true", "on")
        self.prefix_caching = bool(pc)
        st = c.speculative_tokens
        if st is None:
            st = int(os.environ.get("PTPU_SPEC_TOKENS", "0") or 0)
        self.spec_tokens = max(0, int(st))
        if self.spec_tokens and self.attention_impl != "ragged":
            raise ValueError(
                "speculative decoding needs the ragged attention path "
                "(the fixed-shape multi-token verify program); "
                'attention_impl="bucketed" cannot serve it')
        self.cache = BlockKVCache(
            cfg.num_hidden_layers, num_blocks, c.block_size, nh, hd,
            dtype=wdtype, kv_quant=self._kv_quant)
        if monitor.enabled():
            monitor.gauge("lowbit/kv_blocks",
                          "paged KV pool size in blocks").labels(
                dtype=self._kv_quant or str(wdtype)).set(num_blocks)
            if self._kv_quant:
                # what THIS pool's block count would have cost at the
                # model dtype, minus what the quantized pool costs
                fp_cost = num_blocks * cfg.num_hidden_layers \
                    * BlockKVCache.block_bytes(c.block_size, nh, hd, wdtype)
                monitor.counter("lowbit/bytes_saved").labels(
                    wing="kv_cache").add(max(0, fp_cost
                                             - self.cache.pool_bytes))
        self.scheduler = Scheduler(
            self.cache, max_num_seqs=c.max_num_seqs,
            max_num_batched_tokens=(c.max_num_batched_tokens
                                    or self.max_model_len),
            spec_tokens=self.spec_tokens,
            max_model_len=self.max_model_len)
        self._requests: dict = {}
        self._next_id = 0
        self._jit_cache: dict = {}
        self._stack_names = list(model.gpt.blocks._names)
        # monitor handles (cheap no-ops when PTPU_MONITOR=0)
        m = monitor
        self._m_queue = m.gauge("serving/queue_depth",
                                "requests waiting for admission")
        self._m_running = m.gauge("serving/running", "requests decoding")
        self._m_waiting = m.gauge("serving/waiting",
                                  "waiting incl. preempted")
        self._m_blocks = m.gauge("serving/blocks_in_use", "KV blocks held")
        self._m_util = m.gauge("serving/block_utilization",
                               "blocks_in_use / num_blocks")
        self._m_pre_toks = m.counter("serving/prefill_tokens")
        self._m_dec_toks = m.counter("serving/decode_tokens")
        self._m_pre_tps = m.gauge("serving/prefill_tps")
        self._m_dec_tps = m.gauge("serving/decode_tps")
        self._m_preempt = m.counter("serving/preemptions")
        self._m_done = m.counter("serving/requests_finished")
        self._m_expired = m.counter("serving/deadline_expired",
                                    "requests aborted past deadline_s")
        self._m_step = m.histogram("serving/step_time")
        self._m_ttft = m.histogram("serving/ttft",
                                   "arrival to first token, seconds")
        self._m_tpot = m.histogram("serving/tpot",
                                   "inter-token latency after the first, "
                                   "seconds")
        # ISSUE 16 request plane: queue wait as a histogram (the PR-5
        # queue_wait SPAN needs tracing on; this is visible with just
        # the monitor), and the completion mix the slo error_rate reads
        self._m_queue_wait = m.histogram(
            "serving/queue_wait",
            "arrival to first prefill compute, seconds")
        self._m_finish = m.counter(
            "serving/finish_reason",
            "finished requests by outcome "
            "(stop|abort|deadline|released|migrated|shed)")
        # ISSUE 19 multi-tenant breakdowns: tenant-labeled counters.
        # Label children materialize only for requests that CARRY a
        # tenant — default-pool traffic exports zero new series.
        self._m_tenant_tokens = m.counter(
            "serving/tenant_tokens", "generated tokens by tenant")
        self._m_tenant_admitted = m.counter(
            "serving/tenant_admitted", "requests accepted by tenant")
        self._m_tenant_shed = m.counter(
            "serving/tenant_shed",
            "best-effort requests shed by SLO admission control, "
            "by tenant")
        self._m_compiles = m.counter("serving/compiles",
                                     "step-program cache misses")
        self._m_attn_impl = m.counter(
            "serving/attention_impl",
            "decode steps served, by attention path")
        # ISSUE 12 goodput/launch accounting: how many separate compiled
        # programs one decode step dispatches (the mega-kernel PR's
        # before/after number — FLAT across batch compositions on the
        # ragged default), and how much of the fixed-shape decode
        # program is padding
        self._m_kernels = m.gauge(
            "serving/kernels_per_step",
            "distinct compiled programs dispatched per decode step")
        pad = m.gauge(
            "serving/padding_waste",
            "padded fraction of the fixed-shape decode program")
        self._m_pad_rows = pad.labels(kind="rows")
        self._m_pad_toks = pad.labels(kind="tokens")
        self._m_goodput = m.gauge(
            "serving/goodput_tokens_per_s",
            "generated tokens per second of total engine step wall "
            "time (prefill/idle/scheduling included)")
        # ISSUE 15 (b): speculative decoding observability — proposed vs
        # accepted draft tokens, and their cumulative ratio
        self._m_spec_prop = m.counter(
            "serving/spec_proposed", "draft tokens proposed")
        self._m_spec_acc = m.counter(
            "serving/spec_accepted", "draft tokens accepted by verify")
        self._m_spec_rate = m.gauge(
            "serving/spec_accept_rate",
            "cumulative accepted/proposed draft-token ratio")
        # ISSUE 20 memory microscope: free/parked gauges fed from the
        # cache's ONE counts() source (satellite: blocks_in_use /
        # block_utilization / the admission view can no longer drift),
        # tenant-labeled capacity attribution (children materialize
        # only for tenant-carrying requests, like the other tenant
        # metrics), and the per-step memobs state (PTPU_MEMOBS-gated)
        self._m_kv_free = m.gauge(
            "serving/kv_free_blocks",
            "truly free KV blocks (free list only, parked excluded)")
        self._m_kv_parked = m.gauge(
            "serving/kv_parked_blocks",
            "LRU-parked prefix blocks (adoptable AND reclaimable)")
        self._m_tenant_kv = m.gauge(
            "serving/kv_blocks_held", "KV blocks held, by tenant")
        self._m_tenant_kv_peak = m.gauge(
            "serving/kv_blocks_peak_share",
            "peak fraction of the KV pool held, by tenant")
        self._tenant_kv_peak: dict = {}
        self._storm = mmem.StormDetector()
        self._memobs_prev = {"evict": 0, "swap_in": 0}
        self._spec_proposed_total = 0
        self._spec_accepted_total = 0
        self._wall_s_total = 0.0
        self._goodput_toks = 0
        self._launches_this_step = None
        # rid -> trace_id survives release_request (the spans live in the
        # bounded monitor.trace store, not on the request); bounded like
        # that store — entries past it map to evicted traces anyway, and
        # an unbounded dict would leak one entry per request served
        from collections import OrderedDict

        self._trace_ids: "OrderedDict" = OrderedDict()
        self.metrics_server = None
        if c.metrics_port is not None:
            from ..monitor import serve as mserve

            self.metrics_server = mserve.start_server(c.metrics_port)

    # -- request API --------------------------------------------------------

    def add_request(self, prompt_ids, sampling_params=None) -> int:
        params = sampling_params or SamplingParams()
        prompt = [int(t) for t in np.asarray(prompt_ids).reshape(-1)]
        if not prompt:
            raise ValueError("empty prompt")
        total = len(prompt) + params.max_new_tokens
        if total > self.max_model_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({params.max_new_tokens}) exceeds max_model_len "
                f"({self.max_model_len})")
        req = Request(self._next_id, prompt, params)
        self._next_id += 1
        req.key = self._init_key(params)
        if params.deadline_s is not None:
            req.deadline = Deadline(params.deadline_s)
        if self.prefix_caching:
            # chained content keys over the prompt's full blocks: the
            # scheduler matches/adopts against them at admission and
            # _prefill_body registers newly-filled blocks under them
            req.prefix_keys = prefix_block_keys(prompt,
                                                self.cache.block_size)
        self._begin_trace(req)
        self._requests[req.req_id] = req
        self.scheduler.add(req)
        if monitor.enabled() and params.tenant:
            self._m_tenant_admitted.labels(tenant=params.tenant).inc()
        return req.req_id

    def fork_request(self, parent_id, sampling_params=None) -> int:
        """Copy-on-fork: a new request continuing the parent's current
        text, SHARING the parent's KV blocks (refcounted; first divergent
        write copies only the shared partial block).  The shared-prompt
        serving shape: N samplings of one prompt pay its prefill once."""
        parent = self._requests[parent_id]
        if parent.state not in (Request.RUNNING,) or not parent.prefill_done:
            raise ValueError(
                "fork requires a running, fully-prefilled parent")
        params = sampling_params or parent.params
        prompt = parent.prompt_ids + parent.output_ids
        total = len(prompt) + params.max_new_tokens
        if total > self.max_model_len:
            raise ValueError("forked request exceeds max_model_len")
        req = Request(self._next_id, prompt, params)
        self._next_id += 1
        req.key = self._init_key(params)
        if params.deadline_s is not None:
            req.deadline = Deadline(params.deadline_s)
        # parent has written total_len-1 positions (the last sampled token
        # is fed next step); the child re-feeds it as its final "prompt"
        # token through its own prefill continuation
        req.num_computed = parent.total_len - 1
        self.cache.fork(parent_id, req.req_id)
        # that re-feed WRITE lands at position total_len-1, which lives in
        # the (shared) last block — privatize it now so the child's
        # recomputation can never perturb the parent's cache
        self.cache.privatize_last_block(req.req_id)
        self._begin_trace(req, forked_from=parent_id)
        self._requests[req.req_id] = req
        self.scheduler.add(req)
        return req.req_id

    def export_request(self, req_id) -> dict:
        """Detach a RUNNING, fully-prefilled request for migration to
        another engine (ISSUE 17 disaggregated prefill→decode): returns
        its prompt, tokens emitted so far, the row's evolved PRNG key,
        and the bit-exact host KV snapshot (`BlockKVCache.swap_out` —
        the preemption swap path, so restore is bit-identical and the
        local blocks are freed).  The local request finishes with
        reason "migrated".  `adopt_request` on the receiving engine is
        the inverse; the pair is token-identical to never migrating
        (greedy and seeded sampling alike — the shipped key IS the
        row's sampling stream)."""
        req = self._requests[req_id]
        if req.finished or not req.prefill_done or not req.output_ids:
            raise ValueError(
                "export_request needs an unfinished, fully-prefilled "
                "request with at least one emitted token (prefill "
                "samples the first token from its final logits)")
        if req not in self.scheduler.running:
            raise ValueError(
                "export_request needs a RUNNING request (a preempted "
                "one already carries its snapshot in req.swap)")
        handoff = {
            "prompt_ids": list(req.prompt_ids),
            "output_ids": list(req.output_ids),
            "params": req.params,
            "key": np.asarray(req.key, np.uint32),
            "kv": self.cache.swap_out(req_id),
        }
        self.scheduler.running.remove(req)
        self._finish_request(req, "migrated")
        req.state = Request.FINISHED
        del self._requests[req_id]
        return handoff

    def adopt_request(self, prompt_ids, sampling_params, output_ids,
                      key, kv) -> int:
        """Admit a mid-flight request exported by another engine's
        `export_request`: the KV snapshot rides the scheduler's
        swap-resume path (restored bit-exactly at admission), decode
        continues from the shipped PRNG key, and — the disaggregation
        point — this engine never runs a prefill program for it: the
        request enters decode-only, so a dedicated decode worker only
        ever dispatches the one fixed-shape ragged(max_num_seqs, 1)
        program."""
        params = sampling_params or SamplingParams()
        prompt = [int(t) for t in np.asarray(prompt_ids).reshape(-1)]
        out = [int(t) for t in output_ids]
        if not prompt or not out:
            raise ValueError("adopt_request needs a prompt and at least "
                             "one emitted token")
        if len(out) >= params.max_new_tokens:
            raise ValueError("request already finished — ship a result, "
                             "not a handoff")
        if len(prompt) + params.max_new_tokens > self.max_model_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({params.max_new_tokens}) exceeds max_model_len "
                f"({self.max_model_len})")
        req = Request(self._next_id, prompt, params)
        self._next_id += 1
        req.output_ids = out
        req.key = jnp.asarray(np.asarray(key, np.uint32))
        if params.deadline_s is not None:
            req.deadline = Deadline(params.deadline_s)
        # the exporter's cache covered positions [0, total_len-1) — the
        # last emitted token is fed (and its K/V written) by the next
        # decode step, exactly as if it had been sampled here
        req.num_computed = req.total_len - 1
        req.swap = kv
        self._begin_trace(req, adopted=True)
        self._requests[req.req_id] = req
        self.scheduler.add(req)
        return req.req_id

    def _begin_trace(self, req, **attrs) -> None:
        """Stamp arrival (TTFT's zero point) and, with tracing on, open
        the request's root span + its queue-wait child."""
        req.arrival_t = time.perf_counter()
        req.arrival_ts = time.time()   # wall clock for the reqlog event
        if mtrace.enabled():
            root = mtrace.start_span(
                "serving/request", rid=req.req_id,
                prompt_len=req.prompt_len,
                max_new_tokens=req.params.max_new_tokens, **attrs)
            req.trace = root
            req.queue_span = mtrace.start_span("serving/queue_wait",
                                               parent=root)
            self._trace_ids[req.req_id] = root.trace_id
            while len(self._trace_ids) > mtrace._MAX_TRACES:
                self._trace_ids.popitem(last=False)

    def _end_trace(self, req, finish: str, keep: bool = False) -> None:
        """Close the request's open spans (idempotent — step() ends
        finished requests, release_request() ends aborted ones).
        ``keep=True`` marks the root for tail sampling's always-keep
        path (an SLO-violating but otherwise normal finish)."""
        if req.queue_span is not None:
            req.queue_span.end(finish=finish)
            req.queue_span = None
        if req.trace is not None:
            if keep:
                req.trace.end(finish=finish,
                              tokens=len(req.output_ids), keep=True)
            else:
                req.trace.end(finish=finish,
                              tokens=len(req.output_ids))
            req.trace = None

    def _finish_request(self, req, reason: str) -> None:
        """The ONE request-finish choke point (idempotent): stamp the
        reason, close spans (marking SLO violators kept for tail
        sampling), count the outcome, and emit the wide reqlog event.
        reasons: "stop" = natural finish, "deadline" = deadline expiry,
        "abort" = released mid-flight, "released" = released while
        still queued (never computed), "migrated" = handed off to
        another replica (drain requeue / failover / disaggregated
        prefill→decode handoff — a success elsewhere, not an error),
        "shed" = best-effort work dropped by SLO-aware admission
        control (ISSUE 19 — deliberate, counted good by the SLO
        error_rate)."""
        if req.finish_reason is not None:
            return
        req.finish_reason = reason
        gen = len(req.output_ids)
        ttft = None
        tpot_avg = None
        if req.first_token_t is not None and req.arrival_t is not None:
            ttft = req.first_token_t - req.arrival_t
        if gen >= 2 and req.first_token_t is not None \
                and req.last_token_t is not None:
            tpot_avg = (req.last_token_t - req.first_token_t) / (gen - 1)
        keep = mslo.enabled() and mslo.violates(
            ttft_s=ttft, tpot_avg_s=tpot_avg,
            queue_wait_s=req.queue_wait_s)
        self._end_trace(req, reason, keep=keep)
        tenant = getattr(req.params, "tenant", None)
        if monitor.enabled():
            self._m_finish.labels(reason=reason).inc()
            if tenant and gen:
                self._m_tenant_tokens.labels(tenant=tenant).inc(gen)
        if mreqlog.enabled():
            mreqlog.emit(mreqlog.event(
                req.req_id,
                trace_id=self._trace_ids.get(req.req_id),
                arrival_ts=req.arrival_ts,
                prompt_tokens=req.prompt_len,
                generated_tokens=gen,
                queue_wait_s=req.queue_wait_s,
                ttft_s=ttft,
                tpot_avg_s=tpot_avg,
                tpot_max_s=req.tpot_max,
                prefill_chunks=req.prefill_chunks,
                prefix_hit_tokens=req.prefix_hit_tokens,
                spec_proposed=req.spec_proposed,
                spec_accepted=req.spec_accepted,
                preemptions=req.num_preemptions,
                peak_kv_blocks=req.peak_kv_blocks,
                finish_reason=reason,
                tenant=tenant,
                priority=getattr(req.params, "priority", None)))

    def request_trace(self, req_id) -> list:
        """The request's finished spans (start-ordered dicts with
        trace/span/parent ids, ts_us/dur_us, attrs) — valid after the
        request is released; [] when it was never traced (PTPU_TRACE off
        at add time) or its trace aged out of the bounded store."""
        tid = self._trace_ids.get(req_id)
        return [] if tid is None else mtrace.get_trace(tid)

    @staticmethod
    def _init_key(params: SamplingParams):
        from ..core import random as _rng

        if params.do_sample:
            if params.seed is not None:
                return jax.random.PRNGKey(params.seed)
            return _rng.next_key()
        return jax.random.PRNGKey(0)    # greedy never consumes it

    def request_output(self, req_id) -> np.ndarray:
        """[prompt + generated] int32 ids (dense generate's row shape)."""
        req = self._requests[req_id]
        return np.asarray(req.prompt_ids + req.output_ids, np.int32)

    def release_request(self, req_id, reason: "str | None" = None) -> None:
        """Drop a request's host state (and abort it if unfinished).
        Callers of the add_request/step API must release requests after
        reading their output — a server that never releases retains every
        prompt/output token list forever.  `generate()` releases its own
        requests.  ``reason`` overrides the finish attribution (the
        deadline sweep passes "deadline"); unfinished releases default
        to "released" while still queued, "abort" mid-flight."""
        req = self._requests.pop(req_id, None)
        if req is None:
            return
        if req.finished:
            self._finish_request(req, "stop")
            return
        if reason is None:
            reason = "released" if req.state == Request.WAITING \
                else "abort"
        self._finish_request(req, reason)
        sched = self.scheduler
        if req in sched.running:
            sched.running.remove(req)
            self.cache.free(req_id)
        elif req in sched.waiting:
            sched.waiting.remove(req)
            if req.req_id in self.cache._tables:   # forked child prefix
                self.cache.free(req_id)
        req.swap = None
        req.state = Request.FINISHED

    def has_unfinished(self) -> bool:
        return self.scheduler.has_work()

    # -- the loop -----------------------------------------------------------

    def generate(self, prompts, sampling_params=None):
        """Run `prompts` (list of id sequences) to completion; returns a
        list of [prompt + generated] int32 arrays in submission order.
        A request aborted by its `SamplingParams.deadline_s` yields None
        in its slot (deadline abort is a cancel, not a truncation)."""
        if sampling_params is None or isinstance(sampling_params,
                                                 SamplingParams):
            params = [sampling_params] * len(prompts)
        else:
            params = list(sampling_params)
            if len(params) != len(prompts):
                raise ValueError("one SamplingParams per prompt (or one "
                                 "shared instance)")
        ids = [self.add_request(p, sp) for p, sp in zip(prompts, params)]
        try:
            while self.scheduler.has_work():
                self.step()
            # a deadline-expired request was aborted and released
            # mid-loop: its row comes back as None (partial output is
            # dropped with the request — deadline abort is a cancel, not
            # a truncation)
            return [self.request_output(i) if i in self._requests else None
                    for i in ids]
        finally:
            # also on error (e.g. a too-small pool raising mid-loop):
            # abandoning admitted requests would leak their KV blocks and
            # poison the next generate() call's work loop
            for i in ids:
                self.release_request(i)

    def _expire_deadlines(self) -> list:
        """Abort every unfinished request whose deadline has passed, via
        the release_request() path (frees its KV blocks / swap snapshot /
        host state — nothing can leak).  Returns the expired ids."""
        expired = [r.req_id for r in self._requests.values()
                   if r.deadline is not None and not r.finished
                   and r.deadline.expired]
        for rid in expired:
            self.release_request(rid, reason="deadline")
            self._m_expired.inc()
        return expired

    def _shed_best_effort(self) -> list:
        """SLO-aware load shedding (ISSUE 19): when the live fast-window
        burn rate breaches `PTPU_SHED_BURN`, drop every still-WAITING
        best-effort request with reason "shed" — bounded time instead of
        queued to death, via the release_request() path so nothing
        leaks.  Interactive/batch classes are never shed (they defer).
        Returns the shed ids."""
        floor = priority_rank("best-effort")
        cand = [r for r in self._requests.values()
                if r.state == Request.WAITING and not r.finished
                and priority_rank(getattr(r.params, "priority", None))
                >= floor]
        if not cand or not mslo.enabled():
            return []
        burn = worst_fast_burn()
        shed = [r for r in cand
                if should_shed(getattr(r.params, "priority", None),
                               burn=burn)]
        for r in shed:
            tenant = getattr(r.params, "tenant", None)
            self.release_request(r.req_id, reason="shed")
            if monitor.enabled() and tenant:
                self._m_tenant_shed.labels(tenant=tenant).inc()
        return [r.req_id for r in shed]

    def step(self) -> list:
        """One scheduler decision + one jitted exec.  Returns the requests
        that FINISHED this step."""
        t0 = time.perf_counter()
        # deterministic hang injection (PTPU_FAULTS="stall@site=engine.step,
        # secs=..."): the step blocks here, completing no span, so the
        # monitor.watchdog post-mortem path is provable in tests
        faults.maybe_stall(site="engine.step")
        self._expire_deadlines()
        self._shed_best_effort()
        try:
            out = self.scheduler.schedule()
        except RuntimeError as e:
            # ISSUE 20 pressure forensics: an admission failure ("KV
            # cache too small") leaves a kv_pressure flight dump naming
            # who actually holds the pool, then propagates untouched
            if "KV cache too small" in str(e):
                self._kv_pressure("admission_failure", error=str(e))
            raise
        if out.preempted:
            self._m_preempt.inc(len(out.preempted))
            for r in out.preempted:
                r.num_preemptions += 1
        if out.kind == "prefill":
            self._step_prefill(out)
            phase, toks = "prefill", out.chunk_len
        elif out.kind == "decode":
            # spec decoding can emit MORE tokens than rows in one step —
            # the decode body reports the real emitted count
            toks = self._step_decode(out)
            phase = "decode"
        else:
            phase, toks = "idle", 0
        if mreqlog.enabled():
            # peak-KV high-water per request: only worth the O(running)
            # walk when someone is collecting the wide events
            for r in self.scheduler.running:
                blocks = len(self.cache._tables.get(r.req_id, ()))
                if blocks > r.peak_kv_blocks:
                    r.peak_kv_blocks = blocks
        done = self.scheduler.retire_finished()
        for req in done:
            self._m_done.inc()
            self._finish_request(req, "stop")
        mslo.maybe_tick()   # one module-global read with PTPU_SLO unset
        dt = time.perf_counter() - t0
        mtrace.heartbeat()   # step completed — feed the watchdog even
        #                      with tracing off (no span ends to beat)
        if monitor.enabled():
            self._m_step.labels(phase=phase).observe(dt)
            # goodput: generated tokens over TOTAL engine wall time —
            # decode_tps reads a single step, this reads the serving
            # story (prefill, scheduling, idle steps all dilute it)
            self._wall_s_total += dt
            if phase == "prefill":
                self._m_pre_toks.inc(toks)
                self._m_pre_tps.set(toks / max(dt, 1e-9))
            elif phase == "decode":
                self._m_dec_toks.inc(toks)
                self._m_dec_tps.set(toks / max(dt, 1e-9))
                self._goodput_toks += toks
            self._m_goodput.set(
                self._goodput_toks / max(self._wall_s_total, 1e-9))
            sched = self.scheduler
            # queue_depth: admission backlog (never-started requests);
            # waiting: everything not running, preempted included
            self._m_queue.set(sum(1 for r in sched.waiting
                                  if r.state == Request.WAITING))
            self._m_running.set(len(sched.running))
            self._m_waiting.set(len(sched.waiting))
            # ISSUE 20: every capacity gauge reads the cache's ONE
            # counts() source — utilization and the admission view
            # (free+parked) can no longer be computed in two places
            c = self.cache.counts()
            self._m_blocks.set(c["in_use"])
            self._m_util.set(c["in_use"] / max(c["total"], 1))
            self._m_kv_free.set(c["free"])
            self._m_kv_parked.set(c["parked"])
        if mmem.enabled():
            self._memobs_step(out)
        return list(done)

    # -- memory microscope (ISSUE 20; PTPU_MEMOBS-gated) --------------------

    def _memobs_step(self, out) -> None:
        """Per-step memory-microscope sampling: one HBM/host timeline
        reading, tenant-labeled capacity attribution, the eviction-
        storm/swap-thrash detector, and the interval-limited /kv pool-
        map publication.  Everything here is host-side dict walking —
        the sequence is charged in bench.py --config trace_overhead
        and must stay inside the <5%-enabled budget."""
        cache = self.cache
        c = cache.counts()
        # (b) timeline: compiled-program HBM peak (perf capture; None
        # with perf off), live KV-pool bytes, host RSS (TTL-cached)
        peak = None
        for rec in mperf.records():
            pk = rec.peak_bytes
            if pk and (peak is None or pk > peak):
                peak = pk
        mmem.sample(hbm_peak=peak,
                    hbm_in_use=c["in_use"] * cache.bytes_per_block,
                    host_rss=mmem.host_rss_bytes())
        # (d) per-tenant capacity attribution (held now + peak share)
        total = max(c["total"], 1)
        for r in self._requests.values():
            tenant = getattr(r.params, "tenant", None)
            if not tenant:
                continue
            t = cache._tables.get(r.req_id)
            if not t:
                continue
            held = self._tenant_kv_peak.setdefault(tenant, [0, 0.0])
            held[0] += len(t)
        for tenant, held in self._tenant_kv_peak.items():
            blocks, peak_share = held
            self._m_tenant_kv.labels(tenant=tenant).set(blocks)
            share = blocks / total
            if share > peak_share:
                held[1] = share
                self._m_tenant_kv_peak.labels(tenant=tenant).set(share)
            held[0] = 0   # re-summed next step
        # (c) storm / swap-thrash detector: preemptions this step plus
        # parked-block evictions and swap-ins since the last step
        ev = cache.acct.events
        x = (len(out.preempted)
             + (ev["evict"] - self._memobs_prev["evict"])
             + (ev["swap_in"] - self._memobs_prev["swap_in"]))
        self._memobs_prev["evict"] = ev["evict"]
        self._memobs_prev["swap_in"] = ev["swap_in"]
        fire = self._storm.observe(x)
        if fire is not None:
            self._kv_pressure("eviction_storm", **fire)
        # (a) the /kv pool map — rebuilt at most every
        # KV_PUBLISH_INTERVAL_S (the fast path is one monotonic read)
        mmem.maybe_publish_kv(lambda: mmem.build_kv_snapshot(
            cache, list(self._requests.values())))

    def _kv_pressure(self, trigger: str, **info) -> "str | None":
        """Write one rate-limited, replica-tagged ``kv_pressure``
        flight dump naming the ranked pool holders, and refresh the
        published /kv map so the endpoint agrees with the forensics."""
        if not mmem.enabled():
            return None
        requests = list(self._requests.values())
        mmem.publish_kv(mmem.build_kv_snapshot(self.cache, requests))
        extra = {"holders": mmem.rank_holders(self.cache, requests),
                 "counts": self.cache.counts()}
        extra.update(info)
        return mmem.reporter().maybe_dump(trigger, extra=extra)

    # -- step bodies --------------------------------------------------------

    def _step_prefill(self, out):
        req = out.prefill_request
        start, chunk = out.chunk_start, out.chunk_len
        req.prefill_chunks += 1
        if req.queue_wait_s is None and req.arrival_t is not None:
            # first compute: queue wait over — recorded as a histogram
            # so it is visible with tracing off (ISSUE 16 satellite)
            req.queue_wait_s = time.perf_counter() - req.arrival_t
            self._m_queue_wait.observe(
                req.queue_wait_s,
                trace_id=req.trace.trace_id
                if req.trace is not None else None)
        if req.queue_span is not None:   # first compute: queue wait over
            req.queue_span.end()
            req.queue_span = None
        sp = None
        if req.trace is not None:
            sp = mtrace.start_span("serving/prefill", parent=req.trace,
                                   chunk_start=start, chunk_len=chunk)
        try:
            self._prefill_body(req, start, chunk)
        finally:
            if sp is not None:
                sp.end()

    def _prefill_body(self, req, start, chunk):
        ids = np.asarray([req.prompt_ids[start:start + chunk]], np.int32)
        positions = np.arange(start, start + chunk, dtype=np.int64)
        slots = np.asarray(
            [[self.cache.slot(req.req_id, int(p)) for p in positions]],
            np.int32)
        kv = self._kv_flat()
        if start == 0 and chunk == req.prompt_len:
            # whole prompt in one chunk: flash within the chunk, the
            # dense prefill's exact arithmetic
            fn = self._get_prefill_exec(chunk)
            logits, kv_out = fn(self._param_arrays(), kv, jnp.asarray(ids),
                                jnp.asarray(slots))
        else:
            tables = jnp.asarray(
                [self.cache.padded_table(req.req_id, self.blocks_per_seq)],
                jnp.int32)
            if self.attention_impl == "ragged":
                fn = self._get_ragged_exec(1, chunk)
                logits, kv_out = fn(
                    self._param_arrays(), kv, jnp.asarray(ids),
                    jnp.asarray([start], jnp.int32),
                    jnp.asarray([start + chunk], jnp.int32), tables,
                    jnp.asarray(slots))
            else:
                fn = self._get_chunk_exec(1, chunk)
                logits, kv_out = fn(
                    self._param_arrays(), kv, jnp.asarray(ids),
                    jnp.asarray([start], jnp.int32), tables,
                    jnp.asarray(slots))
        self._store_kv(kv_out)
        req.num_computed = start + chunk
        if req.prefix_keys:
            # index the blocks this chunk just filled (full prompt blocks
            # only — their content is final while referenced)
            self.cache.register_prefix(req.req_id, req.prefix_keys,
                                       req.num_computed)
        if req.prefill_done:
            if req.params.max_new_tokens <= 0:
                # dense generate(max_new_tokens=0) emits nothing
                req.state = Request.FINISHED
            else:
                self._sample_rows([req], logits)

    def _step_decode(self, out) -> int:
        rows = list(out.decode_requests)
        spans = [mtrace.start_span("serving/decode_step", parent=r.trace,
                                   pos=r.total_len - 1, batch=len(rows))
                 for r in rows if r.trace is not None]
        try:
            return self._decode_body(rows)
        finally:
            for sp in spans:
                sp.end()

    def _decode_body(self, rows) -> int:
        if self.spec_tokens:
            drafts = [self._propose(r) for r in rows]
            if any(drafts):
                return self._decode_body_spec(rows, drafts)
            # zero drafts anywhere this step (cold history, sampling
            # rows, n-gram misses): the plain (bb, 1) program is
            # strictly cheaper — C=1 compute, kernel-eligible on TPU —
            # than a verify launch whose k draft positions are all
            # padding.  Both shapes compile once; steady state stays
            # two launches either way.
            n = self._decode_body_plain(rows)
            for req in rows:
                # release the scheduler's (clamped) draft reservation
                self.cache.truncate_to(req.req_id, req.total_len)
            return n
        return self._decode_body_plain(rows)

    def _decode_body_plain(self, rows) -> int:
        # perf mode (PTPU_PERF=1): the decode hot path reports named,
        # properly-synced sub-step segments — host `prep`, the fused
        # `model` program (gather+attention+cache update), and `sampler`
        # (timed inside _sample_rows, whose np.asarray readback syncs it)
        perf_on = mperf.enabled()
        t0 = time.perf_counter() if perf_on else 0.0
        n = len(rows)
        mon = monitor.enabled()
        # launch accounting (ISSUE 12): every jitted dispatch this step
        # records its cache key; the gauge is the LIVE twin of the
        # BENCH_NOTES round-2 hand count — len() only, never iterated
        self._launches_this_step = set() if mon else None
        ragged = self.attention_impl == "ragged"
        # ragged: ONE fixed shape (max_num_seqs) serves every batch
        # composition — no per-bucket recompiles when the running-request
        # count crosses a power of 2
        bb = (self.scheduler.max_num_seqs if ragged
              else self._bucket_batch(n))
        num_slots = self.cache.num_slots
        # recompile-hazard markers below: on the ragged DEFAULT bb is
        # the FIXED max_num_seqs (zero hazard); only the bucketed
        # fallback derives bb from len(rows), and there the pow-2
        # bucketing bounds the program count at log2(max_num_seqs) BY
        # DESIGN (pinned by the bucket-crossing recompile tests)
        toks = np.zeros((bb, 1), np.int32)  # ptpu-check[recompile-hazard]: pow2-bounded, see above
        pos0 = np.zeros((bb,), np.int32)  # ptpu-check[recompile-hazard]: pow2-bounded, see above
        lens = np.zeros((bb,), np.int32)  # ptpu-check[recompile-hazard]: pow2-bounded, see above
        tables = np.full((bb, self.blocks_per_seq), self.cache.num_blocks,
                         np.int32)  # ptpu-check[recompile-hazard]: pow2-bounded, see above
        slots = np.full((bb, 1), num_slots, np.int32)  # ptpu-check[recompile-hazard]: pow2-bounded, see above
        for i, req in enumerate(rows):
            toks[i, 0] = req.output_ids[-1] if req.output_ids \
                else req.prompt_ids[-1]
            p = req.total_len - 1
            pos0[i] = p
            lens[i] = req.total_len
            tables[i] = self.cache.padded_table(req.req_id,
                                                self.blocks_per_seq)
            slots[i, 0] = self.cache.slot(req.req_id, p)
        self._m_attn_impl.labels(kind=self.attention_impl).inc()
        if perf_on:
            t1 = time.perf_counter()
            mperf.observe_segment("decode", "prep", t1 - t0)
        if ragged:
            fn = self._get_ragged_exec(bb, 1)
            if mon:
                self._launches_this_step.add(("ragged", bb, 1))
            logits, kv_out = fn(self._param_arrays(), self._kv_flat(),
                                jnp.asarray(toks), jnp.asarray(pos0),
                                jnp.asarray(lens), jnp.asarray(tables),
                                jnp.asarray(slots))
        else:
            fn = self._get_chunk_exec(bb, 1)
            if mon:
                self._launches_this_step.add(("chunk", bb, 1))
            logits, kv_out = fn(self._param_arrays(), self._kv_flat(),
                                jnp.asarray(toks), jnp.asarray(pos0),
                                jnp.asarray(tables), jnp.asarray(slots))
        if perf_on:
            jax.block_until_ready(logits)
            mperf.observe_segment("decode", "model",
                                  time.perf_counter() - t1)
        self._store_kv(kv_out)
        self._sample_rows(rows, logits)
        if mon:
            # padding accounting: bb rows ran, n were real — the
            # serving-goodput blind spot the ragged fixed-shape program
            # introduced.  Decode runs C=1, so rows ARE tokens and the
            # two series carry one value today; they diverge only if a
            # multi-token decode (speculative verification, ROADMAP
            # item 1) lands on this path — the schema reserves the
            # distinction now so consumers never need a migration
            waste = (bb - n) / max(bb, 1)
            self._m_pad_rows.set(waste)
            self._m_pad_toks.set(waste)
            self._m_kernels.set(len(self._launches_this_step))
            self._launches_this_step = None
        return n

    # -- speculative decoding (ISSUE 15 b) ----------------------------------

    def _propose(self, req) -> list:
        """Draft tokens for one row.  Sampling rows get none — their
        per-request PRNG stream must advance exactly one draw per
        emitted token, the documented scope of the seeded-sampling
        parity guarantee.  The budget clamps so (emitted ≤ drafts+1)
        never overshoots max_new_tokens and no draft position's write
        ever reaches max_model_len."""
        p = req.params
        if p.do_sample:
            return []
        budget = min(self.spec_tokens,
                     p.max_new_tokens - len(req.output_ids) - 1,
                     self.max_model_len - req.total_len)
        if budget <= 0:
            return []
        c = self.config
        return propose_ngram(req.prompt_ids + req.output_ids, budget,
                             ngram_max=c.spec_ngram_max,
                             ngram_min=c.spec_ngram_min,
                             window=c.spec_lookup_window)

    def _decode_body_spec(self, rows, drafts) -> int:
        """Speculative decode step: ONE fixed-shape ragged
        (max_num_seqs, k+1) verify program scores the last real token
        plus up to k n-gram drafts per row against the paged pools
        (cache update in-program — write-then-attend puts the drafts'
        K/V in the pool before their own queries run, so in-chunk
        causality is the pool's), the longest greedy-matching draft run
        plus the correction token is accepted, and the block table rolls
        back to the accepted length.  Multiple tokens per step at the
        same TWO program launches as plain decode."""
        perf_on = mperf.enabled()
        t0 = time.perf_counter() if perf_on else 0.0
        n = len(rows)
        mon = monitor.enabled()
        self._launches_this_step = set() if mon else None
        k = self.spec_tokens
        cw = k + 1                     # verify chunk width, fixed
        bb = self.scheduler.max_num_seqs
        num_slots = self.cache.num_slots
        # fixed [bb, k+1] shapes: bb is the engine-constant max_num_seqs
        # and k the engine-constant draft budget — zero recompile hazard
        toks = np.zeros((bb, cw), np.int32)
        pos0 = np.zeros((bb,), np.int32)
        lens = np.zeros((bb,), np.int32)
        tables = np.full((bb, self.blocks_per_seq), self.cache.num_blocks,
                         np.int32)
        slots = np.full((bb, cw), num_slots, np.int32)
        for i, req in enumerate(rows):
            toks[i, 0] = req.output_ids[-1] if req.output_ids \
                else req.prompt_ids[-1]
            m = len(drafts[i])
            if m:
                toks[i, 1:1 + m] = drafts[i]
            p = req.total_len - 1
            pos0[i] = p
            lens[i] = req.total_len + m
            tables[i] = self.cache.padded_table(req.req_id,
                                                self.blocks_per_seq)
            for j in range(1 + m):
                # draft positions past m keep the dropped-slot sentinel:
                # no write, garbage logits the emission loop never reads
                slots[i, j] = self.cache.slot(req.req_id, p + j)
        self._m_attn_impl.labels(kind=self.attention_impl).inc()
        if perf_on:
            t1 = time.perf_counter()
            mperf.observe_segment("decode", "prep", t1 - t0)
        fn = self._get_verify_exec(bb, cw)
        if mon:
            self._launches_this_step.add(("verify", bb, cw))
        logits0, greedy, kv_out = fn(
            self._param_arrays(), self._kv_flat(), jnp.asarray(toks),
            jnp.asarray(pos0), jnp.asarray(lens), jnp.asarray(tables),
            jnp.asarray(slots))
        if perf_on:
            jax.block_until_ready(logits0)
            mperf.observe_segment("decode", "model",
                                  time.perf_counter() - t1)
        self._store_kv(kv_out)
        emitted = self._emit_spec(rows, drafts, logits0,
                                  np.asarray(greedy))
        # roll every table back to its accepted length (rejected-draft
        # blocks return to the pool; finished rows are freed by
        # retire_finished right after — truncating first keeps the
        # shared-block refcounts exact either way)
        for req in rows:
            self.cache.truncate_to(req.req_id, req.total_len)
        if mon:
            real_q = n + sum(len(d) for d in drafts)
            self._m_pad_rows.set((bb - n) / max(bb, 1))
            self._m_pad_toks.set((bb * cw - real_q) / max(bb * cw, 1))
            self._m_kernels.set(len(self._launches_this_step))
            self._launches_this_step = None
        return emitted

    def _emit_spec(self, rows, drafts, logits0, greedy_h) -> int:
        """Per-row acceptance + emission.  The position-0 logits run
        through the SAME (\"sample\", bb) program as plain decode — key
        threading and sampling rows' streams are bit-identical to
        spec-off — and greedy rows then extend with their longest
        verified draft run: draft j is accepted iff it equals the greedy
        token at position j-1, which validates position j's logits,
        whose greedy token is emitted (the correction/bonus token ends
        the run)."""
        perf_on = mperf.enabled()
        t0 = time.perf_counter() if perf_on else 0.0
        bb = int(logits0.shape[0])
        keys = np.zeros((bb, 2), np.uint32)
        ds = np.zeros((bb,), bool)
        temp = np.ones((bb,), np.float32)
        topk = np.zeros((bb,), np.int32)
        topp = np.ones((bb,), np.float32)
        for i, req in enumerate(rows):
            p = req.params
            keys[i] = np.asarray(req.key, np.uint32)
            ds[i] = p.do_sample
            temp[i] = p.temperature
            topk[i] = p.top_k
            topp[i] = p.top_p
        fn = self._get_sample_exec(bb)
        if self._launches_this_step is not None:
            self._launches_this_step.add(("sample", bb))
        toks, new_keys = fn(logits0, jnp.asarray(keys), jnp.asarray(ds),
                            jnp.asarray(temp), jnp.asarray(topk),
                            jnp.asarray(topp))
        toks = np.asarray(toks)
        new_keys = np.asarray(new_keys)
        now = time.perf_counter()
        if perf_on:
            mperf.observe_segment("decode", "sampler", now - t0)
        emitted = proposed = accepted = 0
        for i, req in enumerate(rows):
            req.key = jnp.asarray(new_keys[i], jnp.uint32)
            out = [int(toks[i])]
            m = len(drafts[i])
            proposed += m
            if not req.params.do_sample:
                g = greedy_h[i]
                # out[0] == g[0]: both argmax the same fp32 logits row
                for j in range(1, m + 1):
                    if int(drafts[i][j - 1]) != int(g[j - 1]):
                        break
                    out.append(int(g[j]))
            row_emitted = 0
            for tok in out:
                req.record_token(tok)
                row_emitted += 1
                self._record_latency(req, now)
                if req.finished:
                    break          # eos inside the accepted run
            emitted += row_emitted
            accepted += row_emitted - 1
            req.spec_proposed += m
            req.spec_accepted += row_emitted - 1
        self._spec_proposed_total += proposed
        self._spec_accepted_total += accepted
        if monitor.enabled():
            if proposed:
                self._m_spec_prop.inc(proposed)
            if accepted:
                self._m_spec_acc.inc(accepted)
            if self._spec_proposed_total:
                self._m_spec_rate.set(self._spec_accepted_total
                                      / self._spec_proposed_total)
        return emitted

    def _record_latency(self, req, now) -> None:
        """Per-token TTFT/TPOT attribution (the serving-paper
        decomposition); tokens accepted in one spec step share a
        timestamp — their inter-token latency really is ~0.  Each
        observation carries the request's trace_id so PTPU_EXEMPLARS can
        link a bucket to its kept tail-sampled trace."""
        tid = req.trace.trace_id if req.trace is not None else None
        # ISSUE 19: tenant-carrying requests ALSO observe into a
        # tenant-labeled child series; the unlabeled parent observe
        # stays — it is what slo.Objective's latency percentiles read
        tenant = getattr(req.params, "tenant", None)
        if req.first_token_t is None:
            req.first_token_t = now
            if req.arrival_t is not None:
                ttft = now - req.arrival_t
                self._m_ttft.observe(ttft, trace_id=tid)
                if tenant:
                    self._m_ttft.labels(tenant=tenant).observe(ttft)
        else:
            gap = now - req.last_token_t
            self._m_tpot.observe(gap, trace_id=tid)
            if tenant:
                self._m_tpot.labels(tenant=tenant).observe(gap)
            if req.tpot_max is None or gap > req.tpot_max:
                req.tpot_max = gap
        req.last_token_t = now

    def _sample_rows(self, rows, logits):
        """Sample one token per live row from [B, V] fp32 logits (B may
        exceed len(rows) by padding)."""
        perf_on = mperf.enabled()   # read once: flipping perf on between
        # here and the observe below must not pair a real clock with t0=0
        t0 = time.perf_counter() if perf_on else 0.0
        bb = int(logits.shape[0])
        keys = np.zeros((bb, 2), np.uint32)
        ds = np.zeros((bb,), bool)
        temp = np.ones((bb,), np.float32)
        topk = np.zeros((bb,), np.int32)
        topp = np.ones((bb,), np.float32)
        for i, req in enumerate(rows):
            p = req.params
            keys[i] = np.asarray(req.key, np.uint32)
            ds[i] = p.do_sample
            temp[i] = p.temperature
            topk[i] = p.top_k
            topp[i] = p.top_p
        fn = self._get_sample_exec(bb)
        if self._launches_this_step is not None:   # decode-step launch
            # accounting only; the prefill path samples too but is not
            # the steady-state loop the kernel count instruments
            self._launches_this_step.add(("sample", bb))
        toks, new_keys = fn(logits, jnp.asarray(keys), jnp.asarray(ds),
                            jnp.asarray(temp), jnp.asarray(topk),
                            jnp.asarray(topp))
        toks = np.asarray(toks)
        new_keys = np.asarray(new_keys)
        now = time.perf_counter()
        if perf_on:
            # np.asarray above synced the sampler outputs: now - t0 is
            # its true wall time (sampler is its own dispatch)
            mperf.observe_segment("decode", "sampler", now - t0)
        for i, req in enumerate(rows):
            req.key = jnp.asarray(new_keys[i], jnp.uint32)
            req.record_token(int(toks[i]))
            self._record_latency(req, now)

    # -- perf attribution ---------------------------------------------------

    def decode_breakdown(self, reps: int = 2) -> dict:
        """Roofline attribution of the decode step at this engine's LIVE
        shapes (ISSUE 6 / ROADMAP item 1's targeting data).

        The production decode program fuses block gather, attention and
        cache update into one XLA executable, so their split cannot be
        observed in situ; this runs each named segment as its own
        compiled program over the live KV pools — properly synced,
        best-of-``reps`` — and attributes each against its own XLA
        cost-analysis prediction via ``monitor.perf.measure``.  Also
        measures the real fused step program (``decode:step``) so the
        segment sum can be compared against what fusion actually buys.

        Returns ``{segment: perf-record dict}`` plus ``"worst"``: the
        segment with the lowest achieved-vs-optimal ratio — the next
        kernel to rewrite.  Segment arithmetic mirrors
        ``ops.paged_attention`` exactly; numbers are attribution
        estimates (the fused program may never materialize the gather),
        which is precisely their job.

        On the ragged path (ISSUE 8) the dict additionally carries
        ``"ragged_fused"`` — the fused update+attention program of
        `ops.ragged_paged_attention` per layer — so the before-side trio
        (block_gather/attention/cache_update) and the after-side fusion
        sit in ONE report and the fusion win is readable as
        ``ragged_fused.wall_time_s`` vs the trio's sum.
        """
        cfg = self.cfg
        L = cfg.num_hidden_layers
        nh = cfg.num_attention_heads
        hd = cfg.hidden_size // nh
        ragged = self.attention_impl == "ragged"
        # the LIVE decode batch width: the ragged program runs at
        # max_num_seqs, the bucketed fallback at its full-batch bucket
        bb = (self.scheduler.max_num_seqs if ragged
              else self._bucket_batch(self.scheduler.max_num_seqs))
        s_pad = self.blocks_per_seq * self.cache.block_size
        num_slots = self.cache.num_slots
        wdtype = self.model.gpt.embeddings.word_embeddings.weight.dtype
        kv_flat = self._kv_flat()
        tables = (jnp.arange(bb * self.blocks_per_seq, dtype=jnp.int32)
                  % max(self.cache.num_blocks, 1)).reshape(
            bb, self.blocks_per_seq)
        pos0 = jnp.full((bb,), s_pad - 1, jnp.int32)
        slots = (jnp.arange(bb, dtype=jnp.int32) * self.cache.block_size
                 % num_slots).reshape(bb, 1)
        q = jnp.zeros((bb, 1, nh, hd), wdtype)
        rows = jnp.zeros((bb, 1, nh, hd), wdtype)
        quant = bool(self._kv_quant)
        stride = 4 if quant else 2

        from ..ops.paged_attention import (paged_gather_kv_arrays,
                                           quantized_gather_kv_arrays)

        def gather_fn(kv, tbl):
            acc = jnp.float32(0.0)
            for l in range(L):
                part = kv[stride * l:stride * (l + 1)]
                if quant:
                    kg = quantized_gather_kv_arrays(part[0], part[2], tbl)
                    vg = quantized_gather_kv_arrays(part[1], part[3], tbl)
                else:
                    kg = paged_gather_kv_arrays(part[0], tbl)
                    vg = paged_gather_kv_arrays(part[1], tbl)
                acc += jnp.sum(kg.astype(jnp.float32)) \
                    + jnp.sum(vg.astype(jnp.float32))
            return acc

        # one layer's gathered view feeds the attention segment for all L
        # iterations (per-iteration q offsets defeat CSE, so every layer
        # pays its reads/FLOPs in the cost model and on the device)
        if quant:
            kg0 = quantized_gather_kv_arrays(kv_flat[0], kv_flat[2], tables)
            vg0 = quantized_gather_kv_arrays(kv_flat[1], kv_flat[3], tables)
        else:
            kg0 = paged_gather_kv_arrays(kv_flat[0], tables)
            vg0 = paged_gather_kv_arrays(kv_flat[1], tables)

        def attention_fn(q_, kg, vg, pos0_):
            import math as _math

            scale = 1.0 / _math.sqrt(hd)
            acc = jnp.float32(0.0)
            k_pos = jnp.arange(s_pad, dtype=jnp.int32)
            for l in range(L):
                ql = q_ + jnp.asarray(l, q_.dtype)
                logits = jnp.einsum(
                    "bqhd,bkhd->bhqk", ql, kg,
                    preferred_element_type=jnp.float32) * scale
                causal = k_pos[None, None, :] <= pos0_[:, None, None]
                logits = jnp.where(causal[:, None], logits, _NEG_INF)
                probs = jax.nn.softmax(logits, axis=-1)
                o = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(vg.dtype),
                               vg)
                acc += jnp.sum(o.astype(jnp.float32))
            return acc

        def update_fn(kv, rows_, slots_):
            out = list(kv)
            for l in range(L):
                if quant:
                    k2, ks2 = quantized_cache_update_arrays(
                        kv[4 * l], kv[4 * l + 2], rows_, slots_)
                    v2, vs2 = quantized_cache_update_arrays(
                        kv[4 * l + 1], kv[4 * l + 3], rows_, slots_)
                    out[4 * l:4 * l + 4] = [k2, v2, ks2, vs2]
                else:
                    out[2 * l] = paged_cache_update_arrays(
                        kv[2 * l], rows_, slots_)
                    out[2 * l + 1] = paged_cache_update_arrays(
                        kv[2 * l + 1], rows_, slots_)
            return tuple(out)

        kv_copy = tuple(jnp.array(a, copy=True) for a in kv_flat)
        out = {
            "block_gather": mperf.measure(
                gather_fn, kv_flat, tables,
                label="decode:block_gather", reps=reps),
            "attention": mperf.measure(
                attention_fn, q, kg0, vg0, pos0,
                label="decode:attention", reps=reps),
            "cache_update": mperf.measure(
                update_fn, kv_copy, rows, slots,
                label="decode:cache_update", reps=reps,
                donate_argnums=(0,)),
        }
        lens = jnp.full((bb,), s_pad, jnp.int32)
        if ragged:
            # the ISSUE-8 after-side: ONE fused program per layer doing
            # update + attention (+ int8 dequant at the loads) — measured
            # against the same roofline as the before-side trio above
            def ragged_fn(kv, q_, rows_, slots_):
                kvo = list(kv)
                acc = jnp.float32(0.0)
                for l in range(L):
                    ql = q_ + jnp.asarray(l, q_.dtype)   # defeat CSE
                    part = kv[stride * l:stride * (l + 1)]
                    if quant:
                        o, k2, v2, ks2, vs2 = ragged_paged_attention_arrays(
                            ql, rows_, rows_, part[0], part[1], tables,
                            pos0, lens, slots_,
                            k_scales=part[2], v_scales=part[3])
                        kvo[stride * l:stride * (l + 1)] = [k2, v2, ks2,
                                                            vs2]
                    else:
                        o, k2, v2 = ragged_paged_attention_arrays(
                            ql, rows_, rows_, part[0], part[1], tables,
                            pos0, lens, slots_)
                        kvo[stride * l:stride * (l + 1)] = [k2, v2]
                    acc += jnp.sum(o.astype(jnp.float32))
                return tuple(kvo), acc

            kv_copy_r = tuple(jnp.array(a, copy=True) for a in kv_flat)
            out["ragged_fused"] = mperf.measure(
                ragged_fn, kv_copy_r, q, rows, slots,
                label="decode:ragged_fused", reps=reps,
                donate_argnums=(0,),
                rearm=lambda args, o: (o[0],) + args[1:])
        # the real step programs, measured as compiled (donated pools
        # ping-ponged through the output so the engine's live cache is
        # never consumed)
        toks = jnp.zeros((bb, 1), jnp.int32)
        kv_copy2 = tuple(jnp.array(a, copy=True) for a in kv_flat)
        if ragged:
            out["step"] = mperf.measure(
                self._get_ragged_exec(bb, 1),
                self._param_arrays(), kv_copy2, toks, pos0, lens, tables,
                slots, label="decode:step", reps=reps,
                rearm=lambda args, o: args[:1] + (o[1],) + args[2:])
        else:
            out["step"] = mperf.measure(
                self._get_chunk_exec(bb, 1),
                self._param_arrays(), kv_copy2, toks, pos0, tables, slots,
                label="decode:step", reps=reps,
                rearm=lambda args, o: args[:1] + (o[1],) + args[2:])
        logits = jnp.zeros((bb, cfg.vocab_size), jnp.float32)
        out["sampler"] = mperf.measure(
            self._get_sample_exec(bb),
            logits, jnp.zeros((bb, 2), jnp.uint32),
            jnp.zeros((bb,), bool), jnp.ones((bb,), jnp.float32),
            jnp.zeros((bb,), jnp.int32), jnp.ones((bb,), jnp.float32),
            label="decode:sampler_exec", reps=reps)
        # NOT "decode:sampler": the in-situ segment record of that name
        # has no cost analysis, so _match_record would merge this
        # compiled program's flops into its host-loop-inflated walls
        ranked = [(name, d["achieved_vs_optimal"])
                  for name, d in out.items()
                  if name != "step" and d.get("achieved_vs_optimal")]
        out["worst"] = (min(ranked, key=lambda kv_: kv_[1])[0]
                        if ranked else None)
        return out

    # -- array plumbing -----------------------------------------------------

    def _param_arrays(self):
        gpt = self.model.gpt
        params = {n: getattr(gpt.blocks, n)._data for n in self._stack_names}
        params["wte"] = gpt.embeddings.word_embeddings.weight._data
        params["wpe"] = gpt.embeddings.position_embeddings.weight._data
        params["lnf_w"] = gpt.ln_f.weight._data
        params["lnf_b"] = gpt.ln_f.bias._data
        return params

    def _kv_flat(self):
        c = self.cache
        if self._kv_quant:
            return tuple(a for quad in zip(c.k_blocks, c.v_blocks,
                                           c.k_scales, c.v_scales)
                         for a in quad)
        return tuple(a for pair in zip(c.k_blocks, c.v_blocks)
                     for a in pair)

    def _store_kv(self, kv_out):
        L = self.cfg.num_hidden_layers
        c = self.cache
        if self._kv_quant:
            c.k_blocks = [kv_out[4 * l] for l in range(L)]
            c.v_blocks = [kv_out[4 * l + 1] for l in range(L)]
            c.k_scales = [kv_out[4 * l + 2] for l in range(L)]
            c.v_scales = [kv_out[4 * l + 3] for l in range(L)]
        else:
            c.k_blocks = [kv_out[2 * l] for l in range(L)]
            c.v_blocks = [kv_out[2 * l + 1] for l in range(L)]

    # -- jitted step programs ----------------------------------------------

    def _bucket_batch(self, n: int) -> int:
        """Power-of-2 decode bucket — the PR-2 dispatch, reachable only
        through the "bucketed" fallback path (the ragged program always
        runs at max_num_seqs, so batch-composition changes never
        recompile)."""
        bb = 1
        while bb < n:
            bb *= 2
        return min(max(bb, 1), self.scheduler.max_num_seqs)

    # key-tuple field names per program kind — the engine's jit-cache key
    # IS its compile signature, so the recompile explainer (ISSUE 12)
    # diffs keys instead of arg signatures
    _KEY_FIELDS = {"prefill": ("prompt_len",),
                   "chunk": ("batch", "chunk_len"),
                   "ragged": ("batch", "chunk_len"),
                   "verify": ("batch", "chunk_len"),
                   "sample": ("batch",)}

    def _count_compile(self, kind: str, key=None) -> None:
        """A step-program cache miss: counted as `serving/compiles{kind}`
        AND into the framework-wide `jit/recompiles{fn}` attribution (the
        engine drives jax.jit directly, bypassing jit.CompiledFunction's
        counter — the bucket-crossing regression test reads this).

        With `key` (the jit-cache tuple, not yet inserted), the miss is
        additionally EXPLAINED when a same-kind program already exists:
        the first differing key field names the varying axis
        (`jit/recompile_cause{fn,axis}`, e.g. the bucketed fallback's
        "batch 4→8" at a bucket crossing), and a breadcrumb lands in the
        flight ring so post-mortem dumps explain compile storms.  The
        ragged decode program never varies by batch, so its cause series
        stays empty across compositions — the regression-tested
        invariant."""
        self._m_compiles.labels(kind=kind).inc()
        if not monitor.enabled():
            return
        fname = f"serving:{kind}"
        monitor.counter(
            "jit/recompiles",
            "fresh trace+XLA-compile events per function").labels(
            fn=fname).inc()
        if key is None:
            return
        prior = [k for k in self._jit_cache if k[0] == kind]
        if not prior:
            return   # first program of this kind: a compile, not a RE-compile
        fields = self._KEY_FIELDS.get(kind, ())
        best = max(prior, key=lambda k: sum(
            a == b for a, b in zip(k[1:], key[1:])))
        diffs = [i for i, (a, b) in enumerate(zip(best[1:], key[1:]))
                 if a != b]
        if not diffs:
            return
        i = diffs[0]
        axis = fields[i] if i < len(fields) else f"field{i}"
        detail = f"{axis} {best[1 + i]}→{key[1 + i]}"
        monitor.counter(
            "jit/recompile_cause",
            "recompiles by the signature axis that varied").labels(
            fn=fname, axis=axis).inc()
        monitor.flight.note("jit/recompile", fn=fname, axis=axis,
                            detail=detail)

    def _model_logits(self, params, h):
        """Final LN + tied LM head over EVERY position — the dense
        path's ln_f arithmetic (`F.layer_norm`, NOT the block
        `_stacked_ln`) and lm_head einsum, shared at array level so
        parity tracks the oracle by construction.  ALL logits-producing
        step programs (prefill/chunk/ragged tails AND the spec verify
        program) go through here: a change to the oracle tail reaches
        them all."""
        from ..nn.functional import layer_norm_arrays

        hn = layer_norm_arrays(h, params["lnf_w"], params["lnf_b"],
                               epsilon=self.cfg.layer_norm_epsilon)
        return jnp.einsum("bsh,vh->bsv", hn, params["wte"])

    def _model_tail(self, params, h):
        """Last position's fp32 logits — the decode/prefill tail."""
        return self._model_logits(params, h)[:, -1].astype(jnp.float32)

    def _run_blocks(self, params, kv_flat, x, attn_builder):
        from ..models.gpt import _stacked_block_body

        cfg = self.cfg
        nh = cfg.num_attention_heads
        hd = cfg.hidden_size // nh
        eps = cfg.layer_norm_epsilon
        stride = 4 if self._kv_quant else 2
        h = x
        outs = []
        for l in range(cfg.num_hidden_layers):
            layer_kv = kv_flat[stride * l:stride * (l + 1)]
            p = {n: params[n][l] for n in self._stack_names}
            attn_fn = attn_builder(*layer_kv)
            h, extra = _stacked_block_body(p, h, attn_fn, nh, hd, eps)
            outs += list(extra)
        return h, tuple(outs)

    def _get_prefill_exec(self, p_len):
        key = ("prefill", p_len)
        if key not in self._jit_cache:
            self._count_compile("prefill", key)

            def fn(params, kv_flat, ids, slots):
                from ..ops.pallas_ops import flash_attention_arrays

                pos = jnp.arange(ids.shape[1], dtype=jnp.int32)
                x = jnp.take(params["wte"], ids, axis=0) \
                    + jnp.take(params["wpe"], pos, axis=0)

                def builder(kc, vc, ksc=None, vsc=None):
                    def attn_fn(q, k, v, kc=kc, vc=vc, ksc=ksc, vsc=vsc):
                        # flash within the chunk reads the fp K/V it just
                        # computed — only the STORED cache is quantized
                        if ksc is None:
                            kc2 = paged_cache_update_arrays(kc, k, slots)
                            vc2 = paged_cache_update_arrays(vc, v, slots)
                            extra = (kc2, vc2)
                        else:
                            kc2, ks2 = quantized_cache_update_arrays(
                                kc, ksc, k, slots)
                            vc2, vs2 = quantized_cache_update_arrays(
                                vc, vsc, v, slots)
                            extra = (kc2, vc2, ks2, vs2)
                        o = flash_attention_arrays(q, k, v, is_causal=True)
                        return o, extra
                    return attn_fn

                h, kv_out = self._run_blocks(params, kv_flat, x, builder)
                return self._model_tail(params, h), kv_out

            self._jit_cache[key] = jax.jit(fn, donate_argnums=(1,))
        return self._jit_cache[key]

    def _get_chunk_exec(self, b, c):
        key = ("chunk", b, c)
        if key not in self._jit_cache:
            self._count_compile("chunk", key)

            def fn(params, kv_flat, ids, pos0, tables, slots):
                pos = pos0[:, None] + jnp.arange(c, dtype=jnp.int32)[None]
                x = jnp.take(params["wte"], ids, axis=0) \
                    + jnp.take(params["wpe"], pos, axis=0)

                def builder(kc, vc, ksc=None, vsc=None):
                    def attn_fn(q, k, v, kc=kc, vc=vc, ksc=ksc, vsc=vsc):
                        # write-then-attend, the dense cache ordering
                        if ksc is None:
                            kc2 = paged_cache_update_arrays(kc, k, slots)
                            vc2 = paged_cache_update_arrays(vc, v, slots)
                            o = paged_attention_arrays(q, kc2, vc2, tables,
                                                       pos0)
                            return o, (kc2, vc2)
                        # lowbit KV: quantizing write, dequantizing
                        # gather — the current chunk's own K/V round-trip
                        # through int8 too (attend-from-pool, so every
                        # position sees ONE consistent representation)
                        kc2, ks2 = quantized_cache_update_arrays(
                            kc, ksc, k, slots)
                        vc2, vs2 = quantized_cache_update_arrays(
                            vc, vsc, v, slots)
                        o = paged_attention_arrays(
                            q, kc2, vc2, tables, pos0,
                            k_scales=ks2, v_scales=vs2)
                        return o, (kc2, vc2, ks2, vs2)
                    return attn_fn

                h, kv_out = self._run_blocks(params, kv_flat, x, builder)
                return self._model_tail(params, h), kv_out

            self._jit_cache[key] = jax.jit(fn, donate_argnums=(1,))
        return self._jit_cache[key]

    def _get_ragged_exec(self, b, c):
        """The ISSUE-8 decode program: per layer, ONE fused
        `ragged_paged_attention_arrays` call does cache write + attention
        (+ int8 dequant at the block loads) — no separate
        `block_gather/attention/cache_update` triple.  At (max_num_seqs,
        1) this is the single compiled program every decode batch
        composition runs."""
        key = ("ragged", b, c)
        if key not in self._jit_cache:
            self._count_compile("ragged", key)

            def fn(params, kv_flat, ids, pos0, lens, tables, slots):
                pos = pos0[:, None] + jnp.arange(c, dtype=jnp.int32)[None]
                x = jnp.take(params["wte"], ids, axis=0) \
                    + jnp.take(params["wpe"], pos, axis=0)

                def builder(kc, vc, ksc=None, vsc=None):
                    def attn_fn(q, k, v, kc=kc, vc=vc, ksc=ksc, vsc=vsc):
                        if ksc is None:
                            o, kc2, vc2 = ragged_paged_attention_arrays(
                                q, k, v, kc, vc, tables, pos0, lens,
                                slots)
                            return o, (kc2, vc2)
                        o, kc2, vc2, ks2, vs2 = \
                            ragged_paged_attention_arrays(
                                q, k, v, kc, vc, tables, pos0, lens,
                                slots, k_scales=ksc, v_scales=vsc)
                        return o, (kc2, vc2, ks2, vs2)
                    return attn_fn

                h, kv_out = self._run_blocks(params, kv_flat, x, builder)
                return self._model_tail(params, h), kv_out

            self._jit_cache[key] = jax.jit(fn, donate_argnums=(1,))
        return self._jit_cache[key]

    def _get_verify_exec(self, b, c):
        """The ISSUE-15 multi-token scoring program: the ragged fused
        update+attend body at [b, c] (identical to `_get_ragged_exec` up
        to the tail), returning EVERY position's greedy argmax plus the
        position-0 fp32 logits (the sampler's input).  ONE fixed shape
        (max_num_seqs, spec_tokens+1) serves every batch composition and
        every draft hit/miss mix — padded draft positions carry dropped
        slots and their outputs are never read."""
        key = ("verify", b, c)
        if key not in self._jit_cache:
            self._count_compile("verify", key)

            def fn(params, kv_flat, ids, pos0, lens, tables, slots):
                pos = pos0[:, None] + jnp.arange(c, dtype=jnp.int32)[None]
                x = jnp.take(params["wte"], ids, axis=0) \
                    + jnp.take(params["wpe"], pos, axis=0)

                def builder(kc, vc, ksc=None, vsc=None):
                    def attn_fn(q, k, v, kc=kc, vc=vc, ksc=ksc, vsc=vsc):
                        if ksc is None:
                            o, kc2, vc2 = ragged_paged_attention_arrays(
                                q, k, v, kc, vc, tables, pos0, lens,
                                slots)
                            return o, (kc2, vc2)
                        o, kc2, vc2, ks2, vs2 = \
                            ragged_paged_attention_arrays(
                                q, k, v, kc, vc, tables, pos0, lens,
                                slots, k_scales=ksc, v_scales=vsc)
                        return o, (kc2, vc2, ks2, vs2)
                    return attn_fn

                h, kv_out = self._run_blocks(params, kv_flat, x, builder)
                logits = self._model_logits(params, h).astype(jnp.float32)
                greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return logits[:, 0], greedy, kv_out

            self._jit_cache[key] = jax.jit(fn, donate_argnums=(1,))
        return self._jit_cache[key]

    def _get_sample_exec(self, b):
        key = ("sample", b)
        if key not in self._jit_cache:
            self._count_compile("sample", key)

            def row(l, key_, ds, t, k, p):
                # replicates models.gpt._sample_next on a [1, V] row so a
                # request reproduces its solo generate() stream exactly
                l1 = l[None, :]
                greedy = jnp.argmax(l1, axis=-1).astype(jnp.int32)[0]
                ks = jax.random.split(key_)
                new_key, sub = ks[0], ks[1]
                ll = l1 / jnp.maximum(t, jnp.float32(1e-6))
                v = ll.shape[-1]
                asc = jnp.sort(ll, axis=-1)
                kth = jnp.take_along_axis(
                    asc, jnp.clip(v - k, 0, v - 1)[None, None], axis=-1)
                ll = jnp.where(k > 0, jnp.where(ll < kth, _NEG_INF, ll), ll)
                desc = jnp.sort(ll, axis=-1)[:, ::-1]
                probs = jax.nn.softmax(desc, axis=-1)
                cum = jnp.cumsum(probs, axis=-1)
                keep = cum - probs <= p
                thresh = jnp.min(jnp.where(keep, desc, jnp.inf), axis=-1,
                                 keepdims=True)
                ll = jnp.where(p < 1.0,
                               jnp.where(ll < thresh, _NEG_INF, ll), ll)
                samp = jax.random.categorical(sub, ll, axis=-1).astype(
                    jnp.int32)[0]
                tok = jnp.where(ds, samp, greedy)
                out_key = jnp.where(ds, new_key, key_)
                return tok, out_key

            self._jit_cache[key] = jax.jit(jax.vmap(row))
        return self._jit_cache[key]
