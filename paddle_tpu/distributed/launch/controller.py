"""Launcher controller: rendezvous, worker pod, watcher.

Reference analog: launch/controllers/collective.py (CollectiveController
.build_pod + watch), launch/job/pod.py (Container process wrapper),
launch/utils/kv_server.py (master KV) — re-designed around host-level
worker processes and the native TCPStore.
"""
from __future__ import annotations

import dataclasses
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence


@dataclasses.dataclass
class LaunchConfig:
    script: str = ""
    script_args: Sequence[str] = ()
    nnodes: int = 1
    nproc_per_node: int = 1
    master: Optional[str] = None          # "host:port" KV master / coordinator
    node_rank: Optional[int] = None       # None -> rendezvous via master KV
    job_id: str = "default"
    log_dir: str = "log"
    max_restarts: int = 0                 # >0 enables elastic pod restarts
    rendezvous_timeout: float = 120.0
    envs: Dict[str, str] = dataclasses.field(default_factory=dict)
    module: bool = False                  # python -m script
    # a node slot whose controller heartbeat is older than this is
    # considered dead and may be reclaimed by a replacement node
    # (reference: ETCDMaster TTL registrations, launch/controllers/master.py)
    stale_timeout: float = 30.0


class Controller:
    def __init__(self, cfg: LaunchConfig):
        self.cfg = cfg
        self.procs: List[subprocess.Popen] = []
        self.logs: List = []
        self._store = None
        self._server = None
        self._token: Optional[bytes] = None   # slot-ownership fencing token
        self._no_hb_since: Dict[int, float] = {}

    # -- rendezvous --------------------------------------------------------
    # (liveness protocol is intentionally self-contained; fleet/elastic.py's
    # ElasticManager runs a similar TTL heartbeat for TRAINING-process
    # membership — this one leases controller node slots, a different
    # lifecycle. Cross-check both when changing either.)
    def _hb_key(self, slot: int) -> str:
        return f"{self.cfg.job_id}/hb/{slot}"

    def _owner_key(self, slot: int) -> str:
        return f"{self.cfg.job_id}/owner/{slot}"

    def _heartbeat(self, slot: int) -> bool:
        """Renew the slot lease. Returns False when ownership was lost
        (another node took the slot over) — the holder must fence.
        Ownership is a token in the owner key that ONLY an actual takeover
        (compare_set) changes; claim losers never mutate it, so a contested
        startup can't spuriously fence the winner."""
        if self._store is None:
            return True
        try:
            if (self._token is not None and
                    self._store.get(self._owner_key(slot), timeout_ms=2000)
                    != self._token):
                return False   # usurped: a reclaimer swapped the owner token
            self._store.set(self._hb_key(slot),
                            str(time.time()).encode())
        except (OSError, RuntimeError, TimeoutError):
            pass   # store unreachable: keep running, lease may expire
        return True

    def _slot_stale(self, slot: int, max_wait_ms: Optional[int] = None) -> bool:
        # a slow/loaded master must not masquerade as a dead owner: give the
        # heartbeat read real headroom (not a 200 ms hair-trigger) before
        # starting the no-heartbeat grace clock
        get_timeout_ms = max(2000, int(self.cfg.stale_timeout * 1000 / 3))
        if max_wait_ms is not None:
            get_timeout_ms = max(200, min(get_timeout_ms, max_wait_ms))
        try:
            raw = self._store.get(self._hb_key(slot),
                                  timeout_ms=get_timeout_ms)
            self._no_hb_since.pop(slot, None)
            # ptpu-check[wall-clock]: cross-process heartbeat — another
            # node WROTE this wall-clock value; monotonic clocks don't
            # travel between hosts, so wall-vs-wall is the only comparison
            return time.time() - float(raw.decode()) > self.cfg.stale_timeout
        except Exception:
            # claimed but no heartbeat yet: live during a grace window
            # (claimant writes its first beat right after claiming), stale
            # if the beat never appears — a claimant that died immediately
            # must not wedge the slot forever
            # grace window is LOCAL elapsed time -> monotonic (an NTP
            # step must not instantly expire or stretch it)
            first = self._no_hb_since.setdefault(slot, time.monotonic())
            return time.monotonic() - first > self.cfg.stale_timeout

    def _resolve_node_rank(self) -> int:
        """Claim a node slot through the KV master. Fresh slots are taken
        first-come; a slot whose owner's heartbeat went stale (controller
        died) is RECLAIMED by a replacement node — the elastic re-admit
        path (reference: master.py:79 ETCD node registry with TTL +
        watcher-driven re-admission). Latest claimant wins a contested
        stale slot; heartbeats keep live owners uncontested."""
        cfg = self.cfg
        if cfg.nnodes <= 1:
            return 0
        if cfg.node_rank is not None:
            return int(cfg.node_rank)
        if not cfg.master:
            raise ValueError("--master host:port is required when nnodes > 1")
        from ..store import TCPStore

        host, port = cfg.master.rsplit(":", 1)
        # the lowest-rank candidate hosts the KV (reference: launch master
        # auto-elected by who binds the port first)
        try:
            self._server = TCPStore(host, int(port), is_master=True,
                                    timeout=cfg.rendezvous_timeout)
            self._store = self._server
        except (OSError, RuntimeError):
            self._store = TCPStore(host, int(port), is_master=False,
                                   timeout=cfg.rendezvous_timeout)
        # Unique per-controller token (the add-counter is only a sequence
        # dispenser here — nobody compares its value, so concurrent bumps
        # are harmless, unlike the old add-based claim).
        uid = self._store.add(f"{cfg.job_id}/token_seq", 1)
        token = f"{os.getpid()}:{uid}".encode()
        deadline = time.monotonic() + cfg.rendezvous_timeout
        while True:
            for slot in range(cfg.nnodes):
                # heartbeat reads on claimed-but-silent slots block; bound
                # them by the remaining budget so a sweep over several dead
                # claimants cannot overshoot rendezvous_timeout by minutes
                remaining_ms = int((deadline - time.monotonic()) * 1000)
                if remaining_ms <= 0:
                    break
                okey = self._owner_key(slot)
                # non-mutating owner probe: our token never matches a
                # foreign owner, so this compare_set is a pure read
                # (returns b"" for an unclaimed slot)
                cur = self._store.compare_set(okey, token, token)
                if cur == b"":
                    # PRE-BEAT before the claim: a claimant descheduled
                    # between winning the claim and its first heartbeat
                    # write would otherwise look stale under load and get
                    # hijacked (observed under 7-way CI contention).
                    # Refreshing the beat of a slot another racer is
                    # simultaneously claiming is benign — that racer is
                    # alive by definition.
                    self._store.set(self._hb_key(slot),
                                    str(time.time()).encode())
                    if self._store.compare_set(okey, b"", token) == token:
                        self._token = token
                        self._no_hb_since.pop(slot, None)
                        return slot
                    continue  # lost the race for this slot
                if self._slot_stale(slot, max_wait_ms=remaining_ms):
                    # PRE-BEAT for the takeover, same reasoning: without
                    # it a second reclaimer can hijack the first before
                    # its first beat lands, fencing a healthy winner
                    self._store.set(self._hb_key(slot),
                                    str(time.time()).encode())
                    # atomic takeover: swap the owner token from the stale
                    # holder's to ours; only the reclaimer whose compare_set
                    # lands first wins, and the old owner's next heartbeat
                    # sees the foreign token and fences
                    won = self._store.compare_set(okey, cur, token)
                    if won != token:
                        continue
                    self._token = token
                    self._no_hb_since.pop(slot, None)
                    print(f"[launch] reclaimed stale node slot {slot} "
                          f"of job {cfg.job_id!r} (token {token.decode()})",
                          flush=True)
                    return slot
            if time.monotonic() >= deadline:
                raise RuntimeError(
                    f"no free node slot in job {cfg.job_id!r} "
                    f"(nnodes={cfg.nnodes}, all slots held by live nodes)")
            time.sleep(0.5)

    # -- pod lifecycle -----------------------------------------------------
    def _worker_env(self, node_rank: int, local_rank: int) -> Dict[str, str]:
        cfg = self.cfg
        world = cfg.nnodes * cfg.nproc_per_node
        rank = node_rank * cfg.nproc_per_node + local_rank
        env = dict(os.environ)
        env.update(cfg.envs)
        env.update(
            PADDLE_TRAINER_ID=str(rank),
            PADDLE_TRAINERS_NUM=str(world),
            PADDLE_LOCAL_RANK=str(local_rank),
            PADDLE_NNODES=str(cfg.nnodes),
            PADDLE_JOB_ID=cfg.job_id,
        )
        if cfg.master:
            # jax.distributed coordinator rides the port after the KV port
            host, port = cfg.master.rsplit(":", 1)
            env["PADDLE_MASTER"] = f"{host}:{int(port) + 1}"
        return env

    def build_pod(self, node_rank: int):
        cfg = self.cfg
        os.makedirs(cfg.log_dir, exist_ok=True)
        for lr in range(cfg.nproc_per_node):
            rank = node_rank * cfg.nproc_per_node + lr
            logf = open(os.path.join(cfg.log_dir, f"workerlog.{rank}"), "ab")
            cmd = [sys.executable]
            if cfg.module:
                cmd += ["-m", cfg.script]
            else:
                cmd += [cfg.script]
            cmd += list(cfg.script_args)
            p = subprocess.Popen(
                cmd, env=self._worker_env(node_rank, lr),
                stdout=logf, stderr=subprocess.STDOUT,
                start_new_session=True,
            )
            self.procs.append(p)
            self.logs.append(logf)

    def _tail_rank0(self, pos: int) -> int:
        """Mirror new rank-0 log bytes to our stdout (reference watcher
        tails container 0)."""
        try:
            path = self.logs[0].name
            with open(path, "rb") as f:
                f.seek(pos)
                data = f.read()
            if data:
                sys.stdout.buffer.write(data)
                sys.stdout.flush()
            return pos + len(data)
        except (IndexError, OSError):
            return pos

    def stop_pod(self, sig=signal.SIGTERM):
        for p in self.procs:
            if p.poll() is None:
                try:
                    os.killpg(p.pid, sig)
                except (ProcessLookupError, PermissionError):
                    pass
        deadline = time.monotonic() + 10
        for p in self.procs:
            try:
                p.wait(max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(p.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
        for f in self.logs:
            try:
                f.close()
            except OSError:
                pass
        self.procs, self.logs = [], []

    def watch(self, node_rank: int = 0) -> int:
        """Poll children until all succeed or one fails (fail-fast);
        heartbeats the node's slot so live nodes are never reclaimed."""
        pos = 0
        last_hb = 0.0
        while True:
            if time.monotonic() - last_hb > max(self.cfg.stale_timeout / 3,
                                                0.5):
                if not self._heartbeat(node_rank):
                    # fenced: lease lost to a replacement node — running on
                    # would split-brain the slot (duplicate global ranks)
                    print(f"[launch] node slot {node_rank} lease lost; "
                          "fencing this pod", flush=True)
                    self.stop_pod()
                    return 102   # reference ELASTIC re-plan exit code
                last_hb = time.monotonic()
            pos = self._tail_rank0(pos)
            codes = [p.poll() for p in self.procs]
            if any(c not in (None, 0) for c in codes):
                bad = next(i for i, c in enumerate(codes)
                           if c not in (None, 0))
                rc = codes[bad]
                self.stop_pod()
                return rc
            if all(c == 0 for c in codes):
                self._tail_rank0(pos)
                return 0
            time.sleep(0.2)

    def run(self) -> int:
        cfg = self.cfg
        node_rank = self._resolve_node_rank()
        restarts = 0
        while True:
            self.build_pod(node_rank)
            rc = self.watch(node_rank)
            if rc == 0 or restarts >= cfg.max_restarts:
                return rc
            restarts += 1
            print(f"[launch] pod failed rc={rc}; elastic restart "
                  f"{restarts}/{cfg.max_restarts}", flush=True)


def launch_job(cfg: LaunchConfig) -> int:
    return Controller(cfg).run()
