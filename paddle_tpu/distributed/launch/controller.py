"""Launcher controller: rendezvous, worker pod, watcher.

Reference analog: launch/controllers/collective.py (CollectiveController
.build_pod + watch), launch/job/pod.py (Container process wrapper),
launch/utils/kv_server.py (master KV) — re-designed around host-level
worker processes and the native TCPStore.
"""
from __future__ import annotations

import dataclasses
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence


@dataclasses.dataclass
class LaunchConfig:
    script: str = ""
    script_args: Sequence[str] = ()
    nnodes: int = 1
    nproc_per_node: int = 1
    master: Optional[str] = None          # "host:port" KV master / coordinator
    node_rank: Optional[int] = None       # None -> rendezvous via master KV
    job_id: str = "default"
    log_dir: str = "log"
    max_restarts: int = 0                 # >0 enables elastic pod restarts
    rendezvous_timeout: float = 120.0
    envs: Dict[str, str] = dataclasses.field(default_factory=dict)
    module: bool = False                  # python -m script
    # a node slot whose controller heartbeat is older than this is
    # considered dead and may be reclaimed by a replacement node
    # (reference: ETCDMaster TTL registrations, launch/controllers/master.py)
    stale_timeout: float = 30.0


class Controller:
    def __init__(self, cfg: LaunchConfig):
        self.cfg = cfg
        self.procs: List[subprocess.Popen] = []
        self.logs: List = []
        self._store = None
        self._server = None
        self._gen: Optional[int] = None   # claim-counter fencing token
        self._no_hb_since: Dict[int, float] = {}

    # -- rendezvous --------------------------------------------------------
    # (liveness protocol is intentionally self-contained; fleet/elastic.py's
    # ElasticManager runs a similar TTL heartbeat for TRAINING-process
    # membership — this one leases controller node slots, a different
    # lifecycle. Cross-check both when changing either.)
    def _hb_key(self, slot: int) -> str:
        return f"{self.cfg.job_id}/hb/{slot}"

    def _heartbeat(self, slot: int) -> bool:
        """Renew the slot lease. Returns False when ownership was lost
        (another node took the slot over) — the holder must fence."""
        if self._store is None:
            return True
        try:
            key = f"{self.cfg.job_id}/claim/{slot}"
            if self._gen is not None and int(
                    self._store.add(key, 0)) != self._gen:
                return False   # usurped: a reclaimer bumped the counter
            self._store.set(self._hb_key(slot),
                            str(time.time()).encode())
        except (OSError, RuntimeError, TimeoutError):
            pass   # store unreachable: keep running, lease may expire
        return True

    def _slot_stale(self, slot: int) -> bool:
        try:
            raw = self._store.get(self._hb_key(slot), timeout_ms=200)
            return time.time() - float(raw.decode()) > self.cfg.stale_timeout
        except Exception:
            # claimed but no heartbeat yet: live during a grace window
            # (claimant writes its first beat right after claiming), stale
            # if the beat never appears — a claimant that died immediately
            # must not wedge the slot forever
            first = self._no_hb_since.setdefault(slot, time.time())
            return time.time() - first > self.cfg.stale_timeout

    def _resolve_node_rank(self) -> int:
        """Claim a node slot through the KV master. Fresh slots are taken
        first-come; a slot whose owner's heartbeat went stale (controller
        died) is RECLAIMED by a replacement node — the elastic re-admit
        path (reference: master.py:79 ETCD node registry with TTL +
        watcher-driven re-admission). Latest claimant wins a contested
        stale slot; heartbeats keep live owners uncontested."""
        cfg = self.cfg
        if cfg.nnodes <= 1:
            return 0
        if cfg.node_rank is not None:
            return int(cfg.node_rank)
        if not cfg.master:
            raise ValueError("--master host:port is required when nnodes > 1")
        from ..store import TCPStore

        host, port = cfg.master.rsplit(":", 1)
        # the lowest-rank candidate hosts the KV (reference: launch master
        # auto-elected by who binds the port first)
        try:
            self._server = TCPStore(host, int(port), is_master=True,
                                    timeout=cfg.rendezvous_timeout)
            self._store = self._server
        except (OSError, RuntimeError):
            self._store = TCPStore(host, int(port), is_master=False,
                                   timeout=cfg.rendezvous_timeout)
        deadline = time.time() + cfg.rendezvous_timeout
        while True:
            for slot in range(cfg.nnodes):
                key = f"{cfg.job_id}/claim/{slot}"
                n = int(self._store.add(key, 0))
                if n == 0:
                    if int(self._store.add(key, 1)) == 1:
                        self._gen = 1
                        self._heartbeat(slot)
                        return slot
                    continue  # lost the race for this slot
                if self._slot_stale(slot):
                    # atomic takeover: the add counter is the fencing
                    # token — only the reclaimer whose add lands first
                    # (n -> n+1) wins; racers see a later count and move on
                    won = int(self._store.add(key, 1))
                    if won != n + 1:
                        continue
                    self._gen = won
                    self._no_hb_since.pop(slot, None)
                    self._heartbeat(slot)
                    print(f"[launch] reclaimed stale node slot {slot} "
                          f"of job {cfg.job_id!r} (generation {won})",
                          flush=True)
                    return slot
            if time.time() >= deadline:
                raise RuntimeError(
                    f"no free node slot in job {cfg.job_id!r} "
                    f"(nnodes={cfg.nnodes}, all slots held by live nodes)")
            time.sleep(0.5)

    # -- pod lifecycle -----------------------------------------------------
    def _worker_env(self, node_rank: int, local_rank: int) -> Dict[str, str]:
        cfg = self.cfg
        world = cfg.nnodes * cfg.nproc_per_node
        rank = node_rank * cfg.nproc_per_node + local_rank
        env = dict(os.environ)
        env.update(cfg.envs)
        env.update(
            PADDLE_TRAINER_ID=str(rank),
            PADDLE_TRAINERS_NUM=str(world),
            PADDLE_LOCAL_RANK=str(local_rank),
            PADDLE_NNODES=str(cfg.nnodes),
            PADDLE_JOB_ID=cfg.job_id,
        )
        if cfg.master:
            # jax.distributed coordinator rides the port after the KV port
            host, port = cfg.master.rsplit(":", 1)
            env["PADDLE_MASTER"] = f"{host}:{int(port) + 1}"
        return env

    def build_pod(self, node_rank: int):
        cfg = self.cfg
        os.makedirs(cfg.log_dir, exist_ok=True)
        for lr in range(cfg.nproc_per_node):
            rank = node_rank * cfg.nproc_per_node + lr
            logf = open(os.path.join(cfg.log_dir, f"workerlog.{rank}"), "ab")
            cmd = [sys.executable]
            if cfg.module:
                cmd += ["-m", cfg.script]
            else:
                cmd += [cfg.script]
            cmd += list(cfg.script_args)
            p = subprocess.Popen(
                cmd, env=self._worker_env(node_rank, lr),
                stdout=logf, stderr=subprocess.STDOUT,
                start_new_session=True,
            )
            self.procs.append(p)
            self.logs.append(logf)

    def _tail_rank0(self, pos: int) -> int:
        """Mirror new rank-0 log bytes to our stdout (reference watcher
        tails container 0)."""
        try:
            path = self.logs[0].name
            with open(path, "rb") as f:
                f.seek(pos)
                data = f.read()
            if data:
                sys.stdout.buffer.write(data)
                sys.stdout.flush()
            return pos + len(data)
        except (IndexError, OSError):
            return pos

    def stop_pod(self, sig=signal.SIGTERM):
        for p in self.procs:
            if p.poll() is None:
                try:
                    os.killpg(p.pid, sig)
                except (ProcessLookupError, PermissionError):
                    pass
        deadline = time.time() + 10
        for p in self.procs:
            try:
                p.wait(max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(p.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
        for f in self.logs:
            try:
                f.close()
            except OSError:
                pass
        self.procs, self.logs = [], []

    def watch(self, node_rank: int = 0) -> int:
        """Poll children until all succeed or one fails (fail-fast);
        heartbeats the node's slot so live nodes are never reclaimed."""
        pos = 0
        last_hb = 0.0
        while True:
            if time.time() - last_hb > max(self.cfg.stale_timeout / 3, 0.5):
                if not self._heartbeat(node_rank):
                    # fenced: lease lost to a replacement node — running on
                    # would split-brain the slot (duplicate global ranks)
                    print(f"[launch] node slot {node_rank} lease lost; "
                          "fencing this pod", flush=True)
                    self.stop_pod()
                    return 102   # reference ELASTIC re-plan exit code
                last_hb = time.time()
            pos = self._tail_rank0(pos)
            codes = [p.poll() for p in self.procs]
            if any(c not in (None, 0) for c in codes):
                bad = next(i for i, c in enumerate(codes)
                           if c not in (None, 0))
                rc = codes[bad]
                self.stop_pod()
                return rc
            if all(c == 0 for c in codes):
                self._tail_rank0(pos)
                return 0
            time.sleep(0.2)

    def run(self) -> int:
        cfg = self.cfg
        node_rank = self._resolve_node_rank()
        restarts = 0
        while True:
            self.build_pod(node_rank)
            rc = self.watch(node_rank)
            if rc == 0 or restarts >= cfg.max_restarts:
                return rc
            restarts += 1
            print(f"[launch] pod failed rc={rc}; elastic restart "
                  f"{restarts}/{cfg.max_restarts}", flush=True)


def launch_job(cfg: LaunchConfig) -> int:
    return Controller(cfg).run()
