"""`python -m paddle_tpu.distributed.launch` CLI (reference: launch/main.py:18).

Usage:
    python -m paddle_tpu.distributed.launch \
        [--nnodes N] [--nproc_per_node P] [--master host:port] \
        [--node_rank R] [--job_id ID] [--log_dir DIR] [--max_restarts K] \
        [--m | --module] script.py [script args...]
"""
import argparse
import sys

from .controller import LaunchConfig, launch_job


def _parser():
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch", add_help=True)
    p.add_argument("--nnodes", type=int, default=1)
    p.add_argument("--nproc_per_node", type=int, default=1)
    p.add_argument("--master", type=str, default=None,
                   help="KV master host:port (required for nnodes>1)")
    p.add_argument("--node_rank", type=int, default=None)
    p.add_argument("--job_id", type=str, default="default")
    p.add_argument("--log_dir", type=str, default="log")
    p.add_argument("--max_restarts", type=int, default=0)
    p.add_argument("--module", "--m", action="store_true", dest="module")
    p.add_argument("script")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p


def main(argv=None) -> int:
    args = _parser().parse_args(argv)
    cfg = LaunchConfig(
        script=args.script,
        script_args=args.script_args,
        nnodes=args.nnodes,
        nproc_per_node=args.nproc_per_node,
        master=args.master,
        node_rank=args.node_rank,
        job_id=args.job_id,
        log_dir=args.log_dir,
        max_restarts=args.max_restarts,
        module=args.module,
    )
    return launch_job(cfg)


if __name__ == "__main__":
    sys.exit(main())
