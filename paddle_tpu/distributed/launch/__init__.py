"""Distributed job launcher.

Reference analog: python/paddle/distributed/launch/ (main.py:18 CLI,
controllers/collective.py controller + pod/container model, job/ context,
utils KVServer, watcher threads writing per-rank logs).

TPU-native re-design: on TPU one process drives all of a host's chips, so
the unit of launch is the HOST process (not per-GPU containers). The
controller here:

- resolves the node's rank against the master KV (the native TCPStore from
  distributed/store.py — the KVServer analog) or --node_rank,
- spawns `nproc_per_node` local worker processes with the PADDLE_* env
  contract consumed by init_parallel_env (parallel_env.py) — global ranks
  are node_rank * nproc_per_node + local_rank,
- streams each worker to `<log_dir>/workerlog.<rank>` (reference log
  layout) and mirrors rank 0 to stdout,
- watches children: fail-fast (first failure tears the pod down) or, with
  --max_restarts > 0, elastic restart of the whole pod (the reference
  elastic controller's whole-job restart semantics).
"""
from .controller import Controller, LaunchConfig, launch_job

__all__ = ["Controller", "LaunchConfig", "launch_job"]
