"""distributed.io (reference: python/paddle/distributed/io.py —
save/load of persistables in distributed jobs; thin over the framework
save/load since sharded state rides distributed/checkpoint.py)."""
from __future__ import annotations

__all__ = ["save_persistables", "load_persistables", "is_persistable"]


def is_persistable(var):
    return bool(getattr(var, "persistable", False))


def save_persistables(executor, dirname, main_program=None, filename=None):
    from ..framework.io_ import save
    from ..static import default_main_program
    import os

    prog = main_program or default_main_program()
    params = {(t.name or f"param_{i}"): t
              for i, t in enumerate(prog._captured_params())
              if is_persistable(t)}
    os.makedirs(dirname, exist_ok=True)
    save(params, os.path.join(dirname, filename or "__params__.pdparams"))


def load_persistables(executor, dirname, main_program=None, filename=None):
    from ..framework.io_ import load
    from ..static import default_main_program
    import os
    import jax.numpy as jnp
    import numpy as np

    prog = main_program or default_main_program()
    state = load(os.path.join(dirname, filename or "__params__.pdparams"))
    named = {(t.name or f"param_{i}"): t
             for i, t in enumerate(prog._captured_params())}
    for k, t in named.items():
        if k in state:
            v = state[k]
            arr = v._data if hasattr(v, "_data") else jnp.asarray(np.asarray(v))
            t._data = jnp.asarray(arr, t._data.dtype)
