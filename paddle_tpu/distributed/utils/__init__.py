"""distributed.utils (reference: python/paddle/distributed/utils/ —
launch_utils helpers; empty public __all__ there too). Hosts the helper
shims launch tooling imports."""
from __future__ import annotations

__all__ = []


def get_cluster_from_args(args=None):
    """Single-host cluster descriptor from env (launch_utils analog)."""
    import os

    ranks = int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
    return {"nranks": ranks,
            "endpoints": os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")}
