"""distributed.utils (reference: python/paddle/distributed/utils/ —
launch_utils helpers; empty public __all__ there too). Hosts the helper
shims launch tooling imports."""
from __future__ import annotations

__all__ = []


def get_cluster_from_args(args=None):
    """Single-host cluster descriptor from env (launch_utils analog)."""
    import os

    ranks = int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
    return {"nranks": ranks,
            "endpoints": os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")}


# -- MoE ragged collectives (reference: distributed/utils/moe_utils.py
#    global_scatter/global_gather over global_scatter_op.cu.cc) -------------

def _concrete_counts(t):
    """Host-visible int64 counts, or None when traced. Tracer check comes
    FIRST (t.numpy() on a tracer raises, and a broad except would also
    hide real bugs); the conversion itself is then allowed to fail only
    with the concretization error."""
    import jax
    import numpy as np

    if isinstance(getattr(t, "_data", t), jax.core.Tracer):
        return None
    arr = t.numpy() if hasattr(t, "numpy") else t
    return np.asarray(arr).astype(np.int64).reshape(-1)


def _moe_world(group):
    from ..collective import _world  # noqa: the dual-mode world helper

    return _world(group)


def _uniform_all_to_all(x, counts, ax, name):
    """Shared uniform-capacity exchange: card-major blocks through ONE
    lax.all_to_all over the `ax` mesh axis. gather is the same exchange
    run in reverse — all_to_all is its own inverse for this layout."""
    import jax

    from ...core.dispatch import apply
    from ...parallel.mesh import get_mesh

    n_ways = int(dict(get_mesh().shape).get(ax, 1))
    cap = int(counts[0])
    n_groups = max(len(counts) // n_ways, 1)  # n_expert

    def fn(a):
        d = a.shape[-1]
        blocks = a.reshape(n_ways, n_groups * cap, d)
        out = jax.lax.all_to_all(blocks, ax, split_axis=0,
                                 concat_axis=0, tiled=True)
        return out.reshape(-1, d)

    return apply(fn, x, name=name)


def _moe_exchange(x, counts_t, group, name):
    """Regime dispatch shared by global_scatter/global_gather."""
    from ...core.dispatch import apply
    from ..collective import _axis_for

    ax = _axis_for(group)
    if ax is None:
        world = _moe_world(group)
        if world == 1:
            # outside any SPMD region, single process: pure reorder
            return apply(lambda a: a, x, name=name)
        raise RuntimeError(
            f"{name} outside an SPMD region with world={world}: eager "
            "multi-process ragged all-to-all has no XLA lowering — run "
            "inside a mesh/shard region (where uniform-capacity counts "
            "lower to one lax.all_to_all) or use "
            "paddle_tpu.parallel.moe.MoELayer")
    counts = _concrete_counts(counts_t)
    if counts is not None and len(set(counts.tolist())) == 1:
        return _uniform_all_to_all(x, counts, ax, name)
    raise RuntimeError(
        f"{name} with ragged or traced per-expert counts has no "
        "static-shape XLA lowering; pad counts to a uniform capacity "
        "(pass them as concrete host values) or use "
        "paddle_tpu.parallel.moe.MoELayer (capacity-factor dispatch)")


def global_scatter(x, local_count, global_count, group=None,
                   use_calc_stream=True):
    """Dispatch rows of `x` to (card, expert) destinations
    (reference distributed/utils/moe_utils.py:20 — a ragged NCCL
    all-to-all where local_count[i] rows go to expert i % n_expert of
    card i // n_expert, and global_count[i] rows arrive likewise).

    TPU-native contract: XLA collectives are static-shaped, so the ragged
    wire format cannot be expressed directly. Three regimes:

    - world == 1 (the reference's own test regime): pure reorder — counts
      describe the same i-ordering on both sides, data passes through
      unchanged (gradient flows; backward of scatter is gather, which is
      also identity at world 1).
    - uniform concrete counts (fixed capacity per (card, expert)) inside
      an SPMD region: one `lax.all_to_all` over the group axis — exactly
      `parallel.moe`'s dispatch.
    - anything else raises with the regime named: use
      `paddle_tpu.parallel.moe.MoELayer` (capacity-factor dispatch) — the
      TPU answer to ragged expert routing.
    """
    return _moe_exchange(x, local_count, group, "global_scatter")


def global_gather(x, local_count, global_count, group=None,
                  use_calc_stream=True):
    """Inverse of global_scatter (reference moe_utils.py:137): return the
    expert outputs to the cards that sent them. Same TPU contract; at
    world 1 it is the identity, and with uniform capacity the same
    card-major all_to_all (its own inverse for this layout)."""
    return _moe_exchange(x, global_count, group, "global_gather")


__all__ += ["global_scatter", "global_gather"]


# public (non-underscore) aliases at the import path the reference
# docstrings use: paddle.distributed.utils.number_count etc.
from ...incubate.distributed.models.moe.utils import (  # noqa: E402
    _assign_pos as assign_pos,
    _limit_by_capacity as limit_by_capacity,
    _number_count as number_count,
    _prune_gate_by_capacity as prune_gate_by_capacity,
    _random_routing as random_routing,
)

__all__ += ["number_count", "assign_pos", "limit_by_capacity",
            "prune_gate_by_capacity", "random_routing"]
