"""distributed.utils (reference: python/paddle/distributed/utils/ —
launch_utils helpers; empty public __all__ there too). Hosts the helper
shims launch tooling imports."""
from __future__ import annotations

__all__ = []


def get_cluster_from_args(args=None):
    """Single-host cluster descriptor from env (launch_utils analog)."""
    import os

    ranks = int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
    return {"nranks": ranks,
            "endpoints": os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")}


# -- MoE ragged collectives (reference: distributed/utils/moe_utils.py
#    global_scatter/global_gather over global_scatter_op.cu.cc) -------------

def _concrete_counts(t):
    import numpy as np

    try:
        arr = t.numpy() if hasattr(t, "numpy") else t
        import jax

        if isinstance(getattr(t, "_data", t), jax.core.Tracer):
            return None
        return np.asarray(arr).astype(np.int64).reshape(-1)
    except Exception:
        return None


def _moe_world(group):
    from ..collective import _world  # noqa: the dual-mode world helper

    return _world(group)


def global_scatter(x, local_count, global_count, group=None,
                   use_calc_stream=True):
    """Dispatch rows of `x` to (card, expert) destinations
    (reference distributed/utils/moe_utils.py:20 — a ragged NCCL
    all-to-all where local_count[i] rows go to expert i % n_expert of
    card i // n_expert, and global_count[i] rows arrive likewise).

    TPU-native contract: XLA collectives are static-shaped, so the ragged
    wire format cannot be expressed directly. Three supported regimes:

    - world == 1 (the reference's own test regime): pure reorder — counts
      describe the same i-ordering on both sides, data passes through
      unchanged (gradient flows; backward of scatter is gather, which is
      also identity at world 1).
    - uniform counts (fixed capacity per (card, expert)) inside an SPMD
      region: one `lax.all_to_all` over the group axis — exactly
      `parallel.moe`'s dispatch. Counts must be concrete and equal.
    - anything else raises: use `paddle_tpu.parallel.moe.MoELayer`
      (capacity-factor dispatch) — the TPU answer to ragged expert
      routing, matching reference MoELayer end-to-end.
    """
    from ...core.dispatch import apply
    from ..collective import _axis_for

    ax = _axis_for(group)
    world = _moe_world(group) if ax is None else None
    if ax is None and world == 1:
        # outside any SPMD region, single process: pure reorder
        return apply(lambda a: a, x, name="global_scatter")
    lc = _concrete_counts(local_count)
    if ax is not None and lc is not None and len(set(lc.tolist())) == 1:
        import jax

        from ...parallel.mesh import get_mesh

        n_ways = int(dict(get_mesh().shape).get(ax, 1))
        cap = int(lc[0])
        n_groups = max(len(lc) // n_ways, 1)  # n_expert

        def fn(a):
            d = a.shape[-1]
            blocks = a.reshape(n_ways, n_groups * cap, d)
            out = jax.lax.all_to_all(blocks, ax, split_axis=0,
                                     concat_axis=0, tiled=True)
            return out.reshape(-1, d)

        return apply(fn, x, name="global_scatter")
    raise RuntimeError(
        "global_scatter with ragged per-expert counts has no static-shape "
        "XLA lowering; use paddle_tpu.parallel.moe.MoELayer (capacity-"
        "factor dispatch) or pad counts to a uniform capacity")


def global_gather(x, local_count, global_count, group=None,
                  use_calc_stream=True):
    """Inverse of global_scatter (reference moe_utils.py:137): return the
    expert outputs to the cards that sent them. Same TPU contract; at
    world 1 it is the identity, and with uniform capacity it is the
    reverse all_to_all."""
    from ...core.dispatch import apply
    from ..collective import _axis_for

    ax = _axis_for(group)
    world = _moe_world(group) if ax is None else None
    if ax is None and world == 1:
        # outside any SPMD region, single process: pure reorder
        return apply(lambda a: a, x, name="global_gather")
    gc = _concrete_counts(global_count)
    if ax is not None and gc is not None and len(set(gc.tolist())) == 1:
        import jax

        from ...parallel.mesh import get_mesh

        n_ways = int(dict(get_mesh().shape).get(ax, 1))
        cap = int(gc[0])
        n_groups = max(len(gc) // n_ways, 1)

        def fn(a):
            d = a.shape[-1]
            blocks = a.reshape(n_ways, n_groups * cap, d)
            out = jax.lax.all_to_all(blocks, ax, split_axis=0,
                                     concat_axis=0, tiled=True)
            return out.reshape(-1, d)

        return apply(fn, x, name="global_gather")
    raise RuntimeError(
        "global_gather with ragged per-expert counts has no static-shape "
        "XLA lowering; use paddle_tpu.parallel.moe.MoELayer or pad counts "
        "to a uniform capacity")


__all__ += ["global_scatter", "global_gather"]
