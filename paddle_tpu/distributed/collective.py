"""Collective communication API (reference:
python/paddle/distributed/communication/ + collective.py — dygraph
ProcessGroup calls / static c_* ops).

TPU-native: a collective is an XLA HLO op over a named mesh axis. These
functions are dual-mode:

- inside an SPMD region (paddle_tpu.parallel shard context, where tensors
  are per-shard views and a mesh axis name is active) they lower to
  jax.lax.psum / all_gather / ppermute / all_to_all — compiled onto ICI;
- outside (plain eager, single controller) they operate on the global
  tensor, which for world_size==1 is the identity semantics the reference's
  tests use for the trivial group.

Groups are named mesh axes, not socket-bootstrapped NCCL communicators
(device_ext.h xccl hooks have no analog here — XLA owns the transport).
"""
from __future__ import annotations

import threading
import time
from typing import List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import apply
from .. import monitor


def _count_collective(kind, *tensors):
    """Bytes-moved telemetry, labeled by collective kind. Sizes come from
    shape/dtype metadata, so this works on tracers too — under jit each
    collective is counted once per TRACE (per compiled program), on the
    eager path once per call. Payload bytes are the per-participant input
    size (the ICI injection volume, not the algorithm's wire total)."""
    if not monitor.enabled():
        return
    n = 0
    for t in tensors:
        try:
            shape = t.shape
            itemsize = np.dtype(t.dtype).itemsize
        except (TypeError, AttributeError):
            continue
        n += int(np.prod(shape)) * itemsize if shape else itemsize
    monitor.counter("collective/bytes").labels(kind=kind).add(n)
    monitor.counter("collective/calls").labels(kind=kind).inc()

__all__ = [
    "ReduceOp", "Group", "new_group", "get_group", "all_reduce", "all_gather",
    "all_gather_object", "reduce", "broadcast", "scatter", "reduce_scatter",
    "alltoall", "alltoall_single", "all_to_all", "send", "recv", "barrier",
    "wait", "get_backend", "p2p_permute",
]


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


class _AxisContext(threading.local):
    def __init__(self):
        self.axes: List[str] = []


_axis_ctx = _AxisContext()


class axis_scope:
    """Entered by paddle_tpu.parallel when running code under shard_map with
    a live mesh axis; collective calls then lower to lax ops."""

    def __init__(self, axis_name):
        self.axis_name = axis_name

    def __enter__(self):
        _axis_ctx.axes.append(self.axis_name)
        return self

    def __exit__(self, *exc):
        _axis_ctx.axes.pop()
        return False


def _current_axis():
    return _axis_ctx.axes[-1] if _axis_ctx.axes else None


class Group:
    def __init__(self, rank, nranks, id=0, ranks=None, axis_name=None):
        self.rank = rank
        self.nranks = nranks
        self.id = id
        self.ranks = ranks or list(range(nranks))
        self.axis_name = axis_name  # mesh axis this group rides on

    @property
    def world_size(self):
        return self.nranks

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if rank in self.ranks else -1

    def __repr__(self):
        return f"Group(rank={self.rank}, nranks={self.nranks}, axis={self.axis_name})"


_groups = {}
_group_counter = [0]


def new_group(ranks=None, backend=None, timeout=None, axis_name=None):
    from .parallel_env import get_rank, get_world_size

    if ranks is None:
        ranks = list(range(get_world_size()))
    _group_counter[0] += 1
    gid = _group_counter[0]
    my = get_rank()
    g = Group(
        rank=ranks.index(my) if my in ranks else -1,
        nranks=len(ranks),
        id=gid,
        ranks=list(ranks),
        axis_name=axis_name,
    )
    _groups[gid] = g
    return g


def get_group(id=0):
    return _groups.get(id)


def get_backend(group=None):
    return "xla"


def _axis_for(group):
    if group is not None and group.axis_name is not None:
        return group.axis_name
    return _current_axis()


def _world(group):
    from .parallel_env import get_world_size

    return group.nranks if group is not None else get_world_size()


def _reduce_safe(fn, a, axis):
    """Run an all-reduce in f32 for low-precision floats on the CPU
    backend: bf16/f16 all-reduce inside a partial-manual shard_map region
    fatally crashes XLA-CPU's float-normalization pass ('Invalid binary
    instruction opcode copy') — minimal repro in
    tests/test_pipeline.py::test_partial_manual_bf16_psum;
    parallel/pipeline.py:_psum_safe delegates here. TPU keeps the native
    dtype on the wire."""
    dt = getattr(a, "dtype", None)
    if (jax.default_backend() == "cpu"
            and str(dt) in ("bfloat16", "float16")):
        return fn(a.astype(jnp.float32), axis).astype(dt)
    return fn(a, axis)


def _prod_reduce(a, axis):
    # no lax.pprod: gather then product over the gathered dim
    return jnp.prod(jax.lax.all_gather(a, axis, tiled=False), axis=0)


_REDUCE_FNS = {
    ReduceOp.SUM: jax.lax.psum,
    ReduceOp.MAX: jax.lax.pmax,
    ReduceOp.MIN: jax.lax.pmin,
    ReduceOp.AVG: jax.lax.pmean,
    ReduceOp.PROD: _prod_reduce,
}


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True,
               compress=None, error_feedback=None):
    """compress="int8": EQuARX-style quantized all-reduce (lowbit.comm) —
    int8 codes + shared per-chunk scales on the wire, int32 reduction,
    SUM/AVG only.  `error_feedback`: optional same-shape Tensor buffer
    whose contents are added pre-quantization and replaced with the new
    local rounding residual (thread it across steps and the quantization
    noise becomes delayed instead of lost)."""
    if compress is not None:
        return _all_reduce_compressed(tensor, op, group, compress,
                                      error_feedback)
    axis = _axis_for(group)
    if axis is not None:
        _count_collective("all_reduce", tensor)
        out = apply(lambda a: _reduce_safe(_REDUCE_FNS[op], a, axis), tensor,
                    name="all_reduce")
        tensor._data = out._data
        tensor._grad_node = out._grad_node
        tensor._out_index = out._out_index
        tensor.stop_gradient = tensor.stop_gradient and out.stop_gradient
        return tensor
    if _world(group) == 1:
        return tensor
    raise RuntimeError(
        "eager cross-host all_reduce outside an SPMD region is not supported "
        "on TPU — run inside paddle_tpu.parallel or a compiled step"
    )


def _all_reduce_compressed(tensor, op, group, compress, error_feedback):
    from ..lowbit.comm import quantized_all_reduce_arrays

    if compress != "int8":
        raise ValueError(f'compress must be None or "int8", got {compress!r}')
    if op not in (ReduceOp.SUM, ReduceOp.AVG):
        raise ValueError(
            "compressed all_reduce supports SUM/AVG only (MAX/MIN/PROD "
            "are not linear in the codes)")
    axis = _axis_for(group)
    if axis is None:
        if _world(group) == 1:
            return tensor          # trivial group: identity, nothing on
        #                            the wire to compress
        raise RuntimeError(
            "eager cross-host all_reduce outside an SPMD region is not "
            "supported on TPU — run inside paddle_tpu.parallel or a "
            "compiled step")
    _count_collective("all_reduce", tensor)
    res_in = error_feedback._data if error_feedback is not None else None

    def fn(a):
        out, new_res = quantized_all_reduce_arrays(
            a, axis, residual=res_in, average=(op == ReduceOp.AVG))
        return out if new_res is None else (out, new_res)

    if error_feedback is not None:
        out, new_res = apply(fn, tensor, n_outs=2,
                             name="all_reduce_int8")
        error_feedback._data = new_res._data
    else:
        out = apply(fn, tensor, name="all_reduce_int8")
    tensor._data = out._data
    tensor._grad_node = out._grad_node
    tensor._out_index = out._out_index
    tensor.stop_gradient = tensor.stop_gradient and out.stop_gradient
    return tensor


def all_gather(tensor_list, tensor, group=None, sync_op=True, axis=0,
               compress=None):
    # validate BEFORE the axis check — a bad compress value must be loud
    # in single-process runs too, not only once a mesh is live
    if compress not in (None, "int8"):
        raise ValueError(
            f'compress must be None or "int8", got {compress!r}')
    ax = _axis_for(group)
    if ax is not None:
        if compress is not None:
            from ..lowbit.comm import quantized_all_gather_arrays

            _count_collective("all_gather", tensor)
            out = apply(
                lambda a: quantized_all_gather_arrays(a, ax), tensor,
                name="all_gather_int8")
            from ..ops.manipulation import unbind

            parts = unbind(out, 0)
            if isinstance(tensor_list, list):
                tensor_list.clear()
                tensor_list.extend(parts)
            return parts
        _count_collective("all_gather", tensor)
        out = apply(
            lambda a: jax.lax.all_gather(a, ax, tiled=False), tensor, name="all_gather"
        )
        n = out.shape[0]
        from ..ops.manipulation import unbind

        parts = unbind(out, 0)
        if isinstance(tensor_list, list):
            tensor_list.clear()
            tensor_list.extend(parts)
        return parts
    if _world(group) == 1:
        if isinstance(tensor_list, list):
            tensor_list.clear()
            tensor_list.append(tensor)
        return [tensor]
    raise RuntimeError("eager all_gather requires an SPMD region on TPU")


def all_gather_object(object_list, obj, group=None):
    if _world(group) == 1:
        object_list.clear()
        object_list.append(obj)
        return
    raise RuntimeError("all_gather_object requires single-host or SPMD region")


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    # On a mesh, reduce == all_reduce (result replicated; dst distinction is
    # meaningless for SPMD where every shard computes).
    return all_reduce(tensor, op=op, group=group)


def broadcast(tensor, src=0, group=None, sync_op=True):
    ax = _axis_for(group)
    if ax is not None:
        _count_collective("broadcast", tensor)

        def fn(a):
            # select src's value on every member: gather then index (XLA
            # lowers this to a broadcast from src over the axis)
            gathered = jax.lax.all_gather(a, ax, tiled=False)
            return gathered[src]

        out = apply(fn, tensor, name="broadcast")
        tensor._data = out._data
        return tensor
    if _world(group) == 1:
        return tensor
    raise RuntimeError("eager broadcast requires an SPMD region on TPU")


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    if _world(group) == 1:
        if tensor_list:
            tensor._data = tensor_list[0]._data
        return tensor
    ax = _axis_for(group)
    if ax is not None:
        from ..ops.manipulation import stack

        stacked = stack(tensor_list, 0)
        _count_collective("scatter", stacked)

        def fn(a):
            idx = jax.lax.axis_index(ax)
            return jax.lax.dynamic_index_in_dim(a, idx, 0, keepdims=False)

        out = apply(fn, stacked, name="scatter")
        tensor._data = out._data
        return tensor
    raise RuntimeError("eager scatter requires an SPMD region on TPU")


def reduce_scatter(tensor, tensor_list=None, op=ReduceOp.SUM, group=None, sync_op=True):
    ax = _axis_for(group)
    if ax is not None:
        from ..ops.manipulation import concat

        inp = concat(tensor_list, 0) if tensor_list else tensor
        _count_collective("reduce_scatter", inp)

        if op == ReduceOp.SUM:
            def fn(a):
                return _reduce_safe(
                    lambda b, x: jax.lax.psum_scatter(
                        b, x, scatter_dimension=0, tiled=True), a, ax)
        else:
            # non-SUM: reduce fully, then keep this member's chunk
            # (reduce-then-scatter semantics; SUM keeps the fused
            # psum_scatter fast path above)
            def fn(a):
                full = _reduce_safe(_REDUCE_FNS[op], a, ax)
                members = jax.lax.psum(1, ax)   # static axis size in-region
                if full.shape[0] % members:
                    # match the SUM path (psum_scatter tiled=True errors)
                    raise ValueError(
                        f"reduce_scatter: first dim {full.shape[0]} not "
                        f"divisible by group size {members}")
                n = full.shape[0] // members
                idx = jax.lax.axis_index(ax)
                return jax.lax.dynamic_slice_in_dim(full, idx * n, n, 0)

        out = apply(fn, inp, name="reduce_scatter")
        tensor._data = out._data
        return tensor
    if _world(group) == 1:
        if tensor_list:
            tensor._data = tensor_list[0]._data
        return tensor
    raise RuntimeError("eager reduce_scatter requires an SPMD region on TPU")


def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True):
    ax = _axis_for(group)
    if ax is not None:
        from ..ops.manipulation import stack, unbind

        stacked = stack(in_tensor_list, 0)
        _count_collective("alltoall", stacked)
        out = apply(
            lambda a: jax.lax.all_to_all(a, ax, split_axis=0, concat_axis=0, tiled=True),
            stacked,
            name="alltoall",
        )
        parts = unbind(out, 0)
        if isinstance(out_tensor_list, list):
            out_tensor_list.clear()
            out_tensor_list.extend(parts)
        return parts
    if _world(group) == 1:
        if isinstance(out_tensor_list, list):
            out_tensor_list.clear()
            out_tensor_list.extend(in_tensor_list)
        return list(in_tensor_list)
    raise RuntimeError("eager alltoall requires an SPMD region on TPU")


def alltoall_single(in_tensor, out_tensor=None, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    ax = _axis_for(group)
    if ax is not None:
        _count_collective("alltoall", in_tensor)
        out = apply(
            lambda a: jax.lax.all_to_all(a, ax, split_axis=0, concat_axis=0, tiled=True),
            in_tensor,
            name="alltoall_single",
        )
        if out_tensor is not None:
            out_tensor._data = out._data
            return out_tensor
        return out
    if _world(group) == 1:
        if out_tensor is not None:
            out_tensor._data = in_tensor._data
            return out_tensor
        return in_tensor
    raise RuntimeError("eager alltoall requires an SPMD region on TPU")


all_to_all = alltoall


def p2p_permute(tensor, perm, group=None):
    """Static-permutation p2p (the SPMD form of send/recv pairs): `perm` is a
    list of (src_rank, dst_rank) int pairs — exactly XLA collective-permute.
    This is what pipeline-parallel stage hops compile to on ICI
    (reference analog: send_v2/recv_v2 NCCL p2p, SURVEY §3.4)."""
    ax = _axis_for(group)
    if ax is None:
        raise RuntimeError("p2p_permute requires an SPMD region (mesh axis)")
    _count_collective("p2p_permute", tensor)
    return apply(
        lambda a: jax.lax.ppermute(a, ax, [(int(s), int(d)) for s, d in perm]),
        tensor,
        name="p2p_permute",
    )


def send(tensor, dst=0, group=None, sync_op=True):
    raise NotImplementedError(
        "SPMD p2p is a static collective-permute: use "
        "paddle_tpu.distributed.p2p_permute(t, perm) with explicit "
        "(src,dst) pairs, or the pipeline schedules in paddle_tpu.parallel "
        "which emit it for you. Per-rank imperative send/recv only exists in "
        "multi-process runtimes (reference send_v2/recv_v2 over NCCL)."
    )


def recv(tensor, src=0, group=None, sync_op=True):
    send(tensor, src, group)


def _observe_collective_wall(kind, t0):
    """Sync-on-exit wall histogram for the HOST-blocking collective
    boundaries (ISSUE 13 wing d).  Only :func:`barrier` and :func:`wait`
    qualify: every other collective here lowers to an XLA HLO op inside
    a compiled program, where the host never blocks per-collective and
    per-op time is the HLO microscope's job (``perf.hlo_report``) — a
    host timer around a traced call would measure dispatch, not the
    wire.  These two sites already block by definition, so timing them
    adds two clock reads, no new sync."""
    monitor.histogram(
        "collective/time",
        "host-blocked seconds at sync collective boundaries").labels(
        kind=kind).observe(time.perf_counter() - t0)


def barrier(group=None):
    if monitor.enabled():
        t0 = time.perf_counter()
        jnp.zeros(()).block_until_ready()
        _observe_collective_wall("barrier", t0)
        return
    jnp.zeros(()).block_until_ready()


def wait(tensor, group=None, use_calc_stream=True):
    if monitor.enabled():
        t0 = time.perf_counter()
        tensor.block_until_ready()
        _observe_collective_wall("wait", t0)
        return
    tensor.block_until_ready()
