"""paddle_tpu.distributed (reference: python/paddle/distributed/).

TPU-native design (SURVEY §5.8): collectives are XLA HLO ops compiled onto
ICI/DCN via a device Mesh — there is no NCCL, no comm-id bootstrap, no
ProcessGroup streams. The reference's 4-axis HybridCommunicateGroup
topology maps to named mesh axes ("dp","sharding","pp","mp" + "sp"/"ep");
see paddle_tpu.distributed.fleet and paddle_tpu.parallel.

Single-controller model: one python process drives all local chips (and
multi-host via jax.distributed). `rank`/`world_size` therefore describe
*data-parallel shards of the mesh*, not OS processes, except under
multi-host launch where they are per-host.
"""
from __future__ import annotations

import os

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from . import collective as _collective_mod
from .collective import (
    all_reduce, all_gather, all_gather_object, reduce, broadcast, scatter,
    reduce_scatter, alltoall, alltoall_single, all_to_all, send, recv, barrier,
    ReduceOp, new_group, get_group, wait,
)
from .parallel_env import (
    init_parallel_env, get_rank, get_world_size, ParallelEnv, is_initialized,
    destroy_process_group, parallel_mode,
)
from . import fleet
from . import metric
from . import models
from . import communication
from . import stream
from . import checkpoint
from . import sharding
from .sharding import group_sharded_parallel, save_group_sharded_model
from .launch_mod import spawn, launch
from .store import TCPStore
from . import auto_parallel
from . import rpc
from . import tuner
from .tuner import OptimizationTuner

__all__ = [
    "init_parallel_env", "get_rank", "get_world_size", "ParallelEnv",
    "is_initialized", "destroy_process_group", "all_reduce", "all_gather",
    "all_gather_object", "reduce", "broadcast", "scatter", "reduce_scatter",
    "alltoall", "alltoall_single", "all_to_all", "send", "recv", "barrier",
    "ReduceOp", "new_group", "get_group", "wait", "fleet", "spawn", "launch",
    "checkpoint", "DataParallel", "sharding", "group_sharded_parallel",
    "save_group_sharded_model", "TCPStore",
]


class DataParallel:
    """Dygraph DP wrapper (reference: paddle.DataParallel →
    EagerReducer bucketed allreduce, reducer.cc:523).

    TPU-native semantics: under the compiled train step, gradients are
    reduced by XLA (SPMD partitioner inserts the all-reduce over the 'dp'
    axis and its latency-hiding scheduler overlaps it with the backward —
    the role of the reducer's bucketing/overlap machinery). In pure-eager
    multi-device mode this wrapper averages grads via psum at step
    boundaries (see paddle_tpu.parallel.engine.DataParallelEngine).
    """

    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        self._layers = layers
        self.find_unused_parameters = find_unused_parameters

    def __getattr__(self, name):
        return getattr(self.__dict__["_layers"], name)

    def __call__(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def no_sync(self):
        from contextlib import nullcontext

        return nullcontext()

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, *args, **kwargs):
        return self._layers.set_state_dict(*args, **kwargs)

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        pass

from .compat import (  # noqa: E402
    ParallelMode, CountFilterEntry, ProbabilityEntry, ShowClickEntry,
    InMemoryDataset, QueueDataset, broadcast_object_list,
    scatter_object_list, gloo_init_parallel_env, gloo_barrier, gloo_release,
    is_available, isend, irecv, split,
)
from .collective import get_backend  # noqa: E402
from . import io  # noqa: E402

__all__ += [
    "ParallelMode", "CountFilterEntry", "ProbabilityEntry", "ShowClickEntry",
    "InMemoryDataset", "QueueDataset", "broadcast_object_list",
    "scatter_object_list", "gloo_init_parallel_env", "gloo_barrier",
    "gloo_release", "is_available", "isend", "irecv", "get_backend", "io", "split",
]
