"""communication.scatter (reference layout)."""
from ..collective import scatter
from ..compat import scatter_object_list

__all__ = ["scatter", "scatter_object_list"]
