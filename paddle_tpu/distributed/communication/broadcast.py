"""communication.broadcast (reference layout)."""
from ..collective import broadcast
from ..compat import broadcast_object_list

__all__ = ["broadcast", "broadcast_object_list"]
