"""communication.group module layout (reference:
python/paddle/distributed/communication/group.py)."""
from ..collective import Group, barrier, get_backend, get_group, new_group, wait
from ..parallel_env import (destroy_process_group, get_rank,
                            get_world_size, is_initialized)

__all__ = ["Group", "barrier", "destroy_process_group", "get_backend", "get_group",
           "get_rank", "get_world_size", "is_initialized", "new_group",
           "wait"]
