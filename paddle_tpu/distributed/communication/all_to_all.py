"""communication.all_to_all module layout (reference:
python/paddle/distributed/communication/all_to_all.py)."""
from ..collective import all_to_all, alltoall, alltoall_single

__all__ = ["all_to_all", "alltoall", "alltoall_single"]
