"""communication.stream module layout (reference:
python/paddle/distributed/communication/stream/ — task-returning
collective variants on a chosen stream). The implementation is
paddle_tpu.distributed.stream; this module makes the deep import path
`paddle.distributed.communication.stream` resolve.
"""
from ..stream import *  # noqa: F401,F403
from ..stream import __all__  # noqa: F401
