"""communication.recv (reference layout)."""
from ..collective import recv
from ..compat import irecv

__all__ = ["recv", "irecv"]
