"""communication.batch_isend_irecv (reference:
python/paddle/distributed/communication/batch_isend_irecv.py — P2POp
descriptors executed as one batch).

TPU-native: point-to-point pairs inside SPMD regions are ppermute
patterns; outside they fall back to the eager send/recv compat shims.
A P2POp batch executes its ops in order.
"""
from ..compat import irecv, isend

__all__ = ["P2POp", "batch_isend_irecv"]


class P2POp:
    """One pending send/recv (reference signature: (op, tensor, peer,
    group))."""

    def __init__(self, op, tensor, peer, group=None):
        if op not in (isend, irecv) and getattr(op, "__name__", "") not in (
                "isend", "irecv"):
            raise ValueError("op must be paddle.distributed.isend/irecv")
        self.op = op
        self.tensor = tensor
        self.peer = peer
        self.group = group


def batch_isend_irecv(p2p_op_list):
    """Run the batch; returns the per-op tasks (reference returns a list
    of async tasks)."""
    return [op.op(op.tensor, op.peer, group=op.group)
            for op in p2p_op_list]
