"""communication.all_reduce module layout (reference:
python/paddle/distributed/communication/all_reduce.py)."""
from ..collective import all_reduce

__all__ = ["all_reduce"]
