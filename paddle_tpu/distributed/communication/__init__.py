"""paddle.distributed.communication parity (reference:
python/paddle/distributed/communication/__init__.py — per-op modules,
with the functions re-exported at package level in the same order).

The implementations live in paddle_tpu.distributed.collective (dual-mode
collectives: SPMD axis inside shard regions, process world outside),
compat (eager object-list / p2p shims) and stream (task-returning
variants); these modules are the reference's import layout over them.
"""
from .all_gather import all_gather, all_gather_object
from .all_reduce import all_reduce
from .broadcast import broadcast, broadcast_object_list
from .reduce import reduce, ReduceOp
from .send import send, isend
from .recv import recv, irecv
from .scatter import scatter, scatter_object_list
from .batch_isend_irecv import batch_isend_irecv, P2POp
from .reduce_scatter import reduce_scatter
from .all_to_all import all_to_all, alltoall, alltoall_single
from .group import (
    is_initialized,
    destroy_process_group,
    get_group,
    wait,
    barrier,
    get_backend,
)
from ..collective import new_group
from . import group
from . import stream

__all__ = [
    "P2POp", "ReduceOp", "all_gather", "all_gather_object", "all_reduce",
    "all_to_all", "alltoall", "alltoall_single", "barrier",
    "batch_isend_irecv", "broadcast", "broadcast_object_list",
    "destroy_process_group", "get_backend", "get_group", "group", "irecv",
    "is_initialized", "isend", "new_group", "recv", "reduce",
    "reduce_scatter", "scatter", "scatter_object_list", "send", "stream",
    "wait",
]
