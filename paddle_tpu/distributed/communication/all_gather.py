"""communication.all_gather (reference layout)."""
from ..collective import all_gather, all_gather_object

__all__ = ["all_gather", "all_gather_object"]
