"""communication.reduce_scatter module layout (reference:
python/paddle/distributed/communication/reduce_scatter.py)."""
from ..collective import reduce_scatter

__all__ = ["reduce_scatter"]
