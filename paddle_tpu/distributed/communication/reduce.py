"""communication.reduce (reference layout)."""
from ..collective import ReduceOp, reduce

__all__ = ["reduce", "ReduceOp"]
