"""communication.send (reference layout)."""
from ..collective import send
from ..compat import isend

__all__ = ["send", "isend"]
