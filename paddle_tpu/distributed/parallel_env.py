"""Bootstrap / environment (reference: python/paddle/distributed/parallel.py:108
init_parallel_env — TCPStore + ProcessGroup creation).

TPU-native: jax.distributed.initialize handles multi-host rendezvous via the
coordinator address (the TCPStore analog lives inside the JAX runtime);
single-host multi-chip needs no bootstrap at all.
"""
from __future__ import annotations

import os

import jax

_initialized = False


class ParallelEnv:
    def __init__(self):
        self.rank = get_rank()
        self.world_size = get_world_size()
        self.device_id = int(os.environ.get("FLAGS_selected_tpus", "0").split(",")[0] or 0)

    @property
    def local_rank(self):
        return self.rank

    @property
    def nranks(self):
        return self.world_size

    @property
    def dev_id(self):
        return self.device_id


def get_rank(group=None):
    if group is not None:
        return group.rank
    return int(os.environ.get("PADDLE_TRAINER_ID", jax.process_index()))


def get_world_size(group=None):
    if group is not None:
        return group.nranks
    env = os.environ.get("PADDLE_TRAINERS_NUM")
    if env is not None:
        return int(env)
    return jax.process_count()


def is_initialized():
    return _initialized


def init_parallel_env():
    """Multi-host: initialize the jax distributed runtime from launch env
    vars (PADDLE_* set by paddle_tpu.distributed.launch or user env).
    Single-host: records initialization; all chips are already visible."""
    global _initialized
    if _initialized:
        return ParallelEnv()
    coord = os.environ.get("PADDLE_MASTER") or os.environ.get("MASTER_ADDR")
    nproc = os.environ.get("PADDLE_TRAINERS_NUM")
    if coord and nproc and int(nproc) > 1:
        port = os.environ.get("MASTER_PORT", "8476")
        addr = coord if ":" in coord else f"{coord}:{port}"
        jax.distributed.initialize(
            coordinator_address=addr,
            num_processes=int(nproc),
            process_id=int(os.environ.get("PADDLE_TRAINER_ID", "0")),
        )
    _initialized = True
    return ParallelEnv()


def destroy_process_group(group=None):
    global _initialized
    _initialized = False


def parallel_mode():
    return _initialized
