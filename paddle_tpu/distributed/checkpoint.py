"""Distributed / sharded checkpointing (reference: auto_parallel
dist_saver.py + converter.py mesh-reshard, sharding
save_group_sharded_model; SURVEY §5.4).

TPU-native: orbax handles sharded array save/restore; restoring onto a
different mesh reshards automatically from the on-disk global view — the
capability the reference implements by hand in converter.py.
"""
from __future__ import annotations

import os

import numpy as np
import jax

from ..core.tensor import Tensor

__all__ = ["save_state_dict", "load_state_dict", "save_sharded", "load_sharded"]


def _ckptr():
    import orbax.checkpoint as ocp

    return ocp


def save_state_dict(state_dict, path, process_group=None, coordinator_rank=0):
    """Save a (possibly sharded-array) state dict; jax.Array shardings are
    recorded so any-mesh restore works."""
    ocp = _ckptr()
    path = os.path.abspath(path)
    arrays = {
        k: (v._data if isinstance(v, Tensor) else v) for k, v in state_dict.items()
    }
    ckpt = ocp.StandardCheckpointer()
    ckpt.save(path, arrays, force=True)
    ckpt.wait_until_finished()


def load_state_dict(path, shardings=None, process_group=None):
    """Restore; pass `shardings` (name → jax.sharding.Sharding or
    ShapeDtypeStruct) to place arrays directly onto a (new) mesh."""
    ocp = _ckptr()
    path = os.path.abspath(path)
    ckpt = ocp.StandardCheckpointer()
    restored = ckpt.restore(path, target=shardings) if shardings is not None else ckpt.restore(path)
    return {k: Tensor(v) for k, v in restored.items()}


def save_sharded(model, optimizer, path, extra=None):
    state = {}
    for name, p in model.named_parameters():
        state[f"model.{name}"] = p._data
    for name, b in model.named_buffers():
        state[f"buffer.{name}"] = b._data
    if optimizer is not None:
        names = optimizer._param_names()
        for key, slots in optimizer._states.items():
            for sname, arr in slots.items():
                state[f"opt.{names[key]}.{sname}"] = arr
        for key, arr in optimizer._master_weights.items():
            state[f"opt.{names[key]}.master"] = arr
    save_state_dict(state, path)


def load_sharded(model, optimizer, path):
    restored = load_state_dict(path)
    pmap = dict(model.named_parameters())
    bmap = dict(model.named_buffers())
    opt_names = {} if optimizer is None else {v: k for k, v in optimizer._param_names().items()}
    for k, v in restored.items():
        arr = v._data
        if k.startswith("model."):
            pmap[k[len("model."):]]._data = arr
        elif k.startswith("buffer."):
            bmap[k[len("buffer."):]]._data = arr
        elif k.startswith("opt.") and optimizer is not None:
            body = k[len("opt."):]
            pname, sname = body.rsplit(".", 1)
            key = opt_names.get(pname)
            if key is None:
                continue
            if sname == "master":
                optimizer._master_weights[key] = arr
            else:
                optimizer._states.setdefault(key, {})[sname] = arr
