"""Distributed / sharded checkpointing (reference: auto_parallel
dist_saver.py + converter.py mesh-reshard, sharding
save_group_sharded_model; SURVEY §5.4).

TPU-native: orbax handles sharded array save/restore; restoring onto a
different mesh reshards automatically from the on-disk global view — the
capability the reference implements by hand in converter.py.
"""
from __future__ import annotations

import os
import shutil

import numpy as np
import jax

from ..core.tensor import Tensor

__all__ = ["save_state_dict", "load_state_dict", "save_sharded", "load_sharded"]


def _ckptr():
    import orbax.checkpoint as ocp

    return ocp


def _swap_siblings(path):
    """All ``<path>.tmp-*`` / ``<path>.old-*`` staging dirs, any pid."""
    d, base = os.path.split(path)
    tmps, olds = [], []
    try:
        names = os.listdir(d or ".")
    except OSError:
        return tmps, olds
    for n in names:
        if n.startswith(base + ".tmp-"):
            tmps.append(os.path.join(d, n))
        elif n.startswith(base + ".old-"):
            olds.append(os.path.join(d, n))
    return tmps, olds


def _recover_interrupted_swap(path):
    """Complete or roll back a swap a dead process left half-done, and
    sweep its staging remnants.  The protocol is unambiguous:

    - `path` exists          → every tmp/old sibling is garbage (the swap
      either finished or never began); remove them.
    - `path` missing, tmp+old → the crash hit BETWEEN the two renames,
      which only happens after tmp was fully written and fsynced —
      finish the swap (tmp → path), drop old.
    - `path` missing, old only → cannot arise from one crash (tmp is
      still present whenever old is), but if e.g. an earlier partial
      cleanup removed tmp, old is the survivor — roll it back
      (old → path).
    - `path` missing, tmp only → the crash hit mid-payload-write: tmp is
      suspect, but with no alternative it is better than nothing — leave
      it for manual inspection, restore nothing.
    """
    tmps, olds = _swap_siblings(path)
    if os.path.exists(path):
        for p in tmps + olds:
            shutil.rmtree(p, ignore_errors=True)
        return
    if tmps and olds:
        newest = max(tmps, key=os.path.getmtime)
        os.rename(newest, path)
        for p in olds + [t for t in tmps if t != newest]:
            shutil.rmtree(p, ignore_errors=True)
    elif olds:
        newest = max(olds, key=os.path.getmtime)
        os.rename(newest, path)
        for p in [o for o in olds if o != newest]:
            shutil.rmtree(p, ignore_errors=True)


def save_state_dict(state_dict, path, process_group=None, coordinator_rank=0,
                    _atomic=True):
    """Save a (possibly sharded-array) state dict; jax.Array shardings are
    recorded so any-mesh restore works.

    Crash-safe by default: the payload is written to a sibling
    ``<path>.tmp-<pid>`` directory and swapped in only once complete, so
    a save interrupted at ANY point can never clobber a previous good
    checkpoint (the old `force=True` overwrote in place).  A swap a dead
    process left half-done (crash between the two renames) is completed
    by the next save/load at the same path via
    `_recover_interrupted_swap`.  `resilience.CheckpointManager` passes
    ``_atomic=False`` because it owns a whole-checkpoint rename one
    level up — double-staging would just double the IO."""
    ocp = _ckptr()
    path = os.path.abspath(path)
    arrays = {
        k: (v._data if isinstance(v, Tensor) else v) for k, v in state_dict.items()
    }
    ckpt = ocp.StandardCheckpointer()
    if not _atomic:
        ckpt.save(path, arrays, force=True)
        ckpt.wait_until_finished()
        return
    _recover_interrupted_swap(path)
    tmp = f"{path}.tmp-{os.getpid()}"
    old = f"{path}.old-{os.getpid()}"
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    ckpt.save(tmp, arrays, force=True)
    ckpt.wait_until_finished()
    from ..resilience import faults as _faults

    # injection point: payload written, previous checkpoint still intact
    _faults.maybe_crash(site="save_state_dict")
    # the swap: two renames — at every intermediate crash point an intact
    # checkpoint survives (under `path`, or under `tmp`/`old` where the
    # recovery above finds it); a partial write is never visible at `path`
    if os.path.exists(path):
        os.rename(path, old)
    os.rename(tmp, path)
    shutil.rmtree(old, ignore_errors=True)


def load_state_dict(path, shardings=None, process_group=None):
    """Restore; pass `shardings` (name → jax.sharding.Sharding or
    ShapeDtypeStruct) to place arrays directly onto a (new) mesh."""
    ocp = _ckptr()
    path = os.path.abspath(path)
    if not os.path.exists(path):
        # a dead process may have left the swap half-done — recover the
        # intact payload from its staging siblings before restoring
        _recover_interrupted_swap(path)
    ckpt = ocp.StandardCheckpointer()
    restored = ckpt.restore(path, target=shardings) if shardings is not None else ckpt.restore(path)
    return {k: Tensor(v) for k, v in restored.items()}


def _opt_param_names(model, optimizer):
    """id(param) → checkpoint key for optimizer slots.

    STRUCTURAL model names (named_parameters paths), not Tensor autonames:
    autonames come from global per-class counters, so any difference in
    construction history between the saving and loading process shifts
    them — and slots saved under shifted names would be silently skipped
    on restore. Optimizer-only params (not in the model) fall back to
    their autoname."""
    names = {id(p): f"__extra__.{p.name or f'param_{i}'}"
             for i, p in enumerate(optimizer._parameter_list)}
    for name, p in model.named_parameters():
        if id(p) in names:
            names[id(p)] = name
    return names


def save_sharded(model, optimizer, path, extra=None):
    state = {}
    for name, p in model.named_parameters():
        state[f"model.{name}"] = p._data
    for name, b in model.named_buffers():
        state[f"buffer.{name}"] = b._data
    if optimizer is not None:
        names = _opt_param_names(model, optimizer)
        for key, slots in optimizer._states.items():
            for sname, arr in slots.items():
                state[f"opt.{names[key]}.{sname}"] = arr
        for key, arr in optimizer._master_weights.items():
            state[f"opt.{names[key]}.master"] = arr
    save_state_dict(state, path)


def load_sharded(model, optimizer, path):
    restored = load_state_dict(path)
    pmap = dict(model.named_parameters())
    bmap = dict(model.named_buffers())
    opt_names = ({} if optimizer is None
                 else {v: k for k, v in
                       _opt_param_names(model, optimizer).items()})
    def _reshard(arr, like):
        """Place a restored global array onto the DESTINATION's sharding
        (the reference converter.py mesh-reshard: the checkpoint may have
        been written from a different mesh, and the restored array carries
        the saved placement)."""
        if like is None:
            return arr
        try:
            return jax.device_put(arr, like.sharding)
        except (ValueError, TypeError):
            return arr

    skipped = []
    for k, v in restored.items():
        arr = v._data
        if k.startswith("model."):
            p = pmap.get(k[len("model."):])
            if p is None:
                skipped.append(k)
                continue
            p._data = _reshard(arr, p._data)
        elif k.startswith("buffer."):
            b = bmap.get(k[len("buffer."):])
            if b is None:
                skipped.append(k)
                continue
            b._data = _reshard(arr, b._data)
        elif k.startswith("opt.") and optimizer is not None:
            body = k[len("opt."):]
            pname, sname = body.rsplit(".", 1)
            key = opt_names.get(pname)
            if key is None:
                skipped.append(k)
                continue
            if sname == "master":
                like = optimizer._master_weights.get(key)
                optimizer._master_weights[key] = _reshard(arr, like)
            else:
                slots = optimizer._states.setdefault(key, {})
                slots[sname] = _reshard(arr, slots.get(sname))
    if skipped:
        import warnings

        warnings.warn(
            f"load_sharded: {len(skipped)} checkpoint entr(ies) had no "
            f"matching destination and were skipped (first: {skipped[0]}) "
            "— the checkpoint was written for a different parameter set")
