"""Semi-automatic parallelism (reference:
python/paddle/distributed/auto_parallel/ — ProcessMesh (process_mesh.py),
shard_tensor annotation, Engine (engine.py:58, .fit:811, .prepare:1272)
with Completer/Partitioner/Resharder pass pipeline).

TPU-native design: the Completer/Partitioner/Resharder trio IS the XLA
GSPMD partitioner — user annotations become jax shardings on a Mesh, the
compiler propagates them through the whole program and inserts the
collectives. The Engine here wires annotations + whole-graph jit + the
training loop; no hand-written propagation passes are needed."""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..core.tensor import Tensor
from .. import parallel as _P

__all__ = ["ProcessMesh", "shard_tensor", "shard_op", "Engine", "Strategy"]


class ProcessMesh:
    """N-D logical mesh of processes/devices (reference:
    auto_parallel/process_mesh.py). dim_names map onto the framework's
    global device mesh axes."""

    def __init__(self, mesh, dim_names: Optional[Sequence[str]] = None,
                 process_ids=None):
        arr = np.asarray(mesh)
        self.shape = list(arr.shape)
        self.process_ids = arr.reshape(-1).tolist()
        self.dim_names = list(dim_names) if dim_names else [
            f"d{i}" for i in range(arr.ndim)]
        if len(self.dim_names) != arr.ndim:
            raise ValueError("dim_names length must match mesh rank")

    @property
    def ndim(self):
        return len(self.shape)

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dim_names={self.dim_names})"

    def _ensure_device_mesh(self):
        """Materialize a jax Mesh with these axes (axes not named dp/mp/...
        are mapped positionally onto a fresh mesh)."""
        sizes = dict(zip(self.dim_names, self.shape))
        kwargs = {}
        for axis in ("dp", "mp", "pp", "sharding", "sp", "ep"):
            if axis in sizes:
                kwargs[axis] = sizes[axis]
        if kwargs:
            return _P.init_mesh(**kwargs)
        # generic names: map first axis to dp, second to mp
        defaults = ["dp", "mp", "pp", "sp"]
        for name, size in zip(self.dim_names, self.shape):
            kwargs[defaults[len(kwargs)]] = size
        mesh = _P.init_mesh(**kwargs)
        # remember the rename for shard_tensor
        self._rename = dict(zip(self.dim_names, list(kwargs)))
        return mesh

    def _axis(self, name):
        if name is None:
            return None
        return getattr(self, "_rename", {}).get(name, name)


def shard_tensor(x, process_mesh: ProcessMesh = None, shard_spec=None,
                 mesh=None, placements=None):
    """Annotate a tensor/parameter with per-dim mesh axes (reference:
    auto_parallel shard_tensor). shard_spec: list of axis names or None
    per tensor dim."""
    process_mesh = process_mesh or mesh
    if process_mesh is not None:
        process_mesh._ensure_device_mesh()
        spec = [process_mesh._axis(a) for a in (shard_spec or [])]
    else:
        spec = list(shard_spec or [])
    if hasattr(x, "_sharding_axes"):
        x._sharding_axes = spec
    return _P.shard_tensor(x, spec) if not hasattr(x, "trainable") else x


def shard_op(op, process_mesh=None, in_shard_specs=None, out_shard_specs=None):
    """Annotation shim: under GSPMD the compiler propagates op shardings
    from operand shardings, so this only constrains inputs."""

    def wrapped(*args, **kwargs):
        if process_mesh is not None and in_shard_specs:
            args = tuple(
                shard_tensor(a, process_mesh, s) if s is not None else a
                for a, s in zip(args, list(in_shard_specs) + [None] * len(args))
            )
        return op(*args, **kwargs)

    return wrapped


class Strategy:
    """Auto-parallel strategy knobs (reference: auto_parallel/strategy.py);
    the subset that changes behavior here: amp / recompute toggles."""

    def __init__(self):
        self.auto_mode = "semi"
        self.amp = type("amp", (), {"enable": False, "dtype": "bfloat16"})()
        self.recompute = type("rc", (), {"enable": False})()
        self.gradient_merge = type("gm", (), {"enable": False, "k_steps": 1})()


class Engine:
    """Prepare/fit/evaluate/predict driver (reference:
    auto_parallel/engine.py:58). The model's annotated parameters are
    placed on the mesh; the train step is whole-graph jitted so GSPMD
    completes the sharding plan and inserts collectives."""

    def __init__(self, model=None, loss=None, optimizer=None, metrics=None,
                 cluster=None, strategy=None):
        self._model = model
        self._loss = loss
        self._optimizer = optimizer
        self._metrics = metrics or []
        self._strategy = strategy or Strategy()
        self._compiled = None

    def tune(self, global_batch, cluster=None, top_k=5, measure=False,
             measure_top_k=8, report_path=None):
        """Search parallel plans for this engine's model (reference:
        tuner/optimization_tuner.py via Engine _tune). With measure=True
        the top measure_top_k candidates are trial-run on the current
        mesh and the choice is by measurement (roofline recalibrated from
        the trials; report written to report_path). Returns ranked Plans;
        apply one with paddle.parallel.init_mesh(**plan.mesh_kwargs())."""
        from .tuner import ClusterSpec, ModelSpec, OptimizationTuner

        cfg = getattr(self._model, "cfg", None) or getattr(
            getattr(self._model, "gpt", None), "cfg", None)
        if cfg is None or not hasattr(cfg, "hidden_size"):
            raise ValueError(
                "Engine.tune needs a transformer-shaped model config "
                "(hidden_size/num_hidden_layers); construct a "
                "distributed.tuner.ModelSpec manually for other models")
        spec = ModelSpec.from_gpt_config(cfg, global_batch)
        self._tuner = OptimizationTuner(spec, cluster or ClusterSpec())
        return self._tuner.tune(top_k=top_k, measure=measure,
                                measure_top_k=measure_top_k,
                                report_path=report_path)

    def prepare(self, inputs_spec=None, labels_spec=None, mode="train"):
        from .. import jit

        model, loss, opt = self._model, self._loss, self._optimizer
        _P.place_model(model)

        def step(*data):
            n_lab = 1 if len(data) > 1 else 0
            inputs, labels = data[:len(data) - n_lab], data[len(data) - n_lab:]
            out = model(*inputs)
            l = loss(out, *labels) if labels else loss(out)
            l.backward()
            opt.step()
            opt.clear_grad()
            return l

        self._compiled = jit.compile(step, models=(model,), optimizers=(opt,))
        return self._compiled

    @staticmethod
    def _as_loader(data, batch_size, collate_fn, **kw):
        """Wrap map-style data (``__getitem__``/``__len__`` without
        ``__iter__``) in a DataLoader — whether or not it subclasses
        io.Dataset. A bare map-style object iterated directly would hit
        Python's legacy ``__getitem__`` iteration, which never terminates
        when indexing past the end doesn't raise IndexError."""
        from ..io import DataLoader

        if hasattr(data, "__iter__"):
            return data
        return DataLoader(data, batch_size=batch_size, collate_fn=collate_fn,
                          **kw)

    def fit(self, train_data, epochs=1, batch_size=1, steps_per_epoch=None,
            log_freq=10, verbose=0, collate_fn=None):
        loader = self._as_loader(train_data, batch_size, collate_fn,
                                 shuffle=True, drop_last=True)
        if self._compiled is None:
            self.prepare()
        history = []
        for epoch in range(epochs):
            losses = []
            for step_i, batch in enumerate(loader):
                if steps_per_epoch and step_i >= steps_per_epoch:
                    break
                batch = list(batch) if isinstance(batch, (list, tuple)) else [batch]
                l = self._compiled(*batch)
                losses.append(float(l.item() if isinstance(l, Tensor) else l))
                if verbose and step_i % log_freq == 0:
                    print(f"epoch {epoch} step {step_i}: loss {losses[-1]:.4f}")
            history.append(float(np.mean(losses)) if losses else float("nan"))
        return history

    def evaluate(self, eval_data, batch_size=1, collate_fn=None):
        from ..autograd import no_grad

        loader = self._as_loader(eval_data, batch_size, collate_fn)
        losses = []
        with no_grad():
            for batch in loader:
                batch = list(batch) if isinstance(batch, (list, tuple)) else [batch]
                out = self._model(*batch[:-1])
                losses.append(float(self._loss(out, batch[-1]).item()))
        return {"loss": float(np.mean(losses))}

    def predict(self, test_data, batch_size=1, collate_fn=None):
        from ..autograd import no_grad

        loader = self._as_loader(test_data, batch_size, collate_fn)
        outs = []
        with no_grad():
            for batch in loader:
                batch = list(batch) if isinstance(batch, (list, tuple)) else [batch]
                # Datasets yield (input, label) pairs for prediction too:
                # feed only the inputs (hapi Model._split_batch semantics —
                # with a loss configured the last element is the label).
                inputs = batch[:-1] if self._loss is not None and len(batch) > 1 \
                    else batch
                outs.append(self._model(*inputs).numpy())
        return outs

    def save(self, path, training=True):
        from ..framework.io_ import save as _save

        _save(self._model.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            _save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path):
        from ..framework.io_ import load as _load

        self._model.set_state_dict(_load(path + ".pdparams"))
