"""paddle.distributed.stream — stream-variant collective API.

Reference analog: python/paddle/distributed/communication/stream/
(all_reduce.py:73 etc.) — the same collectives with `sync_op` /
`use_calc_stream` controlling which CUDA stream carries the
communication and whether the caller must wait on the returned task.

TPU-native stance: there are no user-visible streams — every collective
is an HLO op inside a compiled program, and XLA's latency-hiding
scheduler decides the overlap the reference manages by hand with
comm/calc streams. The API shape is preserved (fleet code ports
unchanged): results land in the in-place/out arguments exactly like the
reference, and every call returns a Task whose wait()/is_completed()
succeed immediately — under XLA the communication is part of the
program, so the task is born done.
"""
from __future__ import annotations

from . import collective as _c
from .collective import ReduceOp

__all__ = [
    "all_gather", "all_reduce", "alltoall", "alltoall_single", "broadcast",
    "reduce", "reduce_scatter", "recv", "scatter", "send",
]


class _DoneTask:
    """Completed-communication handle (reference: ProcessGroup task)."""

    def is_completed(self):
        return True

    def wait(self):
        return True

    def synchronize(self):
        return True


def _write_out(out, tensors):
    """Reference stream calls accept a pre-allocated out tensor OR a
    tensor list; fill whichever was given so the result stays reachable
    behind the task-only return."""
    if out is None:
        return
    if isinstance(out, list):
        out.clear()
        out.extend(tensors)
        return
    from ..ops.manipulation import concat

    out._data = concat(list(tensors), 0)._data


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True,
               use_calc_stream=False):
    _c.all_reduce(tensor, op=op, group=group)
    return _DoneTask()


def all_gather(tensor_or_tensor_list, tensor, group=None, sync_op=True,
               use_calc_stream=False):
    parts = _c.all_gather(
        tensor_or_tensor_list if isinstance(tensor_or_tensor_list, list)
        else [], tensor, group=group)
    if not isinstance(tensor_or_tensor_list, list):
        _write_out(tensor_or_tensor_list, parts)
    return _DoneTask()


def alltoall(out_tensor_or_tensor_list, in_tensor_or_tensor_list,
             group=None, sync_op=True, use_calc_stream=False):
    outs = _c.alltoall(in_tensor_or_tensor_list, group=group)
    _write_out(out_tensor_or_tensor_list, outs)
    return _DoneTask()


def alltoall_single(out_tensor, in_tensor, out_split_sizes=None,
                    in_split_sizes=None, group=None, sync_op=True,
                    use_calc_stream=False):
    if out_tensor is None:
        raise ValueError(
            "stream.alltoall_single requires a pre-allocated out_tensor "
            "(the task-only return leaves no other way to the result)")
    _c.alltoall_single(in_tensor, out_tensor=out_tensor,
                       in_split_sizes=in_split_sizes,
                       out_split_sizes=out_split_sizes, group=group)
    return _DoneTask()


def broadcast(tensor, src=0, group=None, sync_op=True,
              use_calc_stream=False):
    _c.broadcast(tensor, src=src, group=group)
    return _DoneTask()


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True,
           use_calc_stream=False):
    _c.reduce(tensor, dst=dst, op=op, group=group)
    return _DoneTask()


def reduce_scatter(tensor, tensor_or_tensor_list=None, op=ReduceOp.SUM,
                   group=None, sync_op=True, use_calc_stream=False):
    _c.reduce_scatter(tensor, tensor_list=(
        tensor_or_tensor_list if isinstance(tensor_or_tensor_list, list)
        else None), op=op, group=group)
    return _DoneTask()


def scatter(tensor, tensor_or_tensor_list=None, src=0, group=None,
            sync_op=True, use_calc_stream=False):
    _c.scatter(tensor, tensor_list=(
        tensor_or_tensor_list if isinstance(tensor_or_tensor_list, list)
        else None), src=src, group=group)
    return _DoneTask()


def send(tensor, dst=0, group=None, sync_op=True, use_calc_stream=False):
    _c.send(tensor, dst=dst, group=group)
    return _DoneTask()


def recv(tensor, src=0, group=None, sync_op=True, use_calc_stream=False):
    _c.recv(tensor, src=src, group=group)
    return _DoneTask()
