"""Group-sharded (ZeRO 1/2/3) data parallelism, TPU-native.

Reference analog: python/paddle/distributed/sharding/group_sharded.py
(`group_sharded_parallel`, save util :179) and the dygraph stage
implementations under fleet/meta_parallel/sharding/
(GroupShardedOptimizerStage2, GroupShardedStage2/3) plus
DygraphShardingOptimizer (dygraph_optimizer/dygraph_sharding_optimizer.py:29).

TPU-native re-design (SURVEY §7 "hard parts"): the reference's hook-driven
gather/release machinery does not translate — XLA compiles the whole train
step, so ZeRO becomes *weight-update sharding*: we place optimizer slot
state (stage 1), gradients (stage 2), and parameters (stage 3) with a
NamedSharding split on the 'sharding' mesh axis, and GSPMD inserts the
reduce-scatter (grads → sharded update) and all-gather (params → forward)
collectives on ICI automatically. No per-param hooks, no buckets — the
XLA latency-hiding scheduler overlaps the collectives with compute, which
is the role the reference's bucketing/overlap code played.

Levels (same strings as the reference):
  "os"     — optimizer-state sharding (stage 1)
  "os_g"   — + gradient sharding     (stage 2)
  "p_g_os" — + parameter sharding    (stage 3)
"""
from __future__ import annotations

from typing import Optional

import numpy as np
import jax
from jax.sharding import NamedSharding, PartitionSpec

from ..core.tensor import Tensor
from ..parallel.mesh import get_mesh, axis_size
from ..parallel.api import param_sharding
from .fleet.hybrid_optimizer import (
    _shard_slot_sharding,
    shard_spec_with,
    DygraphShardingOptimizer,
)

__all__ = [
    "group_sharded_parallel",
    "save_group_sharded_model",
    "ShardingPlacer",
    "DygraphShardingOptimizer",
]


class ShardingPlacer:
    """Places an optimizer slot/master/grad array with the owning param's
    sharding spec PLUS the 'sharding' axis on the first divisible free dim
    (fleet/hybrid_optimizer.py:_shard_slot_sharding — composes with an
    existing tensor-parallel annotation instead of dropping it). Installed
    on an Optimizer as `_state_placer`; `Optimizer._ensure_state` and
    `set_state_dict` run every slot/master array through it."""

    def __init__(self, axis: str = "sharding"):
        self.axis = axis

    _warned = False

    def __call__(self, arr, param=None):
        if param is not None and len(param.shape) == len(arr.shape):
            sh = _shard_slot_sharding(param, get_mesh(), self.axis)
        else:
            spec = shard_spec_with(None, arr.shape, self.axis)
            sh = NamedSharding(get_mesh(), PartitionSpec(*spec))
        try:
            return jax.device_put(arr, sh)
        except Exception as e:
            # Leave the array unplaced but say so once — silent fallback here
            # means ZeRO is off and the user finds out as an OOM at scale.
            if not ShardingPlacer._warned:
                ShardingPlacer._warned = True
                import warnings

                warnings.warn(
                    f"ShardingPlacer: device_put failed ({e!r}); optimizer "
                    "state stays replicated (no ZeRO memory savings).",
                    stacklevel=2,
                )
            return arr


def _shard_params_stage3(model, axis: str = "sharding"):
    """Annotate + place every parameter split on `axis` (dim chosen by
    divisibility; composes with an existing tensor-parallel annotation by
    picking a different dim)."""
    for p in model.parameters():
        if not p.shape:
            continue
        spec = shard_spec_with(p._sharding_axes, p.shape, axis)
        if spec != tuple(p._sharding_axes or (None,) * len(p.shape)):
            p._sharding_axes = spec
        p._data = jax.device_put(p._data, param_sharding(p))
    return model


def group_sharded_parallel(
    model,
    optimizer,
    level: str,
    scaler=None,
    group=None,
    offload: bool = False,
    sync_buffers: bool = False,
    buffer_max_size: int = 2 ** 23,
    segment_size: int = 2 ** 20,
    sync_comm: bool = False,
    dp_group=None,
    exclude_layer=None,
):
    """Shard a model + optimizer over the 'sharding' mesh axis.

    Mirrors the reference API (group_sharded.py): returns
    (model, optimizer, scaler). `offload`/buffer sizes are accepted for
    API parity; XLA owns memory scheduling on TPU so they are no-ops.
    """
    if level not in ("os", "os_g", "p_g_os"):
        raise ValueError(f"level must be one of os | os_g | p_g_os, got {level!r}")

    # Accept fleet wrappers (HybridParallelOptimizer / DygraphShardingOptimizer)
    # — the placer must land on the inner Optimizer whose step() reads it,
    # but the caller keeps (and gets back) the object they passed in.
    outer_optimizer = optimizer
    optimizer = getattr(optimizer, "_inner_opt", optimizer)

    if axis_size("sharding") <= 1:
        import warnings

        warnings.warn(
            "group_sharded_parallel: mesh has no 'sharding' axis of size > 1 "
            "(init_mesh(sharding=N) first) — everything stays replicated and "
            "ZeRO saves no memory.",
            stacklevel=2,
        )

    placer = ShardingPlacer("sharding")
    optimizer._state_placer = placer
    # Re-place any states that already exist.
    param_of = {id(p): p for p in optimizer._parameter_list}
    for key, slots in optimizer._states.items():
        optimizer._states[key] = {
            k: placer(v, param_of.get(key)) for k, v in slots.items()
        }
    for key, arr in optimizer._master_weights.items():
        optimizer._master_weights[key] = placer(arr, param_of.get(key))

    if level in ("os_g", "p_g_os"):
        optimizer._shard_grads = placer

    if level == "p_g_os":
        _shard_params_stage3(model, "sharding")

    if sync_buffers:
        # Buffers replicate across the mesh (device_put with no partition).
        mesh = get_mesh()
        rep = NamedSharding(mesh, PartitionSpec())
        for b in model.buffers():
            b._data = jax.device_put(b._data, rep)

    return model, outer_optimizer, scaler


def save_group_sharded_model(model, output: str, optimizer=None):
    """Gather the sharded model (and optimizer) to host and save
    (reference: group_sharded.py:179 — rank-0 consolidated save)."""
    import os

    from ..framework.io_ import save as _save

    if output.endswith((".pdmodel", ".pdopt", ".pdparams")):
        raise ValueError("output should be a directory, not a file path")
    os.makedirs(output, exist_ok=True)
    # np.asarray on a sharded jax.Array performs the all-gather to host.
    state = {k: Tensor(np.asarray(v._data)) for k, v in model.state_dict().items()}
    _save(state, os.path.join(output, "model.pdparams"))
    if optimizer is not None:
        ostate = {}
        for k, v in optimizer.state_dict().items():
            ostate[k] = Tensor(np.asarray(v._data)) if isinstance(v, Tensor) else v
        _save(ostate, os.path.join(output, "model.pdopt"))


# DygraphShardingOptimizer is fleet's class (re-exported above): one
# implementation, hybrid-aware, shared by both entry points.
