"""Distributed long-tail compat (reference: python/paddle/distributed/
__init__.py exports — object collectives, async send/recv handles, gloo
bootstrap, ParallelMode, and the PS-era dataset/entry configs).

TPU-native notes: object collectives pickle through the tensor
collectives; isend/irecv return completed-task handles (XLA collectives
are synchronous at the host API level — the async overlap happens inside
the compiled program, reference ProcessGroup task semantics kept for API
parity); gloo_* bootstrap maps to the TCPStore rendezvous this framework
already runs for multi-host jobs.
"""
from __future__ import annotations

import pickle

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = [
    "ParallelMode", "CountFilterEntry", "ProbabilityEntry", "ShowClickEntry",
    "InMemoryDataset", "QueueDataset", "broadcast_object_list",
    "scatter_object_list", "gloo_init_parallel_env",
    "gloo_barrier", "gloo_release", "is_available", "isend", "irecv", "split",
]


class ParallelMode:
    """Training parallel mode constants (reference parallel.ParallelMode)."""

    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3


def is_available():
    """Whether the distributed package can be used (reference
    distributed.is_available)."""
    return True


class _Task:
    """Completed-task handle (reference ProcessGroup task): wait()/is_completed
    — the collective already ran synchronously by the time this returns."""

    def __init__(self, tensor=None):
        self._tensor = tensor

    def wait(self, timeout=None):
        return True

    def is_completed(self):
        return True


def isend(tensor, dst=0, group=None):
    from .parallel_env import get_world_size

    if get_world_size(group) <= 1:
        return _Task(tensor)     # identity semantics, like the collectives
    from .collective import send

    send(tensor, dst=dst, group=group, sync_op=True)
    return _Task(tensor)


def irecv(tensor, src=0, group=None):
    from .parallel_env import get_world_size

    if get_world_size(group) <= 1:
        return _Task(tensor)
    from .collective import recv

    recv(tensor, src=src, group=group, sync_op=True)
    return _Task(tensor)


def _obj_to_tensor(obj):
    data = np.frombuffer(pickle.dumps(obj), np.uint8).copy()
    return Tensor(jnp.asarray(data)), len(data)


def _tensor_to_obj(t, n):
    return pickle.loads(np.asarray(t._data)[:n].tobytes())


def broadcast_object_list(object_list, src=0, group=None):
    """Broadcast pickled python objects (reference
    communication/broadcast.py broadcast_object_list). Single-program SPMD
    note: every rank holds the same host objects, so outside a multi-host
    launch this is an identity (matching broadcast's identity semantics)."""
    from .parallel_env import get_world_size

    if get_world_size(group) <= 1:
        return object_list
    from .collective import broadcast

    for i, obj in enumerate(object_list):
        t, n = _obj_to_tensor(obj)
        broadcast(t, src=src, group=group)
        object_list[i] = _tensor_to_obj(t, n)
    return object_list


def scatter_object_list(out_object_list, in_object_list=None, src=0,
                        group=None):
    """Scatter pickled objects (reference scatter_object_list)."""
    from .parallel_env import get_rank, get_world_size

    ws = get_world_size(group)
    if ws <= 1:
        out_object_list[:] = [in_object_list[0]] if in_object_list else []
        return out_object_list
    rank = get_rank(group)
    out_object_list[:] = [in_object_list[rank]]
    return out_object_list


def gloo_init_parallel_env(rank_id, rank_num, server_endpoint):
    """CPU-only bootstrap (reference gloo_init_parallel_env). The TCPStore
    rendezvous this framework runs for multi-host jobs plays gloo's role;
    this wires the same env knobs."""
    import os

    os.environ.setdefault("PADDLE_TRAINER_ID", str(rank_id))
    os.environ.setdefault("PADDLE_TRAINERS_NUM", str(rank_num))
    host, _, port = server_endpoint.partition(":")
    os.environ.setdefault("MASTER_ADDR", host)
    os.environ.setdefault("MASTER_PORT", port or "6170")
    from .parallel_env import init_parallel_env

    init_parallel_env()


def gloo_barrier():
    from .collective import barrier

    barrier()


def gloo_release():
    """Release bootstrap resources (reference gloo_release) — the store
    closes with the process here; nothing to free eagerly."""


class _Entry:
    """Sparse-table entry config base (reference distributed/entry_attr.py;
    PS accessors). The parameter-server runtime is out of the TPU critical
    path (SURVEY §2.5.14); these configs validate and serialize so model
    definitions that attach them still construct."""

    def _to_attr(self):
        raise NotImplementedError


class CountFilterEntry(_Entry):
    def __init__(self, count_filter):
        if count_filter < 0:
            raise ValueError("count_filter must be >= 0")
        self._count_filter = int(count_filter)

    def _to_attr(self):
        return f"count_filter_entry:{self._count_filter}"


class ProbabilityEntry(_Entry):
    def __init__(self, probability):
        if not 0 <= probability <= 1:
            raise ValueError("probability must be in [0, 1]")
        self._probability = float(probability)

    def _to_attr(self):
        return f"probability_entry:{self._probability}"


class ShowClickEntry(_Entry):
    def __init__(self, show_name, click_name):
        self._show = show_name
        self._click = click_name

    def _to_attr(self):
        return f"show_click_entry:{self._show}:{self._click}"


class InMemoryDataset:
    """PS-era slot dataset (reference distributed/fleet/dataset/
    InMemoryDataset): loads slot files into memory, supports shuffle and
    batched iteration. Here it is a host-side record store feeding the
    normal DataLoader path (the PS pipeline itself is out of scope)."""

    def __init__(self):
        self._records = []
        self._batch_size = 1
        self._use_var = []

    def init(self, batch_size=1, use_var=None, pipe_command=None,
             thread_num=1, **kwargs):
        self._batch_size = batch_size
        self._use_var = use_var or []

    update_settings = init

    def set_filelist(self, filelist):
        self._filelist = list(filelist)

    def load_into_memory(self):
        self._records = []
        for path in getattr(self, "_filelist", []):
            with open(path) as f:
                for line in f:
                    parts = line.split()
                    if parts:
                        self._records.append(
                            np.asarray([float(v) for v in parts], np.float32))

    def local_shuffle(self):
        import random

        # ptpu-check[determinism]: reference-API contract — paddle's
        # InMemoryDataset shuffles on the global stream, seedable via
        # random.seed() like the reference
        random.shuffle(self._records)

    global_shuffle = local_shuffle

    def get_memory_data_size(self):
        return len(self._records)

    def release_memory(self):
        self._records = []

    def __iter__(self):
        for i in range(0, len(self._records), self._batch_size):
            yield self._records[i:i + self._batch_size]


class QueueDataset(InMemoryDataset):
    """Streaming variant (reference QueueDataset): iterates files directly
    without the load_into_memory staging."""

    def load_into_memory(self):
        raise RuntimeError("QueueDataset streams from files; use iteration "
                           "directly (reference QueueDataset contract)")

    def __iter__(self):
        batch = []
        for path in getattr(self, "_filelist", []):
            with open(path) as f:
                for line in f:
                    parts = line.split()
                    if parts:
                        batch.append(np.asarray([float(v) for v in parts],
                                                np.float32))
                    if len(batch) == self._batch_size:
                        yield batch
                        batch = []
        if batch:
            yield batch


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """Model-parallel op with a split weight (reference
    fleet/layers/mpu/mp_ops.py:653 distributed.split — parallel embedding /
    column- or row-parallel linear). TPU-native: constructs the matching
    mp layer (GSPMD-sharded weight over the 'mp' axis) and applies it —
    num_partitions must equal the mesh's mp degree, as in the reference.
    """
    from ..parallel.mesh import axis_size
    from ..parallel.mp_layers import (ColumnParallelLinear,
                                      RowParallelLinear,
                                      VocabParallelEmbedding)

    mp = axis_size("mp")
    if num_partitions not in (1, mp):
        raise ValueError(
            f"num_partitions ({num_partitions}) must match the mesh mp "
            f"degree ({mp})")
    if operation == "embedding":
        layer = VocabParallelEmbedding(size[0], size[1],
                                       weight_attr=weight_attr)
        return layer(x)
    if operation != "linear":
        raise ValueError("operation must be 'linear' or 'embedding'")
    if axis == 0:
        # weight split along rows -> input-parallel (row-parallel linear)
        layer = RowParallelLinear(size[0], size[1], weight_attr=weight_attr,
                                  bias_attr=bias_attr,
                                  input_is_parallel=False)
        return layer(x)
    layer = ColumnParallelLinear(size[0], size[1], weight_attr=weight_attr,
                                 bias_attr=bias_attr,
                                 gather_output=gather_out)
    return layer(x)
