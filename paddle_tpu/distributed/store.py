"""Rendezvous key-value store (reference:
paddle/phi/core/distributed/store/tcp_store.{h,cc} — MasterDaemon + client,
bound as core.TCPStore and used by init_parallel_env at
python/paddle/distributed/parallel.py:279).

TPU-native role: XLA collectives need no comm-id bootstrap, so the store
only coordinates host-side orchestration — rank assignment, barriers,
elastic membership, checkpoint handoff. Backed by the native C++ server
(csrc/tcp_store.cc) when the toolchain is available, else a pure-Python
socket server with the same wire behavior.
"""
from __future__ import annotations

import ctypes
import os
import pickle
import socket
import socketserver
import struct
import threading
import time
from typing import Optional

from ..core import native
from ..resilience import faults as _faults
from ..resilience.retry import Deadline, retry as _retry

__all__ = ["TCPStore"]


# ---------------------------------------------------------------------------
# Pure-Python fallback server (same semantics as csrc/tcp_store.cc)
# ---------------------------------------------------------------------------
class _PyStoreState:
    def __init__(self):
        self.data = {}
        self.cv = threading.Condition()


class _PyHandler(socketserver.BaseRequestHandler):
    def _read(self, n):
        buf = b""
        while len(buf) < n:
            chunk = self.request.recv(n - len(buf))
            if not chunk:
                raise ConnectionError
            buf += chunk
        return buf

    MAX_BLOB = 64 << 20  # mirror csrc/tcp_store.cc kMaxBlobLen

    def _read_blob(self):
        (n,) = struct.unpack("<I", self._read(4))
        if n > self.MAX_BLOB:
            raise ConnectionError(f"oversized frame ({n} bytes)")
        return self._read(n) if n else b""

    def _write_blob(self, b):
        self.request.sendall(struct.pack("<I", len(b)) + b)

    def handle(self):
        st = self.server.state
        try:
            while True:
                cmd = self._read(1)[0]
                key = self._read_blob().decode()
                if cmd == 0:  # SET
                    val = self._read_blob()
                    with st.cv:
                        st.data[key] = val
                        st.cv.notify_all()
                    self.request.sendall(struct.pack("<I", 0))
                elif cmd in (1, 3):  # GET / WAIT
                    (timeout_ms,) = struct.unpack("<I", self._read(4))
                    # monotonic: a wall-clock (NTP) step mid-wait would
                    # stretch or instantly expire the timeout
                    deadline = None if timeout_ms == 0 else time.monotonic() + timeout_ms / 1e3
                    with st.cv:
                        while key not in st.data:
                            remain = None if deadline is None else deadline - time.monotonic()
                            if remain is not None and remain <= 0:
                                break
                            st.cv.wait(remain if remain is not None else 0.2)
                        found = key in st.data
                        val = st.data.get(key)
                    self.request.sendall(struct.pack("<I", 1 if found else 0))
                    if found and cmd == 1:
                        self._write_blob(val)
                elif cmd == 2:  # ADD
                    (amount,) = struct.unpack("<q", self._read(8))
                    with st.cv:
                        cur = struct.unpack("<q", st.data.get(key, b"\0" * 8))[0]
                        cur += amount
                        st.data[key] = struct.pack("<q", cur)
                        st.cv.notify_all()
                    self.request.sendall(struct.pack("<q", cur))
                elif cmd == 4:  # DEL
                    with st.cv:
                        n = 1 if st.data.pop(key, None) is not None else 0
                    self.request.sendall(struct.pack("<I", n))
                elif cmd == 5:  # PING
                    self.request.sendall(struct.pack("<I", 0xA11CE))
                elif cmd == 6:  # CAS (set iff current == expected;
                    # missing key matches empty expected; reply = post-op value)
                    expected = self._read_blob()
                    desired = self._read_blob()
                    with st.cv:
                        cur = st.data.get(key)
                        if (cur is None and expected == b"") or cur == expected:
                            st.data[key] = desired
                            out = desired
                        else:
                            out = cur if cur is not None else b""
                        st.cv.notify_all()
                    self._write_blob(out)
        except (ConnectionError, OSError):
            return


class _PyServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class _PyClient:
    def __init__(self, host, port, timeout_s):
        self._addr = (host, port)
        self._connect(timeout_s)
        self.lock = threading.Lock()

    def _connect(self, timeout_s):
        """Bounded exponential-backoff dial (resilience.retry): a worker
        that starts BEFORE the master has bound its port keeps knocking
        until `timeout_s` instead of raising ConnectionRefusedError."""
        host, port = self._addr
        deadline = Deadline(timeout_s)

        def dial():
            _faults.maybe_raise("conn_error", site="store.connect",
                                exc=ConnectionRefusedError)
            sock = socket.create_connection((host, port), timeout=5)
            sock.settimeout(None)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            return sock

        try:
            # retries sized so backoff doubling spans the whole deadline
            self.sock = _retry(dial, retries=10_000, backoff=0.05,
                               max_backoff=1.0, deadline=deadline,
                               site="store.connect",
                               retryable=(OSError,))()
        except OSError as e:
            raise TimeoutError(
                f"cannot reach store at {host}:{port} "
                f"within {timeout_s}s") from e

    def reconnect(self, timeout_s=5.0):
        # under the client lock: another thread may be blocked in _read()
        # on this socket (it holds the lock for its whole op) — closing it
        # out from under them would cascade teardown and desync the
        # request/response framing
        with self.lock:
            try:
                self.sock.close()
            except OSError:
                pass
            self._connect(timeout_s)

    def _read(self, n):
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("store connection closed")
            buf += chunk
        return buf

    def _req(self, cmd, key, payload=b""):
        kb = key.encode()
        self.sock.sendall(bytes([cmd]) + struct.pack("<I", len(kb)) + kb + payload)

    def set(self, key, value):
        with self.lock:
            self._req(0, key, struct.pack("<I", len(value)) + value)
            self._read(4)

    def get(self, key, timeout_ms):
        with self.lock:
            self._req(1, key, struct.pack("<I", timeout_ms))
            (found,) = struct.unpack("<I", self._read(4))
            if not found:
                return None
            (n,) = struct.unpack("<I", self._read(4))
            return self._read(n) if n else b""

    def add(self, key, amount):
        with self.lock:
            self._req(2, key, struct.pack("<q", amount))
            return struct.unpack("<q", self._read(8))[0]

    def compare_set(self, key, expected, desired):
        with self.lock:
            self._req(6, key,
                      struct.pack("<I", len(expected)) + expected +
                      struct.pack("<I", len(desired)) + desired)
            (n,) = struct.unpack("<I", self._read(4))
            return self._read(n) if n else b""

    def wait_key(self, key, timeout_ms):
        with self.lock:
            self._req(3, key, struct.pack("<I", timeout_ms))
            (found,) = struct.unpack("<I", self._read(4))
            return bool(found)

    def delete(self, key):
        with self.lock:
            self._req(4, key)
            return struct.unpack("<I", self._read(4))[0]

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------
class TCPStore:
    """paddle-style TCPStore: rank 0 (is_master=True) also hosts the server.

    Values are bytes; `set`/`get` pickle arbitrary objects when
    `raw=False` convenience wrappers are used.
    """

    GET_TIMEOUT_MS = 120_000
    OP_RETRIES = 3   # transient-ConnectionError retries per get/set

    def __init__(self, host: str, port: int, is_master: bool = False,
                 world_size: int = 1, timeout: float = 30.0):
        self.host = host
        self.port = port
        self.is_master = is_master
        self.world_size = world_size  # default participant count for barrier()
        self._barrier_added = {}      # name -> round this client counted in
        self._native = native.load()
        self._srv = None
        self._py_srv = None
        if is_master:
            if self._native is not None:
                h = self._native.pts_server_start((host or "").encode(), port)
                if h > 0:
                    self._srv = h
                else:
                    raise OSError(f"TCPStore server failed on port {port} ({h})")
            else:
                self._py_srv = _PyServer((host if host else "0.0.0.0", port),
                                         _PyHandler)
                self._py_srv.state = _PyStoreState()
                threading.Thread(target=self._py_srv.serve_forever,
                                 daemon=True).start()
        if self._native is not None:
            self._cli = self._native.pts_connect(
                (host or "127.0.0.1").encode(), port, int(timeout * 1000))
            if self._cli <= 0:
                raise TimeoutError(f"cannot reach store at {host}:{port}")
            self._py_cli = None
        else:
            self._py_cli = _PyClient(host or "127.0.0.1", port, timeout)
            self._cli = None

    def _py_op(self, site, op, deadline=None):
        """Run a py-client op with transient-failure retry: a
        ConnectionError (peer reset, half-open socket after a master
        restart) reconnects and re-issues; a TimeoutError is a semantic
        result and propagates untouched.  Safe because every store op is
        idempotent (SET is last-writer-wins, GET/WAIT read-only; ADD/CAS
        deliberately do NOT route through here).  `deadline` bounds the
        TOTAL time across re-attempts (get threads its timeout through it
        so retries never multiply the caller's bound)."""

        def attempt():
            _faults.maybe_raise("conn_error", site=site)
            return op()

        def reconnect(attempt_no, exc, delay):
            # a failed reconnect raises TimeoutError("cannot reach store")
            # out of the retry loop — the accurate error, instead of the
            # EBADF the next attempt would hit on the closed socket
            self._py_cli.reconnect()

        # OSError included: an attempt on a socket a failed reconnect
        # closed raises EBADF (plain OSError).  TimeoutError cannot arise
        # inside op() — the py-client sockets are blocking and the store
        # GET/WAIT timeout is a protocol reply (None), not an exception.
        return _retry(attempt, retries=self.OP_RETRIES, backoff=0.05,
                      max_backoff=1.0, retryable=(ConnectionError, OSError),
                      site=site, on_retry=reconnect, deadline=deadline)()

    # -- raw bytes API ------------------------------------------------------
    def set(self, key: str, value) -> None:
        data = value if isinstance(value, (bytes, bytearray)) else pickle.dumps(value)
        if self._py_cli is not None:
            self._py_op("store.set",
                        lambda: self._py_cli.set(key, bytes(data)))
        else:
            rc = self._native.pts_set(self._cli, key.encode(), bytes(data), len(data))
            if rc != 0:
                raise ConnectionError("store set failed")

    @staticmethod
    def _native_read(fn, on_status=None, initial_cap=1 << 20):
        """Run a native call returning a value length into a caller buffer,
        growing the buffer on -3 (too small). `fn(buf, cap) -> n`;
        `on_status` maps a negative status to an exception (else
        ConnectionError). NOTE: -3 re-issues the request — callers of
        non-idempotent commands must size initial_cap so their own
        successful result always fits (see compare_set)."""
        cap = initial_cap
        while True:
            buf = ctypes.create_string_buffer(cap)
            n = fn(buf, cap)
            if n == -3:
                cap *= 16
                continue
            if n < 0:
                if on_status is not None:
                    on_status(n)
                raise ConnectionError("store request failed")
            return buf.raw[:n]

    def get(self, key: str, timeout_ms: Optional[int] = None) -> bytes:
        timeout_ms = self.GET_TIMEOUT_MS if timeout_ms is None else timeout_ms
        if self._py_cli is not None:
            # ONE deadline across re-attempts: a reconnect-retry re-issues
            # with the REMAINING budget, not the full timeout again
            # (timeout_ms=0 is the protocol's "wait forever")
            dl = Deadline(timeout_ms / 1e3 if timeout_ms else None)

            def issue():
                rm = dl.remaining_ms()
                return self._py_cli.get(key, 0 if rm is None else max(rm, 1))

            out = self._py_op("store.get", issue, deadline=dl)
            if out is None:
                raise TimeoutError(f"store get({key!r}) timed out")
            return out

        def on_status(n):
            if n == -1:
                raise TimeoutError(f"store get({key!r}) timed out")

        return self._native_read(
            lambda buf, cap: self._native.pts_get(
                self._cli, key.encode(), buf, cap, timeout_ms),
            on_status)

    def get_obj(self, key: str, timeout_ms: Optional[int] = None):
        return pickle.loads(self.get(key, timeout_ms))

    def add(self, key: str, amount: int = 1) -> int:
        if self._py_cli is not None:
            return self._py_cli.add(key, amount)
        out = ctypes.c_int64()
        rc = self._native.pts_add(self._cli, key.encode(), amount, ctypes.byref(out))
        if rc != 0:
            raise ConnectionError("store add failed")
        return out.value

    def compare_set(self, key: str, expected, desired) -> bytes:
        """Atomic compare-and-set (reference analog: torch-style
        TCPStore.compare_set). Stores `desired` iff the current value equals
        `expected`; a missing key matches an empty `expected`. Returns the
        post-op value — equal to `desired` exactly when the caller won,
        PROVIDED desired values are unique per caller (embed a token, e.g.
        from `add` on a sequence key): if the current value already equals
        `desired`, a losing no-op also returns `desired`. Losers observe
        the current value WITHOUT mutating anything, which is what makes
        this safe as a claim/fencing primitive (an add-based claim lets
        losers corrupt the winner's token)."""
        exp = expected if isinstance(expected, (bytes, bytearray)) else str(expected).encode()
        des = desired if isinstance(desired, (bytes, bytearray)) else str(desired).encode()
        if self._py_cli is not None:
            return self._py_cli.compare_set(key, bytes(exp), bytes(des))
        # initial_cap >= len(desired): a WINNING CAS always fits the buffer,
        # so the -3 grow-and-retry path can only re-run a LOSING attempt
        # (oversized foreign current value). A retried attempt that then
        # wins is a legitimate late linearization of this call; a won-but-
        # truncated first attempt being re-applied after an intervening
        # foreign write would not be, which is why the cap matters.
        return self._native_read(
            lambda buf, cap: self._native.pts_cas(
                self._cli, key.encode(), bytes(exp), len(exp),
                bytes(des), len(des), buf, cap),
            initial_cap=max(1 << 20, len(des)))

    def wait(self, keys, timeout_ms: Optional[int] = None) -> None:
        timeout_ms = self.GET_TIMEOUT_MS if timeout_ms is None else timeout_ms
        keys = [keys] if isinstance(keys, str) else list(keys)
        for k in keys:
            if self._py_cli is not None:
                if not self._py_cli.wait_key(k, timeout_ms):
                    raise TimeoutError(f"store wait({k!r}) timed out")
            else:
                if self._native.pts_wait(self._cli, k.encode(), timeout_ms) != 0:
                    raise TimeoutError(f"store wait({k!r}) timed out")

    def delete_key(self, key: str) -> bool:
        if self._py_cli is not None:
            return bool(self._py_cli.delete(key))
        return self._native.pts_delete_key(self._cli, key.encode()) > 0

    def barrier(self, name: str, world_size: Optional[int] = None,
                timeout_ms: Optional[int] = None):
        """Reusable count-up barrier: all `world_size` participants block
        until the counter reaches world_size. Each call with the same name
        is a new round (locally tracked round id keys the counter), and the
        release check is >= so a stray over-count can't hang everyone."""
        world_size = world_size if world_size is not None else self.world_size
        # Round id lives in the store (add(.., 0) reads the counter), not in
        # this client object: a participant that reconnects with a fresh
        # TCPStore (elastic rejoin) must join the *current* round, not
        # replay round 0 whose done key still exists.
        rkey = f"__barrier__/{name}/round"
        rnd = self.add(rkey, 0)
        key = f"__barrier__/{name}/{rnd}"
        # A client whose wait() timed out and retries the same round must
        # not count itself twice (it would release a later round early).
        # Note the barrier is anonymous counting — like the reference's —
        # so a NON-participant calling barrier() still breaks it; rounds in
        # the store only guarantee that legitimate reconnects (elastic
        # rejoin) land on the current round.
        if self._barrier_added.get(name) == rnd:
            arrived = self.add(key, 0)
        else:
            arrived = self.add(key, 1)
            self._barrier_added[name] = rnd
        if arrived >= world_size:
            # Advance the round before releasing waiters, so every client's
            # next barrier() (ordered after wait() below) reads rnd+1.
            # set() is idempotent under the >= over-count race.
            self.set(rkey, struct.pack("<q", rnd + 1))
            self.set(f"{key}/done", b"1")
        self.wait(f"{key}/done", timeout_ms)

    def close(self):
        if self._py_cli is not None:
            self._py_cli.close()
        elif self._cli:
            self._native.pts_close(self._cli)
            self._cli = None
        if self._srv:
            self._native.pts_server_stop(self._srv)
            self._srv = None
        if self._py_srv is not None:
            self._py_srv.shutdown()
            self._py_srv = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # ptpu-check[silent-except]: interpreter teardown — modules the
            # close path touches may already be torn down; raising in
            # __del__ only prints noise
            pass
