"""Process launch (reference: python/paddle/distributed/launch/main.py:18 —
per-GPU process spawn + KV-store rendezvous; spawn.py).

TPU-native: a single controller process drives all local chips, so
single-host "launch" is just running the script. Multi-host TPU pods run one
process per host; `launch` starts them with PADDLE_* env set so
init_parallel_env wires jax.distributed. Elastic/etcd modes are
reference capabilities carried by the ElasticManager analog in
paddle_tpu.distributed.elastic (later round on real multi-host).
"""
from __future__ import annotations

import os
import runpy
import subprocess
import sys

__all__ = ["launch", "spawn", "run_commandline"]


def _spawn_target(func, args, rank, nprocs, backend):
    os.environ["PADDLE_TRAINER_ID"] = str(rank)
    os.environ["PADDLE_TRAINERS_NUM"] = str(nprocs)
    os.environ["PADDLE_LOCAL_RANK"] = str(rank)
    # fleet identity: /healthz + PTPU_FLEET_STORE registration label the
    # replica by rank without out-of-band config (monitor.fleet).  Force,
    # don't setdefault: an inherited PTPU_REPLICA_ID would give every
    # rank the SAME name and discovery (newest-per-name) would collapse
    # the fleet to one visible replica.  An inherited id becomes the
    # PREFIX instead (launch sets r<host> per host; spawn under it
    # yields r<host>.<rank> — unique across hosts, not just locally)
    parent_rid = os.environ.get("PTPU_REPLICA_ID")
    os.environ["PTPU_REPLICA_ID"] = \
        f"{parent_rid}.{rank}" if parent_rid else f"r{rank}"
    if backend:
        # belt and braces with the parent-side env (set before p.start()):
        # paddle_tpu/jax are already imported by the unpickle of this
        # target, so re-pin directly too (legal until a backend initializes)
        os.environ["PTPU_FORCE_PLATFORM"] = backend
        try:
            import jax

            jax.config.update("jax_platforms", backend)
        except Exception:  # ptpu-check[silent-except]: backend pin is advisory in the child
            # — PTPU_FORCE_PLATFORM already pinned it in __init__
            pass
    func(*args)


def spawn(func, args=(), nprocs=1, join=True, daemon=False, backend=None,
          **options):
    """Reference API parity (launch/spawn.py). On TPU a single process owns
    every local chip, so nprocs>1 is the CPU-emulation/debug path: children
    run under multiprocessing "spawn" with the PADDLE_* env contract and
    (by default) the CPU backend pinned via PTPU_FORCE_PLATFORM — one real
    chip cannot be shared by several local processes.

    `func` must be picklable (module-level). Returns the multiprocessing
    context with `.processes` when join=False (reference return shape).
    """
    if nprocs in (1, -1, None):
        os.environ.setdefault("PADDLE_TRAINER_ID", "0")
        os.environ.setdefault("PADDLE_TRAINERS_NUM", "1")
        func(*args)
        return None

    import multiprocessing as mp

    ctx = mp.get_context("spawn")
    backend = backend or "cpu"
    procs = []
    # children snapshot os.environ at start(): export the platform pin so
    # the paddle_tpu import hook fires BEFORE any jax state exists in the
    # child (the in-target re-pin alone runs after paddle_tpu imports)
    prev = os.environ.get("PTPU_FORCE_PLATFORM")
    os.environ["PTPU_FORCE_PLATFORM"] = backend
    try:
        for rank in range(nprocs):
            p = ctx.Process(
                target=_spawn_target, args=(func, args, rank, nprocs, backend),
                daemon=daemon,
            )
            p.start()
            procs.append(p)
    finally:
        if prev is None:
            os.environ.pop("PTPU_FORCE_PLATFORM", None)
        else:
            os.environ["PTPU_FORCE_PLATFORM"] = prev

    class _SpawnContext:
        processes = procs

        def join(self, timeout=None):
            for proc in procs:
                proc.join(timeout)
            bad = [(i, proc.exitcode) for i, proc in enumerate(procs)
                   if proc.exitcode not in (0, None)]
            if bad:
                raise RuntimeError(f"spawned process(es) failed: {bad}")
            return all(proc.exitcode == 0 for proc in procs)

    sc = _SpawnContext()
    if join:
        sc.join()
    return sc


def launch(training_script, args=(), hosts=None, nproc_per_node=1, master=None):
    """Start one worker per host (DCN scale-out bring-up)."""
    if not hosts or len(hosts) <= 1:
        env = dict(os.environ, PADDLE_TRAINER_ID="0", PADDLE_TRAINERS_NUM="1")
        return subprocess.call([sys.executable, training_script, *args], env=env)
    procs = []
    master = master or hosts[0]
    for i, h in enumerate(hosts):
        # per-host worker identity; PTPU_REPLICA_ID is forced per rank
        # (an inherited id would name every host the same and fleet
        # discovery keeps only the newest record per name), and
        # PTPU_FLEET_STORE is forwarded when the launcher has one so
        # every worker's monitor.start_server self-registers
        worker_env = {
            "PADDLE_TRAINER_ID": str(i),
            "PADDLE_TRAINERS_NUM": str(len(hosts)),
            "PADDLE_MASTER": master,
            "PTPU_REPLICA_ID": f"r{i}",
        }
        if os.environ.get("PTPU_FLEET_STORE"):
            worker_env["PTPU_FLEET_STORE"] = os.environ["PTPU_FLEET_STORE"]
        if h != "localhost":
            # Popen's env= only reaches the LOCAL ssh client — ssh does
            # not forward arbitrary variables, so the worker env must
            # ride the remote command line itself
            cmd = ["ssh", h, "env",
                   *[f"{k}={v}" for k, v in worker_env.items()],
                   sys.executable, training_script, *args]
            procs.append(subprocess.Popen(cmd))
        else:
            cmd = [sys.executable, training_script, *args]
            procs.append(subprocess.Popen(
                cmd, env=dict(os.environ, **worker_env)))
    rc = 0
    for p in procs:
        rc |= p.wait()
    return rc


def run_commandline():
    """`python -m paddle_tpu.distributed.launch script.py` entry."""
    argv = sys.argv[1:]
    if not argv:
        print("usage: python -m paddle_tpu.distributed.launch script.py [args...]")
        return 1
    script, *rest = argv
    sys.argv = [script, *rest]
    os.environ.setdefault("PADDLE_TRAINER_ID", "0")
    os.environ.setdefault("PADDLE_TRAINERS_NUM", "1")
    runpy.run_path(script, run_name="__main__")
    return 0
