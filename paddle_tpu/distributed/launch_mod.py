"""Process launch (reference: python/paddle/distributed/launch/main.py:18 —
per-GPU process spawn + KV-store rendezvous; spawn.py).

TPU-native: a single controller process drives all local chips, so
single-host "launch" is just running the script. Multi-host TPU pods run one
process per host; `launch` starts them with PADDLE_* env set so
init_parallel_env wires jax.distributed. Elastic/etcd modes are
reference capabilities carried by the ElasticManager analog in
paddle_tpu.distributed.elastic (later round on real multi-host).
"""
from __future__ import annotations

import os
import runpy
import subprocess
import sys

__all__ = ["launch", "spawn", "run_commandline"]


def spawn(func, args=(), nprocs=1, join=True, daemon=False, **options):
    """Reference API parity. On TPU a single process owns every local chip,
    so nprocs>1 local spawn is a CPU-emulation/debug path: we run
    sequentially with PADDLE_TRAINER_ID set (parity tests use world_size 1
    semantics; real scale-out is multi-host `launch`)."""
    if nprocs in (1, -1, None):
        os.environ.setdefault("PADDLE_TRAINER_ID", "0")
        os.environ.setdefault("PADDLE_TRAINERS_NUM", "1")
        func(*args)
        return
    raise NotImplementedError(
        "local multi-process spawn has no TPU analog (one controller drives "
        "all chips); use the Mesh APIs (paddle_tpu.parallel) for multi-chip "
        "and distributed.launch for multi-host"
    )


def launch(training_script, args=(), hosts=None, nproc_per_node=1, master=None):
    """Start one worker per host (DCN scale-out bring-up)."""
    if not hosts or len(hosts) <= 1:
        env = dict(os.environ, PADDLE_TRAINER_ID="0", PADDLE_TRAINERS_NUM="1")
        return subprocess.call([sys.executable, training_script, *args], env=env)
    procs = []
    master = master or hosts[0]
    for i, h in enumerate(hosts):
        env = dict(
            os.environ,
            PADDLE_TRAINER_ID=str(i),
            PADDLE_TRAINERS_NUM=str(len(hosts)),
            PADDLE_MASTER=master,
        )
        cmd = ["ssh", h, sys.executable, training_script, *args] if h != "localhost" else [sys.executable, training_script, *args]
        procs.append(subprocess.Popen(cmd, env=env))
    rc = 0
    for p in procs:
        rc |= p.wait()
    return rc


def run_commandline():
    """`python -m paddle_tpu.distributed.launch script.py` entry."""
    argv = sys.argv[1:]
    if not argv:
        print("usage: python -m paddle_tpu.distributed.launch script.py [args...]")
        return 1
    script, *rest = argv
    sys.argv = [script, *rest]
    os.environ.setdefault("PADDLE_TRAINER_ID", "0")
    os.environ.setdefault("PADDLE_TRAINERS_NUM", "1")
    runpy.run_path(script, run_name="__main__")
    return 0
