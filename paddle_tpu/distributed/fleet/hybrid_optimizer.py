"""HybridParallelOptimizer (reference:
fleet/meta_parallel/dygraph_optimizer/hybrid_parallel_optimizer.py:186 —
cross-group grad clip + mp/pp grad sync + inner optimizer).

TPU-native: grad synchronization is GSPMD's job inside the compiled step;
what remains is (1) ZeRO weight-update sharding of optimizer slots along the
'sharding' axis and (2) API parity. Slot sharding: each optimizer state
array is placed with its parameter's sharding PLUS the 'sharding' axis on
the first divisible dim — the XLA-side formulation of ZeRO stage-1 (the
reference's DygraphShardingOptimizer partitions the param list by rank
instead; same memory effect, no gather/release hooks needed).
"""
from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec

from ...parallel.mesh import get_mesh, axis_size
from ...parallel.api import param_sharding

__all__ = ["HybridParallelOptimizer", "DygraphShardingOptimizer"]


def shard_spec_with(base, shape, axis="sharding"):
    """Compose `axis` into a per-dim spec: split the first dim whose size the
    axis degree divides (stacking onto an existing single-axis annotation
    when needed). Returns `base` unchanged if `axis` already appears, the
    degree is 1, or no dim divides. The one dim-picker shared by slot
    placement, anonymous state placement, and stage-3 param sharding."""
    base = tuple(base) if base else (None,) * len(shape)
    deg = axis_size(axis)
    already = any(
        a == axis or (isinstance(a, (tuple, list)) and axis in a) for a in base
    )
    if deg <= 1 or already:
        return base
    spec = list(base)
    for i, (dim, ax) in enumerate(zip(shape, base)):
        if dim <= 0:
            continue
        if ax is None and dim % deg == 0:
            spec[i] = axis
            break
        if isinstance(ax, str) and dim % (deg * axis_size(ax)) == 0:
            spec[i] = (ax, axis)
            break
    return tuple(spec)


def _shard_slot_sharding(param, mesh, axis="sharding"):
    """Sharding for an optimizer slot of `param`: param's own spec with the
    sharding axis composed onto the first dim it divides."""
    base = getattr(param, "_sharding_axes", None)
    return NamedSharding(
        mesh, PartitionSpec(*shard_spec_with(base, param.shape, axis))
    )


class HybridParallelOptimizer:
    def __init__(self, optimizer, hcg=None, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy
        self._placed = False

    def _place_states(self):
        """Device_put params + slots with their SPMD shardings (ZeRO stage-1
        weight-update sharding included)."""
        mesh = get_mesh()
        opt = self._inner_opt
        for p in opt._parameter_list:
            try:
                p._data = jax.device_put(p._data, param_sharding(p))
            except Exception:  # ptpu-check[silent-except]: device_put onto a partial mesh
                # can reject a shape; the array stays on its current placement
                pass
            opt._ensure_state(p)
            slot_sh = _shard_slot_sharding(p, mesh)
            key = id(p)
            for sname, arr in opt._states[key].items():
                try:
                    opt._states[key][sname] = jax.device_put(arr, slot_sh)
                except Exception:  # ptpu-check[silent-except]: same best-effort placement as
                    # above
                    pass
            if key in opt._master_weights:
                try:
                    opt._master_weights[key] = jax.device_put(
                        opt._master_weights[key], slot_sh
                    )
                except Exception:  # ptpu-check[silent-except]: same best-effort placement as
                    # above
                    pass
        self._placed = True

    def step(self):
        if not self._placed:
            self._place_states()
        self._inner_opt.step()

    def clear_grad(self, *a, **k):
        self._inner_opt.clear_grad(*a, **k)

    clear_gradients = clear_grad

    def minimize(self, loss, **kwargs):
        loss.backward()
        self.step()
        return None, None

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, state):
        return self._inner_opt.set_state_dict(state)

    def get_lr(self):
        return self._inner_opt.get_lr()

    def set_lr(self, v):
        return self._inner_opt.set_lr(v)

    @property
    def _learning_rate(self):
        return self._inner_opt._learning_rate

    @property
    def _parameter_list(self):
        return self._inner_opt._parameter_list

    def __getattr__(self, name):
        return getattr(self.__dict__["_inner_opt"], name)

    _OWN_ATTRS = frozenset({"_inner_opt", "_hcg", "_strategy", "_placed"})

    def __setattr__(self, name, value):
        # Reads proxy to the inner optimizer (__getattr__), so writes must
        # too — otherwise jit.compile's `opt._step_count += 1` or traced
        # lr/step overrides land on the wrapper while step()/state_dict()
        # read the inner's stale values.
        if name not in self._OWN_ATTRS and "_inner_opt" in self.__dict__:
            setattr(self.__dict__["_inner_opt"], name, value)
        else:
            object.__setattr__(self, name, value)


class DygraphShardingOptimizer(HybridParallelOptimizer):
    """ZeRO stage-1 API name parity (reference:
    dygraph_optimizer/dygraph_sharding_optimizer.py:29)."""

    def __init__(self, optimizer, hcg=None, strategy=None, **kwargs):
        super().__init__(optimizer, hcg, strategy)
