"""fleet — hybrid-parallel orchestration (reference:
python/paddle/distributed/fleet/fleet.py:101,169,1044 + base/topology.py).

TPU-native: `fleet.init` builds the 5-axis device Mesh instead of NCCL
groups; `distributed_model`/`distributed_optimizer` return wrappers whose
train_batch/step compile to ONE SPMD program over that mesh.
"""
from .base import (
    DistributedStrategy, HybridCommunicateGroup, PaddleCloudRoleMaker,
    UserDefinedRoleMaker, Role, UtilBase, CommunicateTopology,
    MultiSlotDataGenerator, MultiSlotStringDataGenerator,
)
from .fleet_api import (
    init, distributed_model, distributed_optimizer, get_hybrid_communicate_group,
    worker_index, worker_num, is_first_worker, barrier_worker, get_mesh,
)
from . import utils
from . import elastic
from . import meta_optimizers
from .meta_optimizers import (
    GradientMergeOptimizer, LocalSGDOptimizer, DGCMomentumOptimizer,
    QuantAllReduceOptimizer,
)
from .elastic import ElasticManager, ElasticStatus
from .meta_parallel import (
    TensorParallel, PipelineParallel, ShardingParallel, PipelineLayer, LayerDesc,
    SharedLayerDesc,
)

class Fleet:
    """Instance API over the module-level fleet functions (reference
    fleet/fleet.py:101 — the `paddle.distributed.fleet` singleton's
    class)."""

    def __init__(self):
        self.util = UtilBase()

    def init(self, role_maker=None, is_collective=True, strategy=None,
             log_level="INFO"):
        from . import fleet_api

        return fleet_api.init(role_maker, is_collective, strategy, log_level)

    def __getattr__(self, name):
        from . import fleet_api

        return getattr(fleet_api, name)


__all__ = [
    "Fleet", "Role", "UtilBase", "CommunicateTopology",
    "MultiSlotDataGenerator", "MultiSlotStringDataGenerator",
    "init", "distributed_model", "distributed_optimizer",
    "get_hybrid_communicate_group", "DistributedStrategy",
    "HybridCommunicateGroup", "worker_index", "worker_num", "is_first_worker",
    "barrier_worker", "utils", "TensorParallel", "PipelineParallel",
    "ShardingParallel", "PipelineLayer", "LayerDesc", "SharedLayerDesc",
    "get_mesh",
]
