"""Fleet meta-optimizers (reference: fleet/meta_optimizers/ — each
`_can_apply()`s off DistributedStrategy and rewrites the program; SURVEY
§8.6: gradient_merge_optimizer.py:21, localsgd_optimizer.py,
dgc_optimizer.py:30).

TPU-native re-design: no program rewriting — each meta-optimizer is a
state-carrying wrapper around the inner optimizer whose extra state
(accumulators, error-feedback buffers, counters) lives in the inner
optimizer's `_states`, so the jit.compile state threading (and
checkpointing via state_dict) picks it up with zero extra wiring. All
branching is `jnp.where`-select on a threaded counter, keeping ONE XLA
executable regardless of step parity (no retrace per micro-step).

What carries over semantically vs the reference:
- GradientMerge: exact (k-step grad accumulation, averaged or summed).
- LocalSGD: inner updates run every step; parameter averaging over the
  'dp' axis every k steps. In single-program GSPMD data parallelism the
  gradients are already globally averaged (params never diverge), so the
  averaging is an identity there — the wrapper matters on the multi-host
  DCN path where each process steps locally.
- DGC: momentum correction + error feedback + top-k masking are exact;
  the *bandwidth* saving of sparse allreduce is not realized (XLA's dense
  ICI collectives are the transport — comm compression is a NCCL-era
  concern the TPU fabric does not need).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["GradientMergeOptimizer", "LocalSGDOptimizer",
           "DGCMomentumOptimizer", "QuantAllReduceOptimizer",
           "apply_strategy"]

_COUNTER_KEY = "@meta_counter"


class _MetaOptimizer:
    """Shared delegation shell: exposes the inner optimizer's state surface
    (_states, _master_weights, _parameter_list, lr/step plumbing) so
    jit._StateSpec and checkpointing see one merged optimizer."""

    def __init__(self, inner):
        object.__setattr__(self, "_inner", inner)

    # the attributes _StateSpec and CompiledFunction touch — all delegated
    def __getattr__(self, name):
        return getattr(self._inner, name)

    def __setattr__(self, name, value):
        if name.startswith("_meta_") or name in self.__class__.__dict__ or \
                name in ("_inner",):
            object.__setattr__(self, name, value)
        else:
            setattr(self._inner, name, value)

    def _counter(self):
        """Threaded scalar step counter living in inner._states (so it rides
        the compiled program's state I/O and state_dict)."""
        slot = self._inner._states.setdefault(_COUNTER_KEY, {})
        if "count" not in slot:
            slot["count"] = jnp.zeros((), jnp.int32)
        return slot["count"]

    def _set_counter(self, v):
        self._inner._states[_COUNTER_KEY]["count"] = v

    def _meta_slots_for(self, slot, p):
        """Subclass hook: add this meta-optimizer's extra slots."""

    def _ensure_state(self, p):
        """Called by jit._StateSpec BEFORE tracing — create every meta slot
        here so the threaded state structure is stable from the first trace
        (a slot first created inside a trace would leak tracers through the
        state restore in CompiledFunction.pure)."""
        slot = self._inner._ensure_state(p)
        self._counter()          # materialize the counter slot
        self._meta_slots_for(slot, p)
        return slot

    def clear_grad(self, *a, **k):
        return self._inner.clear_grad(*a, **k)

    def state_dict(self):
        return self._inner.state_dict()

    def set_state_dict(self, state):
        return self._inner.set_state_dict(state)


def _snapshot(opt, params, copy=False):
    """Snapshot (params, states, masters). copy=True materializes copies:
    the inner _fused_update DONATES its param/state/master buffers
    (optimizer.py donate_argnums), so a held-for-select 'before' snapshot
    must not alias them (eager buffers would be deleted; under a jit trace
    the copy is a no-op XLA folds away)."""

    def c(x):
        return None if x is None else (jnp.copy(x) if copy else x)

    return (
        [c(p._data) for p in params],
        [{k: c(v) for k, v in opt._states.get(id(p), {}).items()}
         for p in params],
        [c(opt._master_weights.get(id(p))) for p in params],
    )


def _select_tree(cond, a, b):
    """Elementwise select over (possibly asymmetric) state dicts. The inner
    step REPLACES each param's slot dict with freshly built slots
    (optimizer.step: `self._states[id(p)] = ns`), dropping meta slots — a
    key present on only one side takes that side's value."""
    if isinstance(a, dict):
        out = {}
        # a's insertion order first, then b-only keys: set() iteration is
        # hash-seed-dependent, and the jit state threading reads slot dicts
        # positionally — a hash-ordered rebuild would permute the threaded
        # state between calls of one compiled program
        for k in list(a) + [k for k in b if k not in a]:
            if k not in a:
                out[k] = b[k]
            elif k not in b:
                out[k] = a[k]
            else:
                out[k] = _select_tree(cond, a[k], b[k])
        return out
    if a is None:
        return None
    return jnp.where(cond, a, b)


class GradientMergeOptimizer(_MetaOptimizer):
    """k-step gradient accumulation before the inner update (reference
    GradientMergeOptimizer: gradient_merge_optimizer.py:21, @GRAD@MERGED
    vars + conditional optimize block). avg=True divides by k_steps."""

    def __init__(self, inner, k_steps: int = 1, avg: bool = True):
        super().__init__(inner)
        self._meta_k = int(k_steps)
        self._meta_avg = bool(avg)

    def _meta_slots_for(self, slot, p):
        if "gm_acc" not in slot:
            slot["gm_acc"] = jnp.zeros_like(p._data)

    def step(self):
        inner = self._inner
        k = self._meta_k
        if k <= 1:
            return inner.step()
        params = [p for p in inner._parameter_list
                  if p.grad is not None and p.trainable]
        if not params:
            return
        count = self._counter() + 1
        apply_now = (count % k) == 0
        # accumulate into a gm_acc slot per param
        for p in params:
            slot = self._ensure_state(p)
            slot["gm_acc"] = slot["gm_acc"] + p.grad._data

        before = _snapshot(inner, params, copy=True)
        # run the inner update on the merged grads (computed every step,
        # applied conditionally — static program shape, no retrace)
        from ...core.tensor import Tensor

        saved_grads = [p.grad for p in params]
        try:
            for p in params:
                merged = inner._states[id(p)]["gm_acc"]
                if self._meta_avg:
                    merged = merged / k
                p.grad = Tensor(merged)
            inner.step()
        finally:
            pass
        after = _snapshot(inner, params)
        # select applied-vs-held state; reset accumulators on apply
        for i, p in enumerate(params):
            p._set_data(jnp.where(apply_now, after[0][i], before[0][i]))
            sel = _select_tree(apply_now, after[1][i], before[1][i])
            sel["gm_acc"] = jnp.where(
                apply_now, jnp.zeros_like(sel["gm_acc"]), sel["gm_acc"])
            inner._states[id(p)] = sel
            if after[2][i] is not None:
                inner._master_weights[id(p)] = jnp.where(
                    apply_now, after[2][i], before[2][i])
            p.grad = saved_grads[i]
        self._set_counter(count)


class LocalSGDOptimizer(_MetaOptimizer):
    """Local updates + periodic parameter averaging over the data-parallel
    group (reference localsgd_optimizer.py: every k_steps inserts
    c_allreduce of params / dp_degree)."""

    def __init__(self, inner, k_steps: int = 1):
        super().__init__(inner)
        self._meta_k = max(1, int(k_steps))

    def step(self):
        inner = self._inner
        inner.step()
        count = self._counter() + 1
        self._set_counter(count)
        if self._meta_k <= 1:
            return
        from ..collective import _current_axis

        axis = _current_axis()
        if axis is None:
            # Single-program GSPMD data parallelism: gradients are already
            # globally averaged every step, so local params never diverge
            # and the periodic average is an identity — nothing to do. The
            # wrapper only acts inside a manual shard region (axis_scope /
            # shard_map over 'dp'), where per-device updates CAN diverge.
            return
        sync_now = (count % self._meta_k) == 0
        for p in inner._parameter_list:
            if not p.trainable:
                continue
            avg = jax.lax.pmean(p._data, axis)
            p._set_data(jnp.where(sync_now, avg, p._data))


class DGCMomentumOptimizer(_MetaOptimizer):
    """Deep Gradient Compression semantics (reference dgc_optimizer.py:30 +
    operators/dgc_op.cc): momentum correction (U), error feedback (V),
    top-(1-sparsity) magnitude masking with a warmup rampup schedule.
    The masked-out residual re-enters next step's V — convergence behavior
    matches; the transport stays XLA-dense (see module docstring)."""

    def __init__(self, inner, momentum: float = 0.9,
                 rampup_begin_step: int = 0, rampup_step: int = 1,
                 sparsity=(0.999,)):
        super().__init__(inner)
        self._meta_m = float(momentum)
        self._meta_begin = int(rampup_begin_step)
        self._meta_ramp = max(1, int(rampup_step))
        self._meta_sparsity = tuple(float(s) for s in sparsity)

    def _meta_slots_for(self, slot, p):
        if "dgc_u" not in slot:
            slot["dgc_u"] = jnp.zeros_like(p._data)
            slot["dgc_v"] = jnp.zeros_like(p._data)

    def _sparsity_at(self, count):
        # piecewise rampup: sparsity[i] for segment i of rampup_step steps
        seg = jnp.clip((count - self._meta_begin) // self._meta_ramp,
                       0, len(self._meta_sparsity) - 1)
        table = jnp.asarray(self._meta_sparsity, jnp.float32)
        return table[seg]

    def step(self):
        inner = self._inner
        params = [p for p in inner._parameter_list
                  if p.grad is not None and p.trainable]
        if not params:
            return
        from ...core.tensor import Tensor

        count = self._counter() + 1
        self._set_counter(count)
        active = count > self._meta_begin
        sp = self._sparsity_at(count)
        saved = [p.grad for p in params]
        for p in params:
            slot = self._ensure_state(p)
            g = p.grad._data
            u = slot["dgc_u"]
            v = slot["dgc_v"]
            u_new = self._meta_m * u + g          # momentum correction
            v_new = v + u_new                      # error feedback accum
            flat = jnp.abs(v_new).reshape(-1).astype(jnp.float32)
            thresh = jnp.quantile(flat, jnp.clip(sp, 0.0, 1.0))
            mask = jnp.abs(v_new) >= thresh
            sparse = jnp.where(mask, v_new, 0)
            # masked-out residue stays in U/V (dgc_op.cc semantics)
            slot["dgc_u"] = jnp.where(active, jnp.where(mask, 0, u_new), u)
            slot["dgc_v"] = jnp.where(active, jnp.where(mask, 0, v_new), v)
            p.grad = Tensor(jnp.where(active, sparse, g))
        feedback = [(inner._states[id(p)]["dgc_u"],
                     inner._states[id(p)]["dgc_v"]) for p in params]
        inner.step()
        # inner.step rebuilt each slot dict — re-attach the feedback buffers
        for p, g, (u, v) in zip(params, saved, feedback):
            slot = inner._states[id(p)]
            slot["dgc_u"] = u
            slot["dgc_v"] = v
            p.grad = g


class QuantAllReduceOptimizer(_MetaOptimizer):
    """EQuARX-style int8 gradient all-reduce (paddle_tpu.lowbit.comm) on
    the manual-DP sync path: inside a live mesh axis (axis_scope /
    shard_map over 'dp') each parameter's gradient is quantized to int8
    with shared per-chunk scales, pmean-reduced exactly in int32, and
    dequantized before the inner optimizer's update — 4× less gradient
    traffic on the wire.  The per-chunk rounding residual lives in an
    error-feedback slot (``qar_residual``) that re-enters the next step's
    quantization, so the noise is delayed, not lost (same convergence
    argument as DGC's V buffer).

    Under single-program GSPMD data parallelism (no manual axis) the
    gradients are already globally averaged by XLA — the wrapper is an
    exact no-op there, like LocalSGDOptimizer."""

    def __init__(self, inner, error_feedback: bool = True,
                 chunk: int = 256, bits: int = 8):
        super().__init__(inner)
        self._meta_ef = bool(error_feedback)
        self._meta_chunk = int(chunk)
        self._meta_bits = int(bits)

    def _meta_slots_for(self, slot, p):
        if self._meta_ef and "qar_residual" not in slot:
            slot["qar_residual"] = jnp.zeros(p.shape, jnp.float32)

    def step(self):
        from ..collective import _current_axis
        from ...lowbit.comm import quantized_all_reduce_arrays

        inner = self._inner
        axis = _current_axis()
        if axis is None:
            # GSPMD single-program DP: grads arrive pre-averaged
            inner.step()
            return
        from ...core.tensor import Tensor

        params = [p for p in inner._parameter_list
                  if p.grad is not None and p.trainable]
        saved = [p.grad for p in params]
        feedback = []
        for p in params:
            slot = self._ensure_state(p)
            res = slot.get("qar_residual")
            g, new_res = quantized_all_reduce_arrays(
                p.grad._data, axis, bits=self._meta_bits,
                chunk=self._meta_chunk, residual=res, average=True)
            if res is not None:
                slot["qar_residual"] = new_res
            feedback.append(slot.get("qar_residual"))
            p.grad = Tensor(g)
        inner.step()
        # inner.step may rebuild slot dicts — re-attach the EF buffers
        for p, g, res in zip(params, saved, feedback):
            if res is not None:
                inner._states[id(p)]["qar_residual"] = res
            p.grad = g


def apply_strategy(optimizer, strategy):
    """Wrap `optimizer` per DistributedStrategy flags — the TPU analog of
    the reference's StrategyCompiler meta-optimizer composition
    (fleet/base/strategy_compiler.py)."""
    if strategy is None:
        return optimizer
    if getattr(strategy, "dgc", False):
        cfg = getattr(strategy, "dgc_configs", {}) or {}
        optimizer = DGCMomentumOptimizer(
            optimizer,
            momentum=cfg.get("momentum", 0.9),
            rampup_begin_step=cfg.get("rampup_begin_step", 0),
            rampup_step=cfg.get("rampup_step", 1),
            sparsity=cfg.get("sparsity", (0.999,)))
    if getattr(strategy, "gradient_merge", False):
        cfg = strategy.gradient_merge_configs or {}
        optimizer = GradientMergeOptimizer(
            optimizer, k_steps=cfg.get("k_steps", 1),
            avg=cfg.get("avg", True))
    if getattr(strategy, "localsgd", False):
        cfg = getattr(strategy, "localsgd_configs", {}) or {}
        optimizer = LocalSGDOptimizer(optimizer,
                                      k_steps=cfg.get("k_steps", 1))
    if getattr(strategy, "int8_allreduce", False):
        cfg = getattr(strategy, "int8_allreduce_configs", {}) or {}
        # outermost: the quantized grad sync must run before any inner
        # meta-optimizer consumes the (now globally averaged) gradients
        optimizer = QuantAllReduceOptimizer(
            optimizer, error_feedback=cfg.get("error_feedback", True),
            chunk=cfg.get("chunk", 256))
    return optimizer
