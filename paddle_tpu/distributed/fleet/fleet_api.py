"""fleet user API (reference: fleet/fleet.py:101 init, :169/model.py:30
distributed_model, :1044 distributed_optimizer)."""
from __future__ import annotations

from typing import Optional

from ...parallel.mesh import init_mesh, get_mesh as _get_mesh
from .base import DistributedStrategy, HybridCommunicateGroup, PaddleCloudRoleMaker
from .meta_parallel import TensorParallel, PipelineParallel, ShardingParallel, PipelineLayer
from .hybrid_optimizer import HybridParallelOptimizer

__all__ = [
    "init", "distributed_model", "distributed_optimizer",
    "get_hybrid_communicate_group", "worker_index", "worker_num",
    "is_first_worker", "barrier_worker", "get_mesh",
]

_fleet_state = {"strategy": None, "hcg": None, "initialized": False}


def init(role_maker=None, is_collective=True, strategy=None, log_level="INFO"):
    strategy = strategy or DistributedStrategy()
    hc = strategy.hybrid_configs
    init_mesh(
        dp=hc.get("dp_degree", 1),
        mp=hc.get("mp_degree", 1),
        pp=hc.get("pp_degree", 1),
        sharding=hc.get("sharding_degree", 1),
        sp=hc.get("sp_degree", 1),
    )
    _fleet_state["strategy"] = strategy
    _fleet_state["hcg"] = HybridCommunicateGroup(strategy)
    _fleet_state["initialized"] = True
    return None


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _fleet_state["hcg"]


def get_mesh():
    return _get_mesh()


def _strategy() -> DistributedStrategy:
    return _fleet_state["strategy"] or DistributedStrategy()


def distributed_model(model):
    """Wrap per active strategy (reference fleet/model.py:30 chooses
    PipelineParallel | TensorParallel | ShardingParallel | DataParallel)."""
    strategy = _strategy()
    hc = strategy.hybrid_configs
    if isinstance(model, PipelineLayer) or hc.get("pp_degree", 1) > 1:
        return PipelineParallel(model, strategy=strategy)
    if hc.get("mp_degree", 1) > 1:
        return TensorParallel(model, strategy=strategy)
    if hc.get("sharding_degree", 1) > 1:
        return ShardingParallel(model, strategy=strategy)
    from .. import DataParallel

    return DataParallel(model)


def distributed_optimizer(optimizer, strategy=None):
    strategy = strategy or _strategy()
    # meta-optimizer composition off strategy flags (StrategyCompiler analog)
    from .meta_optimizers import apply_strategy

    optimizer = apply_strategy(optimizer, strategy)
    return HybridParallelOptimizer(optimizer, _fleet_state["hcg"], strategy)


def worker_index():
    return PaddleCloudRoleMaker().worker_index()


def worker_num():
    return PaddleCloudRoleMaker().worker_num()


def is_first_worker():
    return worker_index() == 0


def barrier_worker():
    from ..collective import barrier

    barrier()
