"""fleet.utils (reference: fleet/utils/ — recompute, hybrid_parallel_util)."""
from .recompute import recompute, recompute_sequential
from . import fs
from .fs import LocalFS, HDFSClient

__all__ = ["recompute", "recompute_sequential", "fused_allreduce_gradients", "fs", "LocalFS", "HDFSClient"]


def fused_allreduce_gradients(parameter_list, hcg=None):
    """Reference: fleet/utils/hybrid_parallel_util.py:206 — fused dp-group
    allreduce of grads. Under SPMD compilation XLA already reduced them;
    eager single-process is a no-op. Kept for script parity."""
    return None
