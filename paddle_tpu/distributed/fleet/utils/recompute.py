"""Activation recompute (reference: fleet/recompute/recompute.py — PyLayer
that RNG-checkpoints and re-runs forward in backward).

TPU-native: jax.checkpoint (rematerialization) on the pure forward — the
compiler re-forms the forward inside the backward, with RNG handled by the
counter-split key (deterministic replay by construction).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ....core.tensor import Tensor
from ....core.dispatch import apply
from ....core import random as _rng
from ....autograd import tape

__all__ = ["recompute", "recompute_sequential"]


def recompute(function, *args, **kwargs):
    preserve_rng_state = kwargs.pop("preserve_rng_state", True)
    use_reentrant = kwargs.pop("use_reentrant", True)

    tensor_args = [a for a in args if isinstance(a, Tensor)]
    other_args = [(i, a) for i, a in enumerate(args) if not isinstance(a, Tensor)]
    t_idx = [i for i, a in enumerate(args) if isinstance(a, Tensor)]
    base_key = _rng.next_key() if preserve_rng_state else _rng.get_state()

    def pure(*arrays):
        rebuilt = list(args)
        for i, arr in zip(t_idx, arrays):
            rebuilt[i] = Tensor(arr)
        with _rng.key_scope(base_key):
            with tape.no_grad():
                out = function(*rebuilt, **kwargs)
        if isinstance(out, (tuple, list)):
            return tuple(o._data if isinstance(o, Tensor) else o for o in out)
        return out._data if isinstance(out, Tensor) else out

    ckpt = jax.checkpoint(pure)
    return apply(ckpt, *tensor_args, name="recompute")


def recompute_sequential(ctx, functions, *args, **kwargs):
    segments = ctx.get("segments", 1) if isinstance(ctx, dict) else 1
    if not isinstance(functions, (list, tuple)):
        functions = list(functions)
    n = len(functions)
    seg_size = max(n // max(segments, 1), 1)
    x = args[0] if len(args) == 1 else args

    def run_segment(fs):
        def seg_fn(inp):
            out = inp
            for f in fs:
                out = f(out)
            return out

        return seg_fn

    out = x
    i = 0
    while i < n:
        fs = functions[i : i + seg_size]
        out = recompute(run_segment(fs), out)
        i += seg_size
    return out
